"""Owner-side task + object bookkeeping.

Capability parity with the reference's ownership layer
(reference: src/ray/core_worker/task_manager.h:175 — pending task table,
retries, completion; reference_counter.h:43 — pinning objects while
references exist; object location bookkeeping in
ownership_object_directory.cc).

Divergence from the reference: ownership is centralized in the head
process rather than distributed per-worker. On a single TPU host (and a
head-coordinated pod) this removes the distributed-GC protocol while
keeping the same API semantics; the seam (`owner` field on TaskSpec)
is where per-worker ownership would slot back in.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ray_tpu.core.ids import NodeID, ObjectID, TaskID
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.devtools import refsan
from ray_tpu.util.metrics import Counter, Histogram

logger = logging.getLogger(__name__)

# Task lifecycle instrumentation (reference: task events + the
# dashboard's task metrics): submit→start queueing, worker-measured run
# time, and submit→finish end-to-end latency, observed on the
# completion path in runtime._record_execution_events.
TASK_QUEUE_SECONDS = Histogram(
    "ray_tpu_task_queue_seconds",
    "Time from task submission to execution start on a worker")
TASK_RUN_SECONDS = Histogram(
    "ray_tpu_task_run_seconds",
    "Worker-measured task execution time")
TASK_E2E_SECONDS = Histogram(
    "ray_tpu_task_e2e_seconds",
    "Time from task submission to completion reply")
TASKS_FINISHED = Counter(
    "ray_tpu_tasks_completed_total",
    "Tasks completed, by terminal state", tag_keys=("state",))


@dataclass
class PendingTask:
    spec: TaskSpec
    retries_left: int
    node_id: Optional[NodeID] = None
    submitted_at: float = field(default_factory=time.time)


@dataclass
class ObjectLocation:
    kind: str                   # "memory" | "shm" | "spilled"
    node_id: Optional[NodeID] = None
    # filesystem path of the spilled payload (kind == "spilled";
    # reference: spilled object URLs, local_object_manager.h:43)
    path: Optional[str] = None


class ReferenceCounter:
    """Counts local references per object; fires a deleter at zero.

    reference: src/ray/core_worker/reference_counter.h:43. Deletion is
    deferred while the producing task is still pending (lineage keeps the
    spec anyway, but the object may be produced after the last ref dies).
    """

    def __init__(self):
        # RLock: the deleter may recursively remove refs pinned by the
        # deleted object (nested references)
        self._lock = threading.RLock()
        self._counts: Dict[ObjectID, int] = {}
        self._deleter: Optional[Callable[[ObjectID], None]] = None
        self._on_first: Optional[Callable[[ObjectID], None]] = None
        # refsan ledger role tag: "owner" on the head's counter,
        # "borrower" on worker/client counters (set by their runtimes).
        # The fold only judges grace violations against owner events.
        self.refsan_role = "local"

    def set_deleter(self, fn: Callable[[ObjectID], None]) -> None:
        self._deleter = fn

    def set_on_first(self, fn: Callable[[ObjectID], None]) -> None:
        """Hook fired when an object's local count goes 0 -> 1 (workers
        use it to report borrowed refs to the owner, reference:
        reference_counter.h borrowing protocol)."""
        self._on_first = fn

    def add_local_reference(self, object_id: ObjectID) -> None:
        # hooks fire under the lock so ADD/DROP notifications are emitted
        # in count-transition order even across threads
        with self._lock:
            count = self._counts.get(object_id, 0)
            self._counts[object_id] = count + 1
            led = refsan.LEDGER
            if led is not None:
                led.ref_event(refsan.KIND_REF_ADD, object_id.binary(),
                              count + 1, self.refsan_role)
            if count == 0 and self._on_first is not None:
                try:
                    self._on_first(object_id)
                except Exception:
                    logger.exception("on_first_reference callback "
                                     "failed for %s", object_id)

    def remove_local_reference(self, object_id: ObjectID,
                               defer: Optional[tuple] = None) -> None:
        """Drop one reference. `defer=(delay_s, schedule_fn)` delays the
        zero-count deleter by `delay_s` via `schedule_fn(delay, fn)`,
        firing only if the count is still zero then (grace window for
        in-flight borrows)."""
        with self._lock:
            count = self._counts.get(object_id)
            led = refsan.LEDGER
            if count is None:
                if led is not None:
                    led.ref_event(refsan.KIND_REF_DROP_MISSING,
                                  object_id.binary(), 0, self.refsan_role)
                return
            if led is not None:
                led.ref_event(refsan.KIND_REF_DROP, object_id.binary(),
                              count - 1, self.refsan_role)
            if count > 1:
                self._counts[object_id] = count - 1
                return
            del self._counts[object_id]
            if led is not None:
                led.ref_event(
                    refsan.KIND_REF_DEFER if defer is not None
                    else refsan.KIND_REF_ZERO,
                    object_id.binary(), 0, self.refsan_role)
            deleter = self._deleter
            if deleter is not None and defer is None:
                try:
                    deleter(object_id)
                except Exception:
                    logger.exception("deleter failed for %s; the "
                                     "object may leak", object_id)
        if deleter is not None and defer is not None:
            delay, schedule = defer
            schedule(delay,
                     lambda: self._delete_if_still_zero(object_id, deleter))

    def delete_if_unreferenced(self, object_id: ObjectID,
                               defer: Optional[tuple] = None) -> None:
        """Fire the deleter iff no refs exist (checked under the lock at
        fire time). With `defer=(delay, schedule)` the check happens
        after the grace window, so in-flight borrows can land first."""
        deleter = self._deleter
        if deleter is None:
            return
        with self._lock:
            if self._counts.get(object_id, 0) > 0:
                # The common case (caller still holds its ObjectRef):
                # that ref's drop is what deletes; scheduling a deferred
                # re-check per task result would only churn the expiry
                # heap on the hot path.
                return
        if defer is None:
            self._delete_if_still_zero(object_id, deleter)
            return
        delay, schedule = defer
        schedule(delay,
                 lambda: self._delete_if_still_zero(object_id, deleter))

    def _delete_if_still_zero(self, object_id: ObjectID, deleter) -> None:
        with self._lock:
            if self._counts.get(object_id, 0) > 0:
                led = refsan.LEDGER
                if led is not None:
                    led.ref_event(refsan.KIND_RECLAIM_SKIP,
                                  object_id.binary(),
                                  self._counts.get(object_id, 0),
                                  self.refsan_role)
                return  # re-borrowed during the grace window
            try:
                deleter(object_id)
            except Exception:
                logger.exception("deferred deleter failed for %s; the "
                                 "object may leak", object_id)

    def live_object_ids(self) -> List[ObjectID]:
        """Every object id with a nonzero local count (the client's
        reconnect path snapshots these as lost across a head restart)."""
        with self._lock:
            return [oid for oid, n in self._counts.items() if n > 0]

    def count(self, object_id: ObjectID) -> int:
        with self._lock:
            return self._counts.get(object_id, 0)

    def tracked(self) -> int:
        with self._lock:
            return len(self._counts)


class TaskManager:
    """Tracks in-flight tasks, their return objects, and completion waiters."""

    def __init__(self):
        self._lock = threading.RLock()
        self._pending: Dict[TaskID, PendingTask] = {}
        self._object_to_task: Dict[ObjectID, TaskID] = {}
        self._locations: Dict[ObjectID, ObjectLocation] = {}
        # Readiness is a set + one shared condition instead of one
        # threading.Event per object: Event construction (lock+condvar)
        # was a top entry in the task-throughput profile, and the common
        # case (pipelined submit, result consumed as it lands) rarely
        # waits. notify_all fires only while a waiter is registered
        # (reference: memory_store.h:48 GetAsync callback design).
        self._ready_objects: Set[ObjectID] = set()
        self._ready_cond = threading.Condition(self._lock)
        # Object ids some thread is currently blocked on (value =
        # waiter count): completions notify only when THEIR object is
        # being waited for, so a getter blocked on a late ref is not
        # woken O(backlog) times while unrelated tasks finish.
        self._waited: Dict[ObjectID, int] = {}
        self._ready_callbacks: Dict[ObjectID, List[Callable[[], None]]] = {}
        # Failed objects: get() raises the stored error.
        self._errors: Dict[ObjectID, Exception] = {}
        self.num_finished = 0
        self.num_failed = 0

    # --- pending tasks -------------------------------------------------
    def add_pending(self, spec: TaskSpec) -> None:
        with self._lock:
            self._pending[spec.task_id] = PendingTask(spec, spec.max_retries)
            for oid in spec.return_ids():
                self._object_to_task[oid] = spec.task_id

    def mark_dispatched(self, task_id: TaskID, node_id: NodeID) -> None:
        with self._lock:
            task = self._pending.get(task_id)
            if task:
                task.node_id = node_id
                submitted_at = task.submitted_at
            else:
                submitted_at = None
        if submitted_at is not None:
            # every dispatch path (fast-dispatch, scheduling loop, burst
            # grants) funnels through here — ONE observation site for
            # submit→dispatch placement latency
            from ray_tpu.core.scheduler import PLACEMENT_LATENCY
            PLACEMENT_LATENCY.observe(max(0.0, time.time() - submitted_at))

    def get_pending(self, task_id: TaskID) -> Optional[PendingTask]:
        with self._lock:
            return self._pending.get(task_id)

    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def consume_retry(self, task_id: TaskID) -> Optional[TaskSpec]:
        """Returns the spec to resubmit if retries remain, else None."""
        with self._lock:
            task = self._pending.get(task_id)
            if task is None or task.retries_left <= 0:
                return None
            task.retries_left -= 1
            return task.spec

    # --- completion ----------------------------------------------------
    def complete(self, task_id: TaskID) -> None:
        with self._lock:
            self._pending.pop(task_id, None)
            self.num_finished += 1
        TASKS_FINISHED.inc(tags={"state": "FINISHED"})

    def fail(self, task_id: TaskID, error: Exception) -> None:
        TASKS_FINISHED.inc(tags={"state": "FAILED"})
        with self._lock:
            task = self._pending.pop(task_id, None)
            self.num_failed += 1
            if task is not None:
                for oid in task.spec.return_ids():
                    self._errors[oid] = error
        if task is not None:
            for oid in task.spec.return_ids():
                self.mark_object_ready(oid)

    # --- object readiness & location ----------------------------------
    def set_location(self, object_id: ObjectID, location: ObjectLocation) -> None:
        with self._lock:
            self._locations[object_id] = location

    def get_location(self, object_id: ObjectID) -> Optional[ObjectLocation]:
        with self._lock:
            return self._locations.get(object_id)

    def get_error(self, object_id: ObjectID) -> Optional[Exception]:
        with self._lock:
            return self._errors.get(object_id)

    def producing_task(self, object_id: ObjectID) -> Optional[TaskID]:
        with self._lock:
            return self._object_to_task.get(object_id)

    def mark_object_ready(self, object_id: ObjectID) -> None:
        self.set_location_and_ready(object_id, None)

    def put_error(self, object_id: ObjectID, error: Exception) -> None:
        """Resolve an object as failed — get() raises ``error``. For
        results produced outside the task path (e.g. C++ worker calls,
        reference: task_manager.h error-object storage)."""
        with self._lock:
            self._errors[object_id] = error
        self.mark_object_ready(object_id)

    def set_location_and_ready(self, object_id: ObjectID,
                               location: Optional[ObjectLocation]) -> None:
        """Record the primary-copy location and flip readiness under ONE
        lock acquisition — this pair runs once per task result on the
        completion hot path."""
        with self._lock:
            if location is not None:
                self._locations[object_id] = location
            self._ready_objects.add(object_id)
            callbacks = self._ready_callbacks.pop(object_id, None)
            if self._waited and object_id in self._waited:
                self._ready_cond.notify_all()
        if callbacks:
            for cb in callbacks:
                try:
                    cb()
                except Exception:
                    logger.exception("ready callback failed for %s",
                                     object_id)

    def is_ready(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._ready_objects

    def wait_ready(self, object_id: ObjectID, timeout: Optional[float]) -> bool:
        with self._ready_cond:
            if object_id in self._ready_objects:
                return True
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            self._waited[object_id] = self._waited.get(object_id, 0) + 1
            try:
                while object_id not in self._ready_objects:
                    if deadline is None:
                        self._ready_cond.wait()
                    else:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return False
                        self._ready_cond.wait(remaining)
                return True
            finally:
                left = self._waited.get(object_id, 1) - 1
                if left <= 0:
                    self._waited.pop(object_id, None)
                else:
                    self._waited[object_id] = left

    def on_ready(self, object_id: ObjectID, callback: Callable[[], None]) -> None:
        """Invoke callback when object becomes ready (immediately if it is)."""
        fire = False
        with self._lock:
            if object_id in self._ready_objects:
                fire = True
            else:
                self._ready_callbacks.setdefault(object_id, []).append(callback)
        if fire:
            callback()

    def objects_on_node(self, node_id: NodeID) -> List[ObjectID]:
        """Objects whose primary copy lives on `node_id` (shm or
        spilled-to-its-disk)."""
        with self._lock:
            return [oid for oid, loc in self._locations.items()
                    if loc.node_id == node_id]

    def mark_object_unready(self, object_id: ObjectID) -> None:
        """Reset readiness for lineage reconstruction: subsequent
        get()/dep-waits block until the re-executed producer completes
        (reference: object_recovery_manager.h:41)."""
        with self._lock:
            self._ready_objects.discard(object_id)
            self._locations.pop(object_id, None)
            self._errors.pop(object_id, None)

    def forget_object(self, object_id: ObjectID) -> None:
        with self._lock:
            self._locations.pop(object_id, None)
            self._ready_objects.discard(object_id)
            self._errors.pop(object_id, None)
            self._object_to_task.pop(object_id, None)
