"""Chunked node-to-node object transfer over TCP.

Capability parity with the reference's object manager transfer path
(reference: src/ray/object_manager/object_manager.h:128 — chunked
Push/Pull, object_manager.proto:63-66; pull_manager.h:50 admission
control). Each node (head and daemons) runs an ``ObjectServer`` that
streams sealed objects out of the node's shared-memory store in bounded
chunks; a puller writes chunks straight into its local store arena and
seals, so neither side ever buffers a whole object in Python memory and
a 100 GiB object moves with O(chunk) overhead.

Wire protocol (framed messages, see protocol.py):
  puller -> server:  {"kind": "PULL", "object_id": bytes}
  server -> puller:  {"kind": "PULL_META", "size": int}      (or PULL_ERR)
                     raw chunk frames (length-prefixed bytes, no pickle)
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Optional, Tuple

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.protocol import (
    MessageConnection,
    connect_tcp,
    listen_tcp,
    recv_msg,
    send_msg,
)

_LEN = struct.Struct("<I")


class ObjectServer:
    """Serves chunked object reads from local shared-memory stores.

    ``resolve`` maps an ObjectID to a store holding it (the head serves
    every in-process simulated node from one server; a daemon serves its
    single store). Admission control: at most
    ``object_pull_concurrency`` concurrent outbound streams.
    """

    def __init__(self, resolve: Callable[[ObjectID], Optional[object]],
                 host: str = "127.0.0.1"):
        self._resolve = resolve
        self._listener = listen_tcp(host, 0)
        self.address: Tuple[str, int] = self._listener.getsockname()
        self._sem = threading.Semaphore(get_config().object_pull_concurrency)
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="object-server", daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve, args=(sock,),
                             daemon=True).start()

    def _serve(self, sock: socket.socket) -> None:
        chunk_size = get_config().object_chunk_size
        try:
            while True:
                msg = recv_msg(sock)
                if msg is None or msg.get("kind") != "PULL":
                    return
                oid = ObjectID(msg["object_id"])
                source = self._resolve(oid)
                if source is None:
                    send_msg(sock, {"kind": "PULL_ERR",
                                    "error": "object not found"})
                    continue
                if isinstance(source, tuple) and source[0] == "file":
                    # spilled payload: stream straight off disk
                    # (reference: serving spilled objects back out of
                    # external storage)
                    self._serve_file(sock, source[1], chunk_size)
                    continue
                buf = source.get_buffer(oid, timeout_s=2.0)
                if buf is None:
                    send_msg(sock, {"kind": "PULL_ERR",
                                    "error": "object not found"})
                    continue
                with self._sem:
                    try:
                        size = len(buf)
                        send_msg(sock, {"kind": "PULL_META", "size": size})
                        # Raw length-prefixed chunks — no pickling of
                        # payload bytes on the hot path.
                        for off in range(0, size, chunk_size):
                            part = buf[off:off + chunk_size]
                            sock.sendall(_LEN.pack(len(part)))
                            sock.sendall(part)
                    finally:
                        del buf
                        source.release(oid)
        except OSError:
            return
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _serve_file(self, sock: socket.socket, path: str,
                    chunk_size: int) -> None:
        import os
        try:
            size = os.path.getsize(path)
        except OSError:
            send_msg(sock, {"kind": "PULL_ERR", "error": "spill file gone"})
            return
        with self._sem:
            send_msg(sock, {"kind": "PULL_META", "size": size})
            with open(path, "rb") as f:
                while True:
                    part = f.read(chunk_size)
                    if not part:
                        break
                    sock.sendall(_LEN.pack(len(part)))
                    sock.sendall(part)

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass


def _recv_exact_into(sock: socket.socket, view: memoryview) -> bool:
    remaining = len(view)
    off = 0
    while remaining:
        n = sock.recv_into(view[off:], remaining)
        if n == 0:
            return False
        off += n
        remaining -= n
    return True


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    out = bytearray(n)
    if not _recv_exact_into(sock, memoryview(out)):
        return None
    return bytes(out)


def pull_object(addr: Tuple[str, int], object_id: ObjectID, dest_store,
                timeout: float = 30.0) -> bool:
    """Pull one object from a remote ObjectServer into ``dest_store``.

    Returns True on success. If another puller races us into the same
    store (create -> EXISTS), wait for its seal instead of re-pulling.
    """
    if dest_store.contains(object_id):
        return True
    try:
        sock = connect_tcp(addr[0], addr[1], timeout=timeout)
    except OSError:
        return False
    try:
        sock.settimeout(timeout)
        send_msg(sock, {"kind": "PULL", "object_id": object_id.binary()})
        header = _recv_exact(sock, _LEN.size)
        if header is None:
            return False
        (length,) = _LEN.unpack(header)
        meta_raw = _recv_exact(sock, length)
        if meta_raw is None:
            return False
        from ray_tpu.core import serialization
        meta = serialization.loads(meta_raw)
        if meta.get("kind") != "PULL_META":
            return False
        size = meta["size"]
        try:
            dest = dest_store.create(object_id, size)
        except FileExistsError:
            # concurrent pull of the same object; wait for its seal
            buf = dest_store.get_buffer(object_id, timeout_s=timeout)
            if buf is None:
                return False
            del buf
            dest_store.release(object_id)
            return True
        ok = True
        try:
            written = 0
            while written < size:
                h = _recv_exact(sock, _LEN.size)
                if h is None:
                    ok = False
                    break
                (n,) = _LEN.unpack(h)
                if n == 0 or written + n > size:
                    ok = False
                    break
                if not _recv_exact_into(sock, dest[written:written + n]):
                    ok = False
                    break
                written += n
        finally:
            del dest
        if not ok:
            dest_store.delete(object_id)
            return False
        dest_store.seal(object_id)
        return True
    except OSError:
        try:
            dest_store.delete(object_id)
        except Exception:  # noqa: BLE001
            pass
        return False
    finally:
        try:
            sock.close()
        except OSError:
            pass
