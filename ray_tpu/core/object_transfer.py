"""Chunked node-to-node object transfer over TCP.

Capability parity with the reference's object manager transfer path
(reference: src/ray/object_manager/object_manager.h:128 — chunked
Push/Pull, object_manager.proto:63-66; pull_manager.h:50 admission
control). Each node (head and daemons) runs an ``ObjectServer`` that
streams sealed objects out of the node's shared-memory store in bounded
chunks; a puller writes chunks straight into its local store arena and
seals, so neither side ever buffers a whole object in Python memory and
a 100 GiB object moves with O(chunk) overhead.

Wire protocol (framed messages, see protocol.py):
  puller -> server:  {"kind": "PULL", "object_id": bytes}
  server -> puller:  {"kind": "PULL_META", "size": int}      (or PULL_ERR)
                     raw chunk frames (length-prefixed bytes, no pickle)
"""

from __future__ import annotations

import heapq
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Optional, Tuple

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.devtools import threadguard
from ray_tpu.core.protocol import (
    connect_tcp,
    listen_tcp,
    send_msg,
)
from ray_tpu.util import flight_recorder as _flight
from ray_tpu.util.metrics import Counter, Histogram

# Object-plane transfer instrumentation (reference: object manager
# stats — chunked transfer bytes/latency). ``transport`` distinguishes
# inline completion-reply payloads (counted in runtime.on_task_done),
# in-process store-to-store replication, and chunked TCP pulls.
TRANSFER_BYTES = Counter(
    "ray_tpu_object_transfer_bytes_total",
    "Object bytes moved through the object plane", tag_keys=("transport",))
TRANSFER_SECONDS = Histogram(
    "ray_tpu_object_transfer_seconds",
    "Wall time of one object transfer", tag_keys=("transport",),
    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0])

_LEN = struct.Struct("<I")

# Pull priorities (reference: pull_manager.h:50 — task-argument fetches
# outrank ray.get which outranks background/rebalance traffic).
PRIORITY_TASK_ARG = 0
PRIORITY_GET = 1
PRIORITY_BACKGROUND = 2


class _ByteBudget:
    """Bounded in-flight transfer bytes (reference: push_manager.h:28
    in-flight chunk limit). A single pull is always admitted when
    nothing else is in flight, so an object larger than the budget can
    still move; everyone else waits. TCP flow control provides the
    backpressure while a puller waits (the server blocks in sendall)."""

    def __init__(self, cap: int):
        self.cap = cap
        self._used = 0
        self._active = 0
        self._cv = threading.Condition()

    def charge(self, size: int, deadline_s: float = 30.0) -> None:
        """Block until the charge fits (or nothing is in flight). The
        deadline bounds starvation: an oversize object under sustained
        small-pull traffic is eventually admitted over-budget rather
        than holding its slot + socket forever — the budget is
        backpressure, not a correctness invariant."""
        deadline = time.monotonic() + deadline_s
        with self._cv:
            while (self._active > 0 and self._used + size > self.cap
                   and time.monotonic() < deadline):
                self._cv.wait(0.5)
            self._used += size
            self._active += 1

    def release(self, size: int) -> None:
        with self._cv:
            self._used -= size
            self._active -= 1
            self._cv.notify_all()

    @property
    def inflight_bytes(self) -> int:
        with self._cv:
            return self._used


class _PullFailed(Exception):
    """One pull attempt failed on a (possibly transient) transport
    error; retried by retry_call inside PullManager.pull."""


class _PullNotFound(Exception):
    """The holder definitively answered PULL_ERR — not retried."""


class PullManager:
    """Puller-side admission control: a bounded number of concurrent
    pulls, admitted in priority order, with a shared in-flight byte
    budget and bounded retry on transient failures.

    Reference: src/ray/object_manager/pull_manager.h:50 (admission
    control + prioritized pull queues) — the design here is simpler
    because chunking/restore is handled by ``pull_object`` itself.
    """

    def __init__(self, max_concurrent: Optional[int] = None,
                 max_inflight_bytes: Optional[int] = None):
        cfg = get_config()
        self._max = max_concurrent or cfg.object_pull_concurrency
        self.budget = _ByteBudget(
            max_inflight_bytes or cfg.object_pull_inflight_bytes)
        self._cv = threading.Condition()
        self._active = 0
        self._seq = 0
        self._waiting: list = []  # heap of (priority, seq)

    def pull(self, addr: Tuple[str, int], object_id: ObjectID, dest_store,
             *, priority: int = PRIORITY_GET, timeout: float = 30.0,
             attempts: int = 3) -> bool:
        # Admission wait is deadline-bounded like _ByteBudget.charge:
        # after `timeout` of queueing (sustained higher-priority traffic
        # or slot exhaustion), the pull proceeds over-cap rather than
        # blocking its caller forever — the caps are backpressure, not
        # correctness invariants.
        deadline = time.monotonic() + max(timeout, 10.0)
        with self._cv:
            ticket = (priority, self._seq)
            self._seq += 1
            heapq.heappush(self._waiting, ticket)
            while not (self._active < self._max
                       and self._waiting[0] == ticket):
                if time.monotonic() >= deadline:
                    break
                self._cv.wait(0.5)
            self._waiting.remove(ticket)
            heapq.heapify(self._waiting)
            self._active += 1
            # Another waiter may now be at the heap head with a free
            # slot; wake the pack so it can claim it.
            self._cv.notify_all()
        try:
            from ray_tpu.core.protocol import retry_call

            def _attempt():
                if dest_store.contains(object_id):
                    return True
                result = pull_object(addr, object_id, dest_store,
                                     timeout=timeout, budget=self.budget)
                if result:
                    return True
                if result is None:
                    # Definitive server-side "not found" — retrying the
                    # same holder only delays ObjectLostError upstream.
                    raise _PullNotFound(object_id.hex())
                raise _PullFailed(object_id.hex())

            try:
                return retry_call(_attempt, attempts=attempts,
                                  backoff_s=0.05, retry_on=(_PullFailed,),
                                  description=f"pull {object_id.hex()[:8]}")
            except (_PullFailed, _PullNotFound):
                return False
        finally:
            with self._cv:
                self._active -= 1
                self._cv.notify_all()


_pull_manager: Optional[PullManager] = None
_pull_manager_cfg = None
_pull_manager_lock = threading.Lock()


def get_pull_manager() -> PullManager:
    """Process-wide PullManager (head runtime, node daemons, clients).

    Rebuilt when the session config object changes (init's
    ``system_config`` rebinds the module-global Config), so repeated
    init/shutdown cycles in one process pick up new limits.
    """
    global _pull_manager, _pull_manager_cfg
    cfg = get_config()
    with _pull_manager_lock:
        if _pull_manager is None or _pull_manager_cfg is not cfg:
            _pull_manager = PullManager()
            _pull_manager_cfg = cfg
        return _pull_manager


@threadguard.loop_owned("pending", "busy")
class _PullConn:
    """One puller connection, driven by the shared IO loop (replaces
    the thread-per-puller reader). Requests on a connection are
    answered strictly in order — a connection's reply stream is
    PULL_META followed by that object's chunk frames, so two admitted
    pulls must never interleave on one socket."""

    def __init__(self, server: "ObjectServer", sock: socket.socket):
        self.server = server
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Loop-thread only (frames, stream completions, and retry
        # timers all dispatch there) — no lock.
        self.pending: deque = deque()
        self.busy = False
        self.conn = server._io.register_message_conn(
            sock, self._on_msg, self._on_close, label="object-server")
        server._conns.add(self.conn)

    @threadguard.loop_only(loop_attr="server._io")
    def _on_msg(self, conn, msg: dict) -> None:
        if msg.get("kind") != "PULL":
            conn.close()
            return
        self.pending.append(ObjectID(msg["object_id"]))
        if not self.busy:
            self.busy = True
            self.server._admit(self)

    def _on_close(self, conn) -> None:
        self.server._conns.discard(conn)
        self.pending.clear()


@threadguard.loop_owned("_active", "_ready", "_conns")
class ObjectServer:
    """Serves chunked object reads from local shared-memory stores.

    ``resolve`` maps an ObjectID to a store holding it (the head serves
    every in-process simulated node from one server; a daemon serves its
    single store). Accepts and request parsing ride the shared IO loop —
    no accept thread, no thread per puller; payload chunks go out
    through the loop's streaming writer, which pulls from the chunk
    generator only while the outbound queue is below the low-water
    mark, so a 100 GiB object still moves with O(chunk) memory.
    Admission control: at most ``object_pull_concurrency`` concurrent
    outbound streams; excess pulls queue in arrival order.
    """

    def __init__(self, resolve: Callable[[ObjectID], Optional[object]],
                 host: str = "127.0.0.1"):
        self._resolve = resolve
        self._listener = listen_tcp(host, 0)
        self.address: Tuple[str, int] = self._listener.getsockname()
        self._stopped = threading.Event()
        from ray_tpu.core.io_loop import get_io_loop
        self._io = get_io_loop()
        # Admission state is loop-thread only — no lock.
        self._max = get_config().object_pull_concurrency
        self._active = 0
        self._ready: deque = deque()  # _PullConns waiting for a slot
        self._conns: set = set()
        self._listener_handle = self._io.register_listener(
            self._listener, self._on_accept, label="object-server")

    def _on_accept(self, sock: socket.socket, _addr) -> None:
        _PullConn(self, sock)

    # --- admission (reference: pull_manager.h:50) ---------------------

    @threadguard.loop_only
    def _admit(self, pc: _PullConn) -> None:
        self._ready.append(pc)
        self._pump()

    @threadguard.loop_only
    def _pump(self) -> None:
        while self._ready and self._active < self._max:
            pc = self._ready.popleft()
            if pc.conn.closed or not pc.pending:
                pc.busy = False
                continue
            oid = pc.pending.popleft()
            self._active += 1
            if not self._start(pc, oid):
                # Replied synchronously (PULL_ERR); the slot frees now.
                self._active -= 1
                if pc.pending:
                    self._ready.append(pc)
                else:
                    pc.busy = False

    @threadguard.loop_only
    def _finished(self, pc: _PullConn) -> None:
        """A stream (or deferred attempt) released its slot."""
        self._active -= 1
        if not pc.conn.closed and pc.pending:
            self._ready.append(pc)
        else:
            pc.busy = False
        self._pump()

    # --- one pull ------------------------------------------------------

    def _start(self, pc: _PullConn, oid: ObjectID) -> bool:
        """Begin serving ``oid``; True if a slot-holding continuation
        (stream or retry timer) is now in flight."""
        source = self._resolve(oid)
        if source is None:
            return self._err(pc, "object not found")
        if isinstance(source, tuple) and source[0] == "file":
            # spilled payload: stream straight off disk (reference:
            # serving spilled objects back out of external storage)
            return self._start_file(pc, source[1])
        self._store_step(pc, source, oid, time.monotonic() + 2.0)
        return True

    def _err(self, pc: _PullConn, reason: str) -> bool:
        try:
            pc.conn.send({"kind": "PULL_ERR", "error": reason})
        except OSError:
            pass
        return False

    def _start_file(self, pc: _PullConn, path: str) -> bool:
        import os
        chunk_size = get_config().object_chunk_size
        try:
            size = os.path.getsize(path)
            f = open(path, "rb")
        except OSError:
            return self._err(pc, "spill file gone")

        def chunks():
            try:
                while True:
                    part = f.read(chunk_size)
                    if not part:
                        return
                    yield part
            finally:
                f.close()

        def on_done(exc):
            if exc is None:
                # serve-side accounting; runs on the IO loop, so the
                # no-RPC local write is mandatory (GL010)
                TRANSFER_BYTES.inc_local(
                    float(size), tags={"transport": "tcp_out"})
                # loop-path journal write: lock-free local api (GL013)
                _flight.instant("object", "serve_spill_out",
                                {"bytes": size})
            self._finished(pc)

        try:
            pc.conn.send({"kind": "PULL_META", "size": size})
            pc.conn.send_stream(chunks(), on_done)
        except OSError:
            f.close()
            return False
        return True

    @threadguard.loop_only
    def _store_step(self, pc: _PullConn, source, oid: ObjectID,
                    deadline: float) -> None:
        """One slot-holding attempt to stream ``oid`` out of ``source``.
        An unsealed object (writer mid-put — the old reader thread
        blocked in get_buffer for it) is polled via the loop timer."""
        if pc.conn.closed:
            self._finished(pc)
            return
        buf = source.get_buffer(oid, timeout_s=0.0)
        if buf is None:
            if time.monotonic() < deadline:
                self._io.call_later(0.05, self._store_step,
                                    pc, source, oid, deadline)
                return
            self._err(pc, "object not found")
            self._finished(pc)
            return
        chunk_size = get_config().object_chunk_size
        size = len(buf)
        # The generator reaches the buffer through a holder the
        # completion callback empties, so the shm pin is dropped before
        # release() even though the (discarded) generator may linger.
        holder = [buf]
        del buf

        def chunks():
            for off in range(0, size, chunk_size):
                yield bytes(holder[0][off:off + chunk_size])

        def on_done(exc):
            holder.clear()
            source.release(oid)
            if exc is None:
                # loop-path metric write: *_local only (GL010)
                TRANSFER_BYTES.inc_local(
                    float(size), tags={"transport": "tcp_out"})
                # loop-path journal write: lock-free local api (GL013)
                _flight.instant("object", "serve_out",
                                {"oid": oid.hex()[:12], "bytes": size})
            self._finished(pc)

        try:
            pc.conn.send({"kind": "PULL_META", "size": size})
            pc.conn.send_stream(chunks(), on_done)
        except OSError:
            holder.clear()
            source.release(oid)
            self._finished(pc)

    def stop(self) -> None:
        self._stopped.set()
        self._listener_handle.close(wait=True)

        def _sever():
            for conn in list(self._conns):
                conn.close()

        self._io.call_soon(_sever)


def _recv_exact_into(sock: socket.socket, view: memoryview) -> bool:
    remaining = len(view)
    off = 0
    while remaining:
        n = sock.recv_into(view[off:], remaining)
        if n == 0:
            return False
        off += n
        remaining -= n
    return True


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    out = bytearray(n)
    if not _recv_exact_into(sock, memoryview(out)):
        return None
    return bytes(out)


def pull_object(addr: Tuple[str, int], object_id: ObjectID, dest_store,
                timeout: float = 30.0,
                budget: Optional[_ByteBudget] = None) -> Optional[bool]:
    """Pull one object from a remote ObjectServer into ``dest_store``.

    Returns True on success, None when the holder definitively answers
    PULL_ERR (object gone — don't retry this address), False on
    transport errors (retryable). If another puller races us into the
    same store (create -> EXISTS), wait for its seal instead of
    re-pulling. With ``budget``, the transfer charges the object's size
    against the shared in-flight byte budget after PULL_META reveals it
    and before any chunk is read — while blocked, TCP flow control
    backpressures the server.
    """
    if dest_store.contains(object_id):
        return True
    try:
        sock = connect_tcp(addr[0], addr[1], timeout=timeout)
    except OSError:
        return False
    t0 = time.perf_counter()
    charged = 0
    created = False
    try:
        sock.settimeout(timeout)
        send_msg(sock, {"kind": "PULL", "object_id": object_id.binary()})
        header = _recv_exact(sock, _LEN.size)
        if header is None:
            return False
        (length,) = _LEN.unpack(header)
        meta_raw = _recv_exact(sock, length)
        if meta_raw is None:
            return False
        from ray_tpu.core import serialization
        meta = serialization.loads(meta_raw)
        kind = meta.get("kind")
        if kind == "PULL_ERR":
            return None  # definitive: holder does not have the object
        if kind != "PULL_META":
            return False
        size = meta["size"]
        try:
            dest = dest_store.create(object_id, size)
            created = True
            # Charge only once we own the transfer — the losing side of
            # a concurrent-pull race waits on the winner's seal and must
            # not hold budget while transferring nothing.
            if budget is not None:
                budget.charge(size, deadline_s=timeout)
                charged = size
        except FileExistsError:
            # concurrent pull of the same object; wait for its seal
            buf = dest_store.get_buffer(object_id, timeout_s=timeout)
            if buf is None:
                return False
            del buf
            dest_store.release(object_id)
            return True
        ok = True
        try:
            written = 0
            while written < size:
                h = _recv_exact(sock, _LEN.size)
                if h is None:
                    ok = False
                    break
                (n,) = _LEN.unpack(h)
                if n == 0 or written + n > size:
                    ok = False
                    break
                if not _recv_exact_into(sock, dest[written:written + n]):
                    ok = False
                    break
                written += n
        finally:
            del dest
        if not ok:
            dest_store.delete(object_id)
            return False
        dest_store.seal(object_id)
        TRANSFER_BYTES.inc(float(size), tags={"transport": "tcp"})
        TRANSFER_SECONDS.observe(time.perf_counter() - t0,
                                 tags={"transport": "tcp"})
        rec = _flight.RECORDER
        if rec is not None:
            dur_ns = int((time.perf_counter() - t0) * 1e9)
            rec.record("object", "pull", rec.clock() - dur_ns, dur_ns,
                       {"oid": object_id.hex()[:12], "bytes": size})
        return True
    except OSError:
        # Only roll back an entry THIS call created — a concurrent
        # puller may own an in-progress or sealed buffer for the same
        # object (create raced to FileExistsError, or we failed before
        # create), and deleting it would destroy their copy.
        if created:
            try:
                dest_store.delete(object_id)
            except Exception:  # graftlint: disable=GL004
                pass  # rollback of a failed pull is best-effort
        return False
    finally:
        if budget is not None and charged:
            budget.release(charged)
        try:
            sock.close()
        except OSError:
            pass
