"""User-facing exception hierarchy.

Capability parity with the reference's exceptions
(reference: python/ray/exceptions.py): task errors wrap the remote
traceback, actor errors carry restart context, object loss names the
object, and all of them are serializable across process boundaries.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception; raised from ``get()``.

    Carries the remote traceback text so the driver sees the real failure
    site (reference: python/ray/exceptions.py RayTaskError).
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        # Keep .cause across the wire when it pickles (so callers can
        # unwrap domain exceptions); degrade to None instead of failing
        # the whole error delivery when it doesn't.
        cause = self.cause
        if cause is not None:
            import pickle
            try:
                # Full round-trip, not just dumps: exceptions with
                # custom __init__ signatures pickle fine but explode on
                # LOAD (TypeError in the driver's reader thread would
                # wedge error delivery and hang the caller's get()).
                pickle.loads(pickle.dumps(cause))
            except Exception:
                cause = None
        return (TaskError, (self.function_name, self.traceback_str, cause))


class ActorError(RayTpuError):
    """An actor task cannot complete because the actor is dead or dying."""

    def __init__(self, actor_id=None, message: str = "actor died"):
        self.actor_id = actor_id
        self.message = message
        super().__init__(message)

    def __reduce__(self):
        return (type(self), (self.actor_id, self.message))


class ActorDiedError(ActorError):
    pass


class ActorUnavailableError(ActorError):
    """Actor temporarily unreachable (restarting); call may be retried."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ObjectLostError(RayTpuError):
    def __init__(self, object_id, message: str | None = None):
        self.object_id = object_id
        super().__init__(message or f"object {object_id} was lost and could not be reconstructed")

    def __reduce__(self):
        return (ObjectLostError, (self.object_id, None))


class ObjectStoreFullError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"task {task_id} was cancelled")

    def __reduce__(self):
        return (TaskCancelledError, (self.task_id,))


class RuntimeEnvSetupError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class PlacementGroupUnschedulableError(RayTpuError):
    """No feasible node assignment exists for the requested bundles."""


class HeadRestartedError(RayTpuError, ConnectionError):
    """The head (GCS) connection was lost, typically to a head crash or
    restart. The user-visible contract across a head restart
    (reference: workers reconnecting to a restarted Redis-backed GCS,
    gcs_init_data.cc replay):

    - In-flight ``get``/``wait``/requests fail with THIS error.
    - ObjectRefs created before the restart do not survive it; getting
      one raises this error immediately after reconnection.
    - With ``client_reconnect_s > 0`` the client re-registers in the
      background; new submissions after reconnection succeed.
    - Detached/named actors on surviving nodes are re-attachable via
      ``get_actor(name)`` once their daemon re-registers.
    """
