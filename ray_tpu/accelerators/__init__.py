"""Accelerator managers (reference: python/ray/_private/accelerators/).

TPU is the first-class accelerator here; the manager handles chip
detection, per-worker visibility partitioning, slice metadata, gang
resources, and node labels.
"""

from ray_tpu.accelerators.tpu import (
    TpuAcceleratorManager,
    infer_tpu_pod_type_from_topology,
    reserve_tpu_slice,
)

__all__ = [
    "TpuAcceleratorManager",
    "infer_tpu_pod_type_from_topology",
    "reserve_tpu_slice",
]
