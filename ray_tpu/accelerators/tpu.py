"""TPU accelerator manager: detection, partitioning, slice metadata, gangs.

Capability parity with the reference's TPUAcceleratorManager
(reference: python/ray/_private/accelerators/tpu.py:199-578):
- chip autodetection via /dev/accel* and /dev/vfio (tpu.py:225-245)
- per-worker TPU_VISIBLE_CHIPS + host/chip-bounds env assignment
  (tpu.py:283-323)
- pod type / slice name / worker id / topology from GKE env vars or the
  GCE metadata server (tpu.py:326-433)
- the slice-head gang resource ``TPU-{pod_type}-head`` on worker 0 plus
  the slice-name resource on every host (tpu.py:482-545)
- node labels tpu-slice-name/tpu-worker-id/tpu-topology/tpu-pod-type
  (tpu.py:548-578)
- ``reserve_tpu_slice`` for JaxTrainer gang scheduling (tpu.py:145-196)

Test seam: everything environment-derived reads ordinary env vars (the
GKE names double as the fake interface — set TPU_NAME/TPU_WORKER_ID/
TPU_ACCELERATOR_TYPE/TPU_TOPOLOGY and, for chip count,
RTPU_TPU_NUM_CHIPS), so a dev box simulates any slice topology without
hardware, per SURVEY.md §7 "Testing without TPUs".
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, List, Optional

# GKE-injected env vars (and the test fake interface).
GKE_TPU_ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"
GKE_TPU_NAME_ENV = "TPU_NAME"
GKE_TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
GKE_TPU_TOPOLOGY_ENV = "TPU_TOPOLOGY"

# Worker-visibility env vars consumed by the TPU runtime / JAX
# (reference: tpu.py TPU_VISIBLE_CHIPS_ENV_VAR and bounds vars).
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
TPU_HOST_BOUNDS_ENV = "TPU_HOST_BOUNDS"
_SINGLE_HOST_BOUNDS = "1,1,1"
_1_CHIP_CONFIG = "1,1,1"
_2_CHIP_CONFIG = "1,2,1"

# GCE metadata server (reference: tpu.py GCE_TPU_* keys).
_GCE_METADATA_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                     "instance/attributes/")
_GCE_KEYS = {
    "pod_type": "accelerator-type",
    "name": "instance-id",
    "worker_id": "agent-worker-number",
    "env": "tpu-env",
}

_POD_TYPE_RE = re.compile(r"^v\d+[a-zA-Z]*-\d+$")


import functools


@functools.lru_cache(maxsize=None)
def _gce_metadata(key: str) -> Optional[str]:
    """Poll the GCE metadata server; None off-GCE. Cached per key —
    node registration probes several keys and a non-GCE box would
    otherwise pay the connect timeout on every lookup."""
    import urllib.error
    import urllib.request

    try:
        req = urllib.request.Request(
            _GCE_METADATA_URL + key, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=0.5) as resp:
            return resp.read().decode().strip()
    except (urllib.error.URLError, OSError, ValueError):
        return None


class TpuAcceleratorManager:
    """Google TPU accelerator manager (reference: tpu.py:199)."""

    resource_name = "TPU"

    # --- chip detection -------------------------------------------------
    @staticmethod
    def num_chips_on_node() -> int:
        """Detect local chips: /dev/accel*, then /dev/vfio numeric
        entries (reference: tpu.py:225-245). RTPU_TPU_NUM_CHIPS
        overrides for tests/simulation."""
        override = os.environ.get("RTPU_TPU_NUM_CHIPS")
        if override is not None:
            return int(override)
        accel = glob.glob("/dev/accel*")
        if accel:
            return len(accel)
        try:
            entries = os.listdir("/dev/vfio")
        except FileNotFoundError:
            return 0
        return sum(1 for e in entries if e.isdigit())

    # --- worker visibility ----------------------------------------------
    @staticmethod
    def visible_chip_env(chips: List[int],
                         total_on_node: int) -> Dict[str, Optional[str]]:
        """Env assignment giving a worker a chip subset. Returns a dict
        of env updates (None value = unset). Mirrors the reference's
        combination of visible chips + chip/host bounds so the TPU
        runtime initializes on the subset (reference: tpu.py:283-323,
        and google/jax#14977 for why the bounds are needed)."""
        n = len(chips)
        if total_on_node and n >= total_on_node:
            # full host: let the runtime use its defaults
            return {TPU_VISIBLE_CHIPS_ENV: None,
                    TPU_CHIPS_PER_HOST_BOUNDS_ENV: None,
                    TPU_HOST_BOUNDS_ENV: None}
        env: Dict[str, Optional[str]] = {
            TPU_VISIBLE_CHIPS_ENV: ",".join(str(c) for c in chips)}
        if n == 1:
            env[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = _1_CHIP_CONFIG
            env[TPU_HOST_BOUNDS_ENV] = _SINGLE_HOST_BOUNDS
        elif n == 2:
            env[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = _2_CHIP_CONFIG
            env[TPU_HOST_BOUNDS_ENV] = _SINGLE_HOST_BOUNDS
        # n == 4 on an 8-chip host: visible chips only, no bounds — the
        # reference has no bounds config beyond 1/2 chips either
        # (tpu.py:283-323); request validation limits counts to
        # {1, 2, 4, 8} (remote_function.validate_tpu_quantity).
        return env

    # --- slice metadata (GKE env first, then GCE metadata) ---------------
    @staticmethod
    def pod_type() -> Optional[str]:
        value = os.environ.get(GKE_TPU_ACCELERATOR_TYPE_ENV) or \
            _gce_metadata(_GCE_KEYS["pod_type"])
        if value and _POD_TYPE_RE.match(value):
            return value
        return None

    @staticmethod
    def slice_name() -> Optional[str]:
        return os.environ.get(GKE_TPU_NAME_ENV) or \
            _gce_metadata(_GCE_KEYS["name"])

    @staticmethod
    def worker_id() -> Optional[int]:
        raw = os.environ.get(GKE_TPU_WORKER_ID_ENV) or \
            _gce_metadata(_GCE_KEYS["worker_id"])
        try:
            return int(raw) if raw is not None and raw != "" else None
        except ValueError:
            return None

    @staticmethod
    def topology() -> Optional[str]:
        value = os.environ.get(GKE_TPU_TOPOLOGY_ENV)
        if value:
            return value
        env_blob = _gce_metadata(_GCE_KEYS["env"])
        if env_blob:
            match = re.search(r"TOPOLOGY:\s*'([^']+)'", env_blob)
            if match:
                return match.group(1)
        return None

    @staticmethod
    def accelerator_type() -> Optional[str]:
        """Generation resource string, e.g. "TPU-V5P" (tpu.py:436)."""
        pod = TpuAcceleratorManager.pod_type()
        if pod is None:
            return None
        return "TPU-" + pod.split("-")[0].upper()

    @staticmethod
    def num_workers_in_pod() -> Optional[int]:
        """Hosts in this slice: pod chip count / chips per host
        (reference: tpu.py:402-417)."""
        pod = TpuAcceleratorManager.pod_type()
        per_host = TpuAcceleratorManager.num_chips_on_node()
        if not pod or per_host <= 0:
            return None
        num_chips = int(pod.split("-")[1])
        # pod type counts cores for v2-v4 (2 cores/chip); v5e/v5p/v6e+
        # count chips. Use the topology product when available; else
        # assume the count is chips (modern generations).
        topo = TpuAcceleratorManager.topology()
        if topo:
            total = 1
            for part in topo.lower().split("x"):
                total *= int(part)
            num_chips = total
        workers = num_chips // per_host
        if num_chips % per_host:
            workers += 1
        return max(1, workers)

    # --- node registration ------------------------------------------------
    @staticmethod
    def additional_resources() -> Dict[str, float]:
        """Slice gang resources for this node: the slice name on every
        host and ``TPU-{pod_type}-head`` on worker 0, so gangs pin to one
        slice and the head is targetable (reference: tpu.py:482-545)."""
        out: Dict[str, float] = {}
        name = TpuAcceleratorManager.slice_name()
        worker = TpuAcceleratorManager.worker_id()
        pod = TpuAcceleratorManager.pod_type()
        if name and worker is not None and pod:
            out[name] = 1.0
            if worker == 0:
                out[f"TPU-{pod}-head"] = 1.0
        return out

    @staticmethod
    def node_labels() -> Dict[str, str]:
        """Topology labels for scheduling (reference: tpu.py:548-578)."""
        labels: Dict[str, str] = {}
        name = TpuAcceleratorManager.slice_name()
        if name:
            labels["ray.io/tpu-slice-name"] = name
        worker = TpuAcceleratorManager.worker_id()
        if worker is not None:
            labels["ray.io/tpu-worker-id"] = str(worker)
        topo = TpuAcceleratorManager.topology()
        if topo:
            labels["ray.io/tpu-topology"] = topo
        pod = TpuAcceleratorManager.pod_type()
        if pod:
            labels["ray.io/tpu-pod-type"] = pod
        return labels

    @staticmethod
    def augment_node(resources: Dict[str, float],
                     labels: Dict[str, str]) -> None:
        """Fill in detected TPU resources + labels on a node spec
        (called at node registration; no-ops off-TPU)."""
        chips = TpuAcceleratorManager.num_chips_on_node()
        if chips and "TPU" not in resources:
            resources["TPU"] = float(chips)
        if resources.get("TPU"):
            for key, val in TpuAcceleratorManager.additional_resources().items():
                resources.setdefault(key, val)
            for key, val in TpuAcceleratorManager.node_labels().items():
                labels.setdefault(key, val)


def infer_tpu_pod_type_from_topology(topology: str,
                                     accelerator_type: str) -> Optional[str]:
    """"2x2x2" + "TPU-V4" -> "v4-8" (reference: tpu.py:114-129)."""
    try:
        chips = 1
        for part in topology.strip().lower().split("x"):
            chips *= int(part)
        generation = accelerator_type.lower().replace("tpu-", "")
        return f"{generation}-{chips}"
    except (ValueError, AttributeError):
        return None


class SliceReservation:
    """A held slice reservation: the slice name plus the head placement
    group pinning it. ``release()`` returns the head resource (the
    reference leaves this as a TODO; keeping the PG is required so a
    second reservation doesn't deadlock on the still-consumed head)."""

    def __init__(self, name: str, pg):
        self.name = name
        self.placement_group = pg

    def release(self) -> None:
        from ray_tpu.util.placement_group import remove_placement_group
        if self.placement_group is not None:
            try:
                remove_placement_group(self.placement_group)
            finally:
                self.placement_group = None


def reserve_tpu_slice(topology: str, accelerator_type: str,
                      timeout: float = 100.0) -> Optional[SliceReservation]:
    """Reserve a slice via its head resource; returns a SliceReservation
    (``.name`` is the slice name; call ``.release()`` when done).

    Creates a placement group on ``TPU-{pod_type}-head`` with a label
    selector pinning it to a worker-0 host of a matching slice, then
    reads that node's slice-name label — the gang key JaxTrainer uses to
    put one worker on every host of the same slice (reference:
    tpu.py:145-196 reserve_tpu_slice + fetch_tpu_slice_name_from_pg).
    """
    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.util.placement_group import placement_group

    pod_type = infer_tpu_pod_type_from_topology(topology, accelerator_type)
    if pod_type is None:
        return None
    pg = placement_group(
        bundles=[{f"TPU-{pod_type}-head": 1}],
        strategy="PACK",
        bundle_label_selector=[{
            "ray.io/tpu-worker-id": "0",
            "ray.io/tpu-pod-type": pod_type,
        }])
    if not pg.ready(timeout=timeout):
        # The PG queued (creation never fails fast now) — cancel it, or
        # the abandoned gang would reserve a slice head later with no
        # owner to release it.
        from ray_tpu.util.placement_group import remove_placement_group
        remove_placement_group(pg)
        raise TimeoutError(
            f"failed to reserve a TPU slice head for pod type {pod_type}")
    try:
        rt = runtime_mod.get_runtime()
        node_ids = pg.bundle_node_ids()
        if not node_ids or node_ids[0] is None:
            raise RuntimeError("slice-head placement group has no node")
        record = rt.gcs.nodes.get(node_ids[0])
        name = (record.labels.get("ray.io/tpu-slice-name")
                if record else None)
        if name is None:
            raise RuntimeError(
                "reserved a slice head but its node carries no "
                "ray.io/tpu-slice-name label")
    except BaseException:
        from ray_tpu.util.placement_group import remove_placement_group
        remove_placement_group(pg)
        raise
    return SliceReservation(name, pg)
