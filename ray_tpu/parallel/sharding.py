"""Sharding rules: how parameter/activation pytrees map onto the mesh.

The TPU-native replacement for the reference's DDP/FSDP wrapper classes
(reference: train/torch/train_loop_utils.py:458 DistributedDataParallel
wrap, :473 FullyShardedDataParallel): instead of wrapping the model,
declare rules mapping parameter-path regexes to PartitionSpecs; pjit
lowers them to GSPMD shardings and XLA inserts the gradient psum
(DDP-equivalent) or per-layer all-gather/reduce-scatter
(FSDP/ZeRO-equivalent — arXiv 2004.13336) over ICI.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# A rule: (path regex, PartitionSpec). First match wins.
Rule = Tuple[str, P]


@dataclass
class ShardingRules:
    rules: List[Rule] = field(default_factory=list)
    default: P = P()

    def spec_for(self, path: str, ndim: int) -> P:
        for rule in self.rules:
            if len(rule) == 3:
                pattern, spec, want_ndim = rule
                if want_ndim != ndim:
                    continue  # ndim-constrained rule for another shape
            else:
                pattern, spec = rule
            if re.search(pattern, path):
                if len(spec) > ndim:
                    # Drop trailing axes that don't exist on this param.
                    spec = P(*spec[:ndim])
                return spec
        return self.default


def _tree_paths(tree: Any) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for keypath, leaf in flat:
        path = "/".join(_key_str(k) for k in keypath)
        out.append((path, leaf))
    return out


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    if hasattr(k, "name"):
        return str(k.name)
    return str(k)


def infer_sharding(tree: Any, mesh: Mesh, rules: ShardingRules):
    """Map every leaf to a NamedSharding via the first matching rule."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    shardings = []
    for keypath, leaf in flat:
        path = "/".join(_key_str(k) for k in keypath)
        ndim = getattr(leaf, "ndim", 0)
        spec = rules.spec_for(path, ndim)
        shardings.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, shardings)


def shard_pytree(tree: Any, mesh: Mesh, rules: ShardingRules):
    """Device-put a pytree according to the rules (used at init/restore)."""
    shardings = infer_sharding(tree, mesh, rules)
    return jax.device_put(tree, shardings)


@dataclass
class ShardingConfig:
    """High-level parallelism mode, lowered to rules.

    Modes (reference analog in parentheses):
      ddp   — replicate params, shard batch on `data` (X2 DDP)
      fsdp  — shard params' largest dim on `fsdp`, batch on data+fsdp (X3)
      tp    — tensor-parallel transformer rules on `model` (X4 TP)
      fsdp_tp — 2D: fsdp × model (the standard 7B+ recipe)
    """
    mode: str = "ddp"
    # extra user rules consulted before the mode's built-ins
    extra_rules: List[Rule] = field(default_factory=list)

    def batch_spec(self) -> P:
        if self.mode == "ddp":
            return P(("data",))
        return P(("data", "fsdp"))

    def rules(self) -> ShardingRules:
        built_in: List[Rule]
        if self.mode == "ddp":
            built_in = []          # replicate everything
        elif self.mode == "fsdp":
            built_in = [
                # Shard the contraction/hidden dimension of every ≥2D
                # param across fsdp; 1D (norms, biases) replicated.
                (r"(embedding|lm_head)", P("fsdp", None)),
                (r"(wq|wk|wv|q_proj|k_proj|v_proj|gate|up|w1|w3)",
                 P("fsdp", None)),
                (r"(wo|o_proj|down|w2)", P(None, "fsdp")),
                (r".*", P()),
            ]
        elif self.mode == "tp":
            built_in = _TP_RULES
        elif self.mode == "fsdp_tp":
            built_in = [
                (r"(embedding|lm_head)", P("fsdp", "model")),
                (r"(wq|wk|wv|q_proj|k_proj|v_proj)", P("fsdp", "model")),
                (r"(wo|o_proj)", P("model", "fsdp")),
                (r"(gate|up|w1|w3)", P("fsdp", "model")),
                (r"(down|w2)", P("model", "fsdp")),
                (r".*", P()),
            ]
        else:
            raise ValueError(f"unknown sharding mode: {self.mode}")
        return ShardingRules(rules=list(self.extra_rules) + built_in)


# Megatron-style tensor parallelism: column-parallel in-projections,
# row-parallel out-projections; XLA inserts the psum after wo/w2.
_TP_RULES: List[Rule] = [
    (r"(embedding|lm_head)", P(None, "model")),
    (r"(wq|wk|wv|q_proj|k_proj|v_proj)", P(None, "model")),
    (r"(wo|o_proj)", P("model", None)),
    (r"(gate|up|w1|w3)", P(None, "model")),
    (r"(down|w2)", P("model", None)),
    (r".*", P()),
]


def constrain(x, mesh: Mesh, spec: P):
    """with_sharding_constraint helper usable inside jitted fns."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
