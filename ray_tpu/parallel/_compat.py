"""Version compatibility shims for moving jax entry points.

Currently covers three drift sites: the ``shard_map`` entry point, the
pallas-TPU compiler-params class, and the gloo CPU collectives needed
for multiprocess CPU gangs (see the section comments below).

``shard_map`` has moved twice across jax releases: it started life at
``jax.experimental.shard_map.shard_map``, was promoted to
``jax.sharding.shard_map`` and finally re-exported as
``jax.shard_map``. Along the way the replication-checking kwarg was
renamed ``check_rep`` → ``check_vma``. Importing from a fixed location
therefore breaks test *collection* on whichever jax the image has.

This module feature-detects the location once at import time and
exposes:

- ``shard_map(fn, *, mesh, in_specs, out_specs, check_vma=None)`` — a
  thin wrapper that translates the checking kwarg to whatever the
  resident jax spells it, or ``None`` when no jax on the path provides
  a shard_map at all;
- ``SHARD_MAP_AVAILABLE`` / ``SHARD_MAP_UNAVAILABLE_REASON`` — for
  tests to ``pytest.mark.skipif`` with a reason instead of erroring at
  collection.

Callers inside ``ray_tpu`` should use :func:`require_shard_map` which
raises a descriptive ``RuntimeError`` at *call* time (module import
always succeeds).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Optional

_raw_shard_map: Optional[Callable[..., Any]] = None
SHARD_MAP_UNAVAILABLE_REASON = ""

try:
    from jax import shard_map as _raw_shard_map  # type: ignore[attr-defined]
except ImportError:
    try:
        from jax.sharding import shard_map as _raw_shard_map  # type: ignore
    except ImportError:
        try:
            from jax.experimental.shard_map import (  # type: ignore
                shard_map as _raw_shard_map)
        except ImportError as exc:
            _raw_shard_map = None
            SHARD_MAP_UNAVAILABLE_REASON = (
                "no shard_map in this jax: tried jax.shard_map, "
                f"jax.sharding.shard_map, jax.experimental.shard_map ({exc})")

SHARD_MAP_AVAILABLE = _raw_shard_map is not None

# kwarg rename: old spelling check_rep, new spelling check_vma.
_CHECK_KWARG: Optional[str] = None
if _raw_shard_map is not None:
    try:
        _params = inspect.signature(_raw_shard_map).parameters
        if "check_vma" in _params:
            _CHECK_KWARG = "check_vma"
        elif "check_rep" in _params:
            _CHECK_KWARG = "check_rep"
    except (TypeError, ValueError):  # C-accelerated / no signature
        _CHECK_KWARG = "check_rep"


def shard_map(fn: Callable[..., Any], *, mesh: Any, in_specs: Any,
              out_specs: Any,
              check_vma: Optional[bool] = None) -> Callable[..., Any]:
    """Portable ``shard_map`` across jax versions.

    ``check_vma`` follows the newest spelling; it is translated to
    ``check_rep`` on older jax. ``None`` omits the kwarg entirely.
    """
    if _raw_shard_map is None:
        raise RuntimeError(
            "shard_map is unavailable: " + SHARD_MAP_UNAVAILABLE_REASON)
    kwargs: dict = {}
    if check_vma is not None and _CHECK_KWARG is not None:
        kwargs[_CHECK_KWARG] = check_vma
    return _raw_shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)


def require_shard_map() -> None:
    """Raise a descriptive error when shard_map is missing."""
    if _raw_shard_map is None:
        raise RuntimeError(
            "this operation needs jax shard_map, which is unavailable: "
            + SHARD_MAP_UNAVAILABLE_REASON)


# ---------------------------------------------------------------------------
# Pallas TPU compiler params: ``pltpu.CompilerParams`` on new jax,
# ``pltpu.TPUCompilerParams`` on 0.4.x. Resolved lazily because pallas
# itself is only imported inside kernel builders (it drags in mosaic).
# ---------------------------------------------------------------------------

_PALLAS_PARAMS_CLS: Any = None
PALLAS_COMPILER_PARAMS_UNAVAILABLE_REASON = ""


def pallas_tpu_compiler_params(**kwargs: Any) -> Any:
    """Build a pallas-TPU compiler-params object under either spelling.

    Raises ``RuntimeError`` with a skip-worthy reason when no pallas TPU
    backend is importable at all.
    """
    global _PALLAS_PARAMS_CLS, PALLAS_COMPILER_PARAMS_UNAVAILABLE_REASON
    if _PALLAS_PARAMS_CLS is None:
        try:
            from jax.experimental.pallas import tpu as pltpu
        except ImportError as exc:
            PALLAS_COMPILER_PARAMS_UNAVAILABLE_REASON = (
                f"jax.experimental.pallas.tpu not importable: {exc}")
            raise RuntimeError(
                PALLAS_COMPILER_PARAMS_UNAVAILABLE_REASON) from exc
        cls = (getattr(pltpu, "CompilerParams", None)
               or getattr(pltpu, "TPUCompilerParams", None))
        if cls is None:
            PALLAS_COMPILER_PARAMS_UNAVAILABLE_REASON = (
                "pallas tpu module has neither CompilerParams nor "
                "TPUCompilerParams")
            raise RuntimeError(PALLAS_COMPILER_PARAMS_UNAVAILABLE_REASON)
        _PALLAS_PARAMS_CLS = cls
    return _PALLAS_PARAMS_CLS(**kwargs)


# ---------------------------------------------------------------------------
# CPU multiprocess collectives: the stock CPU client cannot run cross-
# process computations ("Multiprocess computations aren't implemented on
# the CPU backend") unless jaxlib ships the gloo TCP collectives and the
# ``jax_cpu_collectives_implementation`` config selects them BEFORE
# ``jax.distributed.initialize``. Feature-detect so gang tests skip with
# a reason on jaxlibs built without gloo.
# ---------------------------------------------------------------------------

CPU_COLLECTIVES_AVAILABLE = False
CPU_COLLECTIVES_UNAVAILABLE_REASON = ""
try:
    from jax._src.lib import xla_extension as _xla_ext  # type: ignore
    if hasattr(_xla_ext, "make_gloo_tcp_collectives"):
        CPU_COLLECTIVES_AVAILABLE = True
    else:
        CPU_COLLECTIVES_UNAVAILABLE_REASON = (
            "jaxlib built without gloo TCP collectives")
except Exception as _exc:  # noqa: BLE001 — jaxlib layout drift
    CPU_COLLECTIVES_UNAVAILABLE_REASON = (
        f"cannot probe jaxlib for gloo collectives: {_exc}")


def enable_cpu_collectives() -> bool:
    """Select the gloo CPU collectives implementation when available.

    Must run before ``jax.distributed.initialize`` / first backend use
    in the process. Returns True when gloo was selected.
    """
    if not CPU_COLLECTIVES_AVAILABLE:
        return False
    import jax
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — option renamed/absent
        return False
    return True
