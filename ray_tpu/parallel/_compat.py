"""Version compatibility shims for the ``jax.shard_map`` entry point.

``shard_map`` has moved twice across jax releases: it started life at
``jax.experimental.shard_map.shard_map``, was promoted to
``jax.sharding.shard_map`` and finally re-exported as
``jax.shard_map``. Along the way the replication-checking kwarg was
renamed ``check_rep`` → ``check_vma``. Importing from a fixed location
therefore breaks test *collection* on whichever jax the image has.

This module feature-detects the location once at import time and
exposes:

- ``shard_map(fn, *, mesh, in_specs, out_specs, check_vma=None)`` — a
  thin wrapper that translates the checking kwarg to whatever the
  resident jax spells it, or ``None`` when no jax on the path provides
  a shard_map at all;
- ``SHARD_MAP_AVAILABLE`` / ``SHARD_MAP_UNAVAILABLE_REASON`` — for
  tests to ``pytest.mark.skipif`` with a reason instead of erroring at
  collection.

Callers inside ``ray_tpu`` should use :func:`require_shard_map` which
raises a descriptive ``RuntimeError`` at *call* time (module import
always succeeds).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Optional

_raw_shard_map: Optional[Callable[..., Any]] = None
SHARD_MAP_UNAVAILABLE_REASON = ""

try:
    from jax import shard_map as _raw_shard_map  # type: ignore[attr-defined]
except ImportError:
    try:
        from jax.sharding import shard_map as _raw_shard_map  # type: ignore
    except ImportError:
        try:
            from jax.experimental.shard_map import (  # type: ignore
                shard_map as _raw_shard_map)
        except ImportError as exc:
            _raw_shard_map = None
            SHARD_MAP_UNAVAILABLE_REASON = (
                "no shard_map in this jax: tried jax.shard_map, "
                f"jax.sharding.shard_map, jax.experimental.shard_map ({exc})")

SHARD_MAP_AVAILABLE = _raw_shard_map is not None

# kwarg rename: old spelling check_rep, new spelling check_vma.
_CHECK_KWARG: Optional[str] = None
if _raw_shard_map is not None:
    try:
        _params = inspect.signature(_raw_shard_map).parameters
        if "check_vma" in _params:
            _CHECK_KWARG = "check_vma"
        elif "check_rep" in _params:
            _CHECK_KWARG = "check_rep"
    except (TypeError, ValueError):  # C-accelerated / no signature
        _CHECK_KWARG = "check_rep"


def shard_map(fn: Callable[..., Any], *, mesh: Any, in_specs: Any,
              out_specs: Any,
              check_vma: Optional[bool] = None) -> Callable[..., Any]:
    """Portable ``shard_map`` across jax versions.

    ``check_vma`` follows the newest spelling; it is translated to
    ``check_rep`` on older jax. ``None`` omits the kwarg entirely.
    """
    if _raw_shard_map is None:
        raise RuntimeError(
            "shard_map is unavailable: " + SHARD_MAP_UNAVAILABLE_REASON)
    kwargs: dict = {}
    if check_vma is not None and _CHECK_KWARG is not None:
        kwargs[_CHECK_KWARG] = check_vma
    return _raw_shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)


def require_shard_map() -> None:
    """Raise a descriptive error when shard_map is missing."""
    if _raw_shard_map is None:
        raise RuntimeError(
            "this operation needs jax shard_map, which is unavailable: "
            + SHARD_MAP_UNAVAILABLE_REASON)
