"""Device meshes for SPMD parallelism.

The mesh is the TPU-native replacement for the reference's process-group
plumbing (reference: torch.distributed init in train/torch/config.py,
NCCL groups in util/collective/collective_group/nccl_collective_group.py):
instead of wiring communicators between processes, we lay devices out on
a named mesh and let XLA/GSPMD insert collectives that ride ICI.

Axis conventions (the "How to Scale Your Model" recipe):
  data   — data parallelism (batch split; gradient psum)
  fsdp   — fully-sharded data parallelism (params/optimizer sharded,
           all-gathered per layer; arXiv 2004.13336 weight-update sharding)
  model  — tensor parallelism (attention heads / mlp hidden split)
  seq    — sequence/context parallelism (ring attention, Ulysses)
  pipe   — pipeline stages
  expert — MoE expert parallelism
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("pipe", "data", "fsdp", "seq", "expert", "model")


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. Axes of size 1 are kept (harmless to GSPMD)."""
    data: int = 1
    fsdp: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1

    def axes(self) -> Dict[str, int]:
        return {
            "pipe": self.pipe, "data": self.data, "fsdp": self.fsdp,
            "seq": self.seq, "expert": self.expert, "model": self.model,
        }

    @property
    def size(self) -> int:
        return math.prod(self.axes().values())

    @staticmethod
    def for_devices(n: int, *, model: int = 1, seq: int = 1,
                    pipe: int = 1, expert: int = 1,
                    fsdp: Optional[int] = None) -> "MeshSpec":
        """Fill the data/fsdp axes with whatever devices remain."""
        rest = n // (model * seq * pipe * expert)
        if rest * model * seq * pipe * expert != n:
            raise ValueError(
                f"{n} devices not divisible by model*seq*pipe*expert="
                f"{model * seq * pipe * expert}")
        if fsdp is None:
            return MeshSpec(data=rest, model=model, seq=seq, pipe=pipe,
                            expert=expert)
        if rest % fsdp:
            raise ValueError(f"residual {rest} not divisible by fsdp={fsdp}")
        return MeshSpec(data=rest // fsdp, fsdp=fsdp, model=model, seq=seq,
                        pipe=pipe, expert=expert)


def make_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    """Build a jax.sharding.Mesh laid out so the innermost (most
    communication-heavy) axes are contiguous in device order — on a TPU
    slice contiguous device ids are ICI neighbors, so `model`/`seq`
    collectives ride the fastest links while `pipe`/`data` span the
    slower dimension (and DCN on multi-slice)."""
    import jax
    if devices is None:
        devices = jax.devices()
    if len(devices) < spec.size:
        raise ValueError(
            f"mesh needs {spec.size} devices, have {len(devices)}")
    axes = spec.axes()
    shape = tuple(axes[name] for name in AXIS_ORDER)
    arr = np.asarray(devices[: spec.size]).reshape(shape)
    return jax.sharding.Mesh(arr, AXIS_ORDER)


def single_device_mesh():
    """A trivial mesh for one chip (bench on the single real TPU)."""
    return make_mesh(MeshSpec())


def mesh_axis_size(mesh, name: str) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(name, 1)


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap over DCN.

    reference: train/v2/jax/config.py:29 _setup_jax_tpu_environment —
    each train worker calls jax.distributed.initialize so every host's
    jax sees the full pod's devices. No-op when already initialized or
    single-process.
    """
    import jax
    if num_processes in (None, 0, 1):
        return
    # CPU gangs (virtual-device CI, JAX_PLATFORMS=cpu) need the gloo
    # collectives selected before initialize, or every cross-process
    # computation dies with "not implemented on the CPU backend".
    # Checked via the env var, NOT jax.default_backend(): touching the
    # backend here would finalize it pre-initialize.
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        from ray_tpu.parallel import _compat
        _compat.enable_cpu_collectives()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    except RuntimeError:
        pass  # already initialized
