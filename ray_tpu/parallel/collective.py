"""Out-of-band collectives between actors/tasks.

Capability parity with the reference's ray.util.collective
(reference: python/ray/util/collective/collective.py —
init_collective_group:180, allreduce:325, barrier:365, broadcast:440,
allgather:490, reducescatter:539, send:598/recv:661; NCCL rendezvous via
named actor + GCS KV, collective_group/nccl_collective_group.py:29).

TPU-native stance (SURVEY.md §5.8): in-graph SPMD math should use
`jax.lax.psum`/`all_gather` over a mesh — XLA emits ICI collective DMA
and no framework code runs per step. This module covers the *out-of-band*
cases the reference uses NCCL for: host tensors moving between actors
(weight broadcast to env-runners, parameter servers, metric reduction)
plus the multi-process DDP gradient-sync path for dev boxes without a
shared mesh.

Ops must be called in the same order by every rank of a group (the
standard collective contract).

Design notes (round-2 rework + round-7 bandwidth work):
- Rendezvous is EVENT-DRIVEN: ranks block on a GCS ``kv_wait`` (head
  fires the reply when the key lands). The wait re-arms with
  exponentially growing chunks up to a HARD deadline so a dropped
  waiter registration re-registers instead of hanging, and a timeout
  names the missing rank.
- Payloads above an inline threshold move through the OBJECT PLANE
  (put → ref in KV → peers get()), so tensor bytes travel shm/direct
  node-to-node transfer, not inline through the head's control socket.
- ``allreduce`` defaults to a bandwidth-optimal RING (reduce-scatter +
  all-gather over 1/world chunks: each rank moves ~2·payload bytes
  total regardless of world size); small payloads use the round-2
  binomial TREE (2·log2(world) transfers — fewer sequential rendezvous
  rounds when latency dominates).
- Quantized transport (EQuARX-style, PAPERS.md): ``compression="int8"``
  (or ``"fp8"`` where ml_dtypes provides e4m3) block-quantizes every
  hop's payload — per-block scale/zero-point, dequantize-accumulate-
  requantize at each ring hop — cutting wire bytes ~4x. With an
  ``ef_key`` an ERROR-FEEDBACK residual per leaf persists across
  rounds: every quantization error this rank introduces is added back
  to its contribution next round, so repeated reductions converge
  instead of accumulating bias.
- Round keys are garbage-collected LAZILY one round behind: a rank
  completing round S has (transitively, through the ring/tree chain)
  proven all ranks finished round S-1 — so S-1's keys and payload refs
  are reclaimed then, with the remainder swept by
  destroy_collective_group.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core import serialization
from ray_tpu.devtools import collsan as _collsan
from ray_tpu.exceptions import GetTimeoutError
from ray_tpu.util import flight_recorder as _flight
from ray_tpu.util.backoff import jittered

logger = logging.getLogger(__name__)

_DEFAULT_TIMEOUT = 60.0
# payloads larger than this ride the object plane instead of the KV
_INLINE_MAX = 32 * 1024
# below this the tree's log2(world) rendezvous rounds beat the ring's
# 2(world-1) rounds (latency-bound regime); above it bandwidth wins
_RING_MIN_BYTES = 8 * 1024
# quantization block: scale/zero-point granularity (256 f32 = 1 KB of
# payload carries 8 B of block metadata → int8 moves ~3.9x fewer bytes)
_QUANT_BLOCK = 256

try:  # fp8-e4m3 is available wherever jax is (ml_dtypes is a jax dep),
    # but gate it so a slim host install degrades to int8 cleanly
    import ml_dtypes as _ml_dtypes
    _FP8_DTYPE = np.dtype(_ml_dtypes.float8_e4m3fn)
except Exception:  # pragma: no cover - ml_dtypes ships with jax
    _FP8_DTYPE = None

_COMPRESSIONS = ("int8", "fp8")


def _kv_put(key: str, value: bytes) -> None:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        rt.gcs.kv.put(key.encode(), value, namespace="collective")
    else:
        rt.gcs_call("kv_put", key.encode(), value, "collective")


def _kv_get(key: str) -> Optional[bytes]:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        return rt.gcs.kv.get(key.encode(), namespace="collective")
    return rt.gcs_call("kv_get", key.encode(), "collective")


def _kv_del(key: str) -> None:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        rt.gcs.kv.delete(key.encode(), namespace="collective")
    else:
        rt.gcs_call("kv_del", key.encode(), "collective")


# re-arm chunks for _kv_wait: event-driven inside each chunk, doubling
# up to the cap so a lost waiter registration costs at most one chunk
_WAIT_INITIAL_S = 0.25
_WAIT_MAX_S = 4.0


def _kv_wait(key: str, timeout: float, what: Optional[str] = None) -> bytes:
    """Block until the key exists — event-driven: the head wakes us via
    the KV waiter hook (gcs.py KVStore.add_waiter), no polling. The wait
    is re-armed with exponentially growing chunks against a HARD
    deadline: a waiter registration lost to a head hiccup re-registers
    within one chunk instead of hanging forever, and expiry raises a
    timeout that names the peer being waited on."""
    rt = runtime_mod.get_runtime()
    deadline = time.monotonic() + timeout
    chunk = _WAIT_INITIAL_S
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            who = what or f"key {key!r}"
            raise GetTimeoutError(
                f"collective rendezvous timed out after {timeout:.1f}s "
                f"waiting for {who}; that rank likely died or never "
                f"entered the same collective round (key {key!r})")
        # Jitter each re-arm slice (util/backoff.py) so a whole gang
        # re-registering after a head hiccup staggers its kv_wait storm.
        slice_s = min(jittered(chunk, jitter=0.25), remaining)
        if rt.is_driver:
            value = rt.gcs.kv.wait(key.encode(), namespace="collective",
                                   timeout=slice_s)
        else:
            value = rt.gcs_call("kv_wait", key.encode(), "collective",
                                slice_s, timeout=slice_s + 10.0)
        if value is not None:
            return value
        chunk = min(chunk * 2.0, _WAIT_MAX_S)


def _pack_payload(value, keepalive: List) -> bytes:
    """Inline small payloads; large ones go through the object plane so
    the bytes move node-to-node, not through the head's control socket.
    ``value`` is a tensor or a quantized-chunk tuple. The producer must
    keep ``keepalive`` refs until consumers have certainly read (see the
    round-GC invariant in the module docstring)."""
    if value is None:
        return b""
    blob = serialization.pack(value)
    if len(blob) <= _INLINE_MAX:
        return b"I" + blob
    import ray_tpu
    ref = ray_tpu.put(value)
    keepalive.append(ref)
    return b"R" + serialization.dumps(ref)


def _unpack_payload(blob: bytes):
    if not blob:
        return None
    tag, body = blob[:1], blob[1:]
    if tag == b"I":
        return serialization.unpack(body)
    import ray_tpu
    return ray_tpu.get(serialization.loads(body))


# --- block quantization codecs (EQuARX-style, PAPERS.md) ----------------
# A quantized chunk travels as ("q8", n, q, scale, zp) / ("f8", n, q,
# scale): per-_QUANT_BLOCK affine int8 (scale + zero-point per block) or
# scaled fp8-e4m3. Host-side numpy mirror of the jit-side scale math in
# ray_tpu/ops/quant_matmul.py.


def _block_view(flat: np.ndarray) -> Tuple[np.ndarray, int]:
    """Pad a 1-D f32 array to a block multiple, viewed as [nblocks, B]."""
    n = flat.size
    pad = (-n) % _QUANT_BLOCK
    if pad:
        flat = np.concatenate(
            [flat, np.zeros(pad, dtype=np.float32)])
    return flat.reshape(-1, _QUANT_BLOCK), n


def _quantize_chunk(chunk: np.ndarray, compression: str) -> tuple:
    flat = np.ascontiguousarray(chunk, dtype=np.float32).ravel()
    blocks, n = _block_view(flat)
    if compression == "int8":
        lo = blocks.min(axis=1, keepdims=True) if blocks.size else \
            np.zeros((blocks.shape[0], 1), np.float32)
        hi = blocks.max(axis=1, keepdims=True) if blocks.size else lo
        zp = ((hi + lo) * 0.5).astype(np.float32)
        scale = np.maximum((hi - lo) / 254.0, 1e-12).astype(np.float32)
        q = np.clip(np.rint((blocks - zp) / scale), -127, 127).astype(
            np.int8)
        return ("q8", n, q, scale.ravel(), zp.ravel())
    if compression == "fp8":
        if _FP8_DTYPE is None:
            raise RuntimeError(
                "fp8 compression needs ml_dtypes (float8_e4m3fn); "
                "use compression='int8' on this host")
        amax = (np.max(np.abs(blocks), axis=1, keepdims=True)
                if blocks.size else
                np.zeros((blocks.shape[0], 1), np.float32))
        scale = np.maximum(amax / 448.0, 1e-12).astype(np.float32)
        q = (blocks / scale).astype(_FP8_DTYPE)
        return ("f8", n, q, scale.ravel())
    raise ValueError(f"unknown compression {compression!r}; "
                     f"expected one of {_COMPRESSIONS}")


def _dequantize_chunk(payload: tuple) -> np.ndarray:
    tag = payload[0]
    if tag == "q8":
        _, n, q, scale, zp = payload
        out = q.astype(np.float32) * scale[:, None] + zp[:, None]
    elif tag == "f8":
        _, n, q, scale = payload
        out = q.astype(np.float32) * scale[:, None]
    else:
        raise ValueError(f"unknown quantized payload tag {tag!r}")
    return out.ravel()[:n]


def _is_quantized(payload) -> bool:
    return isinstance(payload, tuple)


def _decode_chunk(payload) -> np.ndarray:
    if _is_quantized(payload):
        return _dequantize_chunk(payload)
    return payload


def _payload_nbytes(payload) -> int:
    """Actual tensor bytes this payload puts on the wire (framing and
    pickle overhead excluded on both sides of the compression ratio)."""
    if _is_quantized(payload):
        return sum(int(p.nbytes) for p in payload if
                   isinstance(p, np.ndarray))
    return int(payload.nbytes)


# --- error feedback -----------------------------------------------------
# One persistent residual buffer per (group, leaf key). Every
# quantization error a rank introduces — input quantization, per-hop
# requantization, the final all-gather quantization — is added back to
# that rank's contribution on the NEXT round. The reduction is a sum, so
# compensating anywhere in the sum compensates globally: the
# time-averaged reduced value converges to the true reduction at O(1/T)
# instead of carrying a constant quantization bias.

# Keyed by (group, leaf key, flat size): a re-created group whose leaf
# happens to land on a different tensor size must not inherit (or trip
# over) the previous run's residual, and init/destroy both clear the
# group's residuals outright — a rank that skipped destroy (killed and
# restarted) still starts its new incarnation clean.
_ef_buffers: Dict[Tuple[str, str, int], np.ndarray] = {}


def reset_error_feedback(group_name: Optional[str] = None) -> None:
    """Drop persistent error-feedback residuals (all groups, or one)."""
    if group_name is None:
        _ef_buffers.clear()
        return
    for key in [k for k in _ef_buffers if k[0] == group_name]:
        del _ef_buffers[key]


def error_feedback_residual(group_name: str,
                            ef_key: str) -> Optional[np.ndarray]:
    """The current residual for a leaf (copy; None if never used)."""
    for (g, k, _size), buf in _ef_buffers.items():
        if g == group_name and k == ef_key:
            return buf.copy()
    return None


def _ef_buffer(group_name: str, ef_key: str, size: int) -> np.ndarray:
    key = (group_name, ef_key, size)
    buf = _ef_buffers.get(key)
    if buf is None:
        buf = np.zeros(size, dtype=np.float32)
        _ef_buffers[key] = buf
    return buf


# --- collective transport metrics (GL006-compliant names) ---------------
# Defined here so descriptions register; recorded through the BATCHED
# metrics path (util.metrics.record_batch → one control-plane RPC per
# collective op, not one per series).
from ray_tpu.util.metrics import Counter as _MCounter, Gauge as _MGauge

COLLECTIVE_BYTES = _MCounter(
    "ray_tpu_train_collective_bytes_total",
    "Tensor payload bytes this rank put on the wire in collective ops",
    tag_keys=("op", "dtype"))
COLLECTIVE_COMPRESSION = _MGauge(
    "ray_tpu_train_collective_compression_ratio",
    "Uncompressed-equivalent bytes / wire bytes of the last collective",
    tag_keys=("op", "dtype"))


def _note_bytes(op: str, dtype: str, wire: int, raw: int,
                t0_ns: Optional[int] = None) -> None:
    rec = _flight.RECORDER
    if rec is not None and t0_ns:
        # one journal span per collective hop, carrying the achieved
        # compression ratio (raw/wire) — the EQuARX-style attribution
        rec.record("collective", op, t0_ns, rec.clock() - t0_ns,
                   {"dtype": dtype, "wire": int(wire),
                    "ratio": (round(float(raw) / float(wire), 3)
                              if wire > 0 else 1.0)})
    if wire <= 0:
        return
    try:
        from ray_tpu.util.metrics import record_batch
        record_batch([
            ("counter", "ray_tpu_train_collective_bytes_total",
             {"op": op, "dtype": dtype}, float(wire), None),
            ("gauge", "ray_tpu_train_collective_compression_ratio",
             {"op": op, "dtype": dtype}, float(raw) / float(wire), None),
        ])
    except Exception:
        logger.debug("collective metrics flush failed", exc_info=True)


@dataclass
class GroupInfo:
    world_size: int
    rank: int
    name: str
    seq: int = 0
    # round → this rank's keys + object refs pending lazy GC
    pending_gc: Dict[int, List] = field(default_factory=dict)


_groups: Dict[str, GroupInfo] = {}


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    """Join a collective group (each rank calls once)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    # A fresh group must not inherit residuals from a previous
    # same-named incarnation (this rank may have skipped destroy —
    # killed mid-run and restarted at a different world/tensor size).
    reset_error_feedback(group_name)
    _groups[group_name] = GroupInfo(world_size, rank, group_name)
    _kv_put(f"grp/{group_name}/{rank}", str(world_size).encode())


def destroy_collective_group(group_name: str = "default",
                             timeout: float = _DEFAULT_TIMEOUT) -> None:
    """Tear down a group. This is itself a COLLECTIVE call — every rank
    must call it, like the ops. A closing barrier proves all ranks
    finished the last real op, making its keys/refs safe to reclaim
    (the lazy-GC invariant covers only rounds strictly before the one a
    rank just completed — GC'ing the in-flight round here would yank
    keys out from under slower peers). The barrier round's own
    world_size empty keys are intentionally leaked: deleting them has
    the same race, and they are ~20 bytes each."""
    group = _groups.pop(group_name, None)
    if group is None:
        return
    barrier_seq = group.seq
    try:
        _groups[group_name] = group  # barrier() needs the group entry
        barrier(group_name=group_name, timeout=timeout)
    finally:
        _groups.pop(group_name, None)
        # even when the closing barrier fails (a peer died), this
        # rank's residuals are stale the moment the group is gone
        reset_error_feedback(group_name)
    for seq in list(group.pending_gc):
        if seq < barrier_seq:
            _gc_round(group, seq)
    _kv_del(f"grp/{group.name}/{group.rank}")


def _gc_round(group: GroupInfo, seq: int) -> None:
    """Reclaim this rank's keys + payload refs from a finished round."""
    entries = group.pending_gc.pop(seq, None)
    if not entries:
        return
    for key in entries[0]:
        _kv_del(key)
    entries[1].clear()  # drop ObjectRefs → owner may reclaim


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups[group_name].world_size


def _group(group_name: str) -> GroupInfo:
    group = _groups.get(group_name)
    if group is None:
        raise ValueError(
            f"collective group {group_name!r} not initialized in this "
            f"process; call init_collective_group first")
    return group


def _exchange(group: GroupInfo, tensor: Optional[np.ndarray],
              timeout: float) -> List[Optional[np.ndarray]]:
    """All ranks deposit, all ranks read everyone's payload.

    GC invariant: completing round S required reading every rank's
    round-S deposit, and a rank deposits in S only after fully finishing
    S-1 — so on completing S, round S-1's keys/refs are provably done
    and are reclaimed here (each rank deletes its own; idempotent)."""
    seq = group.seq
    group.seq += 1
    prefix = f"col/{group.name}/{seq}"
    my_key = f"{prefix}/{group.rank}"
    keepalive: List = []
    _kv_put(my_key, _pack_payload(tensor, keepalive))
    group.pending_gc[seq] = [[my_key], keepalive]
    out: List[Optional[np.ndarray]] = []
    for rank in range(group.world_size):
        blob = _kv_wait(f"{prefix}/{rank}", timeout,
                        what=f"rank {rank} of group {group.name!r}")
        out.append(_unpack_payload(blob))
    _gc_round(group, seq - 1)
    return out


_PAIR_OPS = {
    "sum": np.add,
    "prod": np.multiply,
    "max": np.maximum,
    "min": np.minimum,
}


def _tree_allreduce(group: GroupInfo, acc: np.ndarray, op: str,
                    timeout: float) -> np.ndarray:
    """Binomial-tree allreduce: partial sums flow up the tree (log2
    rounds of p2p transfers), the root broadcasts the result back down —
    2·log2(world) payload movements total vs the naive world² reads of
    an all-to-all through one KV (reference analog: NCCL's tree
    algorithms). Best for SMALL payloads, where the ring's 2(world-1)
    sequential rendezvous rounds cost more than the extra bytes."""
    world, rank = group.world_size, group.rank
    pair = _PAIR_OPS["sum" if op == "mean" else op]
    seq = group.seq
    group.seq += 1
    prefix = f"col/{group.name}/{seq}"
    my_keys: List[str] = []
    keepalive: List = []
    group.pending_gc[seq] = [my_keys, keepalive]
    wire = 0

    # reduce up: at level k, odd multiples of k send to even multiples
    k = 1
    sent_at = 0  # level at which this rank handed off (0 = never → root)
    while k < world:
        if rank % (2 * k) == k:
            dst = rank - k
            key = f"{prefix}/up/{rank}"
            wire += acc.nbytes
            _kv_put(key, _pack_payload(acc, keepalive))
            my_keys.append(key)
            sent_at = k
            break
        if rank % (2 * k) == 0 and rank + k < world:
            blob = _kv_wait(f"{prefix}/up/{rank + k}", timeout,
                            what=f"rank {rank + k} of group "
                                 f"{group.name!r} (tree reduce)")
            acc = pair(acc, _unpack_payload(blob))
        k *= 2

    # broadcast down: reverse the tree, highest level first
    top = 1
    while top < world:
        top *= 2
    k = top // 2
    while k >= 1:
        if rank % (2 * k) == k and k == sent_at:
            blob = _kv_wait(f"{prefix}/down/{rank}", timeout,
                            what=f"rank {rank - k} of group "
                                 f"{group.name!r} (tree broadcast)")
            acc = _unpack_payload(blob)
        elif rank % (2 * k) == 0 and rank + k < world:
            key = f"{prefix}/down/{rank + k}"
            wire += acc.nbytes
            _kv_put(key, _pack_payload(acc, keepalive))
            my_keys.append(key)
        k //= 2
    _gc_round(group, seq - 1)
    _note_bytes("allreduce", str(acc.dtype), wire, wire)
    return acc


def _chunk_bounds(n: int, world: int) -> List[int]:
    """Start offsets (plus final n) of np.array_split's flat chunking."""
    base, extra = divmod(n, world)
    bounds = [0]
    for i in range(world):
        bounds.append(bounds[-1] + base + (1 if i < extra else 0))
    return bounds


def _encode_chunk(chunk: np.ndarray, compression: Optional[str],
                  residual: Optional[np.ndarray], offset: int,
                  stats: Dict[str, int]):
    """Encode one outgoing chunk; quantization error (value − dequant)
    lands in this rank's residual slice for next-round compensation."""
    if compression is None:
        payload = np.ascontiguousarray(chunk)
    else:
        payload = _quantize_chunk(chunk, compression)
        if residual is not None and chunk.size:
            residual[offset:offset + chunk.size] += (
                np.asarray(chunk, dtype=np.float32).ravel()
                - _dequantize_chunk(payload))
    stats["wire"] += _payload_nbytes(payload)
    stats["raw"] += int(chunk.size) * 4 if compression else int(chunk.nbytes)
    return payload


def _ring_reduce_scatter_flat(group: GroupInfo, flat: np.ndarray, op: str,
                              timeout: float, compression: Optional[str],
                              residual: Optional[np.ndarray],
                              stats: Dict[str, int]
                              ) -> Tuple[np.ndarray, List[int]]:
    """Ring reduce-scatter over flat chunks: world−1 hops, each sending
    one 1/world chunk to the next rank. Quantized hops dequantize,
    accumulate in f32, and requantize (EQuARX's in-network pattern);
    every requantization error is error-fed via ``residual``. Returns
    (this rank's fully reduced chunk — exact f32, never requantized —
    and the chunk bounds). ``op`` must be sum/mean when compressed."""
    world, rank = group.world_size, group.rank
    pair = _PAIR_OPS["sum" if op == "mean" else op]
    bounds = _chunk_bounds(flat.size, world)
    acc: List[np.ndarray] = [
        np.array(flat[bounds[i]:bounds[i + 1]],
                 dtype=np.float32 if compression else flat.dtype)
        for i in range(world)]
    seq = group.seq
    group.seq += 1
    prefix = f"col/{group.name}/{seq}"
    my_keys: List[str] = []
    keepalive: List = []
    group.pending_gc[seq] = [my_keys, keepalive]
    prev = (rank - 1) % world
    for s in range(world - 1):
        send_idx = (rank - 1 - s) % world
        recv_idx = (rank - 2 - s) % world
        payload = _encode_chunk(acc[send_idx], compression, residual,
                                bounds[send_idx], stats)
        key = f"{prefix}/rs{s}/{rank}"
        _kv_put(key, _pack_payload(payload, keepalive))
        my_keys.append(key)
        blob = _kv_wait(f"{prefix}/rs{s}/{prev}", timeout,
                        what=f"rank {prev} of group {group.name!r} "
                             f"(ring reduce-scatter step {s})")
        acc[recv_idx] = pair(acc[recv_idx],
                             _decode_chunk(_unpack_payload(blob)))
    _gc_round(group, seq - 1)
    return acc[rank], bounds


def _ring_allgather_payloads(group: GroupInfo, my_payload, timeout: float,
                             stats: Dict[str, int],
                             raw_nbytes: int) -> List:
    """Ring all-gather: each rank's payload travels around the ring,
    forwarded VERBATIM at every hop (no requantization, so no further
    error). Returns payloads indexed by owning rank."""
    world, rank = group.world_size, group.rank
    payloads: List = [None] * world
    payloads[rank] = my_payload
    seq = group.seq
    group.seq += 1
    prefix = f"col/{group.name}/{seq}"
    my_keys: List[str] = []
    keepalive: List = []
    group.pending_gc[seq] = [my_keys, keepalive]
    prev = (rank - 1) % world
    carry = my_payload
    carry_raw = raw_nbytes
    for s in range(world - 1):
        key = f"{prefix}/ag{s}/{rank}"
        stats["wire"] += _payload_nbytes(carry)
        stats["raw"] += carry_raw
        _kv_put(key, _pack_payload(carry, keepalive))
        my_keys.append(key)
        blob = _kv_wait(f"{prefix}/ag{s}/{prev}", timeout,
                        what=f"rank {prev} of group {group.name!r} "
                             f"(ring all-gather step {s})")
        carry = _unpack_payload(blob)
        owner = (rank - 1 - s) % world
        payloads[owner] = carry
        carry_raw = (int(carry[1]) * 4 if _is_quantized(carry)
                     else int(carry.nbytes))
    _gc_round(group, seq - 1)
    return payloads


def _check_compression(compression: Optional[str], op: str,
                       dtype) -> None:
    if compression is None:
        return
    if compression not in _COMPRESSIONS:
        raise ValueError(f"unknown compression {compression!r}; "
                         f"expected one of {_COMPRESSIONS} or None")
    if op not in ("sum", "mean"):
        raise ValueError(
            f"compression={compression!r} only supports sum/mean "
            f"(dequantize-accumulate is additive), not op={op!r}")
    if not np.issubdtype(np.dtype(dtype), np.floating):
        raise ValueError(
            f"compression={compression!r} needs a float tensor, "
            f"got dtype {dtype}")


def allreduce(tensor, op: str = "sum", group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT,
              compression: Optional[str] = None,
              ef_key: Optional[str] = None,
              algorithm: Optional[str] = None) -> np.ndarray:
    """Allreduce across the group.

    ``algorithm``: "ring" (reduce-scatter + all-gather over 1/world
    chunks — bandwidth-optimal, the default for payloads ≥ 8 KB or
    whenever compression is on) or "tree" (binomial; fewest rendezvous
    rounds, default for small payloads). ``compression``: "int8"/"fp8"
    block-quantizes every hop (sum/mean only). ``ef_key``: stable
    per-leaf id enabling the persistent error-feedback residual — use
    the same key for the same logical tensor every round.

    All ranks return bitwise-identical results: with compression the
    reduced chunks are quantized ONCE by their owning rank and every
    rank (owner included) decodes the same wire bytes.
    """
    group = _group(group_name)
    world = group.world_size
    acc = np.asarray(tensor)
    _check_compression(compression, op, acc.dtype)
    led = _collsan.LEDGER
    cs = None if led is None else led.record_enter(
        group.name, group.rank, world,
        _collsan.fingerprint("allreduce", acc.dtype, acc.size, acc.shape,
                             compression, ef_key, algorithm))
    try:
        if world == 1:
            return acc / world if op == "mean" else acc.copy()
        _rec = _flight.RECORDER
        flight_t0 = _rec.clock() if _rec is not None else None
        if algorithm is None:
            algorithm = ("ring" if compression is not None
                         or acc.nbytes >= _RING_MIN_BYTES else "tree")
        if algorithm == "tree":
            if compression is not None:
                raise ValueError("compression requires algorithm='ring'")
            out = _tree_allreduce(group, acc, op, timeout)
            if _rec is not None:
                _rec.record("collective", "allreduce", flight_t0,
                            _rec.clock() - flight_t0,
                            {"algorithm": "tree",
                             "dtype": str(acc.dtype), "ratio": 1.0})
            return out / world if op == "mean" else out
        if algorithm != "ring":
            raise ValueError(f"unknown algorithm {algorithm!r}")

        orig_shape, orig_dtype = acc.shape, acc.dtype
        flat = acc.ravel()
        residual = None
        if compression is not None:
            flat = flat.astype(np.float32)
            if ef_key is not None:
                residual = _ef_buffer(group.name, ef_key, flat.size)
                flat = flat + residual
                residual[:] = 0.0  # re-filled with this round's errors
        stats = {"wire": 0, "raw": 0}
        own, bounds = _ring_reduce_scatter_flat(
            group, flat, op, timeout, compression, residual, stats)
        # stats deliberately excluded here: this encode is not itself a
        # send — the all-gather below counts it when it first travels
        own_payload = _encode_chunk(own, compression, residual,
                                    bounds[group.rank],
                                    {"wire": 0, "raw": 0})
        # the all-gather moves each payload world-1 hops in total around
        # the ring; this rank forwards whatever arrives, verbatim
        payloads = _ring_allgather_payloads(
            group, own_payload, timeout, stats,
            int(own.size) * 4 if compression else int(own.nbytes))
        parts = [_decode_chunk(p) for p in payloads]
        out = (np.concatenate([np.asarray(p, dtype=np.float32
                                          if compression else orig_dtype)
                               for p in parts])
               if world > 1 else parts[0])
        if op == "mean":
            out = out / world
        out = out.reshape(orig_shape)
        if compression is not None and np.issubdtype(orig_dtype,
                                                     np.floating):
            out = out.astype(orig_dtype)
        _note_bytes("allreduce", compression or str(orig_dtype),
                    stats["wire"], stats["raw"], t0_ns=flight_t0)
        return out
    finally:
        if led is not None:
            led.record_exit(group.name, group.rank, world, cs, "allreduce")


def reduce_scatter_flat(tensor, op: str = "sum",
                        group_name: str = "default",
                        timeout: float = _DEFAULT_TIMEOUT,
                        compression: Optional[str] = None,
                        ef_key: Optional[str] = None
                        ) -> Tuple[np.ndarray, int]:
    """Ring reduce-scatter of the FLATTENED tensor: returns (this rank's
    reduced 1/world chunk in full precision, its flat offset). This is
    the gradient half of a ZeRO-1 step — half the wire bytes of a full
    allreduce, and the chunk a rank owns is exact f32 (hop errors are
    error-fed by the ranks that introduced them when ``ef_key`` is
    set)."""
    group = _group(group_name)
    world = group.world_size
    flat = np.asarray(tensor).ravel()
    _check_compression(compression, op, flat.dtype)
    led = _collsan.LEDGER
    cs = None if led is None else led.record_enter(
        group.name, group.rank, world,
        _collsan.fingerprint("reduce_scatter_flat", flat.dtype, flat.size,
                             flat.shape, compression, ef_key, None))
    try:
        if world == 1:
            out = flat.astype(np.float32) if compression else flat.copy()
            return (out / world if op == "mean" else out), 0
        residual = None
        _rec = _flight.RECORDER
        flight_t0 = _rec.clock() if _rec is not None else None
        if compression is not None:
            flat = flat.astype(np.float32)
            if ef_key is not None:
                residual = _ef_buffer(group.name, ef_key, flat.size)
                flat = flat + residual
                residual[:] = 0.0
        stats = {"wire": 0, "raw": 0}
        own, bounds = _ring_reduce_scatter_flat(
            group, flat, op, timeout, compression, residual, stats)
        if op == "mean":
            own = own / world
        _note_bytes("reduce_scatter", compression or str(flat.dtype),
                    stats["wire"], stats["raw"], t0_ns=flight_t0)
        return own, bounds[group.rank]
    finally:
        if led is not None:
            led.record_exit(group.name, group.rank, world, cs,
                            "reduce_scatter_flat")


def allgather_flat(shard, group_name: str = "default",
                   timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    """Ring all-gather of per-rank flat shards (sizes may differ by one
    element — np.array_split chunking), concatenated in rank order. The
    parameter half of a ZeRO-1 step: each rank contributes its updated
    shard and receives the full parameter vector."""
    group = _group(group_name)
    shard = np.ascontiguousarray(np.asarray(shard).ravel())
    led = _collsan.LEDGER
    # per-rank shard sizes legitimately differ by one element
    # (np.array_split chunking) — size/shape stay out of the fingerprint
    cs = None if led is None else led.record_enter(
        group.name, group.rank, group.world_size,
        _collsan.fingerprint("allgather_flat", shard.dtype))
    try:
        if group.world_size == 1:
            return shard.copy()
        stats = {"wire": 0, "raw": 0}
        _rec = _flight.RECORDER
        flight_t0 = _rec.clock() if _rec is not None else None
        payloads = _ring_allgather_payloads(group, shard, timeout, stats,
                                            int(shard.nbytes))
        _note_bytes("allgather", str(shard.dtype), stats["wire"],
                    stats["raw"], t0_ns=flight_t0)
        return np.concatenate([np.asarray(p) for p in payloads])
    finally:
        if led is not None:
            led.record_exit(group.name, group.rank, group.world_size,
                            cs, "allgather_flat")


def allgather(tensor, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT) -> List[np.ndarray]:
    group = _group(group_name)
    arr = np.asarray(tensor)
    led = _collsan.LEDGER
    # _exchange carries arbitrary per-rank payloads; only op/dtype are
    # part of the cross-rank contract here
    cs = None if led is None else led.record_enter(
        group.name, group.rank, group.world_size,
        _collsan.fingerprint("allgather", arr.dtype))
    try:
        return [np.asarray(p) for p in _exchange(group, arr, timeout)]
    finally:
        if led is not None:
            led.record_exit(group.name, group.rank, group.world_size,
                            cs, "allgather")


def reducescatter(tensor, op: str = "sum", group_name: str = "default",
                  timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    """Reduce across ranks, then each rank keeps its 1/world shard along
    axis 0 (reference-compatible shape semantics; for the flat ZeRO-1
    chunking use reduce_scatter_flat)."""
    group = _group(group_name)
    arr = np.asarray(tensor)
    led = _collsan.LEDGER
    cs = None if led is None else led.record_enter(
        group.name, group.rank, group.world_size,
        _collsan.fingerprint("reducescatter", arr.dtype, arr.size,
                             arr.shape))
    try:
        reduced = allreduce(arr, op=op, group_name=group_name,
                            timeout=timeout)
        shards = np.array_split(reduced, group.world_size, axis=0)
        return shards[group.rank]
    finally:
        if led is not None:
            led.record_exit(group.name, group.rank, group.world_size,
                            cs, "reducescatter")


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    group = _group(group_name)
    led = _collsan.LEDGER
    # only the src rank holds the payload, so the cross-rank-comparable
    # identity is the op + agreed root (carried in the ef_key slot)
    cs = None if led is None else led.record_enter(
        group.name, group.rank, group.world_size,
        _collsan.fingerprint("broadcast", ef_key=f"src={src_rank}"))
    try:
        payload = np.asarray(tensor) if group.rank == src_rank else None
        parts = _exchange(group, payload, timeout)
        return np.asarray(parts[src_rank])
    finally:
        if led is not None:
            led.record_exit(group.name, group.rank, group.world_size,
                            cs, "broadcast")


def barrier(group_name: str = "default",
            timeout: float = _DEFAULT_TIMEOUT) -> None:
    group = _group(group_name)
    led = _collsan.LEDGER
    cs = None if led is None else led.record_enter(
        group.name, group.rank, group.world_size,
        _collsan.fingerprint("barrier"))
    try:
        _exchange(group, np.zeros((), dtype=np.int8), timeout)
    finally:
        if led is not None:
            led.record_exit(group.name, group.rank, group.world_size,
                            cs, "barrier")


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    group = _group(group_name)
    arr = np.asarray(tensor)
    led = _collsan.LEDGER
    # p2p programs legitimately differ per rank: recorded under the
    # p2p: pseudo-group, which fold() skips and the watchdog still scans
    cs = None if led is None else led.record_enter(
        _collsan.P2P_PREFIX + group.name, group.rank, group.world_size,
        _collsan.fingerprint("send", arr.dtype, arr.size, arr.shape,
                             ef_key=f"{group.rank}->{dst_rank}/{tag}"))
    key = f"p2p/{group.name}/{group.rank}->{dst_rank}/{tag}"
    _kv_put(key, serialization.pack(arr))
    if led is not None:
        led.record_exit(_collsan.P2P_PREFIX + group.name, group.rank,
                        group.world_size, cs, "send")


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    group = _group(group_name)
    led = _collsan.LEDGER
    cs = None if led is None else led.record_enter(
        _collsan.P2P_PREFIX + group.name, group.rank, group.world_size,
        _collsan.fingerprint("recv",
                             ef_key=f"{src_rank}->{group.rank}/{tag}"))
    try:
        key = f"p2p/{group.name}/{src_rank}->{group.rank}/{tag}"
        blob = _kv_wait(key, timeout,
                        what=f"rank {src_rank} of group {group.name!r} "
                             f"(p2p send tag {tag})")
        _kv_del(key)
        return serialization.unpack(blob)
    finally:
        if led is not None:
            led.record_exit(_collsan.P2P_PREFIX + group.name, group.rank,
                            group.world_size, cs, "recv")


# --- in-graph SPMD collectives (the TPU hot path) -----------------------
# These are thin names over jax.lax; inside a jitted/shard_mapped fn they
# compile to ICI collective DMA. Use these for all per-step math — the
# KV backend above is control-plane only.

def psum(x, axis_name: str):
    import jax
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    import jax
    return jax.lax.pmean(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    import jax
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute(x, axis_name: str, perm):
    import jax
    return jax.lax.ppermute(x, axis_name, perm)


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0):
    import jax
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)


def quantized_psum(x, axis_name: str, dtype: str = "int8",
                   block: int = _QUANT_BLOCK, error=None):
    """Bandwidth-cheap psum for the GSPMD gradient-sync path: block-
    quantize with a SHARED scale (per-block |max| pmax'd across the
    axis, so every replica quantizes onto the same grid), accumulate the
    int8 payloads exactly in int32 (EQuARX's accumulate-in-wide-int),
    dequantize once. ``dtype``: "int8" or "fp8" (e4m3; accumulated in
    f32 — the int-accumulate trick has no fp8 analog). Scale math shared
    with ray_tpu/ops/quant_matmul.py.

    With ``error`` (the previous round's residual, same shape as ``x``)
    returns ``(psum, new_error)`` — the error-feedback pair: callers
    carry the residual across steps so quantization bias cancels over
    time instead of accumulating into the optimizer state.
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.quant_matmul import scale_from_amax

    if dtype not in ("int8", "fp8"):
        raise ValueError(f"dtype must be int8|fp8, got {dtype!r}")
    orig_shape, orig_dtype = x.shape, x.dtype
    n = x.size
    flat = x.reshape(-1).astype(jnp.float32)
    if error is not None:
        flat = flat + error.reshape(-1).astype(jnp.float32)
    pad = (-n) % block
    if pad:
        flat_p = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    else:
        flat_p = flat
    blocks = flat_p.reshape(-1, block)
    amax = jax.lax.pmax(
        jnp.max(jnp.abs(blocks), axis=1, keepdims=True), axis_name)
    if dtype == "int8":
        scale = scale_from_amax(amax, 127.0)
        q = jnp.clip(jnp.round(blocks / scale), -127.0, 127.0)
        deq_own = q * scale  # own contribution as the wire sees it
        acc = jax.lax.psum(q.astype(jnp.int32), axis_name)
        out_blocks = acc.astype(jnp.float32) * scale
    else:
        scale = scale_from_amax(amax, 448.0)
        q = (blocks / scale).astype(jnp.float8_e4m3fn)
        deq_own = q.astype(jnp.float32) * scale
        out_blocks = jax.lax.psum(deq_own, axis_name)
    out = out_blocks.reshape(-1)[:n].reshape(orig_shape)
    if jnp.issubdtype(orig_dtype, jnp.floating):
        out = out.astype(orig_dtype)
    if error is None:
        return out
    new_error = (blocks - deq_own).reshape(-1)[:n].reshape(orig_shape)
    return out, new_error


def quantized_pmean(x, axis_name: str, dtype: str = "int8",
                    block: int = _QUANT_BLOCK, error=None):
    """quantized_psum / axis size — the DDP gradient-mean drop-in."""
    import jax
    world = jax.lax.psum(1, axis_name)
    result = quantized_psum(x, axis_name, dtype=dtype, block=block,
                            error=error)
    if error is None:
        return result / world
    out, new_error = result
    return out / world, new_error


def quantized_reduce_scatter(x, axis_name: str, dtype: str = "int8",
                             block: int = _QUANT_BLOCK):
    """Quantized reduce-scatter of a flat vector: each device gets its
    1/world shard of the sum, transported as shared-scale int8
    accumulated in int32 via psum_scatter (fp8: f32-accumulated). The
    ZeRO-1 gradient half inside jit: x must be 1-D with
    ``x.size % (axis_size * block) == 0`` (pad at the call site)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.quant_matmul import scale_from_amax

    if dtype not in ("int8", "fp8"):
        raise ValueError(f"dtype must be int8|fp8, got {dtype!r}")
    if x.ndim != 1:
        raise ValueError(f"expected flat 1-D input, got shape {x.shape}")
    if x.size % block:
        raise ValueError(
            f"x.size={x.size} must divide the quant block {block} "
            "(pad at the call site)")
    blocks = x.reshape(-1, block).astype(jnp.float32)
    amax = jax.lax.pmax(
        jnp.max(jnp.abs(blocks), axis=1, keepdims=True), axis_name)
    idx = jax.lax.axis_index(axis_name)
    if dtype == "int8":
        scale = scale_from_amax(amax, 127.0)
        q = jnp.clip(jnp.round(blocks / scale), -127.0, 127.0)
        shard = jax.lax.psum_scatter(q.astype(jnp.int32), axis_name,
                                     scatter_dimension=0, tiled=True)
        # the shared scales are replicated; slice this shard's rows
        scale_shard = jax.lax.dynamic_slice_in_dim(
            scale, idx * shard.shape[0], shard.shape[0], 0)
        return (shard.astype(jnp.float32) * scale_shard).reshape(-1)
    scale = scale_from_amax(amax, 448.0)
    deq = (blocks / scale).astype(jnp.float8_e4m3fn).astype(
        jnp.float32) * scale
    shard = jax.lax.psum_scatter(deq, axis_name, scatter_dimension=0,
                                 tiled=True)
    return shard.reshape(-1)
