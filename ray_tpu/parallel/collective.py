"""Out-of-band collectives between actors/tasks.

Capability parity with the reference's ray.util.collective
(reference: python/ray/util/collective/collective.py —
init_collective_group:180, allreduce:325, barrier:365, broadcast:440,
allgather:490, reducescatter:539, send:598/recv:661; NCCL rendezvous via
named actor + GCS KV, collective_group/nccl_collective_group.py:29).

TPU-native stance (SURVEY.md §5.8): in-graph SPMD math should use
`jax.lax.psum`/`all_gather` over a mesh — XLA emits ICI collective DMA
and no framework code runs per step. This module covers the *out-of-band*
cases the reference uses NCCL for: host tensors moving between actors
(weight broadcast to env-runners, parameter servers, metric reduction).
The backend rendezvouses through the GCS KV store and moves payloads
through the shared-memory object plane — no NCCL, no CUDA, and on a
TPU host no extra copies (the store is the staging buffer the device
transfer reads from anyway).

Ops must be called in the same order by every rank of a group (the
standard collective contract).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core import serialization
from ray_tpu.exceptions import GetTimeoutError

_DEFAULT_TIMEOUT = 60.0
_POLL_S = 0.002


def _kv_put(key: str, value: bytes) -> None:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        rt.gcs.kv.put(key.encode(), value, namespace="collective")
    else:
        rt.gcs_call("kv_put", key.encode(), value, "collective")


def _kv_get(key: str) -> Optional[bytes]:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        return rt.gcs.kv.get(key.encode(), namespace="collective")
    return rt.gcs_call("kv_get", key.encode(), "collective")


def _kv_del(key: str) -> None:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        rt.gcs.kv.delete(key.encode(), namespace="collective")
    else:
        rt.gcs_call("kv_del", key.encode(), "collective")


def _kv_wait(key: str, timeout: float) -> bytes:
    deadline = time.monotonic() + timeout
    while True:
        value = _kv_get(key)
        if value is not None:
            return value
        if time.monotonic() >= deadline:
            raise GetTimeoutError(f"collective rendezvous timed out on {key}")
        time.sleep(_POLL_S)


@dataclass
class GroupInfo:
    world_size: int
    rank: int
    name: str
    seq: int = 0


_groups: Dict[str, GroupInfo] = {}


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    """Join a collective group (each rank calls once)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    _groups[group_name] = GroupInfo(world_size, rank, group_name)
    _kv_put(f"grp/{group_name}/{rank}", str(world_size).encode())


def destroy_collective_group(group_name: str = "default") -> None:
    _groups.pop(group_name, None)


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups[group_name].world_size


def _group(group_name: str) -> GroupInfo:
    group = _groups.get(group_name)
    if group is None:
        raise ValueError(
            f"collective group {group_name!r} not initialized in this "
            f"process; call init_collective_group first")
    return group


def _exchange(group: GroupInfo, tensor: Optional[np.ndarray],
              timeout: float) -> List[Optional[np.ndarray]]:
    """All ranks deposit, all ranks read everyone's payload."""
    seq = group.seq
    group.seq += 1
    prefix = f"col/{group.name}/{seq}"
    _kv_put(f"{prefix}/{group.rank}",
            serialization.pack(tensor) if tensor is not None else b"")
    out: List[Optional[np.ndarray]] = []
    for rank in range(group.world_size):
        blob = _kv_wait(f"{prefix}/{rank}", timeout)
        out.append(serialization.unpack(blob) if blob else None)
    # Everyone acks; the last rank out cleans the round's keys.
    _kv_put(f"{prefix}/ack/{group.rank}", b"1")
    if all(_kv_get(f"{prefix}/ack/{r}") is not None
           for r in range(group.world_size)):
        # Last rank out cleans payload AND ack keys — without this the
        # head KV leaks world_size entries per collective call.
        for rank in range(group.world_size):
            _kv_del(f"{prefix}/{rank}")
            _kv_del(f"{prefix}/ack/{rank}")
    return out


_REDUCE_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "prod": lambda xs: np.prod(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
    "mean": lambda xs: np.mean(xs, axis=0),
}


def allreduce(tensor, op: str = "sum", group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    group = _group(group_name)
    parts = _exchange(group, np.asarray(tensor), timeout)
    return _REDUCE_OPS[op](np.stack([np.asarray(p) for p in parts]))


def allgather(tensor, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT) -> List[np.ndarray]:
    group = _group(group_name)
    return [np.asarray(p) for p in _exchange(group, np.asarray(tensor), timeout)]


def reducescatter(tensor, op: str = "sum", group_name: str = "default",
                  timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    """Reduce across ranks, then each rank keeps its 1/world shard along
    axis 0."""
    group = _group(group_name)
    reduced = allreduce(tensor, op=op, group_name=group_name, timeout=timeout)
    shards = np.array_split(reduced, group.world_size, axis=0)
    return shards[group.rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    group = _group(group_name)
    payload = np.asarray(tensor) if group.rank == src_rank else None
    parts = _exchange(group, payload, timeout)
    return np.asarray(parts[src_rank])


def barrier(group_name: str = "default",
            timeout: float = _DEFAULT_TIMEOUT) -> None:
    group = _group(group_name)
    _exchange(group, np.zeros((), dtype=np.int8), timeout)


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    group = _group(group_name)
    key = f"p2p/{group.name}/{group.rank}->{dst_rank}/{tag}"
    _kv_put(key, serialization.pack(np.asarray(tensor)))


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    group = _group(group_name)
    key = f"p2p/{group.name}/{src_rank}->{group.rank}/{tag}"
    blob = _kv_wait(key, timeout)
    _kv_del(key)
    return serialization.unpack(blob)


# --- in-graph SPMD collectives (the TPU hot path) -----------------------
# These are thin names over jax.lax; inside a jitted/shard_mapped fn they
# compile to ICI collective DMA. Use these for all per-step math — the
# KV backend above is control-plane only.

def psum(x, axis_name: str):
    import jax
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    import jax
    return jax.lax.pmean(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    import jax
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute(x, axis_name: str, perm):
    import jax
    return jax.lax.ppermute(x, axis_name, perm)


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0):
    import jax
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)
