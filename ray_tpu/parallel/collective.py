"""Out-of-band collectives between actors/tasks.

Capability parity with the reference's ray.util.collective
(reference: python/ray/util/collective/collective.py —
init_collective_group:180, allreduce:325, barrier:365, broadcast:440,
allgather:490, reducescatter:539, send:598/recv:661; NCCL rendezvous via
named actor + GCS KV, collective_group/nccl_collective_group.py:29).

TPU-native stance (SURVEY.md §5.8): in-graph SPMD math should use
`jax.lax.psum`/`all_gather` over a mesh — XLA emits ICI collective DMA
and no framework code runs per step. This module covers the *out-of-band*
cases the reference uses NCCL for: host tensors moving between actors
(weight broadcast to env-runners, parameter servers, metric reduction).
The backend rendezvouses through the GCS KV store and moves payloads
through the shared-memory object plane — no NCCL, no CUDA, and on a
TPU host no extra copies (the store is the staging buffer the device
transfer reads from anyway).

Ops must be called in the same order by every rank of a group (the
standard collective contract).

Design notes (round-2 rework):
- Rendezvous is EVENT-DRIVEN: ranks block on a GCS ``kv_wait`` (head
  fires the reply when the key lands) instead of polling — no 2ms
  busy-loops, no per-wait head load (reference analog: long-poll
  subscribers, src/ray/pubsub/publisher.h:245).
- Payloads above an inline threshold move through the OBJECT PLANE
  (put → ref in KV → peers get()), so tensor bytes travel shm/direct
  node-to-node transfer, not inline through the head's control socket.
- ``allreduce`` is a binomial TREE (reduce up, broadcast down):
  2·log2(world) p2p transfers instead of world² reads through one
  process.
- Round keys are garbage-collected LAZILY one round behind: a rank
  completing round S has read every round-S deposit, which proves all
  ranks finished round S-1 — so S-1's keys and payload refs are
  reclaimed then, with the remainder swept by destroy_collective_group.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core import serialization
from ray_tpu.exceptions import GetTimeoutError

_DEFAULT_TIMEOUT = 60.0
# payloads larger than this ride the object plane instead of the KV
_INLINE_MAX = 32 * 1024


def _kv_put(key: str, value: bytes) -> None:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        rt.gcs.kv.put(key.encode(), value, namespace="collective")
    else:
        rt.gcs_call("kv_put", key.encode(), value, "collective")


def _kv_get(key: str) -> Optional[bytes]:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        return rt.gcs.kv.get(key.encode(), namespace="collective")
    return rt.gcs_call("kv_get", key.encode(), "collective")


def _kv_del(key: str) -> None:
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        rt.gcs.kv.delete(key.encode(), namespace="collective")
    else:
        rt.gcs_call("kv_del", key.encode(), "collective")


def _kv_wait(key: str, timeout: float) -> bytes:
    """Block until the key exists — event-driven: the head wakes us via
    the KV waiter hook (gcs.py KVStore.add_waiter), no polling."""
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        value = rt.gcs.kv.wait(key.encode(), namespace="collective",
                               timeout=timeout)
    else:
        value = rt.gcs_call("kv_wait", key.encode(), "collective", timeout,
                            timeout=timeout + 10.0)
    if value is None:
        raise GetTimeoutError(f"collective rendezvous timed out on {key}")
    return value


def _pack_payload(value: Optional[np.ndarray], keepalive: List) -> bytes:
    """Inline small tensors; large ones go through the object plane so
    the bytes move node-to-node, not through the head's control socket.
    The producer must keep ``keepalive`` refs until consumers have
    certainly read (see the round-GC invariant in the module docstring)."""
    if value is None:
        return b""
    blob = serialization.pack(value)
    if len(blob) <= _INLINE_MAX:
        return b"I" + blob
    import ray_tpu
    ref = ray_tpu.put(value)
    keepalive.append(ref)
    return b"R" + serialization.dumps(ref)


def _unpack_payload(blob: bytes) -> Optional[np.ndarray]:
    if not blob:
        return None
    tag, body = blob[:1], blob[1:]
    if tag == b"I":
        return serialization.unpack(body)
    import ray_tpu
    return ray_tpu.get(serialization.loads(body))


@dataclass
class GroupInfo:
    world_size: int
    rank: int
    name: str
    seq: int = 0
    # round → this rank's keys + object refs pending lazy GC
    pending_gc: Dict[int, List] = field(default_factory=dict)


_groups: Dict[str, GroupInfo] = {}


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    """Join a collective group (each rank calls once)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    _groups[group_name] = GroupInfo(world_size, rank, group_name)
    _kv_put(f"grp/{group_name}/{rank}", str(world_size).encode())


def destroy_collective_group(group_name: str = "default",
                             timeout: float = _DEFAULT_TIMEOUT) -> None:
    """Tear down a group. This is itself a COLLECTIVE call — every rank
    must call it, like the ops. A closing barrier proves all ranks
    finished the last real op, making its keys/refs safe to reclaim
    (the lazy-GC invariant covers only rounds strictly before the one a
    rank just completed — GC'ing the in-flight round here would yank
    keys out from under slower peers). The barrier round's own
    world_size empty keys are intentionally leaked: deleting them has
    the same race, and they are ~20 bytes each."""
    group = _groups.pop(group_name, None)
    if group is None:
        return
    barrier_seq = group.seq
    try:
        _groups[group_name] = group  # barrier() needs the group entry
        barrier(group_name=group_name, timeout=timeout)
    finally:
        _groups.pop(group_name, None)
    for seq in list(group.pending_gc):
        if seq < barrier_seq:
            _gc_round(group, seq)
    _kv_del(f"grp/{group.name}/{group.rank}")


def _gc_round(group: GroupInfo, seq: int) -> None:
    """Reclaim this rank's keys + payload refs from a finished round."""
    entries = group.pending_gc.pop(seq, None)
    if not entries:
        return
    for key in entries[0]:
        _kv_del(key)
    entries[1].clear()  # drop ObjectRefs → owner may reclaim


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups[group_name].world_size


def _group(group_name: str) -> GroupInfo:
    group = _groups.get(group_name)
    if group is None:
        raise ValueError(
            f"collective group {group_name!r} not initialized in this "
            f"process; call init_collective_group first")
    return group


def _exchange(group: GroupInfo, tensor: Optional[np.ndarray],
              timeout: float) -> List[Optional[np.ndarray]]:
    """All ranks deposit, all ranks read everyone's payload.

    GC invariant: completing round S required reading every rank's
    round-S deposit, and a rank deposits in S only after fully finishing
    S-1 — so on completing S, round S-1's keys/refs are provably done
    and are reclaimed here (each rank deletes its own; idempotent)."""
    seq = group.seq
    group.seq += 1
    prefix = f"col/{group.name}/{seq}"
    my_key = f"{prefix}/{group.rank}"
    keepalive: List = []
    _kv_put(my_key, _pack_payload(tensor, keepalive))
    group.pending_gc[seq] = [[my_key], keepalive]
    out: List[Optional[np.ndarray]] = []
    for rank in range(group.world_size):
        blob = _kv_wait(f"{prefix}/{rank}", timeout)
        out.append(_unpack_payload(blob))
    _gc_round(group, seq - 1)
    return out


_PAIR_OPS = {
    "sum": np.add,
    "prod": np.multiply,
    "max": np.maximum,
    "min": np.minimum,
}


def allreduce(tensor, op: str = "sum", group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    """Binomial-tree allreduce: partial sums flow up the tree (log2
    rounds of p2p transfers), the root broadcasts the result back down —
    2·log2(world) payload movements total vs the naive world² reads of
    an all-to-all through one KV (reference analog: NCCL's tree
    algorithms; here payloads ride the object plane between nodes)."""
    group = _group(group_name)
    world, rank = group.world_size, group.rank
    pair = _PAIR_OPS["sum" if op == "mean" else op]
    acc = np.asarray(tensor)
    if world == 1:
        return acc / world if op == "mean" else acc.copy()
    seq = group.seq
    group.seq += 1
    prefix = f"col/{group.name}/{seq}"
    my_keys: List[str] = []
    keepalive: List = []
    group.pending_gc[seq] = [my_keys, keepalive]

    # reduce up: at level k, odd multiples of k send to even multiples
    k = 1
    sent_at = 0  # level at which this rank handed off (0 = never → root)
    while k < world:
        if rank % (2 * k) == k:
            dst = rank - k
            key = f"{prefix}/up/{rank}"
            _kv_put(key, _pack_payload(acc, keepalive))
            my_keys.append(key)
            sent_at = k
            break
        if rank % (2 * k) == 0 and rank + k < world:
            blob = _kv_wait(f"{prefix}/up/{rank + k}", timeout)
            acc = pair(acc, _unpack_payload(blob))
        k *= 2

    # broadcast down: reverse the tree, highest level first
    top = 1
    while top < world:
        top *= 2
    k = top // 2
    while k >= 1:
        if rank % (2 * k) == k and k == sent_at:
            blob = _kv_wait(f"{prefix}/down/{rank}", timeout)
            acc = _unpack_payload(blob)
        elif rank % (2 * k) == 0 and rank + k < world:
            key = f"{prefix}/down/{rank + k}"
            _kv_put(key, _pack_payload(acc, keepalive))
            my_keys.append(key)
        k //= 2
    _gc_round(group, seq - 1)
    return acc / world if op == "mean" else acc


def allgather(tensor, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT) -> List[np.ndarray]:
    group = _group(group_name)
    return [np.asarray(p) for p in _exchange(group, np.asarray(tensor), timeout)]


def reducescatter(tensor, op: str = "sum", group_name: str = "default",
                  timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    """Reduce across ranks, then each rank keeps its 1/world shard along
    axis 0."""
    group = _group(group_name)
    reduced = allreduce(tensor, op=op, group_name=group_name, timeout=timeout)
    shards = np.array_split(reduced, group.world_size, axis=0)
    return shards[group.rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    group = _group(group_name)
    payload = np.asarray(tensor) if group.rank == src_rank else None
    parts = _exchange(group, payload, timeout)
    return np.asarray(parts[src_rank])


def barrier(group_name: str = "default",
            timeout: float = _DEFAULT_TIMEOUT) -> None:
    group = _group(group_name)
    _exchange(group, np.zeros((), dtype=np.int8), timeout)


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    group = _group(group_name)
    key = f"p2p/{group.name}/{group.rank}->{dst_rank}/{tag}"
    _kv_put(key, serialization.pack(np.asarray(tensor)))


def recv(src_rank: int, group_name: str = "default", tag: int = 0,
         timeout: float = _DEFAULT_TIMEOUT) -> np.ndarray:
    group = _group(group_name)
    key = f"p2p/{group.name}/{src_rank}->{group.rank}/{tag}"
    blob = _kv_wait(key, timeout)
    _kv_del(key)
    return serialization.unpack(blob)


# --- in-graph SPMD collectives (the TPU hot path) -----------------------
# These are thin names over jax.lax; inside a jitted/shard_mapped fn they
# compile to ICI collective DMA. Use these for all per-step math — the
# KV backend above is control-plane only.

def psum(x, axis_name: str):
    import jax
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    import jax
    return jax.lax.pmean(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    import jax
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute(x, axis_name: str, perm):
    import jax
    return jax.lax.ppermute(x, axis_name, perm)


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0):
    import jax
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)
