"""Mixture-of-Experts with expert parallelism over the mesh.

The reference has no in-tree MoE (SURVEY §2.3 X4: TP/PP/EP appear only
as config passthrough to vLLM/DeepSpeed); here expert parallelism is a
first-class library component, TPU-first: expert weights are sharded on
the ``expert`` mesh axis and dispatch/combine are einsums over one-hot
routing masks — under jit, GSPMD partitions the token and expert
dimensions and inserts the all-to-all collectives over ICI (the
Mesh-TensorFlow / Switch-Transformer formulation, which is how MoE is
idiomatically expressed for XLA rather than hand-written sends).

Components:
- ``top_k_gating``: softmax router → top-k experts per token with
  renormalized weights and a Switch-style load-balancing aux loss.
- ``moe_dispatch``/``moe_combine``: capacity-bounded one-hot routing.
- ``moe_ffn``: the full layer — gate → dispatch → per-expert SwiGLU
  FFN (batched over the expert axis) → combine.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def top_k_gating(x: jax.Array, router: jax.Array, k: int
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Route tokens: returns (gates [T,E], topk_idx [T,k], aux_loss).

    ``x``: [T, D] tokens; ``router``: [D, E]. Gates are zero outside
    the top-k and renormalized over the selected experts. The aux loss
    is the Switch load-balancing term E * sum_e(frac_tokens_e *
    mean_prob_e), minimized at uniform routing.
    """
    logits = x.astype(jnp.float32) @ router.astype(jnp.float32)  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    topk_vals, topk_idx = jax.lax.top_k(probs, k)                # [T, k]
    topk_vals = topk_vals / jnp.maximum(
        topk_vals.sum(axis=-1, keepdims=True), 1e-9)
    num_experts = router.shape[-1]
    gates = jnp.zeros_like(probs).at[
        jnp.arange(x.shape[0])[:, None], topk_idx].set(topk_vals)
    # load-balancing aux (Switch Transformer eq. 4-6)
    top1 = jax.nn.one_hot(topk_idx[:, 0], num_experts)
    frac_tokens = top1.mean(axis=0)
    frac_probs = probs.mean(axis=0)
    aux = num_experts * jnp.sum(frac_tokens * frac_probs)
    return gates, topk_idx, aux


def moe_dispatch(gates: jax.Array, topk_idx: jax.Array,
                 num_experts: int, capacity: int
                 ) -> Tuple[jax.Array, jax.Array]:
    """Build routing masks: (dispatch [T,E,C] one-hot, combine [T,E,C]).

    Each expert accepts at most ``capacity`` tokens; overflow tokens are
    dropped for that expert (their residual path still carries them —
    standard capacity-factor semantics).
    """
    num_tokens, k = topk_idx.shape
    dispatch = jnp.zeros((num_tokens, num_experts, capacity),
                         dtype=gates.dtype)
    # fill k slots sequentially so earlier (higher-gate) choices claim
    # capacity first
    occupancy = jnp.zeros((num_experts,), dtype=jnp.int32)
    for slot in range(k):
        expert = topk_idx[:, slot]                           # [T]
        onehot = jax.nn.one_hot(expert, num_experts,
                                dtype=jnp.int32)             # [T, E]
        # position of each token within its chosen expert's buffer
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1
                         + occupancy[None, :])               # [T, E]
        pos = jnp.take_along_axis(
            pos_in_expert, expert[:, None], axis=1)[:, 0]    # [T]
        keep = pos < capacity
        pos_clamped = jnp.clip(pos, 0, capacity - 1)
        pos_onehot = jax.nn.one_hot(pos_clamped, capacity,
                                    dtype=gates.dtype)       # [T, C]
        slot_dispatch = (onehot.astype(gates.dtype)[:, :, None]
                         * pos_onehot[:, None, :]
                         * keep.astype(gates.dtype)[:, None, None])
        dispatch = dispatch + slot_dispatch
        occupancy = occupancy + onehot.sum(axis=0)
    combine = dispatch * gates[:, :, None]
    return dispatch, combine


def moe_ffn(x: jax.Array, router: jax.Array, w1: jax.Array,
            w3: jax.Array, w2: jax.Array, *, top_k: int = 2,
            capacity_factor: float = 2.0
            ) -> Tuple[jax.Array, jax.Array]:
    """Full MoE SwiGLU layer.

    ``x``: [B, S, D]; ``router``: [D, E]; expert weights stacked on a
    leading expert axis — ``w1``/``w3``: [E, D, H], ``w2``: [E, H, D].
    Shard the expert axis (PartitionSpec("expert", ...)) and GSPMD
    turns the dispatch/combine einsums into all-to-alls over ICI.
    Returns (y [B, S, D], aux_loss).
    """
    b, s, d = x.shape
    num_experts = router.shape[-1]
    tokens = x.reshape(b * s, d)
    gates, topk_idx, aux = top_k_gating(tokens, router, top_k)
    capacity = max(1, int(capacity_factor * top_k * (b * s) / num_experts))
    dispatch, combine = moe_dispatch(gates, topk_idx, num_experts,
                                     capacity)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)
    # [T,E,C] x [T,D] -> [E,C,D]: the all-to-all (tokens -> experts)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, tokens)
    # per-expert SwiGLU, batched over the (sharded) expert axis
    gate = jax.nn.silu(jnp.einsum("ecd,edh->ech", expert_in, w1))
    up = jnp.einsum("ecd,edh->ech", expert_in, w3)
    expert_out = jnp.einsum("ech,ehd->ecd", gate * up, w2)
    # [T,E,C] x [E,C,D] -> [T,D]: the all-to-all back (experts -> tokens)
    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y.reshape(b, s, d), aux
