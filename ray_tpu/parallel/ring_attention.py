"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no in-tree long-context support (SURVEY.md §5.7 — it
outsources TP/SP/CP to vLLM/DeepSpeed); here they are first-class. Two
schemes over the mesh's `seq` axis:

- **Ring attention** (blockwise attention + K/V rotation): each device
  keeps its Q shard, K/V shards rotate around the ring via
  `lax.ppermute` (ICI neighbor exchange), and softmax is accumulated
  online (log-sum-exp streaming), so full attention over sequences of
  length S costs O(S/n) memory per device and the K/V transfer overlaps
  compute rounds. Communication is nearest-neighbor — exactly the
  topology ICI is fastest at.

- **Ulysses**: `lax.all_to_all` reshards [B, S/n, H, D] → [B, S, H/n, D]
  so each device runs *full-sequence* attention on a head subset, then
  reshards back. Cheaper for moderate S with many heads; requires
  n_heads % n == 0.

Both run inside `shard_map` so XLA sees the collectives and schedules
them against compute.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel._compat import shard_map

NEG_INF = -1e30


def _block_attention(q, k, v, o, m, l, q_offset, kv_offset, causal, scale):
    """One streaming-softmax accumulation step.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; o: [B, Sq, H, D] accumulator;
    m/l: [B, H, Sq] running max / normalizer.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = kv_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    m_block = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_block)
    # Guard fully-masked rows (m_new == NEG_INF) against exp overflow.
    m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    alpha = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - m_safe)
    alpha = jnp.where(m <= NEG_INF / 2, 0.0, alpha)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None].swapaxes(1, 2) + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v)
    return o_new, m_new, l_new


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool):
    """Per-device body: rotate K/V around the ring, accumulate online."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    q_offset = idx * sq
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        kv_idx = (idx - i) % n
        kv_offset = kv_idx * k_blk.shape[1]
        o, m, l = _block_attention(q, k_blk, v_blk, o, m, l,
                                   q_offset, kv_offset, causal, scale)
        # Rotate AFTER use; XLA overlaps the ppermute with the next
        # round's einsum where possible.
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_blk, v_blk), None

    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, sq), dtype=jnp.float32)
    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k.astype(jnp.float32), v.astype(jnp.float32)),
        jnp.arange(n))
    l = jnp.maximum(l, 1e-20)
    out = o / l[..., None].swapaxes(1, 2)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, causal: bool = True,
                   axis_name: str = "seq"):
    """Full attention over a sequence sharded on ``axis_name``.

    q/k/v: [batch, seq, heads, head_dim], seq sharded across the mesh's
    ``seq`` axis (batch may additionally be sharded on data/fsdp — those
    axes pass through untouched).
    """
    spec = P(None, axis_name, None, None)
    fn = functools.partial(_ring_attention_local, axis_name=axis_name,
                           causal=causal)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool):
    n = lax.psum(1, axis_name)

    def scatter_heads(x):
        # [B, S/n, H, D] -> [B, S, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_seq(x):
        # [B, S, H/n, D] -> [B, S/n, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (d ** 0.5)
    if causal:
        sq = q.shape[1]
        mask = jnp.tril(jnp.ones((sq, sq), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return gather_seq(out)


def ulysses_attention(q, k, v, mesh: Mesh, *, causal: bool = True,
                      axis_name: str = "seq"):
    """Ulysses-style sequence parallelism (head-scatter all-to-all)."""
    n = 1
    for name, size in zip(mesh.axis_names, mesh.devices.shape):
        if name == axis_name:
            n = size
    if q.shape[2] % max(n, 1) != 0:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by seq axis "
            f"size ({n})")
    spec = P(None, axis_name, None, None)
    fn = functools.partial(_ulysses_local, axis_name=axis_name,
                           causal=causal)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def reference_attention(q, k, v, *, causal: bool = True):
    """Unsharded reference for correctness tests."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (d ** 0.5)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
