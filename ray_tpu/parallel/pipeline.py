"""Pipeline parallelism over the mesh's `pipe` axis.

The reference treats pipeline parallelism as configuration passed to
external engines (SURVEY.md §2.3 X4 — vLLM TP/PP passthrough,
vllm_models.py:214); here it is an in-tree transform. The schedule is
the classic GPipe rotation expressed as a `lax.scan` of
`lax.ppermute` steps inside `shard_map` (MPMD-over-SPMD, cf. arXiv
2412.14374): device i holds stage i's parameters; microbatches enter at
stage 0, activations hop to the ICI neighbor each tick, and outputs
drain from the last stage. Total ticks = n_micro + n_stages - 1, bubble
fraction (n_stages-1)/(n_micro+n_stages-1).

For a stage function f(stage_params, x) -> y with x and y of identical
shape (the transformer-block contract), `pipeline()` computes the
composition stage_{n-1} ∘ ... ∘ stage_0 over every microbatch.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def _pipeline_local(params, x, *, fn, axis_name: str):
    """Per-device pipeline loop. params: stage-local pytree (leading
    stage axis of size 1); x: [n_micro, mb, ...] full microbatch stack
    (replicated — only stage 0 reads it)."""
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), params)
    n_micro = x.shape[0]
    steps = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t (clamped; extra ticks feed dummies
        # whose outputs are never recorded).
        inject = x[jnp.minimum(t, n_micro - 1)]
        inp = jnp.where(stage == 0, inject, state)
        out = fn(params, inp)
        # Last stage drains microbatch t-(n_stages-1).
        mb_idx = t - (n_stages - 1)
        record = jnp.logical_and(stage == n_stages - 1, mb_idx >= 0)
        idx = jnp.maximum(mb_idx, 0)
        outputs = jnp.where(
            record,
            lax.dynamic_update_index_in_dim(outputs, out, idx, axis=0),
            outputs)
        state = lax.ppermute(out, axis_name, perm)
        return (state, outputs), None

    state0 = jnp.zeros_like(x[0])
    out0 = jnp.zeros_like(x)
    (_, outputs), _ = lax.scan(tick, (state0, out0), jnp.arange(steps))
    # Only the last stage holds real outputs; broadcast them to all
    # stages so the result is replicated over `pipe`.
    outputs = lax.psum(
        jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs


def pipeline(fn: Callable[[Any, jax.Array], jax.Array], stage_params: Any,
             x: jax.Array, mesh: Mesh, *, num_microbatches: int,
             axis_name: str = "pipe") -> jax.Array:
    """Run ``x`` through all pipeline stages.

    stage_params: pytree whose leaves have a leading ``n_stages`` axis
    (sharded over ``pipe``); x: [batch, ...] — split internally into
    ``num_microbatches``.
    """
    if x.shape[0] % num_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by num_microbatches "
            f"{num_microbatches}")
    mb = x.shape[0] // num_microbatches
    x_mb = x.reshape(num_microbatches, mb, *x.shape[1:])
    param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
    local = functools.partial(_pipeline_local, fn=fn, axis_name=axis_name)
    out = shard_map(
        local, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )(stage_params, x_mb)
    return out.reshape(x.shape[0], *out.shape[2:])
