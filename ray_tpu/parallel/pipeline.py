"""Pipeline parallelism over the mesh's `pipe` axis.

The reference treats pipeline parallelism as configuration passed to
external engines (SURVEY.md §2.3 X4 — vLLM TP/PP passthrough,
vllm_models.py:214); here it is an in-tree transform. The schedule is
the classic GPipe rotation expressed inside `shard_map` (MPMD-over-SPMD,
cf. arXiv 2412.14374): device i holds stage i's parameters; microbatches
enter at stage 0, activations hop to the ICI neighbor each tick, and
outputs drain from the last stage. Total ticks = n_micro + n_stages - 1,
bubble fraction (n_stages-1)/(n_micro+n_stages-1).

Memory layout (round-2 rework): the microbatch stack is SHARDED over
the pipe axis in a strided layout (device d holds microbatches d, d+S,
d+2S, ...), not replicated. Each round of S ticks all-gathers exactly
one microbatch per device for injection, and each drained output is
ppermuted from the last stage straight to its home device — so
per-device memory is O(batch/S) for inputs + outputs plus an O(S)
round buffer, and per-tick interconnect traffic stays at ~2 microbatch
activations (one ring hop, one gather/scatter share).

``remat=True`` wraps the stage function in jax.checkpoint so training
recomputes within-stage activations in the backward pass — the
activation-memory motivation behind 1F1B, in scan-compatible form.
(A literal 1F1B interleaving of forward/backward ticks requires a
hand-written custom_vjp schedule; under jax.grad the scan's backward
already runs ticks in reverse, and what remains live per tick is the
carried activation, which remat keeps to one microbatch per stage.)

Contract: f(stage_params, x) -> y with x and y of identical shape (the
transformer-block contract). Put shape-changing embed/unembed layers
outside the pipelined region.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel._compat import require_shard_map, shard_map


def _pipeline_local(params, x_local, *, fn, axis_name: str,
                    n_stages: int):
    """Per-device pipeline loop.

    params: stage-local pytree (leading stage axis of size 1);
    x_local: [R, 1, mb, ...] — this device's strided share of the
    microbatch stack (R = n_micro / n_stages rounds).
    """
    S = n_stages
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), params)
    x_local = jnp.squeeze(x_local, axis=1)          # [R, mb, ...]
    R = x_local.shape[0]
    ring = [(i, (i + 1) % S) for i in range(S)]

    def tick(state, out_local, inject, s, slot, valid):
        """One pipeline tick at static in-round offset ``s``: stage 0
        consumes ``inject``; the drained microbatch (if ``valid``) is
        ppermuted from the last stage to its home device and written at
        ``slot``."""
        inp = jnp.where(stage == 0, inject, state)
        out = fn(params, inp)
        home = (s + 1) % S  # drained microbatch m has m % S == home
        piece = lax.ppermute(out, axis_name, [(S - 1, home)])
        write = jnp.logical_and(valid, stage == home)
        out_local2 = jnp.where(
            write,
            lax.dynamic_update_index_in_dim(out_local, piece, slot, axis=0),
            out_local)
        state = lax.ppermute(out, axis_name, ring)
        return state, out_local2

    def round_body(carry, r):
        state, out_local = carry
        # one microbatch per device for this round: [S, mb, ...]
        round_buf = lax.all_gather(
            lax.dynamic_index_in_dim(x_local, r, 0, keepdims=False),
            axis_name, axis=0, tiled=False)
        for s in range(S):  # S is static: unrolled, ppermute perms static
            slot = r - 1 + (s + 1) // S
            valid = jnp.logical_or(r > 0, s == S - 1)
            state, out_local = tick(state, out_local, round_buf[s],
                                    s, slot, valid)
        return (state, out_local), None

    state0 = jnp.zeros_like(x_local[0])
    out0 = jnp.zeros_like(x_local)
    (state, out_local), _ = lax.scan(
        round_body, (state0, out0), jnp.arange(R))
    # drain: S-1 ticks with dummy injection; outputs land in slot R-1
    for k in range(S - 1):
        state, out_local = tick(state, out_local, state0, k,
                                R - 1, jnp.bool_(True))
    return out_local[:, None]                        # [R, 1, mb, ...]


def pipeline(fn: Callable[[Any, jax.Array], jax.Array], stage_params: Any,
             x: jax.Array, mesh: Mesh, *, num_microbatches: int,
             axis_name: str = "pipe", remat: bool = False) -> jax.Array:
    """Run ``x`` through all pipeline stages.

    stage_params: pytree whose leaves have a leading ``n_stages`` axis
    (sharded over ``pipe``); x: [batch, ...] — split internally into
    ``num_microbatches`` (must be a multiple of the pipe size so the
    strided input sharding is even). ``remat``: checkpoint the stage fn
    for training (backward recomputes within-stage activations).
    """
    require_shard_map()
    n_stages = mesh.shape[axis_name]
    if x.shape[0] % num_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by num_microbatches "
            f"{num_microbatches}")
    if num_microbatches % n_stages:
        raise ValueError(
            f"num_microbatches {num_microbatches} not divisible by the "
            f"pipe size {n_stages} (required for the strided input "
            "sharding)")
    mb = x.shape[0] // num_microbatches
    rounds = num_microbatches // n_stages
    x_mb = x.reshape(rounds, n_stages, mb, *x.shape[1:])
    param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
    body = jax.checkpoint(fn) if remat else fn
    local = functools.partial(_pipeline_local, fn=body,
                              axis_name=axis_name, n_stages=n_stages)
    out = shard_map(
        local, mesh=mesh,
        in_specs=(param_specs, P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False,
    )(stage_params, x_mb)
    return out.reshape(x.shape[0], *out.shape[3:])
