"""Parallelism primitives: meshes, sharding configs, collectives,
sequence/context parallelism, and pipeline parallelism.

Unlike the reference — which outsources TP/PP/SP/ring-attention to
external engines (SURVEY.md §5.7) — these are first-class library
components lowering to GSPMD mesh shardings, shard_map, and Pallas
kernels (SURVEY.md §2.3 X1–X7)."""

from ray_tpu.parallel.mesh import MeshSpec, make_mesh
from ray_tpu.parallel.sharding import (
    ShardingConfig,
    ShardingRules,
    infer_sharding,
    shard_pytree,
)
