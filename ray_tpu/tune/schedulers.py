"""Trial schedulers: FIFO, ASHA, PBT.

Capability parity with the reference's scheduler layer
(reference: python/ray/tune/schedulers/ — trial_scheduler.py decision
protocol, async_hyperband.py ASHAScheduler rung/cutoff logic,
pbt.py PopulationBasedTraining exploit/explore).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional


class TrialScheduler:
    CONTINUE = "CONTINUE"
    PAUSE = "PAUSE"
    STOP = "STOP"

    def set_search_properties(self, metric: str, mode: str) -> None:
        self.metric, self.mode = metric, mode

    def on_trial_result(self, controller, trial, result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, controller, trial,
                          result: Optional[Dict[str, Any]]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (reference: trial_scheduler.py)."""


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving
    (reference: python/ray/tune/schedulers/async_hyperband.py).

    Rungs at grace_period * reduction_factor^k iterations; when a trial
    reaches a rung, it continues only if its metric is within the top
    1/reduction_factor of completed results at that rung.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 3,
                 max_t: int = 100):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._rungs: Dict[float, List[float]] = {}
        self._trial_rung: Dict[str, int] = {}  # index of next rung per trial
        milestones = []
        t = float(grace_period)
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self._milestones = milestones

    def on_trial_result(self, controller, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return self.STOP
        metric = result.get(self.metric)
        if metric is None:
            return self.CONTINUE
        value = float(metric) if self.mode == "max" else -float(metric)
        # Record each rung once per trial, the first time t crosses it.
        next_rung = self._trial_rung.get(trial.trial_id, 0)
        while next_rung < len(self._milestones) \
                and t >= self._milestones[next_rung]:
            milestone = self._milestones[next_rung]
            next_rung += 1
            self._trial_rung[trial.trial_id] = next_rung
            recorded = self._rungs.setdefault(milestone, [])
            recorded.append(value)
            k = max(1, int(len(recorded) / self.rf))
            cutoff = sorted(recorded, reverse=True)[k - 1]
            if value < cutoff:
                return self.STOP
        return self.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: python/ray/tune/schedulers/pbt.py).

    Every perturbation_interval iterations, a trial in the bottom
    quantile exploits (checkpoint-copies) a top-quantile trial and
    explores by perturbing the mutated hyperparameters.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_probability = resample_probability
        self.rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}

    def _score(self, trial) -> Optional[float]:
        r = trial.last_result or {}
        if self.metric not in r:
            return None
        v = float(r[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, controller, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        if t - self._last_perturb.get(trial.trial_id, 0) < self.interval:
            return self.CONTINUE
        self._last_perturb[trial.trial_id] = t
        population = [tr for tr in controller.trials
                      if self._score(tr) is not None]
        if len(population) < 2:
            return self.CONTINUE
        ranked = sorted(population, key=self._score, reverse=True)
        n_q = max(1, int(math.ceil(len(ranked) * self.quantile)))
        top, bottom = ranked[:n_q], ranked[-n_q:]
        if trial not in bottom or trial in top:
            return self.CONTINUE
        donor = self.rng.choice(top)
        if donor is trial:
            return self.CONTINUE
        new_config = self._explore(dict(donor.config))
        controller.exploit(trial, donor, new_config)
        return self.CONTINUE

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search import Domain
        for key, spec in self.mutations.items():
            if key not in config:
                continue
            if isinstance(spec, Domain):
                if self.rng.random() < self.resample_probability:
                    config[key] = spec.sample(self.rng)
                else:
                    factor = self.rng.choice([0.8, 1.2])
                    if isinstance(config[key], (int, float)):
                        config[key] = type(config[key])(config[key] * factor)
            elif isinstance(spec, list):
                config[key] = self.rng.choice(spec)
            elif callable(spec):
                config[key] = spec()
        return config
