"""External HPO searcher adapters.

Capability parity with the reference's pluggable searcher integrations
(reference: python/ray/tune/search/optuna/optuna_search.py:127 — an
adapter translating Tune's param space into the external library's
ask/tell API, behind the same ``Searcher`` interface the in-tree
searchers implement). Libraries import lazily: the adapter is always
importable; constructing it without the library installed raises with
an install hint. Flat ``Domain`` dimensions are driven by the external
optimizer; nested dicts / grid_search / sample_from fall back to the
same random resolution the in-tree TPESearcher uses.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ray_tpu.tune.search import (
    Categorical,
    Float,
    Integer,
    Searcher,
    flat_domains,
    random_grid_assignment,
    resolve_config,
)


class OptunaSearch(Searcher):
    """optuna-backed suggestions over the flat Domain dimensions
    (reference: OptunaSearch wrapping an optuna.Study via ask/tell)."""

    def __init__(self, num_samples: int = 32, sampler=None,
                 seed: Optional[int] = None):
        try:
            import optuna
        except ImportError as err:
            raise ImportError(
                "OptunaSearch requires the 'optuna' package "
                "(pip install optuna)") from err
        self._optuna = optuna
        self.num_samples = num_samples
        self._sampler = sampler
        self._seed = seed
        self.rng = random.Random(seed)
        self._study = None
        self._trials: Dict[str, Any] = {}
        self._suggested = 0

    def _ensure_study(self):
        if self._study is None:
            direction = ("maximize" if getattr(self, "mode", "max") == "max"
                         else "minimize")
            sampler = self._sampler or self._optuna.samplers.TPESampler(
                seed=self._seed)
            self._study = self._optuna.create_study(
                direction=direction, sampler=sampler)
        return self._study

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        study = self._ensure_study()
        trial = study.ask()
        # Ask optuna FIRST, then resolve the space with the suggestions
        # substituted as literals — so sample_from entries depending on
        # optuna-driven dimensions see the final values, not a discarded
        # random draw (they resolve after their siblings).
        space = dict(self.param_space)
        for key, dom in flat_domains(self.param_space).items():
            if isinstance(dom, Float):
                space[key] = trial.suggest_float(
                    key, dom.lower, dom.upper, log=dom.log)
            elif isinstance(dom, Integer):
                # ray_tpu Integer is [lower, upper); optuna inclusive
                space[key] = trial.suggest_int(key, dom.lower,
                                               dom.upper - 1)
            elif isinstance(dom, Categorical):
                space[key] = trial.suggest_categorical(
                    key, dom.categories)
        grid = random_grid_assignment(space, self.rng)
        cfg = resolve_config(space, self.rng, grid)
        self._trials[trial_id] = trial
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        trial = self._trials.pop(trial_id, None)
        if trial is None:
            return
        if result is None or self.metric not in result:
            self._study.tell(trial,
                             state=self._optuna.trial.TrialState.FAIL)
            return
        self._study.tell(trial, float(result[self.metric]))
