"""Search spaces and search algorithms.

Capability parity with the reference's Tune search layer
(reference: python/ray/tune/search/ — sample.py distributions,
basic_variant.py BasicVariantGenerator grid/random expansion,
searcher.py Searcher ABC). Model-based searchers in the reference
(hyperopt/optuna/bayesopt) are external-library adapters; here a
dependency-free TPE-style searcher (`TPESearcher`) fills that slot.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, List, Optional, Tuple


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError

    # Seam for model-based searchers: map to/from the unit interval.
    def to_unit(self, value: Any) -> float:
        raise NotImplementedError

    def from_unit(self, u: float) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log = float(lower), float(upper), log

    def sample(self, rng: random.Random) -> float:
        return self.from_unit(rng.random())

    def to_unit(self, value: Any) -> float:
        if self.log:
            return (math.log(value) - math.log(self.lower)) / (
                math.log(self.upper) - math.log(self.lower))
        return (value - self.lower) / (self.upper - self.lower)

    def from_unit(self, u: float) -> float:
        u = min(max(u, 0.0), 1.0)
        if self.log:
            return math.exp(math.log(self.lower)
                            + u * (math.log(self.upper) - math.log(self.lower)))
        return self.lower + u * (self.upper - self.lower)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = int(lower), int(upper)  # [lower, upper)

    def sample(self, rng: random.Random) -> int:
        return rng.randrange(self.lower, self.upper)

    def to_unit(self, value: Any) -> float:
        span = max(self.upper - 1 - self.lower, 1)
        return (value - self.lower) / span

    def from_unit(self, u: float) -> int:
        u = min(max(u, 0.0), 1.0)
        return min(self.upper - 1,
                   self.lower + int(u * (self.upper - self.lower)))


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        if not categories:
            raise ValueError("choice() needs at least one option")
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)

    def to_unit(self, value: Any) -> float:
        idx = self.categories.index(value)
        return (idx + 0.5) / len(self.categories)

    def from_unit(self, u: float) -> Any:
        idx = min(len(self.categories) - 1,
                  int(min(max(u, 0.0), 1.0) * len(self.categories)))
        return self.categories[idx]


class SampleFrom(Domain):
    def __init__(self, fn: Callable[[dict], Any]):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:  # resolved late, with config
        raise NotImplementedError("SampleFrom is resolved against the config")


# -- public space constructors (reference: ray.tune.{uniform,choice,...}) --

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[dict], Any]) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


def _is_grid(spec: Any) -> bool:
    return isinstance(spec, dict) and set(spec.keys()) == {"grid_search"}


def resolve_config(param_space: Dict[str, Any], rng: random.Random,
                   grid_assignment: Optional[Dict[str, Any]] = None,
                   ) -> Dict[str, Any]:
    """Resolve one concrete config from a (possibly nested) param space."""
    grid_assignment = grid_assignment or {}

    def _resolve(space: Dict[str, Any], prefix: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        deferred: List[Tuple[str, SampleFrom]] = []
        for key, spec in space.items():
            path = f"{prefix}{key}"
            if _is_grid(spec):
                out[key] = grid_assignment[path]
            elif isinstance(spec, SampleFrom):
                deferred.append((key, spec))
            elif isinstance(spec, Domain):
                out[key] = spec.sample(rng)
            elif isinstance(spec, dict):
                out[key] = _resolve(spec, path + "/")
            else:
                out[key] = spec
        for key, spec in deferred:  # after siblings, so fn sees them
            out[key] = spec.fn(out)
        return out

    return _resolve(param_space, "")


def grid_axes(param_space: Dict[str, Any], prefix: str = "",
              ) -> List[Tuple[str, List[Any]]]:
    axes: List[Tuple[str, List[Any]]] = []
    for key, spec in param_space.items():
        path = f"{prefix}{key}"
        if _is_grid(spec):
            axes.append((path, spec["grid_search"]))
        elif isinstance(spec, dict) and not _is_grid(spec):
            axes.extend(grid_axes(spec, path + "/"))
    return axes


def flat_domains(param_space: Dict[str, Any]) -> Dict[str, "Domain"]:
    """Top-level Domain dimensions an external/model-based searcher can
    drive directly (nested dicts / grid_search / sample_from fall back
    to random resolution)."""
    return {k: v for k, v in param_space.items()
            if isinstance(v, Domain) and not isinstance(v, SampleFrom)}


def random_grid_assignment(param_space: Dict[str, Any],
                           rng: random.Random) -> Dict[str, Any]:
    return {path: rng.choice(vals)
            for path, vals in grid_axes(param_space)}


class Searcher:
    """ABC (reference: python/ray/tune/search/searcher.py).

    suggest() returns a concrete config (or None = exhausted);
    on_trial_complete feeds the final score back.
    """

    def set_search_properties(self, metric: str, mode: str,
                              param_space: Dict[str, Any]) -> None:
        self.metric, self.mode, self.param_space = metric, mode, param_space

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product x num_samples random sampling
    (reference: python/ray/tune/search/basic_variant.py)."""

    def __init__(self, num_samples: int = 1, seed: Optional[int] = None):
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._queue: Optional[List[Dict[str, Any]]] = None

    def _build_queue(self) -> List[Dict[str, Any]]:
        axes = grid_axes(self.param_space)
        combos: List[Dict[str, Any]] = [{}]
        if axes:
            names = [n for n, _ in axes]
            combos = [dict(zip(names, vals)) for vals in
                      itertools.product(*[vs for _, vs in axes])]
        configs = []
        for _ in range(self.num_samples):
            for assignment in combos:
                configs.append(resolve_config(self.param_space, self.rng,
                                              assignment))
        return configs

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._queue is None:
            self._queue = self._build_queue()
        return self._queue.pop(0) if self._queue else None

    def total_trials(self) -> int:
        if self._queue is None:
            self._queue = self._build_queue()
        return len(self._queue)


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator over flat Domain spaces — the
    in-tree stand-in for the reference's external model-based searchers
    (reference: python/ray/tune/search/{hyperopt,optuna}/). Nested dicts
    and grid_search entries fall back to random sampling.

    Candidates are scored by the density ratio l(x)/g(x) of Gaussian
    kernel estimates fit to the good / bad halves of observed trials,
    per-dimension in unit space.
    """

    def __init__(self, num_samples: int = 32, n_startup: int = 8,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: Optional[int] = None):
        self.num_samples = num_samples
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._suggested = 0
        self._pending: Dict[str, Dict[str, Any]] = {}
        self._observed: List[Tuple[Dict[str, Any], float]] = []

    def _flat_domains(self) -> Dict[str, Domain]:
        return flat_domains(self.param_space)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        domains = self._flat_domains()
        if len(self._observed) < self.n_startup or not domains:
            cfg = resolve_config(self.param_space, self.rng,
                                 self._random_grid_assignment())
            self._pending[trial_id] = cfg
            return cfg
        ranked = sorted(self._observed, key=lambda o: o[1],
                        reverse=(self.mode == "max"))
        n_good = max(1, int(self.gamma * len(ranked)))
        good, bad = ranked[:n_good], ranked[n_good:] or ranked[:1]

        def density(us: List[float], u: float) -> float:
            bw = max(0.1, 1.0 / max(len(us), 1) ** 0.5)
            return sum(math.exp(-0.5 * ((u - x) / bw) ** 2)
                       for x in us) / (len(us) * bw) + 1e-12

        cfg = resolve_config(self.param_space, self.rng,
                             self._random_grid_assignment())
        for key, dom in domains.items():
            good_us = [dom.to_unit(c[key]) for c, _ in good if key in c]
            bad_us = [dom.to_unit(c[key]) for c, _ in bad if key in c]
            best_u, best_score = None, -math.inf
            for _ in range(self.n_candidates):
                base = self.rng.choice(good_us) if good_us else self.rng.random()
                u = min(max(base + self.rng.gauss(0, 0.15), 0.0), 1.0)
                score = math.log(density(good_us, u)) - math.log(
                    density(bad_us, u))
                if score > best_score:
                    best_u, best_score = u, score
            cfg[key] = dom.from_unit(best_u)
        self._pending[trial_id] = cfg
        return cfg

    def _random_grid_assignment(self) -> Dict[str, Any]:
        return random_grid_assignment(self.param_space, self.rng)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or result is None or self.metric not in result:
            return
        self._observed.append((cfg, float(result[self.metric])))


class BayesOptSearcher(Searcher):
    """Native Gaussian-process Bayesian optimization — the in-tree
    equivalent of the reference's bayesopt searcher
    (reference: python/ray/tune/search/bayesopt/bayesopt_search.py:41,
    which wraps the external `bayesian-optimization` package; here the
    GP is ~60 lines of numpy, so the common case needs no external
    dependency — the OptunaSearch adapter seam remains for the rest).

    All flat domains map to the unit cube (log/int/categorical via
    Domain.to_unit); an RBF-kernel GP posterior over observed trials
    scores random candidates by expected improvement. Nested dicts and
    grid entries fall back to random sampling, like TPESearcher.
    """

    def __init__(self, num_samples: int = 32, n_startup: int = 6,
                 n_candidates: int = 256, length_scale: float = 0.2,
                 noise: float = 1e-4, xi: float = 0.01,
                 seed: Optional[int] = None):
        self.num_samples = num_samples
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        self.noise = noise
        self.xi = xi
        self.rng = random.Random(seed)
        self._suggested = 0
        self._pending: Dict[str, Dict[str, Any]] = {}
        self._observed: List[Tuple[Dict[str, Any], float]] = []

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        domains = flat_domains(self.param_space)
        cfg = resolve_config(self.param_space, self.rng,
                             random_grid_assignment(self.param_space,
                                                    self.rng))
        if len(self._observed) >= self.n_startup and domains:
            u = self._acquire(domains)
            for i, (key, dom) in enumerate(sorted(domains.items())):
                cfg[key] = dom.from_unit(u[i])
        self._pending[trial_id] = cfg
        return cfg

    def _acquire(self, domains: Dict[str, Domain]):
        import numpy as np

        keys = sorted(domains)
        sign = 1.0 if self.mode == "max" else -1.0
        xs, ys = [], []
        for cfg, score in self._observed:
            if not all(k in cfg for k in keys):
                continue
            xs.append([domains[k].to_unit(cfg[k]) for k in keys])
            ys.append(sign * score)
        X = np.asarray(xs, dtype=np.float64)        # [n, d]
        y = np.asarray(ys, dtype=np.float64)
        y_mean, y_std = y.mean(), max(y.std(), 1e-9)
        y = (y - y_mean) / y_std

        def rbf(A, B):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / self.length_scale ** 2)

        K = rbf(X, X) + self.noise * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, y))
        best = y.max()

        cand = np.asarray(
            [[self.rng.random() for _ in keys]
             for _ in range(self.n_candidates)])           # [m, d]
        Kc = rbf(cand, X)                                  # [m, n]
        mu = Kc @ alpha
        v = np.linalg.solve(L, Kc.T)                       # [n, m]
        var = np.maximum(1.0 - (v ** 2).sum(0), 1e-12)
        sigma = np.sqrt(var)
        z = (mu - best - self.xi) / sigma
        # standard-normal pdf/cdf without scipy
        pdf = np.exp(-0.5 * z ** 2) / math.sqrt(2 * math.pi)
        cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2)))
        ei = (mu - best - self.xi) * cdf + sigma * pdf
        return cand[int(np.argmax(ei))]

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or result is None or self.metric not in result:
            return
        self._observed.append((cfg, float(result[self.metric])))
