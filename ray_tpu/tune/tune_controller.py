"""The Tune controller: drives trial actors to completion.

Capability parity with the reference's execution layer (reference:
python/ray/tune/execution/tune_controller.py TuneController — trial
lifecycle, searcher/scheduler hooks, failure retry with
checkpoint-restore, periodic experiment snapshots). Trials are actors on
the core runtime; each `train()` is one actor call, so many trials step
concurrently and the controller multiplexes with `wait()`.
"""

from __future__ import annotations

import logging
import os
import uuid
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.core import serialization
from ray_tpu.exceptions import RayTpuError
from ray_tpu.tune import experiment as exp_mod
from ray_tpu.tune.experiment import ExperimentState, Trial
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import Searcher

logger = logging.getLogger(__name__)


class _TrialRunner:
    """Actor hosting one trial's Trainable."""

    def __init__(self, trainable_blob: bytes, config: Dict[str, Any]):
        cls = serialization.loads(trainable_blob)
        self.trainable = cls(config)

    def train(self) -> Dict[str, Any]:
        return self.trainable.train()

    def save(self, checkpoint_root: str) -> Optional[str]:
        return self.trainable.save(checkpoint_root)

    def restore(self, path: str) -> None:
        self.trainable.restore(path)

    def reset(self, config: Dict[str, Any]) -> bool:
        return self.trainable.reset(config)

    def stop(self) -> None:
        self.trainable.stop()


class TuneController:
    def __init__(self, trainable_cls: type, *,
                 searcher: Searcher,
                 scheduler: Optional[TrialScheduler],
                 metric: str, mode: str,
                 experiment_dir: str,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 max_concurrent: Optional[int] = None,
                 stop: Union[None, Dict[str, Any], Callable] = None,
                 max_failures: int = 0,
                 checkpoint_freq: int = 0,
                 restored_trials: Optional[List[Trial]] = None):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.trainable_blob = serialization.dumps(trainable_cls)
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_search_properties(metric, mode)
        self.metric, self.mode = metric, mode
        self.experiment_dir = experiment_dir
        self.resources = dict(resources_per_trial or {"CPU": 1})
        self.stop_criteria = stop
        self.max_failures = max_failures
        self.checkpoint_freq = checkpoint_freq
        self.trials: List[Trial] = list(restored_trials or [])
        self.state = ExperimentState(experiment_dir)
        self._actors: Dict[str, Any] = {}
        self._inflight: Dict[Any, Trial] = {}  # train() ref -> trial
        if max_concurrent is None:
            cpus = ray_tpu.cluster_resources().get("CPU", 1.0)
            per = self.resources.get("CPU", 1.0) or 1.0
            max_concurrent = max(1, int(cpus // per))
        self.max_concurrent = max_concurrent
        # A restored experiment resumes its existing trials; the searcher
        # is not re-run (reference: Tuner.restore resumes, param_space
        # changes require a new experiment).
        self._exhausted = restored_trials is not None

    # -- trial lifecycle --

    def _next_trial(self) -> Optional[Trial]:
        runnable = [t for t in self.trials
                    if t.status in (exp_mod.PENDING, exp_mod.PAUSED)]
        if runnable:
            return runnable[0]
        if self._exhausted:
            return None
        trial_id = f"trial_{len(self.trials):05d}_{uuid.uuid4().hex[:6]}"
        config = self.searcher.suggest(trial_id)
        if config is None:
            self._exhausted = True
            return None
        trial = Trial(trial_id=trial_id, config=config,
                      local_dir=os.path.join(self.experiment_dir, trial_id))
        os.makedirs(trial.local_dir, exist_ok=True)
        self.trials.append(trial)
        return trial

    def _make_actor(self, config: Dict[str, Any]):
        Runner = ray_tpu.remote(_TrialRunner)
        opts: Dict[str, Any] = {}
        if "CPU" in self.resources:
            opts["num_cpus"] = self.resources["CPU"]
        if "TPU" in self.resources:
            opts["num_tpus"] = self.resources["TPU"]
        return Runner.options(**opts).remote(self.trainable_blob, config)

    def _start_trial(self, trial: Trial) -> None:
        actor = self._make_actor(trial.config)
        if trial.checkpoint_path:
            ray_tpu.get(actor.restore.remote(trial.checkpoint_path))
        self._actors[trial.trial_id] = actor
        trial.status = exp_mod.RUNNING
        self._submit_train(trial)

    def _submit_train(self, trial: Trial) -> None:
        ref = self._actors[trial.trial_id].train.remote()
        self._inflight[ref] = trial

    def _terminate_trial(self, trial: Trial, status: str,
                         error: Optional[str] = None) -> None:
        trial.status = status
        trial.error_msg = error
        actor = self._actors.pop(trial.trial_id, None)
        if actor is not None:
            try:
                # fire-and-forget pre-kill stop nudge; the actor dies
                # right after, so nobody can hold the result
                actor.stop.remote()  # graftlint: disable=GL015
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001 — trial actor already dead
                logger.debug("trial teardown kill failed", exc_info=True)
        self.searcher.on_trial_complete(trial.trial_id, trial.last_result)
        self.scheduler.on_trial_complete(self, trial, trial.last_result)

    def _should_stop(self, trial: Trial, result: Dict[str, Any]) -> bool:
        if result.get("done"):
            return True
        stop = self.stop_criteria
        if stop is None:
            return False
        if callable(stop):
            return bool(stop(trial.trial_id, result))
        return any(k in result and result[k] >= v for k, v in stop.items())

    def _checkpoint_trial(self, trial: Trial) -> None:
        path = ray_tpu.get(
            self._actors[trial.trial_id].save.remote(trial.local_dir))
        if path:
            trial.checkpoint_path = path

    # -- PBT hook (reference: pbt.py _exploit) --

    def exploit(self, trial: Trial, donor: Trial,
                new_config: Dict[str, Any]) -> None:
        donor_actor = self._actors.get(donor.trial_id)
        if donor_actor is None:
            return
        donor_path = ray_tpu.get(donor_actor.save.remote(donor.local_dir))
        if donor_path:
            donor.checkpoint_path = donor_path
        trial.config = dict(new_config)
        actor = self._actors[trial.trial_id]
        reset_ok = ray_tpu.get(actor.reset.remote(new_config))
        if not reset_ok:
            # Replace the actor (trainable can't reconfigure in place).
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001 — trial actor already dead
                logger.debug("exploit kill failed", exc_info=True)
            actor = self._make_actor(new_config)
            self._actors[trial.trial_id] = actor
        if donor_path:
            ray_tpu.get(actor.restore.remote(donor_path))
            trial.checkpoint_path = donor_path

    # -- main loop --

    def run(self) -> List[Trial]:
        step = 0
        while True:
            self._fill()
            if not self._inflight:
                break
            ready, _ = ray_tpu.wait(list(self._inflight.keys()),
                                    num_returns=1, timeout=60.0)
            for ref in ready:
                trial = self._inflight.pop(ref)
                self._process(trial, ref)
            step += 1
            if step % 10 == 0:
                self.state.save(self.trials)
        self.state.save(self.trials)
        return self.trials

    def _fill(self) -> None:
        while len(self._inflight) < self.max_concurrent:
            trial = self._next_trial()
            if trial is None:
                break
            try:
                self._start_trial(trial)
            except RayTpuError as e:
                self._terminate_trial(trial, exp_mod.ERROR, str(e))

    def _process(self, trial: Trial, ref) -> None:
        try:
            result = ray_tpu.get(ref)
        except RayTpuError as e:
            trial.num_failures += 1
            # The actor may still be alive (app-level exception) and
            # holding its resource reservation — always kill it.
            actor = self._actors.pop(trial.trial_id, None)
            if actor is not None:
                try:
                    ray_tpu.kill(actor)
                except Exception:  # noqa: BLE001 — actor already dead
                    logger.debug("failed-trial kill failed",
                                 exc_info=True)
            if trial.num_failures <= self.max_failures:
                trial.status = exp_mod.PENDING  # restart from checkpoint
                return
            self._terminate_trial(trial, exp_mod.ERROR, str(e))
            return
        trial.last_result = result
        trial.metrics_history.append(result)
        if (self.checkpoint_freq
                and result.get("training_iteration", 0)
                % self.checkpoint_freq == 0):
            self._checkpoint_trial(trial)
        if self._should_stop(trial, result):
            self._terminate_trial(trial, exp_mod.TERMINATED)
            return
        decision = self.scheduler.on_trial_result(self, trial, result)
        if decision == TrialScheduler.STOP:
            self._terminate_trial(trial, exp_mod.TERMINATED)
        elif decision == TrialScheduler.PAUSE:
            self._checkpoint_trial(trial)
            actor = self._actors.pop(trial.trial_id, None)
            if actor is not None:
                try:
                    ray_tpu.kill(actor)
                except Exception:  # noqa: BLE001 — actor already dead
                    logger.debug("pause kill failed", exc_info=True)
            trial.status = exp_mod.PAUSED
        else:
            self._submit_train(trial)
