"""Tuner: the public HPO entry point.

Capability parity with the reference's Tuner API (reference:
python/ray/tune/tuner.py:312 Tuner.fit; tune/tune.py run;
tune/result_grid.py ResultGrid). Accepts class trainables, function
trainables, and JaxTrainer instances (trainer-as-trainable, the
reference's Tuner(trainer) pattern).
"""

from __future__ import annotations

import inspect
import os
import tempfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import Result, RunConfig
from ray_tpu.tune import experiment as exp_mod
from ray_tpu.tune.experiment import ExperimentState, Trial
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.tune_controller import TuneController


@dataclass
class TuneConfig:
    """reference: python/ray/tune/tune_config.py"""
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    seed: Optional[int] = None


class _BudgetedSearcher(Searcher):
    """Caps a user-supplied searcher at TuneConfig.num_samples trials."""

    def __init__(self, inner: Searcher, num_samples: int):
        self.inner = inner
        self.num_samples = num_samples
        self._suggested = 0

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        self.inner.set_search_properties(metric, mode, param_space)

    def suggest(self, trial_id):
        if self._suggested >= self.num_samples:
            return None
        cfg = self.inner.suggest(trial_id)
        if cfg is not None:
            self._suggested += 1
        return cfg

    def on_trial_complete(self, trial_id, result):
        self.inner.on_trial_complete(trial_id, result)


@dataclass
class ResultGrid:
    """reference: python/ray/tune/result_grid.py"""
    results: List[Result] = field(default_factory=list)
    trials: List[Trial] = field(default_factory=list)
    experiment_path: str = ""

    def __len__(self) -> int:
        return len(self.results)

    def __getitem__(self, i: int) -> Result:
        return self.results[i]

    @property
    def errors(self) -> List[str]:
        return [t.error_msg for t in self.trials if t.error_msg]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self.results if metric in (r.metrics or {})]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd
        return pd.DataFrame([dict(r.metrics, trial_id=t.trial_id)
                             for r, t in zip(self.results, self.trials)])


def _as_trainable_cls(trainable: Any) -> type:
    from ray_tpu.train.trainer import JaxTrainer
    if isinstance(trainable, JaxTrainer):
        return _trainer_trainable(trainable)
    if inspect.isclass(trainable) and issubclass(trainable, Trainable):
        return trainable
    if callable(trainable):
        return wrap_function(trainable)
    raise TypeError(f"not a trainable: {trainable!r}")


def _trainer_trainable(trainer) -> type:
    """Tuner(JaxTrainer) support: each trial runs trainer.fit() with the
    trial config merged into train_loop_config (reference:
    tuner_internal.py converting trainers to trainables)."""

    def run_trainer(config: Dict[str, Any]) -> None:
        import copy
        from ray_tpu.tune.trainable import report
        t = copy.copy(trainer)
        merged = dict(trainer.train_loop_config or {})
        merged.update(config)
        t.train_loop_config = merged
        result = t.fit()
        if result.error is not None:
            raise result.error
        for metrics in (result.metrics_history or [result.metrics]):
            report(metrics)

    return wrap_function(run_trainer)


class Tuner:
    def __init__(self, trainable: Union[type, Callable, Any],
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 stop: Union[None, Dict[str, Any], Callable] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 max_failures: int = 0,
                 checkpoint_freq: int = 1,
                 _restored_trials: Optional[List[Trial]] = None):
        self.trainable_cls = _as_trainable_cls(trainable)
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig(name="tune_run")
        self.stop = stop
        self.resources_per_trial = resources_per_trial
        self.max_failures = max_failures
        self.checkpoint_freq = checkpoint_freq
        self._restored_trials = _restored_trials

    def _experiment_dir(self) -> str:
        base = self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results")
        name = self.run_config.name or "tune_run"
        path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)
        return path

    def fit(self) -> ResultGrid:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cfg = self.tune_config
        if cfg.search_alg is not None:
            # num_samples caps searcher-driven runs too (reference:
            # tune_config.num_samples governs every search_alg).
            searcher = _BudgetedSearcher(cfg.search_alg, cfg.num_samples)
        else:
            searcher = BasicVariantGenerator(
                num_samples=cfg.num_samples, seed=cfg.seed)
        searcher.set_search_properties(cfg.metric, cfg.mode,
                                       self.param_space)
        exp_dir = self._experiment_dir()
        controller = TuneController(
            self.trainable_cls, searcher=searcher, scheduler=cfg.scheduler,
            metric=cfg.metric, mode=cfg.mode, experiment_dir=exp_dir,
            resources_per_trial=self.resources_per_trial,
            max_concurrent=cfg.max_concurrent_trials, stop=self.stop,
            max_failures=self.max_failures,
            checkpoint_freq=self.checkpoint_freq,
            restored_trials=self._restored_trials)
        trials = controller.run()
        results = [
            Result(metrics=t.last_result or {},
                   checkpoint=(Checkpoint(t.checkpoint_path)
                               if t.checkpoint_path else None),
                   path=t.local_dir,
                   error=(RuntimeError(t.error_msg) if t.error_msg else None),
                   metrics_history=t.metrics_history)
            for t in trials
        ]
        grid = ResultGrid(results=results, trials=trials,
                          experiment_path=exp_dir)
        grid._metric, grid._mode = cfg.metric, cfg.mode
        return grid

    @classmethod
    def restore(cls, path: str, trainable: Union[type, Callable, Any],
                **kwargs) -> "Tuner":
        """Resume an interrupted experiment from its state snapshot
        (reference: tuner.py Tuner.restore). Unfinished trials restart
        (from their last checkpoint when one exists)."""
        trials = ExperimentState(path).load()
        if trials is None:
            raise FileNotFoundError(f"no experiment state under {path}")
        for t in trials:
            if t.status in (exp_mod.RUNNING, exp_mod.PAUSED):
                t.status = exp_mod.PENDING
        run_config = kwargs.pop("run_config", None) or RunConfig(
            name=os.path.basename(path), storage_path=os.path.dirname(path))
        return cls(trainable, run_config=run_config,
                   _restored_trials=trials, **kwargs)


def run(trainable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: str = "loss", mode: str = "min",
        stop=None, search_alg=None, scheduler=None,
        resources_per_trial=None, **kwargs) -> ResultGrid:
    """Functional entry point (reference: ray.tune.run)."""
    tuner = Tuner(trainable, param_space=config or {},
                  tune_config=TuneConfig(metric=metric, mode=mode,
                                         num_samples=num_samples,
                                         search_alg=search_alg,
                                         scheduler=scheduler),
                  stop=stop, resources_per_trial=resources_per_trial,
                  **kwargs)
    return tuner.fit()
