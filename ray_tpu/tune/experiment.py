"""Trials and experiment state.

Capability parity with the reference's experiment layer (reference:
python/ray/tune/experiment/trial.py Trial states + metadata;
tune/execution/experiment_state.py periodic experiment checkpointing so
``Tuner.restore`` resumes interrupted runs).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    last_result: Optional[Dict[str, Any]] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint_path: Optional[str] = None
    error_msg: Optional[str] = None
    num_failures: int = 0
    local_dir: str = ""

    def to_json(self) -> dict:
        return {
            "trial_id": self.trial_id,
            "config": _jsonable(self.config),
            "status": self.status,
            "last_result": _jsonable(self.last_result),
            "metrics_history": _jsonable(self.metrics_history),
            "checkpoint_path": self.checkpoint_path,
            "error_msg": self.error_msg,
            "num_failures": self.num_failures,
            "local_dir": self.local_dir,
        }

    @staticmethod
    def from_json(d: dict) -> "Trial":
        return Trial(trial_id=d["trial_id"], config=d["config"],
                     status=d["status"], last_result=d["last_result"],
                     metrics_history=d.get("metrics_history") or [],
                     checkpoint_path=d.get("checkpoint_path"),
                     error_msg=d.get("error_msg"),
                     num_failures=d.get("num_failures", 0),
                     local_dir=d.get("local_dir", ""))


def _jsonable(obj: Any) -> Any:
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {k: _jsonable(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [_jsonable(v) for v in obj]
        return repr(obj)


class ExperimentState:
    """Periodic JSON snapshot of all trials for resume."""

    FILENAME = "experiment_state.json"

    def __init__(self, experiment_dir: str):
        self.experiment_dir = experiment_dir
        os.makedirs(experiment_dir, exist_ok=True)

    def save(self, trials: List[Trial]) -> None:
        payload = {"saved_at": time.time(),
                   "trials": [t.to_json() for t in trials]}
        tmp = os.path.join(self.experiment_dir, self.FILENAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, os.path.join(self.experiment_dir, self.FILENAME))

    def load(self) -> Optional[List[Trial]]:
        path = os.path.join(self.experiment_dir, self.FILENAME)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            payload = json.load(f)
        return [Trial.from_json(d) for d in payload["trials"]]

    @staticmethod
    def exists(experiment_dir: str) -> bool:
        return os.path.exists(os.path.join(experiment_dir,
                                           ExperimentState.FILENAME))
