"""ray_tpu.tune — trial-based hyperparameter optimization.

Capability parity with Ray Tune (reference: python/ray/tune/ — Tuner,
search spaces, searchers, trial schedulers, experiment checkpointing)
running on ray_tpu actors.
"""

from ray_tpu.tune.experiment import Trial
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.external import OptunaSearch
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    Searcher,
    BayesOptSearcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import (
    Trainable,
    get_checkpoint,
    report,
    wrap_function,
)
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner, run

__all__ = [
    "ASHAScheduler", "BasicVariantGenerator", "FIFOScheduler",
    "OptunaSearch", "PopulationBasedTraining", "ResultGrid", "Searcher",
    "BayesOptSearcher",
    "TPESearcher",
    "Trainable", "TrialScheduler", "TuneConfig", "Tuner", "choice",
    "get_checkpoint", "grid_search", "loguniform", "randint", "report",
    "run", "sample_from", "uniform", "wrap_function",
]
