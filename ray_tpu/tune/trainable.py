"""Trainable API: class trainables and function trainables.

Capability parity with the reference's trainable layer (reference:
python/ray/tune/trainable/trainable.py Trainable setup/step/save/restore;
function_trainable.py — function API running in a thread, reporting
through a session). ``tune.report`` inside a function trainable hands
metrics (and optionally a checkpoint) to the controller one iteration at
a time.
"""

from __future__ import annotations

import os
import queue
import shutil
import tempfile
import threading
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


class Trainable:
    """Class API: subclass and override setup/step/save/load."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = dict(config or {})
        self.iteration = 0
        self.setup(self.config)

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[str]:
        return None

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Return True if the trainable reconfigured in place (enables
        actor reuse for PBT; reference: trainable.py reset_config)."""
        return False

    def stop(self) -> None:
        pass

    # -- controller-facing driver methods --

    def train(self) -> Dict[str, Any]:
        result = self.step() or {}
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        return result

    def save(self, checkpoint_root: str) -> Optional[str]:
        path = os.path.join(checkpoint_root,
                            f"checkpoint_{self.iteration:06d}")
        os.makedirs(path, exist_ok=True)
        self.save_checkpoint(path)
        if not os.listdir(path):
            # Nothing to save (e.g. a function trainable that never
            # reported a checkpoint) — no phantom checkpoint dirs.
            os.rmdir(path)
            return None
        with open(os.path.join(path, ".tune_metadata"), "w") as f:
            f.write(str(self.iteration))
        return path

    def restore(self, checkpoint_path: str) -> None:
        meta = os.path.join(checkpoint_path, ".tune_metadata")
        if os.path.exists(meta):
            with open(meta) as f:
                self.iteration = int(f.read())
        self.load_checkpoint(checkpoint_path)

    def reset(self, new_config: Dict[str, Any]) -> bool:
        if self.reset_config(new_config):
            self.config = dict(new_config)
            return True
        return False


class _FnSession:
    """Per-process session a running trainable function reports into.

    The queue is bounded so report() applies backpressure: the function
    thread cannot race iterations ahead of the controller, which would
    waste compute past an early-stop decision and leak checkpoint copies
    (reference: function trainables block in session.report until the
    result is consumed)."""

    def __init__(self, resume_checkpoint: Optional[Checkpoint]):
        self.results: "queue.Queue" = queue.Queue(maxsize=2)
        self.resume_checkpoint = resume_checkpoint


_session: Optional[_FnSession] = None
_session_lock = threading.Lock()


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report one iteration from a function trainable
    (reference: ray.tune.report / train_fn_utils.report)."""
    if _session is None:
        # Fall back to the train-loop context so one user function works
        # under both JaxTrainer and the Tuner (reference: unified
        # ray.train/ray.tune reporting).
        from ray_tpu.train import context as train_ctx
        train_ctx.report(metrics, checkpoint=checkpoint)
        return
    persisted = None
    if checkpoint is not None:
        persisted = tempfile.mkdtemp(prefix="rtpu_tune_ckpt_")
        shutil.copytree(checkpoint.path, persisted, dirs_exist_ok=True)
    _session.results.put(("result", dict(metrics), persisted))


def get_checkpoint() -> Optional[Checkpoint]:
    if _session is None:
        from ray_tpu.train import context as train_ctx
        return train_ctx.get_checkpoint()
    return _session.resume_checkpoint


class FunctionTrainable(Trainable):
    """Wraps ``def trainable(config): ... tune.report(...)`` into the
    class API. The function runs in a daemon thread; each ``train()``
    call hands back the next reported result."""

    _fn: Callable = None  # set by wrap_function subclass

    def setup(self, config: Dict[str, Any]) -> None:
        self._thread: Optional[threading.Thread] = None
        self._last_checkpoint_dir: Optional[str] = None
        self._resume: Optional[Checkpoint] = None
        self._last_metrics: Dict[str, Any] = {}

    def _start(self) -> None:
        global _session
        self._session = _FnSession(self._resume)

        def runner():
            global _session
            with _session_lock:
                _session = self._session
            try:
                self._fn(self.config)
                self._session.results.put(("done", None, None))
            except BaseException:
                self._session.results.put(
                    ("error", traceback.format_exc(), None))

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()

    def step(self) -> Dict[str, Any]:
        if self._thread is None:
            self._start()
        kind, payload, ckpt_dir = self._session.results.get()
        if kind == "error":
            raise RuntimeError(f"trainable function failed:\n{payload}")
        if kind == "done":
            return dict(self._last_metrics, done=True)
        if ckpt_dir:
            # Only the most recent reported checkpoint is ever consumed;
            # drop the previous temp copy so long runs don't fill /tmp.
            if self._last_checkpoint_dir:
                shutil.rmtree(self._last_checkpoint_dir, ignore_errors=True)
            self._last_checkpoint_dir = ckpt_dir
        result = dict(payload)
        self._last_metrics = dict(payload)
        result.setdefault("done", False)
        return result

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[str]:
        if self._last_checkpoint_dir:
            shutil.copytree(self._last_checkpoint_dir, checkpoint_dir,
                            dirs_exist_ok=True)
            return checkpoint_dir
        return None

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        self._resume = Checkpoint(checkpoint_dir)


def wrap_function(fn: Callable) -> type:
    # The wrapper class's module is ray_tpu.*, which would defeat the
    # by-value shipping of fn's driver-local module — register fn itself.
    from ray_tpu.core.serialization import _maybe_register_by_value
    _maybe_register_by_value(fn)
    return type(f"fn_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})
