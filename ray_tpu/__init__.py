"""ray_tpu — a TPU-native distributed compute framework.

Tasks, actors, and a shared-memory object plane (ray_tpu.core), with
JAX/XLA/Pallas AI libraries on top: sharded training (ray_tpu.train),
parallelism primitives (ray_tpu.parallel), TPU kernels (ray_tpu.ops),
streaming data (ray_tpu.data), tuning (ray_tpu.tune), serving
(ray_tpu.serve), and RL (ray_tpu.rl).

This module stays import-light: no jax import at the top level, so core
worker processes and CLI tools start fast. AI-library subpackages import
jax lazily on first use.
"""

from ray_tpu._version import __version__
from ray_tpu.api import (
    available_resources,
    cancel,
    cluster_resources,
    flight_journal,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    nodes,
    profile_dump,
    put,
    remote,
    shutdown,
    timeline,
    wait,
    whereis,
)
from ray_tpu.core.generator import ObjectRefGenerator
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu import exceptions
from ray_tpu.runtime_env import RuntimeEnv

__all__ = [
    "__version__",
    "available_resources",
    "cancel",
    "cluster_resources",
    "exceptions",
    "flight_journal",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "nodes",
    "ObjectRef",
    "ObjectRefGenerator",
    "profile_dump",
    "put",
    "remote",
    "RuntimeEnv",
    "shutdown",
    "timeline",
    "wait",
    "whereis",
]
