"""`ray-tpu` CLI (reference: python/ray/scripts/scripts.py `ray
status/list/...` and python/ray/util/state/state_cli.py).

The control plane lives inside driver processes, so cluster commands
read the session state snapshot the driver dumps every ~2s
(<session>/state.json, pointer at $TMPDIR/ray_tpu_last_session.json).

    python -m ray_tpu.scripts.cli status
    python -m ray_tpu.scripts.cli list tasks|actors|nodes|jobs|pgs
    python -m ray_tpu.scripts.cli summary
    python -m ray_tpu.scripts.cli events [--follow] [--kind K,K]
    python -m ray_tpu.scripts.cli timeline -o trace.json
    python -m ray_tpu.scripts.cli submit -- python my_driver.py
    python -m ray_tpu.scripts.cli version
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, Optional


def _load_state() -> Optional[Dict[str, Any]]:
    pointer = os.path.join(tempfile.gettempdir(),
                           "ray_tpu_last_session.json")
    try:
        with open(pointer) as f:
            meta = json.load(f)
        with open(meta["state_path"]) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, KeyError):
        return None


def _require_state() -> Dict[str, Any]:
    state = _load_state()
    if state is None:
        print("no live session found (is a driver running ray_tpu.init "
              "on this machine?)", file=sys.stderr)
        sys.exit(1)
    age = time.time() - state.get("timestamp", 0)
    if age > 30:
        print(f"warning: state snapshot is {age:.0f}s old (driver may "
              "have exited)", file=sys.stderr)
    return state


def _fmt_resources(res: Dict[str, float]) -> str:
    return ", ".join(f"{k}: {v:g}" for k, v in sorted(res.items()))


def cmd_status(args) -> None:
    state = _require_state()
    total = state["resources_total"]
    avail = state["resources_available"]
    print(f"======== Cluster status "
          f"(as of {time.ctime(state['timestamp'])}) ========")
    print(f"Nodes: {len(state['nodes'])}")
    for node in state["nodes"]:
        role = "head" if node["is_head"] else "worker"
        print(f"  {node['node_id'][:12]} [{role}] "
              f"{_fmt_resources(node['resources_total'])}")
    used = {k: total.get(k, 0) - avail.get(k, 0) for k in total}
    print("Usage:")
    for key in sorted(total):
        print(f"  {used.get(key, 0):g}/{total[key]:g} {key}")
    summary = state.get("task_summary", {})
    if summary:
        print("Tasks:", ", ".join(f"{k}: {v}"
                                  for k, v in sorted(summary.items())))


def cmd_list(args) -> None:
    state = _require_state()
    key = {"tasks": "tasks", "actors": "actors", "nodes": "nodes",
           "jobs": "jobs", "pgs": "placement_groups"}[args.kind]
    rows = state.get(key, [])
    print(json.dumps(rows, indent=2, default=str))


def cmd_summary(args) -> None:
    state = _require_state()
    print(json.dumps(state.get("task_summary", {}), indent=2))


def cmd_timeline(args) -> None:
    state = _require_state()
    # the snapshot carries recent tasks only; a live driver can export
    # the full trace via ray_tpu.util.state.timeline()
    trace = []
    for task in state.get("tasks", []):
        if task["state"] not in ("FINISHED", "FAILED"):
            continue
        trace.append({
            "name": task["name"], "cat": "task", "ph": "i",
            "ts": task["timestamp"] * 1e6, "pid": task["node_id"] or "?",
            "tid": task["task_id"][:8], "s": "t",
        })
    with open(args.output, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace)} events to {args.output}")


def _event_line(ev: Dict[str, Any]) -> str:
    stamp = time.strftime("%H:%M:%S",
                          time.localtime(ev.get("timestamp", 0)))
    ent = ""
    for key in ("node_id", "actor_id", "worker_id", "task_id"):
        if ev.get(key):
            ent = f" {key.split('_')[0]}={str(ev[key])[:12]}"
            break
    caused = (f" caused_by=#{ev['caused_by']}"
              if ev.get("caused_by") is not None else "")
    msg = f" — {ev['message']}" if ev.get("message") else ""
    return (f"[{stamp}] #{ev['seq']:<5} {ev['severity']:<7} "
            f"{ev['kind']}{ent}{caused}{msg}")


def cmd_events(args) -> None:
    """Print (and optionally follow) the cluster lifecycle event
    stream from the session snapshot (reference: `ray list
    cluster-events`)."""
    def _select(state: Dict[str, Any], since: Optional[int]):
        rows = state.get("events", [])
        if args.kind:
            wanted = set(args.kind.split(","))
            rows = [e for e in rows if e.get("kind") in wanted]
        if args.severity:
            order = ("DEBUG", "INFO", "WARNING", "ERROR")
            floor = order.index(args.severity)
            rows = [e for e in rows
                    if order.index(e.get("severity", "INFO")) >= floor]
        if since is not None:
            rows = [e for e in rows if e.get("seq", 0) > since]
        return rows[-args.limit:]

    state = _require_state()
    rows = _select(state, None)
    for ev in rows:
        print(_event_line(ev))
    if not args.follow:
        return
    cursor = max((e.get("seq", 0) for e in rows), default=0)
    try:
        while True:
            time.sleep(1.0)  # snapshot dump tick is ~2s
            state = _load_state()
            if state is None:
                continue
            fresh = _select(state, cursor)
            for ev in fresh:
                print(_event_line(ev), flush=True)
                cursor = max(cursor, ev.get("seq", 0))
    except KeyboardInterrupt:
        pass


def cmd_submit(args) -> None:
    entry = " ".join(args.entrypoint)
    if not entry:
        print("usage: ray-tpu submit -- <command ...>", file=sys.stderr)
        sys.exit(2)
    proc = subprocess.run(entry, shell=True)
    sys.exit(proc.returncode)


def cmd_version(args) -> None:
    from ray_tpu._version import __version__
    print(__version__)


def cmd_serve(args) -> None:
    """`ray-tpu serve deploy <yaml>` / `ray-tpu serve status` talk to
    the dashboard REST surface in the driver process (reference:
    python/ray/serve/scripts.py deploying via the dashboard agent)."""
    import urllib.request

    state = _require_state()
    url = state.get("dashboard_url")
    if not url:
        print("the live session has no dashboard (init with "
              "include_dashboard=True)", file=sys.stderr)
        sys.exit(1)
    import urllib.error

    try:
        if args.action == "deploy":
            if not args.config:
                print("usage: ray-tpu serve deploy <config.yaml>",
                      file=sys.stderr)
                sys.exit(1)
            import yaml
            try:
                with open(args.config) as f:
                    config = yaml.safe_load(f)
            except OSError as err:
                print(f"cannot read {args.config}: {err}",
                      file=sys.stderr)
                sys.exit(1)
            except yaml.YAMLError as err:
                print(f"invalid YAML in {args.config}: {err}",
                      file=sys.stderr)
                sys.exit(1)
            req = urllib.request.Request(
                url + "/api/serve/deploy",
                data=json.dumps(config).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=300) as resp:
                print(resp.read().decode())
        elif args.action == "status":
            with urllib.request.urlopen(url + "/api/serve",
                                        timeout=30) as resp:
                print(json.dumps(json.load(resp), indent=2))
    except urllib.error.HTTPError as err:
        # surface the server's message (e.g. config validation) cleanly
        detail = err.read().decode(errors="replace")
        print(f"serve {args.action} failed ({err.code}): {detail}",
              file=sys.stderr)
        sys.exit(1)
    except urllib.error.URLError as err:
        print(f"cannot reach the dashboard at {url}: {err.reason} "
              "(driver exited?)", file=sys.stderr)
        sys.exit(1)


def cmd_start(args) -> None:
    from ray_tpu.core import node_daemon
    argv = ["--address", args.address, "--resources", args.resources,
            "--labels", args.labels]
    if args.object_store_memory:
        argv += ["--object-store-memory", str(args.object_store_memory)]
    if args.system_config:
        argv += ["--system-config", args.system_config]
    node_daemon.main(argv)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="ray-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("status").set_defaults(fn=cmd_status)
    p = sub.add_parser("list")
    p.add_argument("kind",
                   choices=["tasks", "actors", "nodes", "jobs", "pgs"])
    p.set_defaults(fn=cmd_list)
    sub.add_parser("summary").set_defaults(fn=cmd_summary)
    p = sub.add_parser(
        "events", help="cluster lifecycle events from the live session "
        "(reference: `ray list cluster-events`)")
    p.add_argument("--follow", action="store_true",
                   help="poll the snapshot and stream new events")
    p.add_argument("--kind", default=None,
                   help="comma-separated kind filter (e.g. "
                   "NODE_DEAD,TASK_RETRY)")
    p.add_argument("--severity", default=None,
                   choices=["DEBUG", "INFO", "WARNING", "ERROR"],
                   help="minimum severity")
    p.add_argument("--limit", type=int, default=200)
    p.set_defaults(fn=cmd_events)
    p = sub.add_parser("timeline")
    p.add_argument("-o", "--output", default="timeline.json")
    p.set_defaults(fn=cmd_timeline)
    p = sub.add_parser("submit")
    p.add_argument("entrypoint", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_submit)
    sub.add_parser("version").set_defaults(fn=cmd_version)
    p = sub.add_parser(
        "serve", help="declarative serve ops against the live session "
        "(reference: the `serve` CLI, python/ray/serve/scripts.py)")
    p.add_argument("action", choices=["deploy", "status"])
    p.add_argument("config", nargs="?", default=None,
                   help="YAML config for `deploy`")
    p.set_defaults(fn=cmd_serve)
    p = sub.add_parser(
        "start", help="start a node daemon joining a head over TCP "
        "(reference: `ray start --address`)")
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument("--resources", default="{}")
    p.add_argument("--labels", default="{}")
    p.add_argument("--object-store-memory", type=int, default=None)
    p.add_argument("--system-config", default=None)
    p.set_defaults(fn=cmd_start)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
