"""Core-runtime microbenchmarks (reference: python/ray/_private/ray_perf.py:95).

Measures the control/object plane, not TPU math: trivial-task
throughput, actor call rates, and put/get rates. Run:

    python -m ray_tpu.scripts.perf [--tasks N]

Prints one JSON line per benchmark and a summary line; committed
numbers live in PERF.md.
"""

from __future__ import annotations

import argparse
import json
import time


def _rate(n: int, seconds: float) -> float:
    return round(n / seconds, 1) if seconds > 0 else float("inf")


def bench_trivial_tasks(rt, n: int) -> dict:
    """Submit-then-drain n no-op tasks (reference: 'tasks sync' +
    'tasks async' in ray_perf)."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    # warmup: spin the worker pool up, prime dispatch/lease caches and
    # worker pipelining (sustained throughput, not ramp, is the metric)
    ray_tpu.get([nop.remote() for _ in range(1000)])
    t0 = time.perf_counter()
    refs = [nop.remote() for _ in range(n)]
    ray_tpu.get(refs)
    dt = time.perf_counter() - t0
    return {"bench": "trivial_tasks", "n": n, "seconds": round(dt, 3),
            "per_second": _rate(n, dt)}


def bench_deep_backlog(rt, n: int) -> dict:
    """Throughput with every task queued up-front (reference envelope:
    1M+ queued per node without collapse, release/benchmarks/README.md:32).

    ``per_second`` is the HONEST end-to-end rate n/(submit start ->
    last completion); completions overlap the submit phase, so a
    phase-sliced "drain rate" would double-count early completions and
    overstate throughput. ``submit_per_second`` isolates the owner-side
    submission leg."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(1000)])
    t0 = time.perf_counter()
    refs = [nop.remote() for _ in range(n)]
    t1 = time.perf_counter()
    ray_tpu.get(refs)
    t2 = time.perf_counter()
    return {"bench": "deep_backlog", "n": n,
            "submit_per_second": _rate(n, t1 - t0),
            "submit_us_per_task": round(1e6 * (t1 - t0) / n, 2),
            "per_second": _rate(n, t2 - t0)}


def bench_task_sync_latency(rt, n: int) -> dict:
    """Round-trip one task at a time (scheduling latency)."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get(nop.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(nop.remote())
    dt = time.perf_counter() - t0
    return {"bench": "task_sync_roundtrip", "n": n,
            "seconds": round(dt, 3), "per_second": _rate(n, dt),
            "latency_ms": round(1000 * dt / n, 3)}


def bench_actor_calls(rt, n: int) -> dict:
    """Pipelined calls on one actor (reference: 'actor calls async')."""
    import ray_tpu

    @ray_tpu.remote
    class A:
        def ping(self):
            return None

    a = A.remote()
    ray_tpu.get(a.ping.remote())
    t0 = time.perf_counter()
    refs = [a.ping.remote() for _ in range(n)]
    ray_tpu.get(refs)
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)
    return {"bench": "actor_calls_pipelined", "n": n,
            "seconds": round(dt, 3), "per_second": _rate(n, dt)}


def bench_actor_sync(rt, n: int) -> dict:
    import ray_tpu

    @ray_tpu.remote
    class A:
        def ping(self):
            return None

    a = A.remote()
    ray_tpu.get(a.ping.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.ping.remote())
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)
    return {"bench": "actor_calls_sync", "n": n, "seconds": round(dt, 3),
            "per_second": _rate(n, dt),
            "latency_ms": round(1000 * dt / n, 3)}


def bench_put_get_small(rt, n: int) -> dict:
    import ray_tpu

    value = {"k": 1, "v": "x" * 100}
    for _ in range(100):  # warmup: shm arena + serializer hot
        ray_tpu.get(ray_tpu.put(value))
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(value))
    dt = time.perf_counter() - t0
    return {"bench": "put_get_small", "n": n, "seconds": round(dt, 3),
            "per_second": _rate(n, dt)}


def bench_put_get_1mb(rt, n: int) -> dict:
    import numpy as np

    import ray_tpu

    value = np.zeros(131_072, dtype=np.float64)  # 1 MiB
    for _ in range(10):
        ray_tpu.get(ray_tpu.put(value))
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(value))
    dt = time.perf_counter() - t0
    gbps = (n * value.nbytes) / dt / 1e9
    return {"bench": "put_get_1mb", "n": n, "seconds": round(dt, 3),
            "per_second": _rate(n, dt), "GB_per_s": round(gbps, 2)}


def bench_wire_submit(native: bool, n: int = 50_000,
                      payload: bytes = b"x" * 700) -> dict:
    """Frames/s through one LoopConnection for SUBMIT-sized frames —
    the wire leg of remote task submission, isolated from scheduling.
    ``native`` picks the C codec vs the pure-Python fallback."""
    import socket
    import threading

    from ray_tpu.core.io_loop import IOLoop
    from ray_tpu.core.protocol import FrameReader

    loop = IOLoop(name="perf-io-loop")
    a, b = socket.socketpair()
    b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1 << 20)
    conn = loop.register(a, lambda c, f: None, label="perf",
                         native=native)
    done = threading.Event()

    def drain():
        reader, cnt = FrameReader(), 0
        while cnt < n:
            data = b.recv(1 << 20)
            if not data:
                return
            cnt += len(reader.feed(data))
        done.set()

    threading.Thread(target=drain, daemon=True).start()
    t0 = time.perf_counter()
    for _ in range(n):
        conn.send_frame(payload)
    assert done.wait(120), "wire drain never completed"
    dt = time.perf_counter() - t0
    conn.close()
    loop.stop()
    b.close()
    return {"bench": "wire_submit_native" if native
            else "wire_submit_fallback", "n": n,
            "frame_bytes": len(payload), "seconds": round(dt, 3),
            "per_second": _rate(n, dt),
            "submit_us_per_frame": round(1e6 * dt / n, 2)}


def bench_recorder_overhead(rt, n: int) -> dict:
    """Flight-recorder cost on the tight trivial-task loop: the same
    submit-then-drain run with the journal disabled, then enabled on
    the driver (the record() hot path is identical on workers). The
    committed guard bound lives in tests/test_flight_recorder.py; this
    row is the measured ratio for PERF.md."""
    import ray_tpu
    from ray_tpu.util import flight_recorder as fr

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(1000)])
    saved = fr.RECORDER
    try:
        fr.disable()
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        dt_off = time.perf_counter() - t0
        fr.enable("driver:bench")
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        dt_on = time.perf_counter() - t0
    finally:
        fr.RECORDER = saved
    return {"bench": "recorder_overhead", "n": n,
            "seconds_disabled": round(dt_off, 3),
            "seconds_enabled": round(dt_on, 3),
            "enabled_over_disabled": round(dt_on / dt_off, 3)
            if dt_off > 0 else 1.0}


def bench_refsan_overhead(rt, n: int) -> dict:
    """Object-lifetime sanitizer cost on the tight trivial-task loop:
    the same submit-then-drain run with the ledger disabled, then
    enabled on the driver. The committed guard bound lives in
    tests/test_refsan.py; this row is the measured ratio for PERF.md."""
    import ray_tpu
    from ray_tpu.devtools import refsan

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(1000)])
    saved = refsan.LEDGER
    try:
        refsan.disable()
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        dt_off = time.perf_counter() - t0
        refsan.enable("driver:bench", canary=False)
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        dt_on = time.perf_counter() - t0
    finally:
        refsan.LEDGER = saved
    return {"bench": "refsan_overhead", "n": n,
            "seconds_disabled": round(dt_off, 3),
            "seconds_enabled": round(dt_on, 3),
            "enabled_over_disabled": round(dt_on / dt_off, 3)
            if dt_off > 0 else 1.0}


def bench_collsan_overhead(rt, n: int) -> dict:
    """Collective-sanitizer cost on the host-collective hot path: a
    solo (world-1) group allreduces a 65536-f32 tensor in a tight loop
    — the world==1 short-circuit isolates the fingerprint stamp from
    wire time, so the ratio bounds the per-op ledger cost. Interleaved
    best-of-3 toggling the ledger; the committed guard bound lives in
    tests/test_collsan.py."""
    import numpy as np
    from ray_tpu.devtools import collsan
    from ray_tpu.parallel import collective

    collective.init_collective_group(1, 0, "collsan-bench")
    x = np.arange(65536, dtype=np.float32)
    rounds = max(200, n // 40)
    for _ in range(50):
        collective.allreduce(x, "sum", "collsan-bench")
    saved = collsan.LEDGER
    best = {False: None, True: None}
    try:
        for _ in range(3):
            for enabled in (False, True):
                if enabled:
                    collsan.enable("driver:bench")
                else:
                    collsan.disable()
                t0 = time.perf_counter()
                for _ in range(rounds):
                    collective.allreduce(x, "sum", "collsan-bench")
                dt = time.perf_counter() - t0
                if best[enabled] is None or dt < best[enabled]:
                    best[enabled] = dt
    finally:
        collsan.LEDGER = saved
        collective.destroy_collective_group("collsan-bench")
    dt_off, dt_on = best[False], best[True]
    return {"bench": "collsan_overhead", "n": rounds,
            "seconds_disabled": round(dt_off, 3),
            "seconds_enabled": round(dt_on, 3),
            "enabled_over_disabled": round(dt_on / dt_off, 3)
            if dt_off > 0 else 1.0}


def bench_events_overhead(rt, n: int) -> dict:
    """Cluster-event-plane cost on the tight trivial-task loop:
    interleaved best-of-3 A/B toggling ``cluster_events_enabled`` (the
    hot-path emit is one LEASE_GRANTED append per grant). The committed
    guard bound lives in tests/test_recovery.py; this row is the
    measured ratio for PERF.md / BENCH_core.json."""
    import ray_tpu
    from ray_tpu.core.config import get_config

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(1000)])
    cfg = get_config()
    saved = cfg.cluster_events_enabled
    best = {False: None, True: None}
    try:
        for _ in range(3):
            for enabled in (False, True):
                cfg.cluster_events_enabled = enabled
                t0 = time.perf_counter()
                ray_tpu.get([nop.remote() for _ in range(n)])
                dt = time.perf_counter() - t0
                if best[enabled] is None or dt < best[enabled]:
                    best[enabled] = dt
    finally:
        cfg.cluster_events_enabled = saved
    dt_off, dt_on = best[False], best[True]
    return {"bench": "events_overhead", "n": n,
            "seconds_disabled": round(dt_off, 3),
            "seconds_enabled": round(dt_on, 3),
            "enabled_over_disabled": round(dt_on / dt_off, 3)
            if dt_off > 0 else 1.0}


def bench_phases(rt, n: int, sample_n: int = 64) -> dict:
    """Submit-path phase budget (PR 18): recorder on + 1-in-N task
    sampling over the 20k-trivial-task harness, folded by
    whereis.task_path_attribution against the independently measured
    submit+drain wall window. ``coverage`` is the fraction of that
    window tiled by the sampled chains (acceptance bar: >= 0.85);
    the per-phase µs means are the baseline ROADMAP item 2 attacks."""
    import ray_tpu
    from ray_tpu.core import task_phase
    from ray_tpu.core.config import get_config
    from ray_tpu.devtools import whereis as whereis_mod
    from ray_tpu.util import flight_recorder as fr

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(1000)])
    cfg = get_config()
    saved = (fr.RECORDER, cfg.task_phase_sample_n)
    task_phase.reset()
    try:
        cfg.task_phase_sample_n = sample_n
        # capacity: 9 events x n/sample_n chains, with headroom
        fr.enable("driver:phases", capacity=max(4096,
                                                12 * n // sample_n))
        lo_ns = fr.clock_ns()
        t0 = time.perf_counter()
        refs = [nop.remote() for _ in range(n)]
        ray_tpu.get(refs)
        wall = time.perf_counter() - t0
        hi_ns = fr.clock_ns()
        report = whereis_mod.task_path_attribution(
            fr.merged_journals(), window_ns=(lo_ns, hi_ns))
    finally:
        fr.RECORDER, cfg.task_phase_sample_n = saved
        task_phase.reset()
    return {"bench": "task_phases", "n": n, "sample_n": sample_n,
            "wall_s": round(wall, 3),
            "tasks_sampled": report["tasks_sampled"],
            "coverage": report["coverage"],
            "mean_chain_us": report["mean_chain_us"],
            "phases": report["phases"]}


def bench_profiler_overhead(rt, n: int) -> dict:
    """Sampling-profiler cost on the tight trivial-task loop:
    interleaved best-of-2 A/B — gates off vs the full observatory on
    (driver sampler at the configured Hz + recorder + phase sampling).
    The committed guard bounds live in tests/test_profiler.py; this
    row is the measured ratio for PERF.md / BENCH_core.json."""
    import ray_tpu
    from ray_tpu.core import task_phase
    from ray_tpu.core.config import get_config
    from ray_tpu.devtools import profiler
    from ray_tpu.util import flight_recorder as fr

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(1000)])
    cfg = get_config()
    saved = (fr.RECORDER, profiler.PROFILER, cfg.task_phase_sample_n)
    best = {False: None, True: None}
    try:
        for _ in range(2):
            for enabled in (False, True):
                if enabled:
                    cfg.task_phase_sample_n = 64
                    fr.enable("driver:bench")
                    profiler.enable("driver:bench")
                else:
                    cfg.task_phase_sample_n = saved[2]
                    fr.disable()
                    profiler.disable()
                task_phase.reset()
                t0 = time.perf_counter()
                ray_tpu.get([nop.remote() for _ in range(n)])
                dt = time.perf_counter() - t0
                if best[enabled] is None or dt < best[enabled]:
                    best[enabled] = dt
    finally:
        profiler.disable()
        fr.RECORDER, _, cfg.task_phase_sample_n = saved
        if saved[1] is not None:   # restart a preexisting sampler
            profiler.enable(saved[1].label, hz=saved[1].hz)
        task_phase.reset()
    dt_off, dt_on = best[False], best[True]
    return {"bench": "profiler_overhead", "n": n,
            "hz": get_config().profiler_hz,
            "seconds_disabled": round(dt_off, 3),
            "seconds_enabled": round(dt_on, 3),
            "enabled_over_disabled": round(dt_on / dt_off, 3)
            if dt_off > 0 else 1.0}


def bench_process_threads(rt) -> dict:
    """Thread topology after a warm workload: with the selector IO
    loop, socket service is ONE rtpu-io-loop thread regardless of
    connection count (the old design paid a reader thread per peer)."""
    import threading

    names = sorted(t.name for t in threading.enumerate())
    return {"bench": "process_threads", "count": len(names),
            "io_loop_threads": names.count("rtpu-io-loop"),
            "names": names}


def _compare_wire(n: int) -> list:
    """Interleaved best-of-3 A/B of the wire submit leg per codec."""
    from ray_tpu.core import io_loop as io_loop_mod
    from ray_tpu.native import _lib

    if _lib.try_load() is None:
        return [{"bench": "wire_compare",
                 "skipped": "native codec unavailable"}]
    best: dict = {}
    for _ in range(3):
        for mode in (False, True):
            out = bench_wire_submit(mode, n)
            prev = best.get(mode)
            if prev is None or out["per_second"] > prev["per_second"]:
                best[mode] = out
    ratio = best[True]["per_second"] / best[False]["per_second"]
    return [best[False], best[True],
            {"bench": "wire_compare",
             "native_over_fallback": round(ratio, 3),
             "native_default": io_loop_mod.use_native_wire()}]


def bench_envelope(ns=(16, 64, 128), tasks_per_node: int = 16) -> dict:
    """Cluster-envelope scaling (ISSUE 17): scheduling throughput,
    head-process thread count, and RSS as virtual node count grows.
    Virtual nodes register over the head's real TCP listener but share
    one executor and one object server (core/virtual_node.py), so the
    numbers isolate CONTROL-plane cost per node, and head_threads
    flat across 16->128 is the O(1)-threads claim, measured."""
    import threading

    import ray_tpu
    from ray_tpu.core.cluster_utils import Cluster

    def rss_mb():
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return round(int(line.split()[1]) / 1024.0, 1)
        except OSError:
            pass
        return None

    cluster = Cluster(system_config={"head_port": 0,
                                     "log_to_driver": False})
    out = {"bench": "envelope", "nodes": {}}
    try:
        @ray_tpu.remote
        def nop():
            return None

        have = 0
        for n in ns:
            t_join = time.perf_counter()
            cluster.add_virtual_nodes(n - have, resources={"CPU": 2.0})
            join_s = time.perf_counter() - t_join
            have = n
            ntasks = tasks_per_node * n
            ray_tpu.get([nop.remote() for _ in range(64)])  # warm
            t0 = time.perf_counter()
            ray_tpu.get([nop.remote() for _ in range(ntasks)])
            dt = time.perf_counter() - t0
            out["nodes"][str(n)] = {
                "tasks": ntasks,
                "per_second": _rate(ntasks, dt),
                "join_seconds": round(join_s, 3),
                "head_threads": threading.active_count(),
                "rss_mb": rss_mb(),
            }
    finally:
        cluster.shutdown()
    return out


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tasks", type=int, default=20000)
    parser.add_argument("--backlog", type=int, default=100000)
    parser.add_argument("--sync-tasks", type=int, default=300)
    parser.add_argument("--actor-calls", type=int, default=2000)
    parser.add_argument("--puts", type=int, default=1000)
    parser.add_argument("--wire-frames", type=int, default=50000)
    parser.add_argument("--json", metavar="PATH", default=None,
                        help="also write the full result list to PATH")
    parser.add_argument("--compare-wire", action="store_true",
                        help="A/B the native C wire codec against the "
                             "pure-Python fallback (submit leg)")
    parser.add_argument("--recorder", action="store_true",
                        help="measure flight-recorder overhead on the "
                             "trivial-task loop (enabled vs disabled)")
    parser.add_argument("--refsan", action="store_true",
                        help="measure object-lifetime-sanitizer ledger "
                             "overhead on the trivial-task loop "
                             "(enabled vs disabled)")
    parser.add_argument("--collsan", action="store_true",
                        help="measure collective-sanitizer fingerprint "
                             "overhead on a solo-group allreduce loop "
                             "(interleaved best-of-3, enabled vs "
                             "disabled)")
    parser.add_argument("--events", action="store_true",
                        help="measure cluster-event-plane overhead on "
                             "the trivial-task loop (interleaved "
                             "best-of-3, enabled vs disabled)")
    parser.add_argument("--phases", action="store_true",
                        help="submit-path phase budget: recorder + 1-in-"
                             "64 task sampling over the trivial-task "
                             "loop, folded per phase (coverage target "
                             ">= 0.85 of submit+drain wall time)")
    parser.add_argument("--profiler", action="store_true",
                        help="measure sampling-profiler overhead on the "
                             "trivial-task loop (full observatory on vs "
                             "off, interleaved best-of-2)")
    parser.add_argument("--envelope", action="store_true",
                        help="cluster-envelope scaling: throughput, "
                             "head thread count, and RSS at 16/64/128 "
                             "virtual nodes (runs instead of the "
                             "standard suite)")
    args = parser.parse_args(argv)

    if args.envelope:
        out = bench_envelope()
        print(json.dumps(out), flush=True)
        if args.json:
            with open(args.json, "w") as f:
                json.dump([out], f, indent=1)
        return

    import ray_tpu
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                      system_config={"log_to_driver": False})
    results = []
    for fn, n in (
        (bench_trivial_tasks, args.tasks),
        (bench_deep_backlog, args.backlog),
        (bench_task_sync_latency, args.sync_tasks),
        (bench_actor_calls, args.actor_calls),
        (bench_actor_sync, args.sync_tasks),
        (bench_put_get_small, args.puts),
        (bench_put_get_1mb, min(args.puts, 300)),
    ):
        out = fn(rt, n)
        results.append(out)
        print(json.dumps(out), flush=True)
    results.append(bench_process_threads(rt))
    print(json.dumps(results[-1]), flush=True)
    if args.recorder:
        out = bench_recorder_overhead(rt, args.tasks)
        results.append(out)
        print(json.dumps(out), flush=True)
    if args.refsan:
        out = bench_refsan_overhead(rt, args.tasks)
        results.append(out)
        print(json.dumps(out), flush=True)
    if args.collsan:
        out = bench_collsan_overhead(rt, args.tasks)
        results.append(out)
        print(json.dumps(out), flush=True)
    if args.events:
        out = bench_events_overhead(rt, args.tasks)
        results.append(out)
        print(json.dumps(out), flush=True)
    if args.phases:
        out = bench_phases(rt, args.tasks)
        results.append(out)
        print(json.dumps(out), flush=True)
    if args.profiler:
        out = bench_profiler_overhead(rt, args.tasks)
        results.append(out)
        print(json.dumps(out), flush=True)
    if args.compare_wire:
        for out in _compare_wire(args.wire_frames):
            results.append(out)
            print(json.dumps(out), flush=True)
    summary = {r["bench"]: r["per_second"] for r in results
               if "per_second" in r}
    print(json.dumps({"bench": "summary", **summary}))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
