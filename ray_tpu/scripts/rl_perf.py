"""RL throughput microbenchmark: PPO env-steps/sec.

BASELINE.json names "RLlib PPO env-steps/sec" as a headline metric
(reference analog: rllib release tests measure sampler+learner
throughput on CartPole-class envs). This drives the in-tree PPO
algorithm end-to-end — jitted env runners sampling a vectorized
CartPole, device-resident learner update — and reports env-steps/sec
over a fixed number of iterations.

Run: python -m ray_tpu.scripts.rl_perf [--iters N] [--batch B]
Prints one JSON line, PERF.md records the numbers.
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--num-envs", type=int, default=32)
    ap.add_argument("--rollout", type=int, default=128)
    ap.add_argument("--warmup", type=int, default=2)
    args = ap.parse_args()

    from ray_tpu.rl import PPO, PPOConfig

    config = (PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=0,
                           num_envs_per_env_runner=args.num_envs,
                           rollout_fragment_length=args.rollout)
              .training(train_batch_size=args.num_envs * args.rollout,
                        minibatch_size=args.num_envs * args.rollout // 4,
                        num_epochs=2))
    algo = PPO(config)
    try:
        for _ in range(args.warmup):  # compile + first-iter costs
            algo.train()
        start_steps = algo.train()["num_env_steps_sampled_lifetime"]
        t0 = time.perf_counter()
        for _ in range(args.iters):
            result = algo.train()
        dt = time.perf_counter() - t0
        steps = result["num_env_steps_sampled_lifetime"] - start_steps
        print(json.dumps({
            "metric": "ppo_env_steps_per_sec",
            "value": round(steps / dt, 1),
            "unit": "env-steps/s",
            "iters": args.iters,
            "num_envs": args.num_envs,
            "rollout": args.rollout,
            "mean_return": result.get("episode_return_mean"),
        }))
    finally:
        algo.stop()


if __name__ == "__main__":
    main()
