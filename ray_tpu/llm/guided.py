"""TPU-native guided decoding: grammar-constrained generation.

Reference: the reference's chat surface inherits ``tools``,
``tool_choice`` and ``response_format`` from vLLM's request models
(python/ray/llm/_internal/serve/core/configs/openai_api_models.py:14-38)
and vLLM's guided-decoding backends do the enforcement. Here it is
in-tree and TPU-shaped: a grammar (JSON schema, generic JSON, or a
tool-call grammar) compiles to a character-level NFA; each decode step
the engine asks for the mask of vocabulary tokens whose FULL string
survives the automaton from the current state and folds everything
else into the slot's device-resident logit-bias row as -1e9 — so the
constraint is enforced inside the jitted on-device sampler, never by
post-hoc retries. The automaton walk itself is host-side (one state
advance per emitted token); masks are memoized per automaton state, so
steady-state cost is one [V] row upload per guided slot per step.

Design notes:
- Generic JSON (``response_format={"type": "json_object"}``) is not a
  regular language; it is compiled with nesting unrolled to a bounded
  depth (default 5). Deeper nesting is rejected by the mask — stated
  divergence from vLLM's pushdown backends.
- Schema objects follow OpenAI structured-output "strict" semantics:
  properties listed in ``required`` are emitted in declaration order;
  non-required properties are not generated.
- Numbers cap at 15 integer / 15 fraction / 3 exponent digits so every
  scalar sub-grammar is finite (greedy decoding cannot loop forever in
  a digit run).
"""

from __future__ import annotations

import json
import threading

from ray_tpu.devtools import locktrace
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "TokenConstraint", "json_schema_constraint", "json_object_constraint",
    "tool_call_constraint",
]

# JSON string content: anything except the quote, the backslash and
# control characters (escapes handled separately).
_STR_EXCLUDED = frozenset({'"', "\\"} | {chr(i) for i in range(0x20)})
_HEX = frozenset("0123456789abcdefABCDEF")
_DIGIT = frozenset("0123456789")
_DIGIT19 = frozenset("123456789")

_MAX_INT_DIGITS = 15
_MAX_FRAC_DIGITS = 15
_MAX_EXP_DIGITS = 3


class _Grammar:
    """Thompson-construction NFA builder over characters.

    Fragments are (start_node, accept_node) pairs; every combinator
    returns FRESH nodes, so a fragment is single-use — repetition
    combinators take zero-arg factories and instantiate copies.
    """

    def __init__(self):
        self.eps: List[List[int]] = []
        # per node: list of (chars, negated, dst)
        self.edges: List[List[Tuple[frozenset, bool, int]]] = []

    def _node(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    # -- combinators ---------------------------------------------------
    def lit(self, s: str):
        start = self._node()
        cur = start
        for ch in s:
            nxt = self._node()
            self.edges[cur].append((frozenset((ch,)), False, nxt))
            cur = nxt
        return (start, cur)

    def cls(self, chars, negated: bool = False):
        a, b = self._node(), self._node()
        self.edges[a].append((frozenset(chars), negated, b))
        return (a, b)

    def seq(self, *frags):
        if not frags:
            a = self._node()
            return (a, a)
        for (_, acc), (nxt, _) in zip(frags, frags[1:]):
            self.eps[acc].append(nxt)
        return (frags[0][0], frags[-1][1])

    def alt(self, *frags):
        s, t = self._node(), self._node()
        for a, b in frags:
            self.eps[s].append(a)
            self.eps[b].append(t)
        return (s, t)

    def opt(self, frag):
        s, t = self._node(), self._node()
        self.eps[s].append(frag[0])
        self.eps[frag[1]].append(t)
        self.eps[s].append(t)
        return (s, t)

    def star(self, frag):
        s = self._node()
        self.eps[s].append(frag[0])
        self.eps[frag[1]].append(s)
        return (s, s)

    def rep(self, factory, lo: int, hi: Optional[int]):
        """factory() repeated between lo and hi times (hi=None: *)."""
        frags = [factory() for _ in range(lo)]
        if hi is None:
            frags.append(self.star(factory()))
        else:
            if hi < lo:
                raise ValueError(f"repetition bounds {lo}..{hi} invalid")
            tail = None
            for _ in range(hi - lo):
                piece = factory()
                if tail is not None:
                    piece = self.seq(piece, tail)
                tail = self.opt(piece)
            if tail is not None:
                frags.append(tail)
        return self.seq(*frags)

    # -- JSON pieces ---------------------------------------------------
    def _string_char(self):
        escape = self.seq(
            self.lit("\\"),
            self.alt(self.cls('"\\/bfnrt'),
                     self.seq(self.lit("u"),
                              *[self.cls(_HEX) for _ in range(4)])))
        return self.alt(self.cls(_STR_EXCLUDED, negated=True), escape)

    def json_string(self, min_len: int = 0, max_len: Optional[int] = None):
        return self.seq(self.lit('"'),
                        self.rep(self._string_char, min_len, max_len),
                        self.lit('"'))

    def _int_body(self):
        return self.alt(
            self.lit("0"),
            self.seq(self.cls(_DIGIT19),
                     self.rep(lambda: self.cls(_DIGIT), 0,
                              _MAX_INT_DIGITS - 1)))

    def json_integer(self):
        return self.seq(self.opt(self.lit("-")), self._int_body())

    def json_number(self):
        frac = self.seq(self.lit("."),
                        self.rep(lambda: self.cls(_DIGIT), 1,
                                 _MAX_FRAC_DIGITS))
        expo = self.seq(self.cls("eE"), self.opt(self.cls("+-")),
                        self.rep(lambda: self.cls(_DIGIT), 1,
                                 _MAX_EXP_DIGITS))
        return self.seq(self.opt(self.lit("-")), self._int_body(),
                        self.opt(frac), self.opt(expo))

    def json_value(self, depth: int):
        """Any JSON value, nesting unrolled to ``depth`` levels."""
        opts = [self.json_string(), self.json_number(),
                self.lit("true"), self.lit("false"), self.lit("null")]
        if depth > 0:
            opts.append(self.any_object(depth - 1))
            opts.append(self.any_array(depth - 1))
        return self.alt(*opts)

    def any_object(self, depth: int):
        def member():
            return self.seq(self.json_string(), self.lit(":"),
                            self.json_value(depth))
        body = self.seq(member(),
                        self.star(self.seq(self.lit(","), member())))
        return self.seq(self.lit("{"), self.opt(body), self.lit("}"))

    def any_array(self, depth: int):
        body = self.seq(self.json_value(depth),
                        self.star(self.seq(self.lit(","),
                                           self.json_value(depth))))
        return self.seq(self.lit("["), self.opt(body), self.lit("]"))

    # -- JSON Schema compiler ------------------------------------------
    def schema(self, schema: Dict[str, Any], depth: int = 24):
        """Compile a JSON-schema subset to a fragment.

        Supported: object (properties + required, strict ordering),
        array (items, minItems/maxItems), string (minLength/maxLength,
        enum), integer, number, boolean, null, enum, const,
        anyOf/oneOf, type lists. Unsupported keywords (pattern, $ref,
        allOf, format-validation) raise ValueError so a request fails
        loudly at validation time instead of silently ignoring its
        schema.
        """
        if depth < 0:
            raise ValueError("schema nesting exceeds compiler depth")
        if schema is True or schema == {}:
            return self.json_value(3)
        if not isinstance(schema, dict):
            raise ValueError("schema must be an object")
        for bad in ("$ref", "allOf", "pattern", "patternProperties",
                    "not", "if"):
            if bad in schema:
                raise ValueError(
                    f"unsupported JSON-schema keyword {bad!r}")
        if "enum" in schema:
            return self.alt(*[
                self.lit(json.dumps(v, separators=(",", ":"),
                                    sort_keys=True))
                for v in schema["enum"]])
        if "const" in schema:
            return self.lit(json.dumps(schema["const"],
                                       separators=(",", ":"),
                                       sort_keys=True))
        for key in ("anyOf", "oneOf"):
            if key in schema:
                return self.alt(*[self.schema(s, depth - 1)
                                  for s in schema[key]])
        t = schema.get("type")
        if isinstance(t, list):
            return self.alt(*[self.schema({**schema, "type": one},
                                          depth - 1) for one in t])
        if t == "string":
            return self.json_string(int(schema.get("minLength", 0)),
                                    schema.get("maxLength"))
        if t == "integer":
            return self.json_integer()
        if t == "number":
            return self.json_number()
        if t == "boolean":
            return self.alt(self.lit("true"), self.lit("false"))
        if t == "null":
            return self.lit("null")
        if t == "array":
            items = schema.get("items", {})
            lo = int(schema.get("minItems", 0))
            hi = schema.get("maxItems")

            def item():
                return self.schema(items, depth - 1)

            if lo == 0:
                body = self.opt(self.seq(
                    item(), self._rep_sep(item, 0, None if hi is None
                                          else hi - 1)))
                if hi == 0:
                    body = self.seq()
            else:
                body = self.seq(item(), self._rep_sep(
                    item, lo - 1, None if hi is None else hi - 1))
            return self.seq(self.lit("["), body, self.lit("]"))
        if t == "object" or (t is None and "properties" in schema):
            props = schema.get("properties", {})
            required = schema.get("required")
            if required is not None:
                unknown = [n for n in required if n not in props]
                if unknown:
                    raise ValueError(
                        f"required names {unknown} not in properties")
                names = [n for n in props if n in set(required)]
            else:
                names = list(props)
            if not names:
                return self.lit("{}")
            parts = [self.lit("{")]
            for i, name in enumerate(names):
                if i:
                    parts.append(self.lit(","))
                parts.append(self.lit(json.dumps(name) + ":"))
                parts.append(self.schema(props[name], depth - 1))
            parts.append(self.lit("}"))
            return self.seq(*parts)
        if t is None:
            return self.json_value(3)
        raise ValueError(f"unsupported schema type {t!r}")

    def _rep_sep(self, item, lo: int, hi: Optional[int]):
        """(',' item) repeated lo..hi times."""
        return self.rep(lambda: self.seq(self.lit(","), item()), lo, hi)


class TokenConstraint:
    """A compiled grammar bound to a vocabulary.

    State is an opaque frozenset of NFA nodes — callers (the engine)
    hold one state per request and thread it through:

        state = c.start_state()
        mask  = c.token_mask(state)        # np.bool_[vocab]
        state = c.advance(state, token_id) # None once dead/complete

    Instances are immutable and thread-safe (mask/step memoization
    guarded by a lock), so one constraint can serve many concurrent
    requests and its mask cache warms across them.
    """

    def __init__(self, grammar: _Grammar, frag, token_strs: List[Optional[str]],
                 eos_id: Optional[int] = None):
        self._eps = grammar.eps
        self._edges = grammar.edges
        self._accept = frag[1]
        self._eos_id = eos_id
        self._token_strs = token_strs
        self._start = self._closure(frozenset((frag[0],)))
        # vocabulary trie: shared prefixes walk the automaton once
        root: Dict[str, Any] = {"kids": {}, "ids": []}
        for tid, s in enumerate(token_strs):
            if not s:  # None (special) or empty string: never allowed
                continue
            node = root
            for ch in s:
                node = node["kids"].setdefault(ch, {"kids": {}, "ids": []})
            node["ids"].append(tid)
        self._trie = root
        self._mask_cache: Dict[frozenset, np.ndarray] = {}
        self._step_cache: Dict[Tuple[frozenset, str], frozenset] = {}
        self._lock = locktrace.traced_lock("llm.guided.masks")

    def __getstate__(self):
        # constraints cross actor boundaries (disagg prefill→decode,
        # batch-inference engine actors): drop the unpicklable lock,
        # ship the memoized caches as-is
        state = dict(self.__dict__)
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = locktrace.traced_lock("llm.guided")

    @property
    def vocab_size(self) -> int:
        return len(self._token_strs)

    def start_state(self) -> frozenset:
        return self._start

    def accepting(self, state: frozenset) -> bool:
        return self._accept in state

    def is_exhausted(self, state: frozenset) -> bool:
        """No character can extend the match — generation must stop."""
        return not any(self._edges[n] for n in state)

    # -- automaton core ------------------------------------------------
    def _closure(self, nodes: frozenset) -> frozenset:
        seen = set(nodes)
        stack = list(nodes)
        while stack:
            for nxt in self._eps[stack.pop()]:
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return frozenset(seen)

    def _step(self, state: frozenset, ch: str) -> frozenset:
        key = (state, ch)
        hit = self._step_cache.get(key)
        if hit is not None:
            return hit
        targets = {dst for n in state
                   for chars, negated, dst in self._edges[n]
                   if (ch in chars) != negated}
        out = self._closure(frozenset(targets)) if targets else frozenset()
        with self._lock:
            self._step_cache[key] = out
        return out

    def token_mask(self, state: frozenset) -> np.ndarray:
        """Boolean [vocab] mask of tokens whose full string survives
        the automaton from ``state`` (EOS allowed iff accepting)."""
        cached = self._mask_cache.get(state)
        if cached is not None:
            return cached
        mask = np.zeros(len(self._token_strs), dtype=bool)
        stack = [(self._trie, state)]
        while stack:
            node, st = stack.pop()
            for tid in node["ids"]:
                mask[tid] = True
            for ch, child in node["kids"].items():
                nst = self._step(st, ch)
                if nst:
                    stack.append((child, nst))
        if self._eos_id is not None and self.accepting(state):
            mask[self._eos_id] = True
        with self._lock:
            self._mask_cache[state] = mask
        return mask

    def advance(self, state: frozenset, token_id: int) -> Optional[frozenset]:
        """State after emitting ``token_id``; None when the automaton
        dies (or the token is a special with no string form)."""
        s = self._token_strs[token_id] if \
            0 <= token_id < len(self._token_strs) else None
        if not s:
            return None
        for ch in s:
            state = self._step(state, ch)
            if not state:
                return None
        return state

    def matches(self, text: str) -> bool:
        """Full-text acceptance check (used by tests and parsers)."""
        state = self._start
        for ch in text:
            state = self._step(state, ch)
            if not state:
                return False
        return self.accepting(state)

    def valid_prefix(self, text: str) -> bool:
        """True if ``text`` can still be extended to an accepted
        string (length-truncated guided output satisfies this)."""
        state = self._start
        for ch in text:
            state = self._step(state, ch)
            if not state:
                return False
        return True


# -- public constructors ----------------------------------------------

def json_schema_constraint(schema: Dict[str, Any],
                           token_strs: List[Optional[str]],
                           eos_id: Optional[int] = None) -> TokenConstraint:
    """Constraint enforcing a JSON-schema subset (OpenAI
    ``response_format={"type": "json_schema", ...}``)."""
    g = _Grammar()
    return TokenConstraint(g, g.schema(schema), token_strs, eos_id)


def json_object_constraint(token_strs: List[Optional[str]],
                           eos_id: Optional[int] = None,
                           max_depth: int = 5) -> TokenConstraint:
    """Constraint enforcing any JSON object (OpenAI
    ``response_format={"type": "json_object"}``), nesting bounded at
    ``max_depth`` levels."""
    g = _Grammar()
    return TokenConstraint(g, g.any_object(max_depth), token_strs, eos_id)


def tool_call_constraint(tools: List[Dict[str, Any]],
                         token_strs: List[Optional[str]],
                         eos_id: Optional[int] = None,
                         forced_name: Optional[str] = None
                         ) -> TokenConstraint:
    """Constraint forcing a well-formed tool call
    ``{"name":"<fn>","arguments":{...}}`` where the arguments object
    obeys the named function's ``parameters`` schema (OpenAI ``tools``
    with ``tool_choice="required"`` or a named function)."""
    g = _Grammar()
    alts = []
    for tool in tools:
        fn = tool.get("function") or {}
        name = fn.get("name")
        if forced_name is not None and name != forced_name:
            continue
        params = fn.get("parameters")
        if params is None:
            params = {"type": "object", "properties": {}}
        alts.append(g.seq(
            g.lit('{"name":' + json.dumps(name) + ',"arguments":'),
            g.schema(params),
            g.lit("}")))
    if not alts:
        raise ValueError(
            f"tool_choice names {forced_name!r} but no such tool")
    return TokenConstraint(g, g.alt(*alts), token_strs, eos_id)


def parse_tool_call(text: str,
                    tool_names: Optional[List[str]] = None
                    ) -> Optional[Dict[str, Any]]:
    """Parse ``{"name": ..., "arguments": {...}}`` out of generated
    text; returns {"name", "arguments"(dict)} or None. Used both for
    grammar-constrained output and for tool_choice="auto" detection."""
    try:
        obj = json.loads(text)
    except (ValueError, TypeError):
        return None
    if not isinstance(obj, dict) or not isinstance(obj.get("name"), str):
        return None
    args = obj.get("arguments")
    if not isinstance(args, dict):
        return None
    if tool_names is not None and obj["name"] not in tool_names:
        return None
    return {"name": obj["name"], "arguments": args}
