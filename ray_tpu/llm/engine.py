"""Continuous-batching inference engine.

Reference: the reference serves LLMs by wrapping vLLM
(python/ray/llm/_internal/serve/engines/vllm/vllm_engine.py —
continuous batching, paged KV). TPU-native redesign (JetStream-style):

- The KV cache is ONE static-shape array pair [L, B, S, KVH, HD] in
  HBM: XLA-friendly, no paging indirection — slot b of the batch
  dimension is the "page table", assigned to one request at a time.
- Decode is a single jitted step for the WHOLE batch every iteration;
  requests join (prefill into a free slot) and leave (EOS/length)
  between steps without recompiling — that is the continuous batching.
- Prefill pads prompts into power-of-two buckets so only O(log S)
  prefill programs ever compile.

Sampling (temperature / top-k / greedy) is ON-DEVICE, fused into the
jitted decode step: only the sampled [B] int32 tokens cross to the
host each iteration, not [B, V] float logits (at 32k vocab x batch 8
that copy would eat the decode budget). Per-request temperature/top-k
ride in as [B] arrays; randomness is a counter-folded PRNG key so the
program never recompiles.

Multi-LoRA multiplexing (reference: vLLM multi-LoRA behind
serve.llm): adapters register into a fixed-size bank ({A,B} stacks,
index 0 = all-zero base); each request may name an adapter, and the
batched decode gathers per-slot A/B — different requests in the SAME
decode batch can use different adapters.
"""

from __future__ import annotations

import itertools
import threading

from ray_tpu.devtools import locktrace
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.models.llama import (
    LlamaConfig, llama_decode_step, llama_init, llama_init_cache,
    llama_prefill, llama_verify_step)
from ray_tpu.util import flight_recorder as _flight
from ray_tpu.util import metrics as _metrics

# --- built-in engine metrics (reference: vLLM engine stats surfaced
# through serve) ----------------------------------------------------
# TTFT is observed per request (request-rate — direct record). Step
# metrics are produced by the stepper hot loop, so they aggregate
# locally in _MetricsBuffer and flush as ONE batched update per
# interval — a per-step RPC from a replica worker would serialize the
# decode loop on the control plane.
_TTFT_BOUNDS = [0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0]
_STEP_BOUNDS = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 5.0]
ENGINE_TTFT = _metrics.Histogram(
    "ray_tpu_engine_ttft_seconds",
    "Time from request admission to its first emitted token",
    boundaries=_TTFT_BOUNDS)
ENGINE_STEP_SECONDS = _metrics.Histogram(
    "ray_tpu_engine_step_seconds",
    "Engine step wall time, by phase (prefill-admitting vs pure decode)",
    boundaries=_STEP_BOUNDS, tag_keys=("phase",))
ENGINE_TOKEN_SECONDS = _metrics.Histogram(
    "ray_tpu_engine_token_seconds",
    "Per-token decode latency (step time per token emitted per slot)",
    boundaries=_STEP_BOUNDS)
ENGINE_TOKENS = _metrics.Counter(
    "ray_tpu_engine_tokens_generated_total",
    "Tokens emitted by the engine")
ENGINE_TOKENS_PER_S = _metrics.Gauge(
    "ray_tpu_engine_tokens_per_second",
    "Decode throughput over the last metrics flush window")
ENGINE_OCCUPANCY = _metrics.Gauge(
    "ray_tpu_engine_batch_occupancy",
    "Active decode slots (continuous-batching occupancy)")
ENGINE_WAITING = _metrics.Gauge(
    "ray_tpu_engine_waiting_requests",
    "Requests queued for a free decode slot")


class _MetricsBuffer:
    """Local aggregation for stepper-loop metrics: bounded samples per
    flush window, shipped via ONE metrics.record_batch call (one
    control-plane RPC from a worker) instead of per-step updates."""

    _SAMPLE_CAP = 64  # histogram samples kept per flush window

    def __init__(self, flush_interval_s: float = 0.5):
        self.flush_interval_s = flush_interval_s
        self._last_flush = time.perf_counter()
        self._step_samples: List[tuple] = []   # (phase, dt)
        self._token_samples: List[float] = []
        self._tokens = 0
        # stats()/flush_metrics() run on request threads concurrently
        # with the stepper's note_step — cheap uncontended lock
        self._buf_lock = locktrace.traced_lock("llm.engine.buf")

    def note_step(self, phase: str, dt: float, tokens: int,
                  active: int) -> None:
        with self._buf_lock:
            self._tokens += tokens
            if len(self._step_samples) < self._SAMPLE_CAP:
                self._step_samples.append((phase, dt))
            if tokens > 0 and active > 0 \
                    and len(self._token_samples) < self._SAMPLE_CAP:
                # per-slot per-token latency: a dense step emits one
                # token per active slot, so this is just dt; fused
                # multi-token paths amortize
                self._token_samples.append(dt * active / tokens)

    def maybe_flush(self, engine, force: bool = False) -> None:
        now = time.perf_counter()
        with self._buf_lock:
            elapsed = now - self._last_flush
            if not force and elapsed < self.flush_interval_s:
                return
            step_samples = self._step_samples
            token_samples = self._token_samples
            tokens = self._tokens
            self._step_samples = []
            self._token_samples = []
            self._tokens = 0
            self._last_flush = now
        if not step_samples and not tokens and not force:
            return
        items = [
            ("histogram", "ray_tpu_engine_step_seconds", {"phase": ph},
             dt, _STEP_BOUNDS)
            for ph, dt in step_samples
        ]
        items += [
            ("histogram", "ray_tpu_engine_token_seconds", {}, dt,
             _STEP_BOUNDS)
            for dt in token_samples
        ]
        if tokens:
            items.append(("counter",
                          "ray_tpu_engine_tokens_generated_total", {},
                          float(tokens), None))
        if elapsed > 0:
            items.append(("gauge", "ray_tpu_engine_tokens_per_second",
                          {}, tokens / elapsed, None))
        active = sum(1 for s in engine.slots if s.request is not None)
        items.append(("gauge", "ray_tpu_engine_batch_occupancy", {},
                      float(active), None))
        items.append(("gauge", "ray_tpu_engine_waiting_requests", {},
                      float(len(engine.waiting)), None))
        try:
            _metrics.record_batch(items)
        except Exception:  # graftlint: disable=GL004
            pass  # observability is best-effort


class EngineSaturatedError(RuntimeError):
    """Raised by add_request when the waiting queue is at
    EngineConfig.max_waiting_requests — the reject-before-enqueue
    hook serve admission control builds on (the LLM server converts
    this into a typed BackpressureError / HTTP 503)."""

    def __init__(self, waiting: int, cap: int):
        self.waiting = waiting
        self.cap = cap
        super().__init__(
            f"engine waiting queue is full ({waiting}/{cap}); "
            "retry after the batch drains")


@dataclass
class EngineConfig:
    # default vocab covers the ByteTokenizer's 258 ids (256 bytes + BOS/EOS)
    model: LlamaConfig = field(
        default_factory=lambda: LlamaConfig.tiny(vocab_size=258))
    max_batch: int = 8
    max_seq: int = 512
    tokenizer: Optional[str] = None  # None/"byte" or an HF id
    seed: int = 0
    # multi-LoRA bank size (adapter slot 0 is the zero/base adapter);
    # 0 disables the LoRA path entirely (no bank in the decode program)
    max_loras: int = 0
    lora_rank: int = 8
    # Weight-only quantization for serving (reference: vLLM
    # quantization passthrough, vllm_models.py:214). "int8" quantizes
    # the target model's FFN stacks on load (per-output-channel
    # scales; Pallas in-register-dequant matmul on TPU — see
    # ops/quant_matmul.py). None serves in the working dtype.
    quantization: Optional[str] = None
    # Static top-k width for on-device sampling: XLA needs a fixed
    # lax.top_k width, so per-request top_k is CLAMPED to this (also at
    # add_request, so the effective value is visible on the request).
    # top_k=0 samples the full vocab.
    max_top_k: int = 256
    # Speculative decoding (reference: vLLM spec-decode): a small
    # draft model greedily proposes spec_tokens-1 tokens per round and
    # the target scores the whole chunk in ONE llama_verify_step
    # forward — up to spec_tokens tokens emitted per target forward.
    # Greedy (temperature<=0) requests get the speculative fast path;
    # sampled requests fall back to one target-verified token per
    # round (still correct, no speedup). None disables.
    # Numerics: every emitted token is the argmax of TARGET logits
    # computed by the chunked verify program; in bf16 that can break
    # argmax ties differently than the single-token decode program
    # (bitwise parity with the dense path holds in f32).
    draft_model: Optional[LlamaConfig] = None
    spec_tokens: int = 4
    # Multi-step scheduling (reference: vLLM --num-scheduler-steps):
    # fuse multi_step decode iterations into ONE device dispatch
    # (lax.scan), amortizing host-device round trips when decode is
    # dispatch-bound. Tokens a request cannot absorb (stop token or
    # max_tokens hit mid-chunk) are discarded host-side: greedy
    # outputs are identical to single-step decoding; sampled requests
    # draw from the same distributions under a different RNG stream.
    # Mutually exclusive with draft_model (the draft cache cannot be
    # kept in sync through a fused chunk).
    multi_step: int = 1
    # Automatic prefix caching (reference: vLLM
    # --enable-prefix-caching): completed prompt KV blocks are kept in
    # an LRU keyed by the token prefix; a new prompt sharing a cached
    # prefix prefills ONLY its suffix (one llama_verify_step chunk at
    # the prefix boundary). Pays off when requests share a long
    # system prompt. Entries hold device (HBM) KV blocks — size the
    # LRU to the memory you can spare. LoRA prefills bypass the cache
    # (adapter-specific KV must not leak across adapters).
    enable_prefix_caching: bool = False
    prefix_cache_entries: int = 16
    prefix_cache_min_tokens: int = 8
    # Chunked prefill (reference: vLLM --enable-chunked-prefill):
    # instead of admission running one whole-prompt prefill that
    # stalls every decoding request for the prompt's full forward,
    # prompts prefill in chunks of this many tokens, one chunk per
    # step, interleaved with decode dispatches — bounding the
    # inter-token latency hit of a long prompt joining the batch to
    # ~one chunk forward. 0 disables. Mutually exclusive with
    # draft_model and enable_prefix_caching; LoRA-adapter requests
    # fall back to blocking prefill.
    chunked_prefill_tokens: int = 0
    # Reject-before-enqueue backpressure (serve admission control):
    # add_request raises EngineSaturatedError instead of appending
    # once this many requests are already waiting — bounding the
    # engine queue so the serve chain sheds instead of building an
    # invisible in-engine backlog. 0 disables (unbounded waiting).
    max_waiting_requests: int = 0


@dataclass
class GenerationRequest:
    prompt_ids: List[int]
    max_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    stop_ids: tuple = ()
    # OpenAI-style logit bias: {token_id: bias} added to the target
    # logits before sampling, every step (values clamped to +-100).
    # Applied in ALL decode paths; speculative drafts propose without
    # it, so a bias that changes the argmax lowers draft acceptance
    # but never affects outputs.
    logit_bias: Optional[Dict[int, float]] = None
    # OpenAI presence/frequency penalties: subtracted from the logits
    # of already-generated tokens each step (presence once per distinct
    # token, frequency per occurrence). Implemented on the SAME
    # device-bias-row machinery as guided decoding: the row is
    # recomputed host-side after each emission (bias_stale) — one [V]
    # upload per penalized slot per step.
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # OpenAI logprobs: None = off; an int >= 0 = number of top
    # alternatives to record per emitted token (0 still records the
    # CHOSEN token's logprob with an empty top list, matching OpenAI's
    # logprobs=0 / top_logprobs=0 semantics; clamped to the engine's
    # static top-k width). Logprobs are log-softmax of the BIASED but
    # UN-temperature-scaled logits — the model's distribution after
    # logit_bias/penalties/grammar masks, before sampling temperature
    # and top-k truncation (the raw-logprobs convention; a sampled
    # token's reported logprob is not its realized sampling
    # probability at temperature != 1). Requests with logprobs take
    # the dense decode path (the fused multi-token paths do not
    # return per-step logprob tensors).
    logprobs: Optional[int] = None
    # Guided decoding (reference: vLLM guided decoding behind
    # response_format/tools): a ray_tpu.llm.guided.TokenConstraint.
    # Its per-state token mask folds into the slot's device bias row
    # (-1e9 on disallowed ids) so the constraint is enforced inside
    # the on-device sampler; the engine advances guided_state per
    # emitted token. Fast batch paths that cannot refresh masks
    # mid-chunk (speculative, multi-step) fall back to dense stepping
    # while any guided request is active.
    guided: Optional[Any] = None
    guided_state: Any = None
    # LoRA adapter name (must be register_adapter'd); None = base model
    adapter: Optional[str] = None
    request_id: int = field(default_factory=itertools.count().__next__)
    # Streaming: when set (queue.Queue), the stepper pushes each emitted
    # token as it decodes; None terminates the stream (reference: vLLM's
    # per-request output stream consumed by serve token streaming).
    stream_queue: Optional[Any] = None
    # filled by the engine
    output_ids: List[int] = field(default_factory=list)
    # per emitted token (when logprobs > 0):
    # {"id", "logprob", "top": [(id, logprob), ...]}
    logprob_data: List[Dict[str, Any]] = field(default_factory=list)
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    # set when finish_reason lands; waiters block on this instead of
    # polling `done` in a sleep loop (graftlint GL003)
    done_event: threading.Event = field(default_factory=threading.Event,
                                        repr=False, compare=False)

    @property
    def done(self) -> bool:
        return self.finish_reason is not None

    def finish(self, reason: str, error: Optional[str] = None) -> None:
        """Mark finished and wake waiters. The ONE completion path —
        assigning finish_reason directly would leave done_event unset
        and strand ``wait_done`` callers."""
        if error is not None:
            self.error = error
        self.finish_reason = reason
        self.done_event.set()

    def wait_done(self, timeout: Optional[float] = None) -> bool:
        """Block until the engine finishes this request. Returns
        ``done`` (False on timeout)."""
        self.done_event.wait(timeout)
        return self.done

    def push_stream(self, item) -> None:
        if self.stream_queue is not None:
            try:
                self.stream_queue.put_nowait(item)
            except Exception:  # graftlint: disable=GL004
                pass  # stream consumer is gone; tokens just drop


class _Slot:
    def __init__(self, index: int):
        self.index = index
        self.request: Optional[GenerationRequest] = None
        self.pos = 0            # position of the NEXT token to decode
        self.next_token = 0
        # False when the draft cache lacks this slot's prompt prefix
        # (disagg adopt without usable prompt_ids) — speculation is
        # skipped while such a slot is active
        self.draft_ready = True
        # chunked prefill: prompt tokens still being prefilled
        self.prefilling = False
        self.prefill_ids: Optional[List[int]] = None
        self.prefill_pos = 0
        # guided decoding: the slot's device bias row no longer matches
        # the request's automaton state (refreshed at the next step)
        self.bias_stale = False
        # logprob rows (chosen_lp, top_vals, top_ids) for the token
        # about to be emitted; consumed (and cleared) by _emit
        self.pending_lp = None


class ContinuousBatchingEngine:
    def __init__(self, config: EngineConfig, params=None,
                 draft_params=None):
        import jax
        import jax.numpy as jnp

        self.config = config
        c = config.model
        if params is None:
            # random weights — real checkpoints load via orbax/train
            params = llama_init(jax.random.PRNGKey(config.seed), c)
        if config.quantization is not None:
            if config.quantization != "int8":
                raise ValueError(
                    f"unknown quantization {config.quantization!r} "
                    "(supported: \"int8\")")
            from ray_tpu.models.llama import quantize_llama_ffn
            # pre-quantized checkpoints (w1_q8 already present) load
            # as-is; float checkpoints quantize on load
            if "w1_q8" not in params["layers"]:
                params = quantize_llama_ffn(params, c)
        self.params = params
        self.cache_k, self.cache_v = llama_init_cache(
            c, config.max_batch, config.max_seq)
        # per-slot logit_bias rows, device-resident so the per-step
        # cost is one [B, V] add — rows are (re)set at admission, so
        # stale rows from finished requests are never read
        self._bias = jnp.zeros((config.max_batch, c.vocab_size),
                               jnp.float32)

        def set_bias_row(bias, row, idx):
            return jax.lax.dynamic_update_slice(
                bias, row[None, :], (idx, 0))

        # idx stays a traced operand: dynamic_update_slice takes
        # dynamic starts, so ONE compile covers every slot (a static
        # idx would compile per slot index)
        self._set_bias = jax.jit(set_bias_row, donate_argnums=(0,))
        self._zero_bias_row = jnp.zeros((c.vocab_size,), jnp.float32)
        # Scratch region: every batched dispatch writes K/V rows for
        # ALL slots, so slots not participating park their writes in
        # the cache tail. Those rows must never hold live history —
        # rows BELOW a slot's position are attended without being
        # rewritten, so clobbering one corrupts generation (rows at or
        # above the position are always written before they become
        # visible). The region is sized for the widest parked write
        # (spec chunk, prefill chunk, multi-step burst, or the 1-row
        # dense step) and requests retire before reaching it.
        self._spec = config.draft_model is not None
        if self._spec:
            dc = config.draft_model
            if dc.vocab_size != c.vocab_size:
                raise ValueError(
                    "draft_model vocab_size must match the target's")
            if config.spec_tokens < 2:
                raise ValueError("spec_tokens must be >= 2 (1 draft + "
                                 "1 verified token minimum)")
            if draft_params is None:
                draft_params = llama_init(
                    jax.random.PRNGKey(config.seed + 1), dc)
            self.draft_params = draft_params
            self.draft_cache_k, self.draft_cache_v = llama_init_cache(
                dc, config.max_batch, config.max_seq)
        scratch = 0
        if self._spec:
            scratch = max(scratch, config.spec_tokens)
        if config.chunked_prefill_tokens > 0:
            scratch = max(scratch, config.chunked_prefill_tokens)
        if config.multi_step > 1:
            scratch = max(scratch, config.multi_step)
        self._pos_limit = config.max_seq - 1 - scratch
        if self._pos_limit < 1:
            raise ValueError(
                f"max_seq={config.max_seq} leaves no usable positions "
                f"after the {scratch}-row scratch region")
        # Plain engines (scratch 0) park idle slots at row 0: idle
        # slots hold no live rows and the next occupant's prefill
        # insert overwrites row 0, so the legacy park keeps the full
        # max_seq-1 context. With a scratch region, parking moves
        # there because a PREFILLING slot's rows below its position
        # are live history.
        self._dense_park = config.max_seq - 1 if scratch else 0
        self.slots = [_Slot(i) for i in range(config.max_batch)]
        self.waiting: List[GenerationRequest] = []
        # disaggregated requests: (request, ks, vs, prompt_len, token)
        self._prefilled_waiting: List[tuple] = []
        self._lock = locktrace.traced_lock("llm.engine")
        self.total_generated = 0
        self._base_key = jax.random.PRNGKey(config.seed)
        self._step_counter = 0
        self._mbuf = _MetricsBuffer()
        self._admitted_last_step = 0
        # multi-LoRA bank: slot 0 is the all-zero base adapter, so
        # "no adapter" needs no conditional in the decode program
        self._adapters: Dict[str, int] = {}
        self._adapter_prefill: Dict[str, Any] = {}
        self._next_adapter_slot = 1  # slot 0 = base (all-zero)
        if config.max_loras > 0:
            n, r, hd = config.max_loras + 1, config.lora_rank, c.head_dim
            self.lora_bank = {
                "A_q": jnp.zeros((n, c.n_layers, c.dim, r), c.dtype),
                "B_q": jnp.zeros((n, c.n_layers, r, c.n_heads * hd),
                                 c.dtype),
                "A_v": jnp.zeros((n, c.n_layers, c.dim, r), c.dtype),
                "B_v": jnp.zeros((n, c.n_layers, r, c.n_kv_heads * hd),
                                 c.dtype),
                # per-adapter scale folded into B at registration
                "scale": jnp.asarray(1.0, c.dtype),
            }
        else:
            self.lora_bank = None

        max_k = min(config.max_top_k, c.vocab_size)
        lp_k = min(20, c.vocab_size)  # static top-logprobs width
        self._lp_k = lp_k

        def sample_tokens(logits, temp, topk, key, bias=None):
            """On-device sampling: greedy / temperature / top-k per
            slot, [B, V] logits -> [B] int32 — only the token ids cross
            to the host. ``bias`` [B, V] is the per-slot logit_bias."""
            n_b = logits.shape[0]
            if bias is not None:
                logits = logits + bias
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
            keys = jax.random.split(key, n_b)
            full = jax.vmap(jax.random.categorical)(
                keys, scaled).astype(jnp.int32)
            vals, idx = jax.lax.top_k(scaled, max_k)
            mask = (jnp.arange(max_k)[None, :]
                    < jnp.clip(topk, 1, max_k)[:, None])
            vals = jnp.where(mask, vals, -jnp.inf)
            choice = jax.vmap(jax.random.categorical)(keys, vals)
            topk_tok = jnp.take_along_axis(
                idx, choice[:, None], axis=1)[:, 0].astype(jnp.int32)
            sampled = jnp.where(topk > 0, topk_tok, full)
            return jnp.where(temp <= 0.0, greedy, sampled)

        def decode(params, cache_k, cache_v, tokens, pos, temp, topk,
                   base_key, step, lora_bank, lora_idx, bias,
                   want_lp=False):
            logits, ck, cv = llama_decode_step(
                params, tokens, cache_k, cache_v, pos, c,
                lora_bank=lora_bank, lora_idx=lora_idx)
            key = jax.random.fold_in(base_key, step)
            tok = sample_tokens(logits, temp, topk, key, bias)
            if not want_lp:
                # static arg: the no-logprobs program carries none of
                # the log_softmax/top_k work or output buffers
                return tok, None, None, None, ck, cv
            # logprobs of the biased (un-temperature-scaled) logits;
            # [B] chosen + [B, lp_k] top alternatives — tiny transfers
            lsm = jax.nn.log_softmax(
                (logits + bias).astype(jnp.float32), axis=-1)
            chosen = jnp.take_along_axis(lsm, tok[:, None], 1)[:, 0]
            top_vals, top_ids = jax.lax.top_k(lsm, lp_k)
            return tok, chosen, top_vals, top_ids, ck, cv

        def prefill(params, tokens, lora):
            return llama_prefill(params, tokens, c, lora=lora)

        def sample_one(logits, temp, topk, key, bias_row,
                       want_lp=False):
            tok = sample_tokens(
                logits[None, :], jnp.full((1,), temp),
                jnp.full((1,), topk, dtype=jnp.int32), key,
                bias_row[None, :])[0]
            if not want_lp:
                return tok, None, None, None
            lsm = jax.nn.log_softmax(
                (logits + bias_row).astype(jnp.float32))
            chosen = lsm[tok]
            top_vals, top_ids = jax.lax.top_k(lsm, lp_k)
            return tok, chosen, top_vals, top_ids

        def insert(cache_k, cache_v, ks, vs, slot):
            # in-place (donated) slot write — no whole-cache copy.
            # ks/vs: [L, 1, bucket, KVH, HD] from a batch-1 prefill.
            ck = jax.lax.dynamic_update_slice(
                cache_k, ks, (0, slot, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                cache_v, vs, (0, slot, 0, 0, 0))
            return ck, cv

        self._decode = jax.jit(decode, donate_argnums=(1, 2),
                               static_argnames=("want_lp",))
        self._prefill = jax.jit(prefill)
        self._sample_one = jax.jit(sample_one,
                                   static_argnames=("want_lp",))
        self._insert = jax.jit(insert, donate_argnums=(0, 1))

        if config.enable_prefix_caching:
            import collections
            # token-tuple -> (ks, vs, prompt_len); LRU, device-resident
            self._prefix_cache = collections.OrderedDict()
            self.prefix_hits = 0
            self.prefix_misses = 0

            def suffix_prefill(tparams, cks, cvs, chunk, start, *,
                               bucket):
                """Seed-and-score in ONE program: pad/crop the cached
                prefix KV to the target bucket and verify the suffix
                chunk at the boundary. Fusing the seeding in keeps the
                hit path at a single dispatch — separate zeros +
                at[].set copies cost more than the full prefill they
                replace."""
                bp = cks.shape[2]
                if bp < bucket:
                    pad = ((0, 0), (0, 0), (0, bucket - bp),
                           (0, 0), (0, 0))
                    base_k = jnp.pad(cks, pad)
                    base_v = jnp.pad(cvs, pad)
                else:
                    base_k = cks[:, :, :bucket]
                    base_v = cvs[:, :, :bucket]
                return llama_verify_step(tparams, chunk, base_k,
                                         base_v, start, c)

            self._suffix_prefill = jax.jit(suffix_prefill,
                                           static_argnames=("bucket",))
        else:
            self._prefix_cache = None

        if config.chunked_prefill_tokens > 0:
            C = config.chunked_prefill_tokens
            if self._spec:
                raise ValueError("chunked_prefill_tokens and "
                                 "draft_model are mutually exclusive")
            if config.enable_prefix_caching:
                raise ValueError("chunked_prefill_tokens and "
                                 "enable_prefix_caching are mutually "
                                 "exclusive")
            def chunk_prefill(tparams, ck, cv, chunk, pos, last_idx,
                              temp, topk, base_key, step, bias):
                """One C-token prefill chunk for every prefilling slot
                (idle/decoding slots park their writes); returns the
                sampled first token per slot, used only for slots
                whose prompt completed this round."""
                logits, ck, cv = llama_verify_step(
                    tparams, chunk, ck, cv, pos, c)
                sel = jnp.take_along_axis(
                    logits, last_idx[:, None, None], axis=1)[:, 0]
                key = jax.random.fold_in(base_key, step)
                tok = sample_tokens(sel, temp, topk, key, bias)
                return tok, ck, cv

            self._chunk_prefill = jax.jit(chunk_prefill,
                                          donate_argnums=(1, 2))

        if config.multi_step > 1:
            if self._spec:
                raise ValueError(
                    "multi_step and draft_model are mutually exclusive")
            K = config.multi_step

            def decode_multi(params, cache_k, cache_v, tokens, pos,
                             temp, topk, base_key, step,
                             lora_bank, lora_idx, bias):
                """K fused decode iterations — one dispatch for up to
                K tokens per slot."""
                round_key = jax.random.fold_in(base_key, step)

                def body(carry, i):
                    tok, ck, cv = carry
                    logits, ck, cv = llama_decode_step(
                        params, tok, ck, cv, pos + i, c,
                        lora_bank=lora_bank, lora_idx=lora_idx)
                    key = jax.random.fold_in(round_key, i)
                    nxt = sample_tokens(logits, temp, topk, key, bias)
                    return (nxt, ck, cv), nxt

                (_, ck, cv), toks = jax.lax.scan(
                    body, (tokens, cache_k, cache_v), jnp.arange(K))
                return toks, ck, cv              # toks: [K, B]

            self._decode_multi = jax.jit(decode_multi,
                                         donate_argnums=(1, 2))

        if self._spec:
            dc = config.draft_model
            n_draft = config.spec_tokens - 1

            def draft_propose(dparams, ck, cv, token0, pos0):
                """All greedy draft steps fused into ONE program
                (lax.scan) — one device dispatch per round instead of
                G-1, which matters when decode is dispatch-bound.

                The scan runs G (not G-1) steps: the extra step's
                OUTPUT is discarded, but it writes d_{G-1}'s K/V into
                the draft cache — on full acceptance the next round
                starts at pos+G, and without that row the draft would
                attend a junk row forever after, silently collapsing
                acceptance exactly in the high-acceptance regime."""
                def body(carry, i):
                    tok, ck, cv = carry
                    logits, ck, cv = llama_decode_step(
                        dparams, tok, ck, cv, pos0 + i, dc)
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    return (nxt, ck, cv), nxt

                (_, ck, cv), drafts = jax.lax.scan(
                    body, (token0, ck, cv), jnp.arange(n_draft + 1))
                return drafts[:n_draft], ck, cv   # drafts: [G-1, B]

            def draft_sync(dparams, ck, cv, tokens, pos):
                """Dense-path companion: write the fed tokens' K/V into
                the draft cache (output discarded) so dense fallback
                rounds don't leave gaps that desync the draft."""
                _logits, ck, cv = llama_decode_step(
                    dparams, tokens, ck, cv, pos, dc)
                return ck, cv

            def verify(tparams, ck, cv, chunk, pos, temp, topk,
                       base_key, step, bias):
                logits, ck, cv = llama_verify_step(
                    tparams, chunk, ck, cv, pos, c)
                greedy = jnp.argmax(logits + bias[:, None, :],
                                    axis=-1).astype(jnp.int32)
                key = jax.random.fold_in(base_key, step)
                first = sample_tokens(logits[:, 0], temp, topk, key,
                                      bias)
                return greedy, first, ck, cv

            self._draft_propose = jax.jit(draft_propose,
                                          donate_argnums=(1, 2))
            self._draft_sync = jax.jit(draft_sync, donate_argnums=(1, 2))
            self._verify = jax.jit(verify, donate_argnums=(1, 2))
            self._draft_prefill = jax.jit(
                lambda p, t: llama_prefill(p, t, dc))

        self._jax = jax
        self._jnp = jnp

    # ------------------------------------------------------------------
    def register_adapter(self, name: str, lora_params) -> None:
        """Install a LoRA adapter into the bank under ``name``
        (reference: vLLM add_lora / serve model multiplexing). The
        adapter's alpha/rank scale is folded into its B matrices so the
        decode program stays scale-free."""
        if self.lora_bank is None:
            raise ValueError("engine built with max_loras=0")
        jnp = self._jnp
        scale = float(lora_params.get("scale", 1.0))
        folded = dict(lora_params)
        folded["B_q"] = lora_params["B_q"] * scale
        folded["B_v"] = lora_params["B_v"] * scale
        folded["scale"] = jnp.asarray(1.0, self.config.model.dtype)
        rank = int(folded["A_q"].shape[-1])
        bank_rank = self.config.lora_rank
        if rank > bank_rank:
            raise ValueError(
                f"adapter rank {rank} exceeds the engine's lora_rank "
                f"{bank_rank}")
        if rank < bank_rank:
            # zero-pad up to the bank's static rank: the extra zero
            # columns are exactly the identity, so the math is unchanged
            pad = bank_rank - rank
            for part, axis in (("A_q", -1), ("A_v", -1),
                               ("B_q", -2), ("B_v", -2)):
                widths = [(0, 0)] * folded[part].ndim
                widths[axis] = (0, pad)
                folded[part] = jnp.pad(folded[part], widths)
        # Full shape validation BEFORE reserving anything — a failed
        # registration must not leak a bank slot.
        for part in ("A_q", "B_q", "A_v", "B_v"):
            want = self.lora_bank[part].shape[1:]
            got = tuple(folded[part].shape)
            if got != want:
                raise ValueError(
                    f"adapter {part} shape {got} does not match the "
                    f"engine's bank slot shape {want} (built for a "
                    "different model config?)")
        # Install under the lock, publishing a COMPLETE new bank dict in
        # one reference swap: the serve stepper reads self.lora_bank
        # once per step, so it sees either the old or the new bank,
        # never mismatched A/B factors; the lock serializes concurrent
        # registrations so neither's slot write is lost.
        with self._lock:
            idx = self._adapters.get(name)
            if idx is None:
                idx = self._next_adapter_slot
                if idx > self.config.max_loras:
                    raise ValueError(
                        f"LoRA bank full ({self.config.max_loras}); "
                        "raise max_loras")
                self._next_adapter_slot += 1
            new_bank = dict(self.lora_bank)
            for part in ("A_q", "B_q", "A_v", "B_v"):
                new_bank[part] = self.lora_bank[part].at[idx].set(
                    folded[part])
            self.lora_bank = new_bank
            self._adapter_prefill[name] = folded
            self._adapters[name] = idx

    def _adapter_index(self, request: GenerationRequest) -> int:
        if request.adapter is None:
            return 0
        idx = self._adapters.get(request.adapter)
        if idx is None:
            raise ValueError(f"unknown LoRA adapter {request.adapter!r}")
        return idx

    def prefill_only(self, prompt_ids: List[int], *,
                     temperature: float = 0.0, top_k: int = 0,
                     adapter: Optional[str] = None,
                     logit_bias: Optional[Dict[int, float]] = None,
                     guided: Optional[Any] = None):
        """Prefill without occupying a decode slot — the PREFILL side of
        prefill/decode disaggregation (reference: serve/llm
        prefill-decode disagg deployments). Returns numpy
        (ks, vs, prompt_len, first_token): the KV block ships through
        the object plane to a decode engine's add_prefilled().

        ``guided``: a TokenConstraint — the FIRST token is sampled
        under its start-state mask; the decode engine re-walks the
        automaton from the start state when it adopts the request, so
        prefill/decode stay consistent without shipping opaque state.
        """
        limit = self._pos_limit
        ids = list(prompt_ids)[-limit:]
        if adapter is not None and adapter not in self._adapters:
            raise ValueError(f"unknown LoRA adapter {adapter!r}")
        bias_row = None
        if logit_bias or guided is not None:
            self._validate_logit_bias(logit_bias)
            fake = GenerationRequest(prompt_ids=[], logit_bias=logit_bias,
                                     guided=guided)
            self._validate_guided(fake)
            bias_row = self._bias_row(fake)
        ks, vs, token, _lp = self._run_prefill(
            ids, adapter, temperature, top_k, bias_row=bias_row)
        return (np.asarray(ks), np.asarray(vs), len(ids), token)

    def add_prefilled(self, request: GenerationRequest, ks, vs,
                      prompt_len: int, first_token: int) -> GenerationRequest:
        """DECODE side of disaggregation: adopt a request whose prefill
        ran elsewhere — the KV block is inserted into a free slot at the
        next admit, skipping local prefill entirely."""
        if request.logprobs is not None:
            raise ValueError(
                "logprobs are not supported on the disaggregated "
                "decode path (the first token's distribution lives on "
                "the prefill engine)")
        if prompt_len > self._pos_limit:
            # pos_limit, not max_seq-1: a speculative engine reserves
            # its scratch rows, and admitting past the limit would
            # end the request after exactly one token
            raise ValueError("prefilled prompt exceeds this engine's "
                             "position limit")
        if ks.shape[2] > self.config.max_seq:
            raise ValueError(
                f"prefilled KV bucket ({ks.shape[2]}) exceeds this "
                f"engine's max_seq ({self.config.max_seq})")
        self._validate_logit_bias(request.logit_bias)
        self._validate_guided(request)
        if request.adapter is not None:
            self._adapter_index(request)  # fail fast: an unknown
            # adapter raising inside step() would fail_all the replica
        if request.top_k > self.config.max_top_k:
            request.top_k = self.config.max_top_k
        request._t_submit = time.perf_counter()
        with self._lock:
            self._prefilled_waiting.append(
                (request, ks, vs, prompt_len, first_token))
        return request

    def add_request(self, request: GenerationRequest) -> GenerationRequest:
        request._t_submit = time.perf_counter()
        self._validate_logit_bias(request.logit_bias)
        self._validate_guided(request)
        limit = self._pos_limit
        if len(request.prompt_ids) > limit:
            request.prompt_ids = request.prompt_ids[-limit:]
        if request.adapter is not None:
            self._adapter_index(request)  # fail fast on unknown names
        if request.top_k > self.config.max_top_k:
            # the sampler's static width bounds per-request top-k; make
            # the effective value visible rather than silently narrower
            request.top_k = self.config.max_top_k
        if request.logprobs is not None:
            request.logprobs = min(max(int(request.logprobs), 0),
                                   self._lp_k)
        with self._lock:
            cap = self.config.max_waiting_requests
            if cap > 0 and len(self.waiting) >= cap:
                waiting = len(self.waiting)
            else:
                waiting = None
                self.waiting.append(request)
        if waiting is not None:
            raise EngineSaturatedError(waiting, cap)
        return request

    def has_work(self) -> bool:
        with self._lock:
            return (bool(self.waiting) or bool(self._prefilled_waiting)
                    or any(s.request is not None for s in self.slots))

    def _free_slots(self) -> List[_Slot]:
        return [s for s in self.slots if s.request is None]

    def _admit_prefilled(self) -> None:
        """Adopt disaggregated requests: their KV arrives ready-made
        from a prefill engine; just insert into a free slot."""
        jnp = self._jnp
        while True:
            with self._lock:
                if not self._prefilled_waiting:
                    return
                free = self._free_slots()
                if not free:
                    return
                request, ks, vs, plen, tok = self._prefilled_waiting.pop(0)
                slot = free[0]
                slot.request = request
            self._install_bias(request, slot.index)
            self.cache_k, self.cache_v = self._insert(
                self.cache_k, self.cache_v, jnp.asarray(ks),
                jnp.asarray(vs), slot.index)
            if self._spec:
                # disagg ships only the TARGET KV; rebuild the draft's
                # prefix locally (draft prefill is cheap). The draft
                # must see EXACTLY the plen tokens the target KV was
                # built from — the disagg protocol may adopt with
                # empty/shorter ids ("KV already computed"), in which
                # case this slot decodes dense (draft_ready=False)
                # rather than speculating on a garbage prefix.
                ids = list(request.prompt_ids)
                if len(ids) >= plen:
                    self._draft_prefill_slot(ids[-plen:], slot.index)
                    slot.draft_ready = True
                else:
                    slot.draft_ready = False
            slot.next_token = tok
            slot.pos = plen
            self._emit(slot, tok)

    def _run_prefill(self, ids: List[int], adapter: Optional[str],
                     temperature: float, top_k: int,
                     bias_row=None, want_logprobs: bool = False):
        """Shared prefill: bucket/pad the prompt, run the jitted
        prefill, sample the first token. Both the colocated admit path
        and prefill_only (disaggregation) call this — one copy, so the
        exact-parity guarantee between the two modes can't drift."""
        jnp = self._jnp
        use_cache = self._prefix_cache is not None and adapter is None
        hit = self._match_prefix(ids) if use_cache else None
        if hit is not None:
            # suffix chunk must fit below max_seq alongside the prefix
            plen_p = hit[2]
            if plen_p + self._bucket_len(len(ids) - plen_p) > \
                    self.config.max_seq:
                hit = None
        if hit is None:
            if use_cache:
                with self._lock:
                    self.prefix_misses += 1
            padded = self._pad_bucket(ids)
            lora = self._adapter_prefill.get(adapter) if adapter else None
            logits, ks, vs = self._prefill(
                self.params, jnp.asarray(padded), lora)
            last_logits = logits[0, len(ids) - 1]
        else:
            # suffix-only prefill: ONE fused program pads the cached
            # prefix KV to the target bucket and scores the suffix
            # chunk at the prefix boundary. Donor rows past the match
            # point may hold ANOTHER prompt's live KV (a longest-
            # common-prefix hit copies the whole entry) — they never
            # leak only because every row at or above plen_p is
            # rewritten (by this suffix chunk or a later decode)
            # before it becomes attendable. Do not weaken that
            # invariant.
            with self._lock:
                self.prefix_hits += 1
            cks, cvs, plen_p = hit
            suffix = ids[plen_p:]
            chunk_len = self._bucket_len(len(suffix))
            bucket = self._bucket_len(plen_p + chunk_len)
            chunk = np.zeros((1, chunk_len), dtype=np.int32)
            chunk[0, : len(suffix)] = suffix
            logits, ks, vs = self._suffix_prefill(
                self.params, cks, cvs, jnp.asarray(chunk),
                jnp.asarray([plen_p], dtype=jnp.int32), bucket=bucket)
            last_logits = logits[0, len(suffix) - 1]
        # stepper-thread-only RNG state
        self._step_counter += 1  # graftlint: disable=GL001
        bias_dev = (self._zero_bias_row if bias_row is None
                    else jnp.asarray(bias_row))
        token, chosen, top_vals, top_ids = self._sample_one(
            last_logits, float(temperature), int(top_k),
            self._jax.random.fold_in(self._base_key, self._step_counter),
            bias_dev, want_lp=want_logprobs)
        if use_cache:
            self._store_prefix(ids, ks, vs)
        first_lp = (float(chosen), np.asarray(top_vals),
                    np.asarray(top_ids)) if want_logprobs else None
        return ks, vs, int(token), first_lp

    def _validate_logit_bias(self, logit_bias) -> None:
        """Reject out-of-vocab ids on the CALLER's thread — every
        admission entry point (add_request, add_prefilled,
        prefill_only) funnels through this, because a raise inside the
        stepper's _admit would fail_all the whole replica, and a
        negative id would silently wrap to the vocab tail in numpy
        indexing."""
        if not logit_bias:
            return
        vocab = self.config.model.vocab_size
        for tid in logit_bias:
            if not 0 <= int(tid) < vocab:
                raise ValueError(
                    f"logit_bias token id {tid} outside vocab "
                    f"[0, {vocab})")

    def _validate_guided(self, request: GenerationRequest) -> None:
        """Caller-thread validation + state init for guided requests
        (same fail-fast rationale as _validate_logit_bias)."""
        if request.guided is None:
            return
        if request.guided.vocab_size > self.config.model.vocab_size:
            raise ValueError(
                f"guided constraint vocab ({request.guided.vocab_size}) "
                f"exceeds model vocab ({self.config.model.vocab_size})")
        if request.guided_state is None:
            request.guided_state = request.guided.start_state()

    @staticmethod
    def _has_dynamic_bias(request: GenerationRequest) -> bool:
        """True when the slot's bias row depends on what has been
        generated so far (guided mask / repetition penalties) and must
        be refreshed between steps — such requests are excluded from
        the fused multi-token fast paths."""
        return (request.guided is not None
                or request.presence_penalty != 0.0
                or request.frequency_penalty != 0.0)

    def _bias_row(self, request: GenerationRequest) -> np.ndarray:
        """Dense [V] f32 bias row from the request's sparse
        logit_bias (values clamped to the OpenAI +-100 range; ids
        outside the vocab rejected at add_request), combined with
        presence/frequency penalties over the tokens generated so far
        and with the guided-decoding mask for the request's CURRENT
        automaton state (-1e9 on disallowed ids — far below every
        other term, so nothing resurrects a grammar-banned token)."""
        vocab = self.config.model.vocab_size
        row = np.zeros(vocab, dtype=np.float32)
        for tid, val in (request.logit_bias or {}).items():
            row[int(tid)] = float(np.clip(val, -100.0, 100.0))
        if (request.presence_penalty or request.frequency_penalty) \
                and request.output_ids:
            ids, counts = np.unique(
                np.asarray(request.output_ids, dtype=np.int64),
                return_counts=True)
            keep = (ids >= 0) & (ids < vocab)
            ids, counts = ids[keep], counts[keep]
            row[ids] -= (request.presence_penalty
                         + request.frequency_penalty * counts)
        if request.guided is not None and request.guided_state is not None:
            mask = request.guided.token_mask(request.guided_state)
            penalty = np.full(vocab, -1e9, dtype=np.float32)
            penalty[: mask.shape[0]][mask] = 0.0
            row = row + penalty
        return row

    def _install_bias(self, request: GenerationRequest,
                      slot_index: int) -> None:
        if request.logit_bias or self._has_dynamic_bias(request):
            row = self._jnp.asarray(self._bias_row(request))
        else:
            row = self._zero_bias_row  # no per-request host build/copy
        self._bias = self._set_bias(self._bias, row,
                                    self._jnp.asarray(slot_index))

    def _bucket_len(self, n: int) -> int:
        bucket = 1
        while bucket < n:
            bucket *= 2
        return min(bucket, self.config.max_seq)

    def _pad_bucket(self, ids: List[int]) -> np.ndarray:
        """Power-of-two bucket/pad a prompt — ONE copy of the policy so
        target and draft prefills can't drift apart (each distinct
        bucket is its own XLA program)."""
        bucket = self._bucket_len(len(ids))
        padded = np.zeros((1, bucket), dtype=np.int32)
        padded[0, : len(ids)] = ids
        return padded

    # -- prefix caching -------------------------------------------------
    def _match_prefix(self, ids: List[int]):
        """Longest COMMON prefix between ids and any cached prompt.

        Causal attention makes any prefix of a cached KV block valid
        on its own, so two prompts sharing only a system prompt still
        hit (the classic case: cached "A+B1" serves "A+B2" up to the
        shared A). Capped at len(ids)-1 so at least one suffix token
        remains to produce the first-token logits.

        Runs under the engine lock — prefill_only is reachable from
        concurrent replica request threads, and an unlocked
        OrderedDict scan would race _store_prefix's insert/evict.
        The token compare is vectorized (numpy mismatch scan), not a
        Python loop — this sits on the TTFT-critical path.
        """
        ids_arr = np.asarray(ids, dtype=np.int64)
        best_key, best_l = None, 0
        with self._lock:
            for key, (key_arr, _ks, _vs) in self._prefix_cache.items():
                n = min(len(key_arr), len(ids_arr))
                neq = np.nonzero(key_arr[:n] != ids_arr[:n])[0]
                l = int(neq[0]) if neq.size else n
                l = min(l, len(ids) - 1)
                if l > best_l:
                    best_key, best_l = key, l
            if best_key is None or \
                    best_l < self.config.prefix_cache_min_tokens:
                return None
            self._prefix_cache.move_to_end(best_key)
            _key_arr, ks, vs = self._prefix_cache[best_key]
            return ks, vs, best_l

    def _store_prefix(self, ids: List[int], ks, vs) -> None:
        key = tuple(ids)
        if len(key) < self.config.prefix_cache_min_tokens:
            return
        with self._lock:
            if key in self._prefix_cache:
                return
            self._prefix_cache[key] = (
                np.asarray(ids, dtype=np.int64), ks, vs)
            while len(self._prefix_cache) > \
                    self.config.prefix_cache_entries:
                self._prefix_cache.popitem(last=False)

    def _draft_prefill_slot(self, ids: List[int], slot_index: int) -> None:
        """Prefill the DRAFT model's cache for a newly admitted prompt
        so its proposals condition on the real prefix (cheap — the
        draft is small by construction)."""
        jnp = self._jnp
        _logits, ks, vs = self._draft_prefill(
            self.draft_params, jnp.asarray(self._pad_bucket(ids)))
        self.draft_cache_k, self.draft_cache_v = self._insert(
            self.draft_cache_k, self.draft_cache_v, ks, vs, slot_index)

    def _admit(self) -> None:
        """Prefill waiting requests into free slots."""
        self._admit_prefilled()
        while True:
            with self._lock:
                if not self.waiting:
                    return
                free = self._free_slots()
                if not free:
                    return
                request = self.waiting.pop(0)
                slot = free[0]
                slot.request = request
            self._admitted_last_step += 1  # graftlint: disable=GL001  # stepper-thread-only
            ids = request.prompt_ids
            self._install_bias(request, slot.index)
            C = self.config.chunked_prefill_tokens
            if C > 0 and request.adapter is None \
                    and request.logprobs is None:
                # chunked admission: no blocking prefill — step() will
                # advance this prompt one chunk at a time. Every chunk
                # write stays in bounds because add_request truncated
                # the prompt to _pos_limit = max_seq-1-scratch with
                # scratch >= C. LoRA requests lack a chunk-program
                # path and take the blocking prefill below.
                slot.prefilling = True
                slot.prefill_ids = list(ids)
                slot.prefill_pos = 0
                slot.pos = 0
                slot.next_token = 0
                continue
            ks, vs, token, first_lp = self._run_prefill(
                ids, request.adapter, request.temperature,
                request.top_k,
                bias_row=(self._bias_row(request)
                          if request.logit_bias
                          or self._has_dynamic_bias(request) else None),
                want_logprobs=request.logprobs is not None)
            if request.logprobs is not None:
                slot.pending_lp = first_lp
            self.cache_k, self.cache_v = self._insert(
                self.cache_k, self.cache_v, ks, vs, slot.index)
            if self._spec:
                self._draft_prefill_slot(ids, slot.index)
                slot.draft_ready = True
            slot.next_token = token
            slot.pos = len(ids)
            self._emit(slot, slot.next_token)

    def _emit(self, slot: _Slot, token: int) -> None:
        request = slot.request
        if request.done:
            # cancelled from another thread mid-step: discard the
            # token and release the slot
            slot.request = None
            return
        request.output_ids.append(token)
        self.total_generated += 1
        if len(request.output_ids) == 1:
            t_submit = getattr(request, "_t_submit", None)
            if t_submit is not None:
                # per-request, not per-step: direct record is fine
                try:
                    ENGINE_TTFT.observe(
                        max(0.0, time.perf_counter() - t_submit))
                except Exception:  # graftlint: disable=GL004
                    pass  # metric observe is best-effort
        if request.logprobs is not None and slot.pending_lp is not None:
            chosen, top_vals, top_ids = slot.pending_lp
            k = min(request.logprobs, len(top_ids))
            request.logprob_data.append({
                "id": token, "logprob": float(chosen),
                "top": [(int(top_ids[i]), float(top_vals[i]))
                        for i in range(k)]})
        slot.pending_lp = None
        if (request.presence_penalty or request.frequency_penalty) \
                and not request.done:
            slot.bias_stale = True
        grammar_done = False
        if request.guided is not None and token not in request.stop_ids:
            state = request.guided.advance(request.guided_state, token)
            request.guided_state = state
            # dead state is unreachable while masks are enforced (the
            # sampler can't pick a -1e9 token); treat it as completion
            # defensively rather than decoding garbage forever
            grammar_done = (state is None
                            or request.guided.is_exhausted(state))
            if not grammar_done:
                slot.bias_stale = True
        if token in request.stop_ids or grammar_done:
            request.finish("stop")
        elif len(request.output_ids) >= request.max_tokens:
            request.finish("length")
        elif slot.pos >= self._pos_limit:
            request.finish("length")
        request.push_stream(token)
        if request.done:
            request.push_stream(None)
            slot.request = None

    def _gather_batch(self, active, pos_fill: int = 0):
        """Host-side per-slot input arrays for the jitted decode
        programs — ONE copy shared by the dense, multi-step, and
        speculative paths so a new per-request field cannot desync
        them. ``pos_fill`` is where idle slots park their writes."""
        n = self.config.max_batch
        tokens = np.zeros(n, dtype=np.int32)
        pos = np.full(n, pos_fill, dtype=np.int32)
        temp = np.zeros(n, dtype=np.float32)
        topk = np.zeros(n, dtype=np.int32)
        lora_idx = np.zeros(n, dtype=np.int32)
        for slot in active:
            request = slot.request
            tokens[slot.index] = slot.next_token
            pos[slot.index] = slot.pos
            temp[slot.index] = request.temperature
            topk[slot.index] = request.top_k
            lora_idx[slot.index] = self._adapter_index(request)
        return tokens, pos, temp, topk, lora_idx

    def _spec_step(self, active) -> int:
        """One speculation round: G-1 batched draft decodes + ONE
        target verify over the [B, G] chunk; each greedy slot emits
        its accepted draft prefix plus the target's correction (1..G
        tokens per round, every one of them exactly what greedy
        target-only decoding would have produced)."""
        jax, jnp = self._jax, self._jnp
        G = self.config.spec_tokens
        park = self.config.max_seq - G  # scratch rows for idle slots
        tokens, pos, temp, topk, _lora = self._gather_batch(
            active, pos_fill=park)
        tokens_j = jnp.asarray(tokens)
        pos_j = jnp.asarray(pos)

        # draft proposals d_1..d_{G-1}: one fused dispatch
        drafts_dev, self.draft_cache_k, self.draft_cache_v = \
            self._draft_propose(self.draft_params, self.draft_cache_k,
                                self.draft_cache_v, tokens_j, pos_j)

        # one target forward scores the whole chunk
        chunk = jnp.concatenate(
            [tokens_j[:, None], drafts_dev.T], axis=1)       # [B, G]
        self._step_counter += 1  # graftlint: disable=GL001  # stepper-thread-only
        greedy, first_sampled, self.cache_k, self.cache_v = \
            self._verify(self.params, self.cache_k, self.cache_v,
                         chunk, pos_j, jnp.asarray(temp),
                         jnp.asarray(topk), self._base_key,
                         self._step_counter, self._bias)
        greedy = np.asarray(greedy)                          # [B, G]
        first_sampled = np.asarray(first_sampled)            # [B]
        drafts_np = np.asarray(drafts_dev).T                 # [B, G-1]

        for slot in active:
            b = slot.index
            if slot.request.temperature > 0.0:
                # sampled request: one properly-sampled token from the
                # target's first-position logits (no speculation)
                emitted = [int(first_sampled[b])]
            else:
                m = 0  # accepted draft tokens
                while m < G - 1 and drafts_np[b, m] == greedy[b, m]:
                    m += 1
                emitted = [int(greedy[b, i]) for i in range(m + 1)]
            for token in emitted:
                slot.pos += 1
                slot.next_token = token
                self._emit(slot, token)
                if slot.request is None:  # finished mid-chunk
                    break
        return len(active)

    def _multi_step(self, active, K: int) -> int:
        """K fused decode iterations in one dispatch; per-slot tokens
        past a stop/max_tokens finish are discarded host-side, so
        outputs match single-step decoding exactly."""
        jnp = self._jnp
        tokens, pos, temp, topk, lora_idx = self._gather_batch(
            active, pos_fill=self.config.max_seq - K)
        self._step_counter += 1  # graftlint: disable=GL001  # stepper-thread-only
        toks, self.cache_k, self.cache_v = self._decode_multi(
            self.params, self.cache_k, self.cache_v,
            jnp.asarray(tokens), jnp.asarray(pos),
            jnp.asarray(temp), jnp.asarray(topk),
            self._base_key, self._step_counter,
            self.lora_bank, jnp.asarray(lora_idx), self._bias)
        toks = np.asarray(toks)                          # [K, B]
        for slot in active:
            for k in range(K):
                slot.pos += 1
                slot.next_token = int(toks[k, slot.index])
                self._emit(slot, slot.next_token)
                if slot.request is None:  # finished mid-chunk:
                    break                 # later tokens are discarded
        return len(active)

    def _prefill_chunk_step(self, prefilling, decoding) -> None:
        """ONE batched llama_verify_step dispatch advances every
        prefilling slot by a chunk AND decodes every (non-LoRA)
        decoding slot by one token — a decode is just a 1-token chunk
        (vLLM's mixed prefill/decode batches). Fusing them matters:
        separate chunk + decode dispatches doubled the inter-token gap
        on dispatch-bound links, making chunked prefill slower than
        the blocking admission it replaces."""
        jnp = self._jnp
        C = self.config.chunked_prefill_tokens
        n = self.config.max_batch
        park = self.config.max_seq - C  # scratch rows for idle slots
        # sampling fields come from the shared gather (one copy across
        # all paths); the chunk overlays its own tokens/positions
        tokens, pos, temp, topk, _lora = self._gather_batch(
            prefilling + decoding, pos_fill=park)
        chunk = np.zeros((n, C), dtype=np.int32)
        chunk[:, 0] = tokens  # decoding slots: 1-token "chunk"
        last_idx = np.zeros(n, dtype=np.int32)
        for slot in prefilling:
            ids, p = slot.prefill_ids, slot.prefill_pos
            part = ids[p: p + C]
            row = np.zeros(C, dtype=np.int32)
            row[: len(part)] = part
            chunk[slot.index] = row
            pos[slot.index] = p
            last_idx[slot.index] = len(part) - 1
        self._step_counter += 1  # graftlint: disable=GL001  # stepper-thread-only
        tok, self.cache_k, self.cache_v = self._chunk_prefill(
            self.params, self.cache_k, self.cache_v,
            jnp.asarray(chunk), jnp.asarray(pos),
            jnp.asarray(last_idx), jnp.asarray(temp),
            jnp.asarray(topk), self._base_key, self._step_counter,
            self._bias)
        tok = np.asarray(tok)
        for slot in prefilling:
            remaining = len(slot.prefill_ids) - slot.prefill_pos
            slot.prefill_pos += min(C, remaining)
            if slot.prefill_pos >= len(slot.prefill_ids):
                slot.prefilling = False
                slot.pos = len(slot.prefill_ids)
                slot.prefill_ids = None
                slot.next_token = int(tok[slot.index])
                self._emit(slot, slot.next_token)
        for slot in decoding:
            slot.pos += 1
            slot.next_token = int(tok[slot.index])
            self._emit(slot, slot.next_token)

    def step(self) -> int:
        """Admit + one whole-batch decode step (sampling fused on
        device — only [B] token ids come back). Returns #active slots.

        Instrumented wrapper: step wall time (phase-tagged prefill vs
        decode), tokens/sec, and batch occupancy accumulate in the
        local buffer and flush as one batched metrics update."""
        t0 = time.perf_counter()
        rec = _flight.RECORDER
        t0_ns = rec.clock() if rec is not None else 0
        tokens_before = self.total_generated
        self._admitted_last_step = 0
        handled = self._step_impl()
        dt = time.perf_counter() - t0
        emitted = self.total_generated - tokens_before
        phase = ("prefill" if self._admitted_last_step
                 or any(s.request is not None and s.prefilling
                        for s in self.slots)
                 else "decode")
        if rec is not None and handled:
            rec.record("serve", "engine_step", t0_ns,
                       rec.clock() - t0_ns,
                       {"phase": phase, "slots": handled,
                        "tokens": emitted})
        self._mbuf.note_step(phase, dt, emitted, handled)
        self._mbuf.maybe_flush(self)
        return handled

    def flush_metrics(self) -> None:
        """Force the buffered step metrics out (tests / shutdown)."""
        self._mbuf.maybe_flush(self, force=True)

    def _step_impl(self) -> int:
        self._admit()
        # guided slots: re-sync device bias rows with automaton states
        # advanced by the previous step's emissions (one [V] row upload
        # per advanced guided slot — masks memoize per state)
        for s in self.slots:
            if s.request is not None and s.bias_stale:
                self._install_bias(s.request, s.index)
                s.bias_stale = False
        handled = 0
        if self.config.chunked_prefill_tokens > 0:
            prefilling = [s for s in self.slots
                          if s.request is not None and s.prefilling]
            if prefilling:
                # fused mixed batch: prefill chunks + 1-token decodes
                # in one dispatch (LoRA decodes lack a chunk-program
                # path and fall through to the dense step below)
                fused_decodes = [
                    s for s in self.slots
                    if s.request is not None and not s.prefilling
                    and s.request.adapter is None
                    and s.request.logprobs is None]
                self._prefill_chunk_step(prefilling, fused_decodes)
                handled = len(prefilling) + len(fused_decodes)
                active = [s for s in self.slots
                          if s.request is not None and not s.prefilling
                          and (s.request.adapter is not None
                               or s.request.logprobs is not None)]
                if not active:
                    return handled
                # fall through: adapter decodes take the dense step
            else:
                active = [s for s in self.slots
                          if s.request is not None]
                if not active:
                    return 0
        else:
            active = [s for s in self.slots if s.request is not None]
            if not active:
                return 0
        if self._spec and \
                any(s.request.temperature <= 0.0 for s in active) and \
                all(s.request.adapter is None for s in active) and \
                not any(self._has_dynamic_bias(s.request)
                        or s.request.logprobs is not None
                        for s in active) and \
                all(s.draft_ready for s in active) and \
                all(s.pos + self.config.spec_tokens
                    <= self.config.max_seq - 1 for s in active):
            # (all-sampled batches skip speculation: a round would pay
            # the draft scan + G-wide verify to emit 1 token/slot)
            return self._spec_step(active)
        K = self.config.multi_step
        if K > 1 and all(s.pos + K <= self.config.max_seq - 1
                         for s in active) and \
                not any(self._has_dynamic_bias(s.request)
                        or s.request.logprobs is not None
                        for s in active):
            # guided/penalized slots need a bias refresh between
            # tokens, which a fused K-step scan cannot do — dense
            # fallback while any such request is active
            return self._multi_step(active, K) + handled
        jnp = self._jnp
        tokens, pos, temp, topk, lora_idx = self._gather_batch(
            active, pos_fill=self._dense_park)
        self._step_counter += 1  # graftlint: disable=GL001  # stepper-thread-only
        want_lp = any(s.request.logprobs is not None for s in active)
        sampled, chosen_lp, top_vals, top_ids, self.cache_k, \
            self.cache_v = self._decode(
                self.params, self.cache_k, self.cache_v,
                jnp.asarray(tokens), jnp.asarray(pos),
                jnp.asarray(temp), jnp.asarray(topk),
                self._base_key, self._step_counter,
                self.lora_bank, jnp.asarray(lora_idx), self._bias,
                want_lp=want_lp)
        if self._spec:
            # keep the draft cache in lockstep through dense rounds,
            # or the next _spec_step would condition on KV gaps
            self.draft_cache_k, self.draft_cache_v = self._draft_sync(
                self.draft_params, self.draft_cache_k,
                self.draft_cache_v, jnp.asarray(tokens),
                jnp.asarray(pos))
        sampled = np.asarray(sampled)
        if want_lp:
            # only logprob requests pay the extra device-to-host syncs
            chosen_lp = np.asarray(chosen_lp)
            top_vals = np.asarray(top_vals)
            top_ids = np.asarray(top_ids)
            for slot in active:
                if slot.request.logprobs is not None:
                    slot.pending_lp = (chosen_lp[slot.index],
                                       top_vals[slot.index],
                                       top_ids[slot.index])
        for slot in active:
            slot.pos += 1
            slot.next_token = int(sampled[slot.index])
            self._emit(slot, slot.next_token)
        return len(active) + handled

    # ------------------------------------------------------------------
    def generate(self, prompts_ids: List[List[int]], *,
                 max_tokens: int = 32, temperature: float = 0.0,
                 top_k: int = 0, stop_ids: tuple = ()) -> List[List[int]]:
        """Synchronous batch API: token ids in, token ids out."""
        requests = [
            self.add_request(GenerationRequest(
                prompt_ids=ids, max_tokens=max_tokens,
                temperature=temperature, top_k=top_k, stop_ids=stop_ids))
            for ids in prompts_ids]
        while any(not r.done for r in requests):
            if self.step() == 0 and any(not r.done for r in requests):
                # nothing active yet (all waiting on slots) — admit again
                time.sleep(0)
        return [r.output_ids for r in requests]

    def fail_all(self, message: str) -> None:
        """Abort every waiting and active request with an error and
        reset the KV caches (used by serving loops when a step raises —
        requests must not hang). The cache reset matters: a failed
        decode/insert may have consumed its donated buffers, leaving
        self.cache_k/v deleted; without fresh caches every later step
        would fail too."""
        with self._lock:
            pending = list(self.waiting)
            self.waiting.clear()
            pending += [entry[0] for entry in self._prefilled_waiting]
            self._prefilled_waiting.clear()
        for request in pending:
            request.finish("error", error=message)
            request.push_stream(None)
        for slot in self.slots:
            if slot.request is not None:
                slot.request.finish("error", error=message)
                slot.request.push_stream(None)
            slot.request = None
            slot.pos = 0
            slot.next_token = 0
            slot.draft_ready = True  # caches reset below
            slot.prefilling = False
            slot.prefill_ids = None
            slot.prefill_pos = 0
            slot.bias_stale = False
            slot.pending_lp = None
        self.cache_k, self.cache_v = llama_init_cache(
            self.config.model, self.config.max_batch, self.config.max_seq)
        if self._spec:
            self.draft_cache_k, self.draft_cache_v = llama_init_cache(
                self.config.draft_model, self.config.max_batch,
                self.config.max_seq)
        if self._prefix_cache is not None:
            # a failed step may have consumed donated buffers that
            # cache entries alias through sharing — drop them all
            with self._lock:
                self._prefix_cache.clear()

    _embed_fn = None  # built lazily on first embed()

    def cancel(self, request: GenerationRequest,
               finish_reason: str = "abort") -> None:
        """Finish a request early from ANY thread (serve stop-string
        hit, client disconnect). Queued requests are withdrawn
        immediately; an active request is marked done and its slot is
        released by the stepper at the request's next emission — no
        cross-thread slot mutation, so no race with a step in flight
        (at most one more token is decoded and discarded)."""
        with self._lock:
            if request.done:
                return
            try:
                self.waiting.remove(request)
            except ValueError:
                pass
            self._prefilled_waiting[:] = [
                e for e in self._prefilled_waiting if e[0] is not request]
            request.finish(finish_reason)
        request.push_stream(None)

    def embed(self, prompt_ids: List[int]) -> np.ndarray:
        """Mean-pooled final-norm hidden state for a prompt — the
        embedding surface (reference: serve/llm embeddings via vLLM
        embedding models). Pure read of the params; safe to call
        concurrently with the stepper thread."""
        jax, jnp = self._jax, self._jnp
        ids = list(prompt_ids)[-self.config.max_seq:]
        if not ids:
            raise ValueError("cannot embed an empty prompt")
        if self._embed_fn is None:
            c = self.config.model
            from ray_tpu.models.llama import llama_forward

            def emb(params, tokens, n):
                h = llama_forward(params, tokens, c,
                                  return_hidden=True)       # [1, S, D]
                mask = (jnp.arange(tokens.shape[1])
                        < n)[None, :, None].astype(h.dtype)
                pooled = (jnp.sum(h * mask, axis=1)
                          / jnp.maximum(n, 1).astype(h.dtype))
                return pooled[0].astype(jnp.float32)

            self._embed_fn = jax.jit(emb)
        return np.asarray(self._embed_fn(
            self.params, jnp.asarray(self._pad_bucket(ids)),
            jnp.asarray(len(ids), jnp.int32)))

    def stats(self) -> Dict[str, Any]:
        self._mbuf.maybe_flush(self, force=True)
        with self._lock:
            out = {
                "waiting": len(self.waiting),
                "active": sum(1 for s in self.slots
                              if s.request is not None),
                "prefilling": sum(1 for s in self.slots
                                  if s.request is not None
                                  and s.prefilling),
                "max_batch": self.config.max_batch,
                "total_generated": self.total_generated,
            }
            if self._prefix_cache is not None:
                out["prefix_cache_entries"] = len(self._prefix_cache)
                out["prefix_hits"] = self.prefix_hits
                out["prefix_misses"] = self.prefix_misses
            return out
