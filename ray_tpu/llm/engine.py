"""Continuous-batching inference engine.

Reference: the reference serves LLMs by wrapping vLLM
(python/ray/llm/_internal/serve/engines/vllm/vllm_engine.py —
continuous batching, paged KV). TPU-native redesign (JetStream-style):

- The KV cache is ONE static-shape array pair [L, B, S, KVH, HD] in
  HBM: XLA-friendly, no paging indirection — slot b of the batch
  dimension is the "page table", assigned to one request at a time.
- Decode is a single jitted step for the WHOLE batch every iteration;
  requests join (prefill into a free slot) and leave (EOS/length)
  between steps without recompiling — that is the continuous batching.
- Prefill pads prompts into power-of-two buckets so only O(log S)
  prefill programs ever compile.

Sampling (temperature / top-k / greedy) is host-side numpy on [B, V]
logits — tiny relative to the decode matmuls and trivially flexible.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.models.llama import (
    LlamaConfig, llama_decode_step, llama_init, llama_init_cache,
    llama_prefill)


@dataclass
class EngineConfig:
    # default vocab covers the ByteTokenizer's 258 ids (256 bytes + BOS/EOS)
    model: LlamaConfig = field(
        default_factory=lambda: LlamaConfig.tiny(vocab_size=258))
    max_batch: int = 8
    max_seq: int = 512
    tokenizer: Optional[str] = None  # None/"byte" or an HF id
    seed: int = 0


@dataclass
class GenerationRequest:
    prompt_ids: List[int]
    max_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    stop_ids: tuple = ()
    request_id: int = field(default_factory=itertools.count().__next__)
    # Streaming: when set (queue.Queue), the stepper pushes each emitted
    # token as it decodes; None terminates the stream (reference: vLLM's
    # per-request output stream consumed by serve token streaming).
    stream_queue: Optional[Any] = None
    # filled by the engine
    output_ids: List[int] = field(default_factory=list)
    finish_reason: Optional[str] = None
    error: Optional[str] = None

    @property
    def done(self) -> bool:
        return self.finish_reason is not None

    def push_stream(self, item) -> None:
        if self.stream_queue is not None:
            try:
                self.stream_queue.put_nowait(item)
            except Exception:  # noqa: BLE001 — consumer gone
                pass


class _Slot:
    def __init__(self, index: int):
        self.index = index
        self.request: Optional[GenerationRequest] = None
        self.pos = 0            # position of the NEXT token to decode
        self.next_token = 0


class ContinuousBatchingEngine:
    def __init__(self, config: EngineConfig, params=None):
        import jax
        import jax.numpy as jnp

        self.config = config
        c = config.model
        if params is None:
            # random weights — real checkpoints load via orbax/train
            params = llama_init(jax.random.PRNGKey(config.seed), c)
        self.params = params
        self._rng = np.random.default_rng(config.seed)
        self.cache_k, self.cache_v = llama_init_cache(
            c, config.max_batch, config.max_seq)
        self.slots = [_Slot(i) for i in range(config.max_batch)]
        self.waiting: List[GenerationRequest] = []
        self._lock = threading.Lock()
        self.total_generated = 0

        def decode(params, cache_k, cache_v, tokens, pos):
            return llama_decode_step(params, tokens, cache_k, cache_v,
                                     pos, c)

        def prefill(params, tokens):
            logits, ks, vs = llama_prefill(params, tokens, c)
            return logits, ks, vs

        def insert(cache_k, cache_v, ks, vs, slot):
            # in-place (donated) slot write — no whole-cache copy.
            # ks/vs: [L, 1, bucket, KVH, HD] from a batch-1 prefill.
            ck = jax.lax.dynamic_update_slice(
                cache_k, ks, (0, slot, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                cache_v, vs, (0, slot, 0, 0, 0))
            return ck, cv

        self._decode = jax.jit(decode, donate_argnums=(1, 2))
        self._prefill = jax.jit(prefill)
        self._insert = jax.jit(insert, donate_argnums=(0, 1))
        self._jnp = jnp

    # ------------------------------------------------------------------
    def add_request(self, request: GenerationRequest) -> GenerationRequest:
        limit = self.config.max_seq - 1
        if len(request.prompt_ids) > limit:
            request.prompt_ids = request.prompt_ids[-limit:]
        with self._lock:
            self.waiting.append(request)
        return request

    def has_work(self) -> bool:
        with self._lock:
            return bool(self.waiting) or any(
                s.request is not None for s in self.slots)

    def _free_slots(self) -> List[_Slot]:
        return [s for s in self.slots if s.request is None]

    def _admit(self) -> None:
        """Prefill waiting requests into free slots."""
        jnp = self._jnp
        while True:
            with self._lock:
                if not self.waiting:
                    return
                free = self._free_slots()
                if not free:
                    return
                request = self.waiting.pop(0)
                slot = free[0]
                slot.request = request
            ids = request.prompt_ids
            bucket = 1
            while bucket < len(ids):
                bucket *= 2
            bucket = min(bucket, self.config.max_seq)
            padded = np.zeros((1, bucket), dtype=np.int32)
            padded[0, : len(ids)] = ids
            logits, ks, vs = self._prefill(self.params, jnp.asarray(padded))
            self.cache_k, self.cache_v = self._insert(
                self.cache_k, self.cache_v, ks, vs, slot.index)
            last = np.asarray(logits[0, len(ids) - 1])
            slot.next_token = self._sample(last, request)
            slot.pos = len(ids)
            self._emit(slot, slot.next_token)

    def _sample(self, logits: np.ndarray, request: GenerationRequest) -> int:
        if request.temperature <= 0.0:
            return int(np.argmax(logits))
        logits = logits / request.temperature
        if request.top_k > 0:
            kth = np.partition(logits, -request.top_k)[-request.top_k]
            logits = np.where(logits < kth, -np.inf, logits)
        logits = logits - logits.max()
        probs = np.exp(logits)
        probs /= probs.sum()
        return int(self._rng.choice(len(probs), p=probs))

    def _emit(self, slot: _Slot, token: int) -> None:
        request = slot.request
        request.output_ids.append(token)
        self.total_generated += 1
        if token in request.stop_ids:
            request.finish_reason = "stop"
        elif len(request.output_ids) >= request.max_tokens:
            request.finish_reason = "length"
        elif slot.pos >= self.config.max_seq - 1:
            request.finish_reason = "length"
        request.push_stream(token)
        if request.done:
            request.push_stream(None)
            slot.request = None

    def step(self) -> int:
        """Admit + one whole-batch decode step. Returns #active slots."""
        self._admit()
        active = [s for s in self.slots if s.request is not None]
        if not active:
            return 0
        jnp = self._jnp
        tokens = np.zeros(self.config.max_batch, dtype=np.int32)
        pos = np.zeros(self.config.max_batch, dtype=np.int32)
        for slot in active:
            tokens[slot.index] = slot.next_token
            pos[slot.index] = slot.pos
        logits, self.cache_k, self.cache_v = self._decode(
            self.params, self.cache_k, self.cache_v,
            jnp.asarray(tokens), jnp.asarray(pos))
        logits = np.asarray(logits)
        for slot in active:
            slot.pos += 1
            slot.next_token = self._sample(logits[slot.index], slot.request)
            self._emit(slot, slot.next_token)
        return len(active)

    # ------------------------------------------------------------------
    def generate(self, prompts_ids: List[List[int]], *,
                 max_tokens: int = 32, temperature: float = 0.0,
                 top_k: int = 0, stop_ids: tuple = ()) -> List[List[int]]:
        """Synchronous batch API: token ids in, token ids out."""
        requests = [
            self.add_request(GenerationRequest(
                prompt_ids=ids, max_tokens=max_tokens,
                temperature=temperature, top_k=top_k, stop_ids=stop_ids))
            for ids in prompts_ids]
        while any(not r.done for r in requests):
            if self.step() == 0 and any(not r.done for r in requests):
                # nothing active yet (all waiting on slots) — admit again
                time.sleep(0)
        return [r.output_ids for r in requests]

    def fail_all(self, message: str) -> None:
        """Abort every waiting and active request with an error and
        reset the KV caches (used by serving loops when a step raises —
        requests must not hang). The cache reset matters: a failed
        decode/insert may have consumed its donated buffers, leaving
        self.cache_k/v deleted; without fresh caches every later step
        would fail too."""
        with self._lock:
            pending = list(self.waiting)
            self.waiting.clear()
        for request in pending:
            request.error = message
            request.finish_reason = "error"
            request.push_stream(None)
        for slot in self.slots:
            if slot.request is not None:
                slot.request.error = message
                slot.request.finish_reason = "error"
                slot.request.push_stream(None)
            slot.request = None
            slot.pos = 0
            slot.next_token = 0
        self.cache_k, self.cache_v = llama_init_cache(
            self.config.model, self.config.max_batch, self.config.max_seq)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "waiting": len(self.waiting),
                "active": sum(1 for s in self.slots
                              if s.request is not None),
                "max_batch": self.config.max_batch,
                "total_generated": self.total_generated,
            }
