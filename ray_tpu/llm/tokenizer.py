"""Tokenizers for the LLM stack.

ByteTokenizer is the hermetic default (UTF-8 bytes + specials) so tests
and benches never need weight/tokenizer downloads; HF tokenizers load
through `transformers` when a model id is given (reference: the
reference's serving stack resolves HF tokenizers the same way).
"""

from __future__ import annotations

from typing import List, Optional


class ByteTokenizer:
    """ids 0..255 = bytes; 256 = BOS; 257 = EOS."""

    bos_id = 256
    eos_id = 257
    vocab_size = 258

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return [self.bos_id] + ids if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", "replace")

    def token_strings(self) -> List[Optional[str]]:
        """Per-id string form for guided decoding (None = special,
        never maskable). Bytes map 1:1 onto U+0000..U+00FF so the
        grammar automaton runs over characters."""
        return [chr(i) for i in range(256)] + [None, None]


class HFTokenizer:
    def __init__(self, name: str):
        from transformers import AutoTokenizer
        self._tok = AutoTokenizer.from_pretrained(name)
        self.bos_id = self._tok.bos_token_id
        self.eos_id = self._tok.eos_token_id
        # len() includes added special tokens; .vocab_size does not, and
        # added ids sit beyond it — the embedding bound must cover them
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def token_strings(self) -> List[Optional[str]]:
        """Per-id decoded string for guided decoding (specials map to
        None). Best-effort: byte-fallback pieces that don't round-trip
        through convert_tokens_to_string decode as replacement chars
        and simply never match a grammar."""
        specials = set(self._tok.all_special_ids or ())
        out: List[Optional[str]] = []
        for tid in range(self.vocab_size):
            if tid in specials:
                out.append(None)
                continue
            piece = self._tok.convert_ids_to_tokens(tid)
            try:
                out.append(self._tok.convert_tokens_to_string([piece]))
            except Exception:  # noqa: BLE001 — odd added tokens
                out.append(None)
        return out


def get_tokenizer(name: Optional[str] = None):
    if name is None or name == "byte":
        return ByteTokenizer()
    return HFTokenizer(name)
