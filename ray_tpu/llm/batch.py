"""LLM batch inference over Data: a Processor pipeline of
tokenize -> continuous-batching engine -> detokenize stages, each a
stateful callable class running in a Data actor pool, returning a lazy
Dataset (reference: python/ray/llm/_internal/batch/processor/base.py:183
Processor and _internal/batch/stages/{tokenize_stage,vllm_engine_stage}
— the engine stage here is the in-tree TPU engine instead of vLLM).

Usage::

    config = ProcessorConfig(engine=EngineConfig(...), concurrency=2)
    processor = build_llm_processor(
        config, preprocess=lambda row: {"prompt": row["question"]})
    out = processor(ray_tpu.data.from_items([{"question": "..."}]))
    out.take_all()   # rows with generated_text / generated_ids
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from ray_tpu.llm.engine import ContinuousBatchingEngine, EngineConfig
from ray_tpu.llm.tokenizer import get_tokenizer


@dataclass
class ProcessorConfig:
    """Pipeline shape + generation defaults (reference:
    batch/processor/base.py:26 ProcessorConfig /
    base.py:134 OfflineProcessorConfig)."""

    engine: EngineConfig = field(default_factory=EngineConfig)
    batch_size: int = 32
    # int n = fixed engine-actor pool of n; (m, n) = autoscaling pool
    # (reference: base.py concurrency semantics)
    concurrency: Union[int, Tuple[int, int]] = 1
    # per-engine-actor resource request (e.g. {"TPU": 1}); None = CPU
    resources: Optional[Dict[str, float]] = None
    # generation defaults, overridable per row via sampling columns
    max_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    # stage toggles (reference: OfflineProcessorConfig.tokenize/detokenize)
    tokenize: bool = True
    detokenize: bool = True

    def __post_init__(self):
        c = self.concurrency
        ok = (isinstance(c, int) and c > 0) or (
            isinstance(c, tuple) and len(c) == 2
            and all(isinstance(v, int) and v > 0 for v in c)
            and c[0] <= c[1])
        if not ok:
            raise ValueError(
                "concurrency must be a positive int or an (m, n) tuple "
                f"with 1 <= m <= n, got {c!r}")


class TokenizeStage:
    """prompt -> prompt_ids (reference: stages/tokenize_stage.py)."""

    def __init__(self, tokenizer_name: Optional[str]):
        self._tok = get_tokenizer(tokenizer_name)

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        batch = dict(batch)
        batch["prompt_ids"] = [
            self._tok.encode(str(p)) for p in batch["prompt"]]
        return batch


class DetokenizeStage:
    """generated_ids -> generated_text (reference:
    stages/tokenize_stage.py DetokenizeStage)."""

    def __init__(self, tokenizer_name: Optional[str]):
        self._tok = get_tokenizer(tokenizer_name)

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        batch = dict(batch)
        batch["generated_text"] = [
            self._tok.decode(list(ids)) for ids in batch["generated_ids"]]
        return batch


class EngineStage:
    """prompt_ids -> generated_ids via one resident
    ContinuousBatchingEngine per actor; the engine's slot admission
    overlaps decode across the whole batch (reference:
    stages/vllm_engine_stage.py vLLMEngineStage — ours drives the
    in-tree engine's generate())."""

    def __init__(self, config: ProcessorConfig):
        self._config = config
        self._engine = ContinuousBatchingEngine(config.engine)
        eos = getattr(get_tokenizer(config.engine.tokenizer),
                      "eos_id", None)
        self._stop_ids = (eos,) if eos is not None else ()

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        cfg = self._config
        prompts = [list(map(int, ids)) for ids in batch["prompt_ids"]]
        start = time.perf_counter()
        outs = self._engine.generate(
            prompts, max_tokens=cfg.max_tokens,
            temperature=cfg.temperature, top_k=cfg.top_k,
            stop_ids=self._stop_ids)
        elapsed = time.perf_counter() - start
        batch = dict(batch)
        batch["generated_ids"] = outs
        batch["num_generated_tokens"] = np.array(
            [len(o) for o in outs], dtype=np.int64)
        # whole-batch wall time attributed per row (reference engine
        # stage emits time_taken_llm the same way)
        batch["time_taken_llm"] = np.full(
            len(outs), elapsed, dtype=np.float64)
        return batch


class Processor:
    """preprocess -> [tokenize] -> engine -> [detokenize] -> postprocess,
    composed lazily over a Dataset (reference:
    batch/processor/base.py:183)."""

    def __init__(self, config: ProcessorConfig,
                 preprocess: Optional[Callable[[dict], dict]] = None,
                 postprocess: Optional[Callable[[dict], dict]] = None):
        self.config = config
        self.preprocess = preprocess
        self.postprocess = postprocess

    def __call__(self, dataset) -> "Any":
        cfg = self.config
        ds = dataset
        if self.preprocess is not None:
            ds = ds.map(self.preprocess)
        tok_name = cfg.engine.tokenizer
        if cfg.tokenize:
            ds = ds.map_batches(
                TokenizeStage, fn_args=(tok_name,),
                batch_size=cfg.batch_size, compute="actors",
                concurrency=cfg.concurrency)
        ds = ds.map_batches(
            EngineStage, fn_args=(cfg,), batch_size=cfg.batch_size,
            compute="actors", concurrency=cfg.concurrency,
            resources=cfg.resources)
        if cfg.detokenize:
            ds = ds.map_batches(
                DetokenizeStage, fn_args=(tok_name,),
                batch_size=cfg.batch_size, compute="actors",
                concurrency=cfg.concurrency)
        if self.postprocess is not None:
            ds = ds.map(self.postprocess)
        return ds


def build_llm_processor(
        config: ProcessorConfig,
        preprocess: Optional[Callable[[dict], dict]] = None,
        postprocess: Optional[Callable[[dict], dict]] = None) -> Processor:
    """Public constructor (reference: ray.data.llm build_llm_processor
    -> ProcessorBuilder.build)."""
    return Processor(config, preprocess=preprocess,
                     postprocess=postprocess)


def throughput_summary(rows: List[dict]) -> Dict[str, float]:
    """Tokens/s over a materialized result (per-batch wall times are
    attributed per row, so sum unique batch times)."""
    total_tokens = int(sum(r.get("num_generated_tokens", 0) for r in rows))
    # each batch stamped every row with the same elapsed value; count
    # each distinct stamp once (good enough for reporting)
    seen: set = set()
    total_time = 0.0
    for r in rows:
        t = float(r.get("time_taken_llm", 0.0))
        if t and t not in seen:
            seen.add(t)
            total_time += t
    return {"num_generated_tokens": float(total_tokens),
            "elapsed_s": total_time,
            "tokens_per_s": total_tokens / total_time
            if total_time else 0.0}
