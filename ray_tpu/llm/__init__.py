"""ray_tpu.llm — LLM serving and batch inference (reference:
python/ray/llm). The engine is in-tree and TPU-native (static-shape KV
caches, jitted whole-batch decode) instead of wrapping vLLM."""

from ray_tpu.llm.batch import (
    Processor, ProcessorConfig, build_llm_processor, throughput_summary)
from ray_tpu.llm.engine import (
    ContinuousBatchingEngine, EngineConfig, GenerationRequest)
from ray_tpu.llm.tokenizer import ByteTokenizer, get_tokenizer

__all__ = [
    "ByteTokenizer", "ContinuousBatchingEngine", "EngineConfig",
    "GenerationRequest", "Processor", "ProcessorConfig",
    "build_llm_processor", "get_tokenizer", "throughput_summary",
]
