"""ray_tpu.llm — LLM serving and batch inference (reference:
python/ray/llm). The engine is in-tree and TPU-native (static-shape KV
caches, jitted whole-batch decode) instead of wrapping vLLM."""

from ray_tpu.llm.batch import (
    Processor, ProcessorConfig, build_llm_processor, throughput_summary)
from ray_tpu.llm.engine import (
    ContinuousBatchingEngine, EngineConfig, EngineSaturatedError,
    GenerationRequest)
from ray_tpu.llm.guided import (
    TokenConstraint, json_object_constraint, json_schema_constraint,
    tool_call_constraint)
from ray_tpu.llm.tokenizer import ByteTokenizer, get_tokenizer

__all__ = [
    "ByteTokenizer", "ContinuousBatchingEngine", "EngineConfig",
    "EngineSaturatedError",
    "GenerationRequest", "Processor", "ProcessorConfig",
    "TokenConstraint", "build_llm_processor", "get_tokenizer",
    "json_object_constraint", "json_schema_constraint",
    "throughput_summary", "tool_call_constraint",
]
