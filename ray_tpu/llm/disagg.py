"""Prefill/decode disaggregation for LLM serving.

Capability parity with the reference's prefill-decode disaggregated
deployments (reference: python/ray/llm/_internal/serve/deployments/ —
separate prefill and decode replica pools with KV blocks transferred
between engines). TPU-native shape: prefill replicas run compute-bound
batch-1 prefills (MXU-heavy, benefits from dedicated chips); decode
replicas run the latency-bound continuous-batching loop; the KV block
for each admitted request moves prefill→decode through the object
plane — shared memory on one host, chunked node-to-node transfer
across hosts (the DCN analog of the reference's NIXL KV transfer).

    from ray_tpu.llm.disagg import build_disagg_app
    app = build_disagg_app(LLMConfig(...), num_prefill=2, num_decode=1)
    handle = serve.run(app)
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu import serve
from ray_tpu.llm.engine import ContinuousBatchingEngine, GenerationRequest
from ray_tpu.llm.tokenizer import get_tokenizer
from ray_tpu.serve.llm import LLMConfig, LLMServer, stream_text_deltas


class PrefillServer:
    """Prefill-only replica: owns model weights, runs one prefill per
    call, returns the KV block + first sampled token. Never decodes."""

    def __init__(self, config: LLMConfig,
                 params_blob: Optional[bytes] = None):
        params = None
        if params_blob is not None:
            from ray_tpu.core import serialization
            params = serialization.loads(params_blob)
        self.config = config
        self.engine = ContinuousBatchingEngine(config.engine, params)
        self.tokenizer = get_tokenizer(config.engine.tokenizer)
        self._constraint_cache: Dict[Any, Any] = {}
        self._token_strs = None

    # guided decoding resolution borrowed from LLMServer (same
    # validation + constraint cache, no engine stepper needed here)
    _vocab_strings = LLMServer._vocab_strings
    _cached_constraint = LLMServer._cached_constraint
    _resolve_guided = LLMServer._resolve_guided

    def prefill(self, prompt: str, *, temperature: float = 0.0,
                top_k: int = 0,
                adapter: Optional[str] = None,
                logit_bias: Optional[Dict[int, float]] = None,
                response_format: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
        guided = None
        if response_format is not None:
            guided = self._resolve_guided(
                {"response_format": response_format},
                allow_tools=False)["constraint"]
        ids = self.tokenizer.encode(prompt)
        ks, vs, prompt_len, first_token = self.engine.prefill_only(
            ids, temperature=temperature, top_k=top_k, adapter=adapter,
            logit_bias=logit_bias, guided=guided)
        return {"ks": ks, "vs": vs, "prompt_len": prompt_len,
                "first_token": first_token, "prompt_tokens": len(ids)}


class DecodeServer(LLMServer):
    """Decode replica: the normal continuous-batching LLMServer plus
    entry points for requests whose prefill ran elsewhere."""

    @staticmethod
    def _materialize_prefill(prefill_out: Any) -> Dict[str, Any]:
        from ray_tpu.core.object_ref import ObjectRef
        if isinstance(prefill_out, ObjectRef):
            # fast path: the router forwarded the prefill replica's raw
            # result ref — the KV block reads straight from the object
            # plane here, never materializing in the router
            import ray_tpu
            prefill_out = ray_tpu.get(prefill_out, timeout=60)
        if not isinstance(prefill_out, dict):
            # a saturated prefill replica answered with a rejection
            # sentinel; the router's slow path re-routes
            raise RuntimeError("prefill result unavailable (rejected)")
        return prefill_out

    def _adopt_prefilled(self, prefill_out: Dict[str, Any], *,
                         max_tokens: int, temperature: float,
                         top_k: int, adapter: Optional[str],
                         logit_bias: Optional[Dict[int, float]] = None,
                         response_format: Optional[Dict[str, Any]] = None,
                         stream_queue=None) -> GenerationRequest:
        guided = None
        if response_format is not None:
            # decode-side rebuild of the prefill side's constraint: the
            # engine re-walks the automaton from the start state when
            # it adopts, so only the spec (not opaque state) ships
            guided = self._resolve_guided(
                {"response_format": response_format},
                allow_tools=False)["constraint"]
        request = GenerationRequest(
            prompt_ids=[],  # KV already computed; ids not needed
            max_tokens=max_tokens,
            temperature=temperature,
            top_k=top_k,
            adapter=adapter,
            logit_bias=logit_bias,
            guided=guided,
            stream_queue=stream_queue,
            stop_ids=(self.tokenizer.eos_id,)
            if self.tokenizer.eos_id is not None else ())
        self.engine.add_prefilled(
            request, prefill_out["ks"], prefill_out["vs"],
            prefill_out["prompt_len"], prefill_out["first_token"])
        self._wake.set()
        return request

    def decode_prefilled_stream(self, prefill_out: Any, *,
                                max_tokens: int, temperature: float = 0.0,
                                top_k: int = 0,
                                adapter: Optional[str] = None,
                                logit_bias: Optional[Dict[int, float]]
                                = None,
                                response_format: Optional[Dict[str, Any]]
                                = None):
        """Streaming disagg decode: yields text deltas as tokens land,
        then one final dict carrying finish_reason + usage (reference:
        python/ray/serve/llm streaming surface over disaggregated
        deployments). The KV handoff cost is the object-plane transfer
        inside _materialize_prefill."""
        import queue
        t_handoff0 = time.perf_counter()
        prefill_out = self._materialize_prefill(prefill_out)
        kv_handoff_s = time.perf_counter() - t_handoff0
        request = self._adopt_prefilled(
            prefill_out, max_tokens=max_tokens, temperature=temperature,
            top_k=top_k, adapter=adapter, logit_bias=logit_bias,
            response_format=response_format,
            stream_queue=queue.Queue())
        yield from stream_text_deltas(self.tokenizer, request)
        yield {
            "finish_reason": request.finish_reason,
            "kv_handoff_ms": round(1000 * kv_handoff_s, 3),
            "usage": {
                "prompt_tokens": prefill_out["prompt_tokens"],
                "completion_tokens": len(request.output_ids),
                "total_tokens": (prefill_out["prompt_tokens"]
                                 + len(request.output_ids)),
            },
        }

    def decode_prefilled(self, prefill_out: Any, *,
                         max_tokens: int, temperature: float = 0.0,
                         top_k: int = 0,
                         adapter: Optional[str] = None,
                         logit_bias: Optional[Dict[int, float]] = None,
                         response_format: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
        prefill_out = self._materialize_prefill(prefill_out)
        request = self._adopt_prefilled(
            prefill_out, max_tokens=max_tokens, temperature=temperature,
            top_k=top_k, adapter=adapter, logit_bias=logit_bias,
            response_format=response_format)
        while not request.done:
            request.wait_done(timeout=1.0)
        if request.error is not None:
            raise RuntimeError(request.error)
        out_ids = [t for t in request.output_ids
                   if t not in request.stop_ids]
        return {
            "text": self.tokenizer.decode(out_ids),
            "prompt_tokens": prefill_out["prompt_tokens"],
            "completion_tokens": len(request.output_ids),
            "finish_reason": request.finish_reason,
        }


class DisaggRouter:
    """Ingress: validates, fans prefill→decode, shapes the OpenAI
    response. The prefill result (with its KV block) flows between the
    two pools as a task result through the object plane — the router
    only moves the reference."""

    def __init__(self, config: LLMConfig, prefill_handle, decode_handle):
        self.config = config
        self.prefill = prefill_handle
        self.decode = decode_handle
        # reuse LLMServer's sampling validation without building an
        # engine: bind the unbound method to this router
        self._validate = LLMServer._validate_sampling
        # guided (response_format) validation needs a vocab view
        self.tokenizer = get_tokenizer(config.engine.tokenizer)
        self._constraint_cache: Dict[Any, Any] = {}
        self._token_strs = None

    _vocab_strings = LLMServer._vocab_strings
    _cached_constraint = LLMServer._cached_constraint
    _resolve_guided = LLMServer._resolve_guided

    def _resolve_adapter(self, model):
        if model is None or model == self.config.model_id:
            return None
        raise ValueError(f"unknown model {model!r}")

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        path = request.get("__path__", "")
        if path.endswith("/chat/completions"):
            # must be checked BEFORE /completions (suffix overlap);
            # chat is not offered on the disagg surface yet
            return {"error": {
                "message": "chat completions are not supported on the "
                           "disaggregated deployment; use /v1/completions",
                "type": "invalid_request_error"}}
        if path.endswith("/completions"):
            return self.completions(request)
        if path.endswith("/models"):
            return {"object": "list",
                    "data": [{"id": self.config.model_id,
                              "object": "model"}]}
        return {"error": f"unknown route {path!r}"}

    def completions(self, body: Dict[str, Any]) -> Dict[str, Any]:
        import uuid

        prompt = body.get("prompt", "")
        if not isinstance(prompt, str):
            return {"error": {"message": "prompt must be a string",
                              "type": "invalid_request_error"}}
        try:
            sampling = self._validate(self, body)
        except ValueError as e:
            return {"error": {"message": str(e),
                              "type": "invalid_request_error"}}
        temperature = sampling.get("temperature",
                                   self.config.temperature)
        top_k = sampling["top_k"]
        if sampling.get("stop"):
            # stop STRINGS need incremental text inspection on the
            # router — not offered on the disagg surface yet; reject
            # loudly instead of silently decoding through the stop
            return {"error": {
                "message": "stop strings are not supported on the "
                           "disaggregated deployment; use stop token "
                           "ids via the engine API",
                "type": "invalid_request_error"}}
        rf = body.get("response_format")
        if rf is not None:
            # validate router-side — replica-side ValueErrors would
            # surface as opaque TaskErrors; replicas rebuild the
            # constraint from the spec against their own vocab
            try:
                self._resolve_guided({"response_format": rf},
                                     allow_tools=False)
            except ValueError as e:
                return {"error": {"message": str(e),
                                  "type": "invalid_request_error"}}
        decode_kwargs = dict(
            max_tokens=sampling.get("max_tokens", self.config.max_tokens),
            temperature=temperature, top_k=top_k,
            adapter=sampling.get("adapter"),
            logit_bias=sampling.get("logit_bias"),
            response_format=rf)
        prefill_ref = self.prefill.prefill.remote(
            prompt, temperature=temperature, top_k=top_k,
            adapter=sampling.get("adapter"),
            logit_bias=sampling.get("logit_bias"),
            response_format=rf)
        if body.get("stream"):
            return self._stream_completions(body, prefill_ref,
                                            decode_kwargs)
        try:
            # fast path: forward the raw result ref so the KV block
            # moves prefill→decode directly through the object plane
            result = self.decode.decode_prefilled.remote(
                prefill_ref._ref, **decode_kwargs).result()
        except Exception:  # noqa: BLE001 — replica exceptions surface
            # as TaskError (not RuntimeError); retry once on the slow
            # path: materialize via the handle's re-routing result(),
            # which absorbs prefill-replica rejection/restart
            prefill_out = prefill_ref.result()
            result = self.decode.decode_prefilled.remote(
                prefill_out, **decode_kwargs).result()
        return {
            "id": f"cmpl-{uuid.uuid4().hex[:24]}",
            "object": "text_completion",
            "model": body.get("model", self.config.model_id),
            "choices": [{
                "index": 0,
                "text": result["text"],
                "finish_reason": result["finish_reason"],
            }],
            "usage": {
                "prompt_tokens": result["prompt_tokens"],
                "completion_tokens": result["completion_tokens"],
                "total_tokens": (result["prompt_tokens"]
                                 + result["completion_tokens"]),
            },
        }


    def _stream_completions(self, body: Dict[str, Any], prefill_ref,
                            decode_kwargs: Dict[str, Any]):
        """SSE generator over the disaggregated path: token deltas
        stream from the decode replica through the router (reference:
        serve/llm streaming everywhere, incl. disagg deployments)."""
        import json as _json
        import uuid

        cid = f"cmpl-{uuid.uuid4().hex[:24]}"
        model = body.get("model", self.config.model_id)

        def chunks(gen):
            finish = "stop"
            usage = None
            handoff_ms = None
            for item in gen:
                if isinstance(item, dict):  # trailing usage record
                    finish = item.get("finish_reason", finish)
                    usage = item.get("usage")
                    handoff_ms = item.get("kv_handoff_ms")
                    continue
                yield {"id": cid, "object": "text_completion",
                       "model": model,
                       "choices": [{"index": 0, "text": item,
                                    "finish_reason": None}]}
            final = {"id": cid, "object": "text_completion",
                     "model": model,
                     "choices": [{"index": 0, "text": "",
                                  "finish_reason": finish}]}
            if usage is not None:
                final["usage"] = usage
            if handoff_ms is not None:
                final["kv_handoff_ms"] = handoff_ms
            yield final

        stream_handle = self.decode.options(stream=True)
        gen = stream_handle.decode_prefilled_stream.remote(
            prefill_ref._ref, **decode_kwargs)
        emitted = False
        try:
            for chunk in chunks(gen):
                emitted = True
                yield f"data: {_json.dumps(chunk)}\n\n"
        except Exception:  # noqa: BLE001 — replica rejection/restart
            if emitted:
                raise  # mid-stream failure: surface, don't restart text
            # slow path: materialize the prefill via the handle's
            # re-routing result(), then retry once
            prefill_out = prefill_ref.result()
            gen = stream_handle.decode_prefilled_stream.remote(
                prefill_out, **decode_kwargs)
            for chunk in chunks(gen):
                yield f"data: {_json.dumps(chunk)}\n\n"
        yield "data: [DONE]\n\n"


def build_disagg_app(config: LLMConfig, *, params=None,
                     num_prefill: int = 1, num_decode: int = 1):
    """A prefill/decode-disaggregated OpenAI app."""
    params_blob = None
    if params is not None:
        from ray_tpu.core import serialization
        params_blob = serialization.dumps(params)
    prefill_dep = serve.deployment(
        PrefillServer, name=f"{config.model_id}-prefill",
        num_replicas=num_prefill,
        max_ongoing_requests=config.max_ongoing_requests)
    decode_dep = serve.deployment(
        DecodeServer, name=f"{config.model_id}-decode",
        num_replicas=num_decode,
        max_ongoing_requests=config.max_ongoing_requests)
    router_dep = serve.deployment(
        DisaggRouter, name=f"{config.model_id}-router",
        num_replicas=1,
        max_ongoing_requests=4 * config.max_ongoing_requests)
    return router_dep.bind(config,
                           prefill_dep.bind(config, params_blob),
                           decode_dep.bind(config, params_blob))
