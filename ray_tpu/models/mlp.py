"""Small MLP classifier — the MNIST-class model for trainer tests
(reference analog: the torch MNIST recipes in release tests,
release_tests.yaml:197)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: tuple = (128, 128)
    out_dim: int = 10
    dtype: Any = jnp.float32


def mlp_init(rng, config: MLPConfig):
    dims = [config.in_dim, *config.hidden, config.out_dim]
    keys = jax.random.split(rng, len(dims) - 1)
    layers = []
    for key, d_in, d_out in zip(keys, dims[:-1], dims[1:]):
        layers.append({
            "w": (jax.random.normal(key, (d_in, d_out)) * (d_in ** -0.5)
                  ).astype(config.dtype),
            "b": jnp.zeros((d_out,), dtype=config.dtype),
        })
    return {"layers": layers}


def mlp_forward(params, x):
    n = len(params["layers"])
    for i, layer in enumerate(params["layers"]):
        x = x @ layer["w"] + layer["b"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def mlp_loss(params, x, y):
    logits = mlp_forward(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))
