"""Vision Transformer + CLIP dual-encoder, functional JAX.

The vision model family for the Data→Train streaming path (the
reference exercises this shape as release workloads — CLIP/SD-XL
pretrain over Ray Data + Train, release/release_tests.yaml — with the
model code living outside the repo; here the family is in-tree,
TPU-first, like models/llama.py).

Design mirrors the llama module so everything downstream (sharding
rules, trainers, bench harnesses) composes identically:

- Layers stacked on a leading axis and iterated with `lax.scan`; one
  compiled block regardless of depth, `jax.checkpoint` per block when
  `remat` is set.
- Patchify is a reshape+transpose to [B, n_patches, patch_dim] followed
  by one large [tokens, features] matmul — no conv needed, the MXU sees
  the same GEMM either way and XLA fuses the layout shuffle.
- Attention pluggable: "flash" (ray_tpu/ops/attention.py — uses the
  Pallas kernel when seq/head_dim fit its 128-tiling, otherwise it
  transparently falls back to the fused-jnp reference path; the stock
  ViT-B/L and CLIP-text presets have head_dim 64, so they take the
  fallback today) or "reference" (jnp), per config.
- Sharding external: `vit_sharding_rules(mode)` / CLIP reuses the same
  rule shapes (ddp/fsdp/tp/fsdp_tp) over its parameter tree.

CLIP pairs the ViT image tower with a small causal text transformer
(pre-LN, learned positions) and trains with the symmetric InfoNCE loss
over in-batch negatives; `clip_loss` is jit/pjit-friendly (batch
sharded on the data axis — the logits matrix [B, B] is tiny relative
to the towers).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import _attention_reference, flash_attention
from ray_tpu.parallel.sharding import ShardingRules


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    n_classes: int = 0       # >0 adds a classifier head on the pooled rep
    pool: str = "mean"       # mean | cls
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    attention: str = "flash"  # flash | reference
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.channels * self.patch_size * self.patch_size

    @property
    def seq_len(self) -> int:
        return self.n_patches + (1 if self.pool == "cls" else 0)

    # --- presets -------------------------------------------------------
    @staticmethod
    def base(**kw) -> "ViTConfig":
        return ViTConfig(**kw)  # ViT-B/16 defaults above

    @staticmethod
    def large(**kw) -> "ViTConfig":
        defaults = dict(dim=1024, n_layers=24, n_heads=16,
                        hidden_dim=4096)
        defaults.update(kw)
        return ViTConfig(**defaults)

    @staticmethod
    def tiny(**kw) -> "ViTConfig":
        """Test-scale config that runs on the 8-device CPU mesh."""
        defaults = dict(image_size=16, patch_size=4, dim=32, n_layers=2,
                        n_heads=4, hidden_dim=64, dtype=jnp.float32,
                        attention="reference", remat=False)
        defaults.update(kw)
        return ViTConfig(**defaults)

    def num_params(self) -> int:
        per_layer = (4 * self.dim * self.dim          # wq wk wv wo
                     + 2 * self.dim * self.hidden_dim  # w1 w2
                     + 4 * self.dim)                   # 2 LN scale+bias
        n = (self.patch_dim * self.dim + self.dim      # patch embed + b
             + self.seq_len * self.dim                 # pos embed
             + self.n_layers * per_layer
             + 2 * self.dim)                           # final LN
        if self.pool == "cls":
            n += self.dim
        if self.n_classes:
            n += self.dim * self.n_classes + self.n_classes
        return n


def layer_norm(x, scale, bias, eps: float):
    """Standard LayerNorm in fp32, cast back to the input dtype (ViT
    uses LN, not RMSNorm — keeping the family faithful)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def patchify(images, config: ViTConfig):
    """[B, H, W, C] -> [B, n_patches, patch_dim] by pure reshapes."""
    c = config
    b, h, w, ch = images.shape
    p = c.patch_size
    x = images.reshape(b, h // p, p, w // p, p, ch)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, Hp, Wp, p, p, C]
    return x.reshape(b, (h // p) * (w // p), p * p * ch)


def fan_in_init(key, shape, fan_in, dtype):
    """Normal(0, 1/fan_in) init in fp32, cast to the model dtype —
    the one initializer every family in this package uses."""
    return (jax.random.normal(key, shape, dtype=jnp.float32)
            * (fan_in ** -0.5)).astype(dtype)


def _encoder_layers_init(keys, L: int, D: int, H: int, dtype):
    """The stacked pre-LN transformer layer tree shared by the ViT and
    CLIP-text towers (identical structure; only the attention mask and
    the surrounding embeddings differ)."""
    def init(key, shape, fan_in):
        return fan_in_init(key, shape, fan_in, dtype)

    return {
        "ln1_scale": jnp.ones((L, D), dtype),
        "ln1_bias": jnp.zeros((L, D), dtype),
        "wq": init(keys[0], (L, D, D), D),
        "wk": init(keys[1], (L, D, D), D),
        "wv": init(keys[2], (L, D, D), D),
        "wo": init(keys[3], (L, D, D), D),
        "ln2_scale": jnp.ones((L, D), dtype),
        "ln2_bias": jnp.zeros((L, D), dtype),
        "w1": init(keys[4], (L, D, H), D),
        "w2": init(keys[5], (L, H, D), H),
    }


def _encoder_block(layer, x, *, n_heads: int, norm_eps: float,
                   attention: str, causal: bool):
    """One pre-LN block: LN → MHA → residual → LN → GELU MLP → residual.
    `attention="flash"` uses the Pallas kernel when the shape fits its
    tiling (ops/attention.py falls back to the fused-jnp reference path
    otherwise — e.g. head_dim 64 ViT/CLIP presets)."""
    b, s, d = x.shape
    hd = d // n_heads
    h = layer_norm(x, layer["ln1_scale"], layer["ln1_bias"], norm_eps)
    q = (h @ layer["wq"]).reshape(b, s, n_heads, hd)
    k = (h @ layer["wk"]).reshape(b, s, n_heads, hd)
    v = (h @ layer["wv"]).reshape(b, s, n_heads, hd)
    if attention == "flash":
        attn = flash_attention(q, k, v, causal=causal)
    else:
        attn = _attention_reference(q, k, v, causal)
    x = x + attn.reshape(b, s, d).astype(x.dtype) @ layer["wo"]
    h = layer_norm(x, layer["ln2_scale"], layer["ln2_bias"], norm_eps)
    y = jax.nn.gelu(h @ layer["w1"]) @ layer["w2"]
    return x + y


def _encoder_scan(layers, x, *, n_heads: int, norm_eps: float,
                  attention: str, causal: bool, remat: bool):
    block = functools.partial(_encoder_block, n_heads=n_heads,
                              norm_eps=norm_eps, attention=attention,
                              causal=causal)
    if remat:
        block = jax.checkpoint(block)

    def scan_body(x, layer):
        return block(layer, x), None

    x, _ = jax.lax.scan(scan_body, x, layers)
    return x


def vit_init(rng, config: ViTConfig) -> Dict[str, Any]:
    """Initialize the parameter pytree (layers stacked on axis 0)."""
    c = config
    keys = jax.random.split(rng, 8)
    D = c.dim

    def init(key, shape, fan_in):
        return fan_in_init(key, shape, fan_in, c.dtype)

    params = {
        "patch_embed": init(keys[0], (c.patch_dim, D), c.patch_dim),
        "patch_bias": jnp.zeros((D,), c.dtype),
        "pos_embed": (jax.random.normal(keys[1], (c.seq_len, D),
                                        dtype=jnp.float32)
                      * 0.02).astype(c.dtype),
        "layers": _encoder_layers_init(keys[2:], c.n_layers, D,
                                       c.hidden_dim, c.dtype),
        "final_ln_scale": jnp.ones((D,), c.dtype),
        "final_ln_bias": jnp.zeros((D,), c.dtype),
    }
    if c.pool == "cls":
        params["cls_token"] = jnp.zeros((D,), c.dtype)
    if c.n_classes:
        params["head_w"] = init(jax.random.fold_in(rng, 99),
                                (D, c.n_classes), D)
        params["head_b"] = jnp.zeros((c.n_classes,), c.dtype)
    return params


def vit_forward(params, images, config: ViTConfig,
                return_pooled: bool = False):
    """images: [B, H, W, C] float -> logits [B, n_classes] (if a head
    is configured) else the pooled representation [B, dim].
    ``return_pooled`` forces the pooled rep even with a head (CLIP
    tower usage)."""
    c = config
    x = patchify(images.astype(c.dtype), c) @ params["patch_embed"]
    x = x + params["patch_bias"]
    if c.pool == "cls":
        cls = jnp.broadcast_to(params["cls_token"],
                               (x.shape[0], 1, c.dim))
        x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"]
    x = _encoder_scan(params["layers"], x, n_heads=c.n_heads,
                      norm_eps=c.norm_eps, attention=c.attention,
                      causal=False, remat=c.remat)
    x = layer_norm(x, params["final_ln_scale"], params["final_ln_bias"],
                   c.norm_eps)
    pooled = x[:, 0] if c.pool == "cls" else jnp.mean(x, axis=1)
    if c.n_classes and not return_pooled:
        return (pooled @ params["head_w"] + params["head_b"]
                ).astype(jnp.float32)
    return pooled


def vit_loss(params, images, labels, config: ViTConfig):
    """Softmax cross-entropy for supervised classification."""
    logits = vit_forward(params, images, config)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None],
                                         axis=-1))


# mode -> (layer in-projection, layer out-projection, embedding) specs;
# shared by vit_sharding_rules and clip_sharding_rules so the two stay
# in lockstep (layer specs have a leading None for the stacked axis).
_MODE_SPECS = {
    "fsdp": (P(None, "fsdp", None), P(None, None, "fsdp"),
             P("fsdp", None)),
    "tp": (P(None, None, "model"), P(None, "model", None),
           P(None, "model")),
    "fsdp_tp": (P(None, "fsdp", "model"), P(None, "model", "fsdp"),
                P("fsdp", "model")),
}


def _mode_specs(mode: str):
    if mode not in _MODE_SPECS:
        raise ValueError(f"unknown sharding mode {mode}")
    return _MODE_SPECS[mode]


def vit_sharding_rules(mode: str = "fsdp") -> ShardingRules:
    """ddp | fsdp | tp | fsdp_tp over the stacked-layer tree (leading
    axis = layers, like llama_sharding_rules)."""
    if mode == "ddp":
        return ShardingRules(rules=[(r".*", P())])
    spec_in, spec_out, embed = _mode_specs(mode)
    return ShardingRules(rules=[
        (r"patch_embed", embed),
        (r"layers/(wq|wk|wv|w1)", spec_in),
        (r"layers/(wo|w2)", spec_out),
        # classifier head [D, n_classes]: same layout as the embedding
        # (column-parallel under tp, like llama's lm_head)
        (r"head_w", embed),
        (r".*", P()),
    ])


# ---------------------------------------------------------------------------
# CLIP dual-encoder
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    max_seq_len: int = 77
    dim: int = 512
    n_layers: int = 12
    n_heads: int = 8
    hidden_dim: int = 2048
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    attention: str = "flash"
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def tiny(**kw) -> "CLIPTextConfig":
        defaults = dict(vocab_size=128, max_seq_len=16, dim=32,
                        n_layers=2, n_heads=4, hidden_dim=64,
                        dtype=jnp.float32, attention="reference",
                        remat=False)
        defaults.update(kw)
        return CLIPTextConfig(**defaults)


@dataclass(frozen=True)
class CLIPConfig:
    vision: ViTConfig = ViTConfig()
    text: CLIPTextConfig = CLIPTextConfig()
    embed_dim: int = 512
    # learnable temperature, stored as log for positivity
    logit_scale_init: float = 2.6592  # log(1/0.07), the CLIP paper value

    @staticmethod
    def tiny(**kw) -> "CLIPConfig":
        defaults = dict(vision=ViTConfig.tiny(), text=CLIPTextConfig.tiny(),
                        embed_dim=16)
        defaults.update(kw)
        return CLIPConfig(**defaults)


def _text_init(rng, c: CLIPTextConfig) -> Dict[str, Any]:
    keys = jax.random.split(rng, 8)
    D = c.dim
    return {
        "embedding": (jax.random.normal(
            keys[0], (c.vocab_size, D), dtype=jnp.float32) * 0.02
            ).astype(c.dtype),
        "pos_embed": (jax.random.normal(
            keys[1], (c.max_seq_len, D), dtype=jnp.float32) * 0.01
            ).astype(c.dtype),
        "layers": _encoder_layers_init(keys[2:], c.n_layers, D,
                                       c.hidden_dim, c.dtype),
        "final_ln_scale": jnp.ones((D,), c.dtype),
        "final_ln_bias": jnp.zeros((D,), c.dtype),
    }


def _text_forward(params, tokens, c: CLIPTextConfig):
    """Causal text tower -> per-sequence rep at the final position
    (callers place EOS last / pad left, the CLIP convention of pooling
    at the EOS token)."""
    s = tokens.shape[1]
    x = params["embedding"][tokens].astype(c.dtype)
    x = x + params["pos_embed"][:s]
    x = _encoder_scan(params["layers"], x, n_heads=c.n_heads,
                      norm_eps=c.norm_eps, attention=c.attention,
                      causal=True, remat=c.remat)
    x = layer_norm(x, params["final_ln_scale"], params["final_ln_bias"],
                   c.norm_eps)
    return x[:, -1]


def clip_init(rng, config: CLIPConfig) -> Dict[str, Any]:
    c = config
    k_v, k_t, k_pv, k_pt = jax.random.split(rng, 4)
    return {
        "vision": vit_init(k_v, c.vision),
        "text": _text_init(k_t, c.text),
        "proj_v": (jax.random.normal(k_pv, (c.vision.dim, c.embed_dim),
                                     dtype=jnp.float32)
                   * (c.vision.dim ** -0.5)).astype(c.vision.dtype),
        "proj_t": (jax.random.normal(k_pt, (c.text.dim, c.embed_dim),
                                     dtype=jnp.float32)
                   * (c.text.dim ** -0.5)).astype(c.text.dtype),
        "logit_scale": jnp.asarray(c.logit_scale_init, jnp.float32),
    }


def clip_encode_image(params, images, config: CLIPConfig):
    rep = vit_forward(params["vision"], images, config.vision,
                      return_pooled=True)
    emb = (rep @ params["proj_v"]).astype(jnp.float32)
    return emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)


def clip_encode_text(params, tokens, config: CLIPConfig):
    rep = _text_forward(params["text"], tokens, config.text)
    emb = (rep @ params["proj_t"]).astype(jnp.float32)
    return emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)


def clip_loss(params, images, tokens, config: CLIPConfig):
    """Symmetric InfoNCE over in-batch negatives (the CLIP objective).
    Under pjit with batch sharded on `data`, the two [B, embed] towers
    compute locally and XLA all-gathers only the tiny embedding
    matrices for the [B, B] logits."""
    img = clip_encode_image(params, images, config)
    txt = clip_encode_text(params, tokens, config)
    scale = jnp.exp(jnp.clip(params["logit_scale"], -5.0, 4.6052))
    logits = scale * (img @ txt.T)  # [B, B]
    labels = jnp.arange(logits.shape[0])
    li = -jnp.mean(jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), labels[:, None], 1))
    lt = -jnp.mean(jnp.take_along_axis(
        jax.nn.log_softmax(logits.T, axis=-1), labels[:, None], 1))
    return 0.5 * (li + lt)


def clip_sharding_rules(mode: str = "fsdp") -> ShardingRules:
    """One rule set over the combined {vision, text, proj_*} tree —
    the tower rules are path-prefixed copies of vit_sharding_rules."""
    if mode == "ddp":
        return ShardingRules(rules=[(r".*", P())])
    in_s, out_s, emb = _mode_specs(mode)
    return ShardingRules(rules=[
        (r"(vision|text)/layers/(wq|wk|wv|w1)", in_s),
        (r"(vision|text)/layers/(wo|w2)", out_s),
        (r"vision/patch_embed", emb),
        (r"text/embedding", emb),
        (r"proj_(v|t)", emb),
        (r".*", P()),
    ])
