"""Model zoo: functional JAX models designed for GSPMD sharding."""

from ray_tpu.models.llama import (
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_loss,
    llama_sharding_rules,
)
from ray_tpu.models.mlp import MLPConfig, mlp_forward, mlp_init
