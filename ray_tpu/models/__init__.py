"""Model zoo: functional JAX models designed for GSPMD sharding."""

from ray_tpu.models.llama import (
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_loss,
    llama_sharding_rules,
)
from ray_tpu.models.dit import (
    DiTConfig,
    dit_forward,
    dit_init,
    dit_loss,
    dit_sample,
    dit_sharding_rules,
)
from ray_tpu.models.mlp import MLPConfig, mlp_forward, mlp_init
from ray_tpu.models.vit import (
    CLIPConfig,
    CLIPTextConfig,
    ViTConfig,
    clip_encode_image,
    clip_encode_text,
    clip_init,
    clip_loss,
    clip_sharding_rules,
    vit_forward,
    vit_init,
    vit_loss,
    vit_sharding_rules,
)
