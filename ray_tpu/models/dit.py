"""DiT — diffusion transformer (adaLN-Zero), functional JAX.

The diffusion model family for the Data→Train pretrain path (the
reference runs SD-XL-class diffusion pretrain as release workloads over
Ray Data + Train, release/release_tests.yaml, with the model code
outside the repo; here the family is in-tree, TPU-first). Architecture
follows the published DiT recipe (Peebles & Xie, arXiv 2212.09748):
patchified inputs, transformer blocks whose LayerNorms are modulated by
a conditioning vector (timestep + optional class label), zero-init
modulation ("adaLN-Zero") so every block starts as the identity.

TPU-first choices, matching models/llama.py and models/vit.py:
- stacked layers + `lax.scan` (one compiled block), optional
  `jax.checkpoint` per block;
- all matmuls [tokens, features] × [features, out], bf16 with fp32
  accumulation; the conditioning modulation is a [B, 6D] vector — tiny
  next to the token matmuls, so XLA fuses it into the block;
- attention via ops/attention.py (Pallas flash when shapes fit its
  128-tiling, fused-jnp fallback otherwise — DiT presets have
  head_dim 64/72 so they take the fallback today);
- sharding external: `dit_sharding_rules(mode)` with the same
  ddp/fsdp/tp/fsdp_tp modes as the other families.

Training uses continuous-time epsilon prediction with the cosine
schedule: x_t = cos(πt/2)·x0 + sin(πt/2)·ε, model predicts ε, MSE loss.
`dit_sample` is a DDIM loop under `lax.fori_loop` (static step count —
jit-friendly).
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.models.vit import _mode_specs, fan_in_init, layer_norm
from ray_tpu.ops.attention import _attention_reference, flash_attention
from ray_tpu.parallel.sharding import ShardingRules


@dataclass(frozen=True)
class DiTConfig:
    input_size: int = 32        # latent (or image) height = width
    patch_size: int = 2
    channels: int = 4           # 4 = VAE latent space, 3 = pixels
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    n_classes: int = 0          # >0 = class-conditional (+ null class)
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    attention: str = "flash"    # flash | reference
    remat: bool = True
    time_freq_dim: int = 256    # sinusoidal timestep feature width

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def n_patches(self) -> int:
        return (self.input_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.channels * self.patch_size * self.patch_size

    # --- presets -------------------------------------------------------
    @staticmethod
    def b_2(**kw) -> "DiTConfig":
        return DiTConfig(**kw)  # DiT-B/2 defaults above

    @staticmethod
    def xl_2(**kw) -> "DiTConfig":
        defaults = dict(dim=1152, n_layers=28, n_heads=16,
                        hidden_dim=4608)
        defaults.update(kw)
        return DiTConfig(**defaults)

    @staticmethod
    def tiny(**kw) -> "DiTConfig":
        """Test-scale config that runs on the 8-device CPU mesh."""
        defaults = dict(input_size=8, patch_size=2, channels=3, dim=32,
                        n_layers=2, n_heads=4, hidden_dim=64,
                        time_freq_dim=16, dtype=jnp.float32,
                        attention="reference", remat=False)
        defaults.update(kw)
        return DiTConfig(**defaults)


def _patchify(x, c: DiTConfig):
    b, h, w, ch = x.shape
    p = c.patch_size
    x = x.reshape(b, h // p, p, w // p, p, ch)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (h // p) * (w // p), p * p * ch)


def _unpatchify(tokens, c: DiTConfig):
    b = tokens.shape[0]
    hp = c.input_size // c.patch_size
    p = c.patch_size
    x = tokens.reshape(b, hp, hp, p, p, c.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, c.input_size, c.input_size, c.channels)


def timestep_embedding(t, freq_dim: int):
    """Sinusoidal features of continuous t in [0, 1] — [B, freq_dim]."""
    half = freq_dim // 2
    freqs = jnp.exp(-math.log(10000.0)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :] * 1000.0
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def dit_init(rng, config: DiTConfig) -> Dict[str, Any]:
    """Parameter pytree (layers stacked on axis 0; modulation
    projections zero-init per adaLN-Zero so blocks start as identity)."""
    c = config
    keys = jax.random.split(rng, 12)
    D, H, L = c.dim, c.hidden_dim, c.n_layers

    def init(key, shape, fan_in):
        return fan_in_init(key, shape, fan_in, c.dtype)

    params = {
        "patch_embed": init(keys[0], (c.patch_dim, D), c.patch_dim),
        "patch_bias": jnp.zeros((D,), c.dtype),
        "pos_embed": (jax.random.normal(keys[1], (c.n_patches, D),
                                        dtype=jnp.float32)
                      * 0.02).astype(c.dtype),
        "time_w1": init(keys[2], (c.time_freq_dim, D), c.time_freq_dim),
        "time_b1": jnp.zeros((D,), c.dtype),
        "time_w2": init(keys[3], (D, D), D),
        "time_b2": jnp.zeros((D,), c.dtype),
        "layers": {
            "wq": init(keys[4], (L, D, D), D),
            "wk": init(keys[5], (L, D, D), D),
            "wv": init(keys[6], (L, D, D), D),
            "wo": init(keys[7], (L, D, D), D),
            "w1": init(keys[8], (L, D, H), D),
            "w2": init(keys[9], (L, H, D), H),
            # adaLN-Zero: 6 modulation vectors per block, zero-init
            "mod_w": jnp.zeros((L, D, 6 * D), c.dtype),
            "mod_b": jnp.zeros((L, 6 * D), c.dtype),
        },
        # final layer: adaLN (shift, scale) + zero-init output proj
        "final_mod_w": jnp.zeros((D, 2 * D), c.dtype),
        "final_mod_b": jnp.zeros((2 * D,), c.dtype),
        "final_w": jnp.zeros((D, c.patch_dim), c.dtype),
        "final_b": jnp.zeros((c.patch_dim,), c.dtype),
    }
    if c.n_classes:
        # +1 slot: the "null" class for classifier-free guidance
        params["label_embed"] = (jax.random.normal(
            keys[10], (c.n_classes + 1, D), dtype=jnp.float32)
            * 0.02).astype(c.dtype)
    return params


def _ada_ln(x, shift, scale, eps: float):
    """Parameter-free LN modulated by per-sample (shift, scale)."""
    ones = jnp.ones((x.shape[-1],), jnp.float32)
    zeros = jnp.zeros((x.shape[-1],), jnp.float32)
    h = layer_norm(x, ones, zeros, eps)
    return h * (1.0 + scale[:, None, :]) + shift[:, None, :]


def _dit_block(layer, x, cond, config: DiTConfig):
    c = config
    b, s, d = x.shape
    mod = (cond @ layer["mod_w"] + layer["mod_b"]).astype(x.dtype)
    (sh1, sc1, g1, sh2, sc2, g2) = jnp.split(mod, 6, axis=-1)

    h = _ada_ln(x, sh1, sc1, c.norm_eps).astype(x.dtype)
    q = (h @ layer["wq"]).reshape(b, s, c.n_heads, c.head_dim)
    k = (h @ layer["wk"]).reshape(b, s, c.n_heads, c.head_dim)
    v = (h @ layer["wv"]).reshape(b, s, c.n_heads, c.head_dim)
    if c.attention == "flash":
        attn = flash_attention(q, k, v, causal=False)
    else:
        attn = _attention_reference(q, k, v, False)
    attn = attn.reshape(b, s, d).astype(x.dtype) @ layer["wo"]
    x = x + g1[:, None, :] * attn

    h = _ada_ln(x, sh2, sc2, c.norm_eps).astype(x.dtype)
    y = jax.nn.gelu(h @ layer["w1"]) @ layer["w2"]
    return x + g2[:, None, :] * y


def dit_forward(params, x_t, t, config: DiTConfig, labels=None):
    """x_t: [B, H, W, C] noised input, t: [B] in [0, 1],
    labels: [B] int (n_classes = null/unconditional slot) → predicted
    noise ε̂ [B, H, W, C]."""
    c = config
    x = _patchify(x_t.astype(c.dtype), c) @ params["patch_embed"]
    x = x + params["patch_bias"] + params["pos_embed"]

    temb = timestep_embedding(t, c.time_freq_dim).astype(c.dtype)
    cond = jax.nn.silu(temb @ params["time_w1"] + params["time_b1"])
    cond = cond @ params["time_w2"] + params["time_b2"]
    if c.n_classes:
        lab = (jnp.full((x.shape[0],), c.n_classes, jnp.int32)
               if labels is None else labels)
        cond = cond + params["label_embed"][lab]

    block = functools.partial(_dit_block, config=c)
    if c.remat:
        block = jax.checkpoint(block)

    def scan_body(x, layer):
        return block(layer, x, cond), None

    x, _ = jax.lax.scan(scan_body, x, params["layers"])

    fmod = (cond @ params["final_mod_w"] + params["final_mod_b"]
            ).astype(x.dtype)
    shift, scale = jnp.split(fmod, 2, axis=-1)
    x = _ada_ln(x, shift, scale, c.norm_eps).astype(x.dtype)
    out = x @ params["final_w"] + params["final_b"]
    return _unpatchify(out.astype(jnp.float32), c)


def cosine_alpha_sigma(t):
    """Continuous cosine schedule: ᾱ, σ with ᾱ² + σ² = 1."""
    angle = 0.5 * jnp.pi * t
    return jnp.cos(angle), jnp.sin(angle)


def dit_loss(params, rng, x0, config: DiTConfig, labels=None,
             label_drop: float = 0.1):
    """Continuous-time ε-prediction MSE. With labels, drops them to the
    null class with prob `label_drop` (classifier-free guidance
    training)."""
    c = config
    k_t, k_eps, k_drop = jax.random.split(rng, 3)
    b = x0.shape[0]
    t = jax.random.uniform(k_t, (b,), minval=1e-4, maxval=1.0 - 1e-4)
    eps = jax.random.normal(k_eps, x0.shape, dtype=jnp.float32)
    alpha, sigma = cosine_alpha_sigma(t)
    x_t = (alpha[:, None, None, None] * x0.astype(jnp.float32)
           + sigma[:, None, None, None] * eps)
    if c.n_classes and labels is not None and label_drop > 0:
        drop = jax.random.uniform(k_drop, (b,)) < label_drop
        labels = jnp.where(drop, c.n_classes, labels)
    pred = dit_forward(params, x_t, t, c, labels)
    return jnp.mean((pred - eps) ** 2)


def dit_sample(params, rng, config: DiTConfig, n: int, steps: int = 50,
               labels=None, guidance_scale: float = 0.0,
               x0_clip: float = 4.0):
    """Deterministic DDIM sampler (static `steps`, lax.fori_loop).
    guidance_scale > 0 runs conditional+null passes per step
    (classifier-free guidance). ``x0_clip`` bounds the denoised
    estimate each step ("clip denoised"): near t=1 the x0 form divides
    by ᾱ→0, so an unclipped estimate amplifies model error by orders
    of magnitude; the start time is also backed off to t=0.99 where
    ᾱ≈0.016 (both standard diffusion-sampler stabilizations)."""
    c = config
    shape = (n, c.input_size, c.input_size, c.channels)
    x = jax.random.normal(rng, shape, dtype=jnp.float32)
    ts = jnp.linspace(0.99, 1e-4, steps + 1)

    def eps_hat(x, t_vec):
        if guidance_scale > 0 and c.n_classes and labels is not None:
            e_c = dit_forward(params, x, t_vec, c, labels)
            e_u = dit_forward(params, x, t_vec, c, None)
            return e_u + (1.0 + guidance_scale) * (e_c - e_u)
        return dit_forward(params, x, t_vec, c, labels)

    def body(i, x):
        t_now, t_next = ts[i], ts[i + 1]
        t_vec = jnp.full((n,), t_now)
        a_now, s_now = cosine_alpha_sigma(t_now)
        a_next, s_next = cosine_alpha_sigma(t_next)
        e = eps_hat(x, t_vec)
        x0 = jnp.clip((x - s_now * e) / a_now, -x0_clip, x0_clip)
        # re-derive ε from the clipped x0 so the update stays consistent
        e = (x - a_now * x0) / jnp.maximum(s_now, 1e-6)
        return a_next * x0 + s_next * e

    return jax.lax.fori_loop(0, steps, body, x)


def dit_sharding_rules(mode: str = "fsdp") -> ShardingRules:
    """ddp | fsdp | tp | fsdp_tp — same mode table as the ViT/CLIP
    family (leading axis = layers on the block weights)."""
    if mode == "ddp":
        return ShardingRules(rules=[(r".*", P())])
    spec_in, spec_out, embed = _mode_specs(mode)
    return ShardingRules(rules=[
        (r"patch_embed", embed),
        (r"layers/(wq|wk|wv|w1)", spec_in),
        (r"layers/(wo|w2)", spec_out),
        (r"layers/mod_w", spec_in),
        (r".*", P()),
    ])
