"""Llama-family decoder (Llama-2/3 architecture), functional JAX.

The flagship model for the Train path (BASELINE.md north star:
Llama-2-7B fine-tune ≥35% MFU on v5p). Design choices for TPU:

- Layers are *stacked* (leading n_layers axis) and iterated with
  `lax.scan`: one compiled block regardless of depth, fast compiles,
  and `jax.checkpoint` per block gives layer-granular rematerialization.
- All matmuls stay [tokens, features] × [features, out] — large, MXU-
  shaped, bfloat16 by default with float32 accumulation.
- Attention pluggable: "flash" (Pallas kernel, ray_tpu/ops/attention.py),
  "reference" (jnp), or "ring"/"ulysses" (sequence-parallel,
  ray_tpu/parallel/ring_attention.py) — selected by the sharding config,
  not the model code.
- Sharding is external: `llama_sharding_rules(mode)` returns rules for
  this parameter tree (ddp/fsdp/tp/fsdp_tp), applied via
  ray_tpu.parallel.sharding. The model itself is sharding-agnostic.
"""

from __future__ import annotations

import dataclasses
import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.rmsnorm import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.parallel.sharding import ShardingRules


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    hidden_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attention: str = "flash"  # flash | reference | ring | ulysses
    remat: bool = True
    # Chunked cross-entropy: tokens per chunk (0/None = dense loss).
    # Avoids materializing [B, S, vocab] fp32 logits — at large batch
    # the dominant activation — trading ~one extra lm_head forward in
    # the backward pass (see chunked_cross_entropy).
    ce_chunk_tokens: int = 0
    # Mixture-of-Experts: >0 replaces the dense FFN with moe_experts
    # expert FFNs routed top-k, expert-parallel over the "expert" mesh
    # axis (ray_tpu/parallel/moe.py; no reference analog — SURVEY §2.3
    # X4 commits EP in-tree).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    # weight of the Switch-style load-balancing aux loss (per layer)
    moe_aux_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    # --- presets -------------------------------------------------------
    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        return LlamaConfig(vocab_size=128256, dim=4096, n_layers=32,
                           n_heads=32, n_kv_heads=8, hidden_dim=14336,
                           max_seq_len=8192, rope_theta=500000.0, **kw)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """Test-scale config that runs on the 8-device CPU mesh."""
        defaults = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=128,
                        dtype=jnp.float32, attention="reference",
                        remat=False)
        defaults.update(kw)
        return LlamaConfig(**defaults)

    @staticmethod
    def mixtral_8x7b(**kw) -> "LlamaConfig":
        """Mixtral-8x7B shape: 8-expert top-2 SwiGLU MoE on a
        Mistral-7B trunk (GQA 8 KV heads). Routed through
        parallel/moe.py, expert-parallel over the "expert" axis."""
        defaults = dict(vocab_size=32000, dim=4096, n_layers=32,
                        n_heads=32, n_kv_heads=8, hidden_dim=14336,
                        max_seq_len=32768, rope_theta=1e6,
                        moe_experts=8, moe_top_k=2)
        defaults.update(kw)
        return LlamaConfig(**defaults)

    @staticmethod
    def tiny_moe(**kw) -> "LlamaConfig":
        """Test-scale MoE config for the 8-device CPU mesh."""
        defaults = dict(moe_experts=4, moe_top_k=2)
        defaults.update(kw)
        return LlamaConfig.tiny(**defaults)

    @staticmethod
    def small_1b(**kw) -> "LlamaConfig":
        defaults = dict(vocab_size=32000, dim=2048, n_layers=16,
                        n_heads=16, n_kv_heads=16, hidden_dim=5504,
                        max_seq_len=2048)
        defaults.update(kw)
        return LlamaConfig(**defaults)

    def num_params(self) -> int:
        hd = self.head_dim
        ffn_copies = max(1, self.moe_experts)
        per_layer = (
            self.dim * self.n_heads * hd          # wq
            + 2 * self.dim * self.n_kv_heads * hd  # wk, wv
            + self.n_heads * hd * self.dim         # wo
            + ffn_copies * 3 * self.dim * self.hidden_dim  # w1, w2, w3
            + (self.dim * self.moe_experts if self.moe_experts else 0)
            + 2 * self.dim                         # norms
        )
        return (self.vocab_size * self.dim * 2     # embedding + lm_head
                + self.n_layers * per_layer + self.dim)

    def active_params_per_token(self) -> int:
        """Parameters actually touched per token: for MoE, only top_k of
        the moe_experts expert FFNs are active."""
        total = self.num_params()
        if self.moe_experts:
            inactive = ((self.moe_experts - min(self.moe_top_k,
                                                self.moe_experts))
                        * 3 * self.dim * self.hidden_dim * self.n_layers)
            total -= inactive
        return total

    def flops_per_token(self) -> float:
        """Approx training FLOPs/token (6 * active params; counting all
        experts would overstate MoE MFU by E/top_k)."""
        return 6.0 * self.active_params_per_token()


def llama_init(rng, config: LlamaConfig) -> Dict[str, Any]:
    """Initialize the parameter pytree (layers stacked on axis 0)."""
    c = config
    hd = c.head_dim
    k_embed, k_layers, k_head = jax.random.split(rng, 3)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32)
                * (fan_in ** -0.5)).astype(c.dtype)

    keys = jax.random.split(k_layers, 7)

    def stack(key, shape, fan_in):
        return dense(key, (c.n_layers, *shape), fan_in)

    layers: Dict[str, Any] = {
        "attn_norm": jnp.ones((c.n_layers, c.dim), dtype=c.dtype),
        "wq": stack(keys[0], (c.dim, c.n_heads * hd), c.dim),
        "wk": stack(keys[1], (c.dim, c.n_kv_heads * hd), c.dim),
        "wv": stack(keys[2], (c.dim, c.n_kv_heads * hd), c.dim),
        "wo": stack(keys[3], (c.n_heads * hd, c.dim), c.n_heads * hd),
        "mlp_norm": jnp.ones((c.n_layers, c.dim), dtype=c.dtype),
    }
    if c.moe_experts:
        # expert-stacked FFN weights [L, E, ...] + per-layer router
        layers["router"] = stack(keys[6], (c.dim, c.moe_experts), c.dim)
        layers["w1"] = stack(keys[4], (c.moe_experts, c.dim, c.hidden_dim),
                             c.dim)
        layers["w3"] = stack(keys[5], (c.moe_experts, c.dim, c.hidden_dim),
                             c.dim)
        layers["w2"] = stack(
            jax.random.fold_in(keys[6], 1),
            (c.moe_experts, c.hidden_dim, c.dim), c.hidden_dim)
    else:
        layers["w1"] = stack(keys[4], (c.dim, c.hidden_dim), c.dim)
        layers["w3"] = stack(keys[5], (c.dim, c.hidden_dim), c.dim)
        layers["w2"] = stack(keys[6], (c.hidden_dim, c.dim), c.hidden_dim)
    params = {
        "embedding": dense(k_embed, (c.vocab_size, c.dim), c.dim),
        "layers": layers,
        "final_norm": jnp.ones((c.dim,), dtype=c.dtype),
        "lm_head": dense(k_head, (c.dim, c.vocab_size), c.dim),
    }
    return params


def _attention(q, k, v, config: LlamaConfig, mesh):
    """Dispatch to the configured attention implementation."""
    n_rep = config.n_heads // config.n_kv_heads
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    if config.attention == "ring":
        from ray_tpu.parallel.ring_attention import ring_attention
        return ring_attention(q, k, v, mesh, causal=True)
    if config.attention == "ulysses":
        from ray_tpu.parallel.ring_attention import ulysses_attention
        return ulysses_attention(q, k, v, mesh, causal=True)
    if config.attention == "flash":
        return flash_attention(q, k, v, True)
    from ray_tpu.ops.attention import _attention_reference
    return _attention_reference(q, k, v, True)


def _int8_mm(x2d, w8, scale):
    """x2d @ dequant(w8, scale): the Pallas in-register-dequant kernel
    on TPU (halved HBM weight traffic — ops/quant_matmul.py), an XLA
    dequant matmul elsewhere (CPU tests; same math, so outputs agree
    across backends up to accumulation order)."""
    if jax.default_backend() == "tpu":
        from ray_tpu.ops.quant_matmul import int8_matmul
        return int8_matmul(x2d.astype(jnp.bfloat16), w8, scale) \
            .astype(x2d.dtype)
    return (x2d @ w8.astype(x2d.dtype)) * scale.astype(x2d.dtype)


def _ffn(layer_params, h, config: LlamaConfig):
    """FFN output (pre-residual): dense SwiGLU or the MoE layer.
    Returns (y, aux) — aux is the MoE load-balancing loss (0 if dense)."""
    c = config
    if c.moe_experts:
        from ray_tpu.parallel.moe import moe_ffn
        return moe_ffn(h, layer_params["router"], layer_params["w1"],
                       layer_params["w3"], layer_params["w2"],
                       top_k=c.moe_top_k,
                       capacity_factor=c.moe_capacity_factor)
    if "w1_q8" in layer_params:
        # weight-only int8 serving path (quantize_llama_ffn)
        b_t = h.shape[:-1]
        h2 = h.reshape(-1, h.shape[-1])
        gate = jax.nn.silu(_int8_mm(h2, layer_params["w1_q8"],
                                    layer_params["w1_s"]))
        up = _int8_mm(h2, layer_params["w3_q8"], layer_params["w3_s"])
        y = _int8_mm((gate * up).astype(h.dtype),
                     layer_params["w2_q8"], layer_params["w2_s"])
        return (y.reshape(*b_t, -1).astype(h.dtype),
                jnp.zeros((), jnp.float32))
    gate = jax.nn.silu(h @ layer_params["w1"])
    up = h @ layer_params["w3"]
    return (gate * up) @ layer_params["w2"], jnp.zeros((), jnp.float32)


def quantize_llama_ffn(params, config: LlamaConfig):
    """Weight-only int8 for the stacked FFN weights (w1/w3/w2 — ~2/3
    of a dense Llama's parameters): replaces each [L, K, N] stack with
    an int8 stack plus per-output-channel scales. Attention
    projections and lm_head stay in the working dtype (their HBM
    traffic is a minority and the KV cache dominates attention reads).
    Reference analog: vLLM quantization passthrough
    (llm/_internal/serve/engines/vllm/vllm_models.py:214)."""
    if config.moe_experts:
        raise ValueError("int8 quantization supports dense FFNs only "
                         "(MoE expert stacks are not wired)")
    from ray_tpu.ops.quant_matmul import quantize_int8
    layers = dict(params["layers"])
    for name in ("w1", "w3", "w2"):
        if name not in layers:
            raise ValueError(f"params missing FFN stack {name!r}")
        w8, scale = jax.vmap(quantize_int8)(layers.pop(name))
        layers[name + "_q8"] = w8
        layers[name + "_s"] = scale
    return {**params, "layers": layers}


def _block(layer_params, x, cos, sin, config: LlamaConfig, mesh,
           lora=None):
    """One transformer block. Returns (x, (k, v)) — K/V are post-rope,
    the layout the KV cache stores; training callers discard them.
    ``lora``: optional (A_q, B_q, A_v, B_v, scale) low-rank deltas on
    the q/v projections (zero extra cost when absent)."""
    c = config
    b, s, _ = x.shape
    hd = c.head_dim
    h = rms_norm(x, layer_params["attn_norm"], c.norm_eps)
    q = h @ layer_params["wq"]
    k = h @ layer_params["wk"]
    v = h @ layer_params["wv"]
    if lora is not None:
        a_q, b_q, a_v, b_v, scale = lora
        q = q + scale * _lora_delta(h, a_q, b_q)
        v = v + scale * _lora_delta(h, a_v, b_v)
    q = q.reshape(b, s, c.n_heads, hd)
    k = k.reshape(b, s, c.n_kv_heads, hd)
    v = v.reshape(b, s, c.n_kv_heads, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn = _attention(q, k, v, c, mesh)
    x = x + attn.reshape(b, s, c.n_heads * hd) @ layer_params["wo"]
    h = rms_norm(x, layer_params["mlp_norm"], c.norm_eps)
    y, aux = _ffn(layer_params, h, c)
    return x + y, (k, v), aux


def llama_forward(params, tokens, config: LlamaConfig, mesh=None,
                  return_aux: bool = False, return_hidden: bool = False):
    """tokens: [B, S] int32 -> logits [B, S, vocab] (float32).
    With return_aux, also returns the summed MoE load-balancing loss.
    With return_hidden, returns the final-norm hidden states INSTEAD of
    logits (the lm_head matmul is skipped — chunked_cross_entropy
    applies it chunk-wise so the [B, S, vocab] tensor never
    materializes)."""
    c = config
    x = params["embedding"][tokens].astype(c.dtype)
    cos, sin = rope_frequencies(c.head_dim, tokens.shape[1], c.rope_theta)

    block = functools.partial(_block, config=c, mesh=mesh)
    if c.remat:
        block = jax.checkpoint(block)

    def scan_body(carry, layer_params):
        x, aux_sum = carry
        x, _kv, aux = block(layer_params, x, cos, sin)
        return (x, aux_sum + aux), None

    (x, aux_sum), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32)), params["layers"])
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    if return_hidden:
        return (x, aux_sum) if return_aux else x
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    if return_aux:
        return logits, aux_sum
    return logits


def chunked_cross_entropy(hidden, lm_head, targets, mask=None, *,
                          chunk_tokens: int = 2048):
    """Token-mean NLL without materializing [B, S, vocab] logits.

    The output projection + log-softmax run per token-chunk inside a
    rematerialized scan: peak memory drops from O(B*S*V) fp32 (the
    dominant activation at train shapes — e.g. 4.2 GB at B16/S2048/
    V32k) to O(chunk*V), at the cost of recomputing each chunk's
    lm_head matmul in the backward pass (~one extra head forward,
    a few percent of model FLOPs). On TPU the freed HBM buys a larger
    batch, which is where the MFU is (reference analog: memory-
    efficient losses in large-vocab LM training; the reference itself
    has no in-tree model code).
    """
    dim = hidden.shape[-1]
    flat_h = hidden.reshape(-1, dim)
    flat_t = targets.reshape(-1)
    n = flat_h.shape[0]
    flat_m = (jnp.ones((n,), jnp.float32) if mask is None
              else mask.reshape(-1).astype(jnp.float32))
    chunk = min(chunk_tokens, n)
    pad = (-n) % chunk
    if pad:
        flat_h = jnp.pad(flat_h, ((0, pad), (0, 0)))
        flat_t = jnp.pad(flat_t, (0, pad))
        flat_m = jnp.pad(flat_m, (0, pad))  # padded tokens weigh 0
    n_chunks = flat_h.shape[0] // chunk

    def body(carry, inp):
        h_c, t_c, m_c = inp
        logits = (h_c @ lm_head).astype(jnp.float32)  # [chunk, V]
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t_c[:, None], axis=-1)[:, 0]
        return carry + jnp.sum((lse - tgt) * m_c), None

    total, _ = jax.lax.scan(
        jax.checkpoint(body), jnp.zeros((), jnp.float32),
        (flat_h.reshape(n_chunks, chunk, dim),
         flat_t.reshape(n_chunks, chunk),
         flat_m.reshape(n_chunks, chunk)))
    return total / jnp.maximum(jnp.sum(flat_m), 1.0)


def llama_loss(params, tokens, targets, config: LlamaConfig, mesh=None,
               mask=None):
    """Next-token cross-entropy (+ MoE load-balancing aux when MoE).
    ``config.ce_chunk_tokens`` switches to the chunked loss that never
    materializes the [B, S, vocab] logits."""
    if config.ce_chunk_tokens:
        hidden, aux = llama_forward(params, tokens, config, mesh,
                                    return_aux=True, return_hidden=True)
        loss = chunked_cross_entropy(
            hidden, params["lm_head"], targets, mask,
            chunk_tokens=config.ce_chunk_tokens)
    else:
        logits, aux = llama_forward(params, tokens, config, mesh,
                                    return_aux=True)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        if mask is None:
            loss = -jnp.mean(ll)
        else:
            loss = -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    if config.moe_experts:
        loss = loss + config.moe_aux_weight * aux / config.n_layers
    return loss


def llama_sharding_rules(mode: str = "fsdp_tp") -> ShardingRules:
    """Sharding rules for this parameter tree (leading axis = layers).

    Modes: ddp | fsdp | tp | fsdp_tp | ep — the JaxTrainer's DDP/FSDP/TP
    settings lower to these (reference analog:
    train/torch/train_loop_utils.py prepare_model wrapping DDP/FSDP;
    here it's a declarative mapping instead of a wrapper). MoE trees
    need no flag: ndim-constrained rule variants shard the 4-D
    expert-stacked FFN weights on D/H (never the expert axis).
    """
    def ffn(spec_in: P, spec_out: P):
        # 4-D variants for MoE expert-stacked weights [L, E, D, H]
        # (matched by ndim, so dense 3-D weights fall through).
        moe_in = P(None, None, *spec_in[1:])
        moe_out = P(None, None, *spec_out[1:])
        return [
            (r"layers/(w1|w3)", moe_in, 4),
            (r"layers/w2", moe_out, 4),
            (r"layers/(wq|wk|wv|w1|w3)", spec_in),
            (r"layers/(wo|w2)", spec_out),
        ]

    if mode == "ddp":
        return ShardingRules(rules=[(r".*", P())])
    if mode == "fsdp":
        return ShardingRules(rules=[
            (r"embedding", P("fsdp", None)),
            (r"lm_head", P(None, "fsdp")),
            *ffn(P(None, "fsdp", None), P(None, None, "fsdp")),
            (r".*", P()),
        ])
    if mode == "tp":
        return ShardingRules(rules=[
            (r"embedding", P(None, "model")),
            (r"lm_head", P(None, "model")),
            *ffn(P(None, None, "model"), P(None, "model", None)),
            (r".*", P()),
        ])
    if mode == "fsdp_tp":
        return ShardingRules(rules=[
            (r"embedding", P("fsdp", "model")),
            (r"lm_head", P(None, ("fsdp", "model"))),
            *ffn(P(None, "fsdp", "model"), P(None, "model", "fsdp")),
            (r".*", P()),
        ])
    if mode == "ep":
        # Expert parallelism: expert-stacked FFN weights [L, E, D, H]
        # partitioned on the "expert" mesh axis; GSPMD turns the MoE
        # dispatch/combine einsums into all-to-alls (parallel/moe.py).
        # Attention/router/embeddings replicate (compose with data axis
        # for the batch).
        return ShardingRules(rules=[
            (r"layers/(w1|w2|w3)", P(None, "expert", None, None)),
            (r".*", P()),
        ])
    raise ValueError(f"unknown sharding mode {mode}")


# ---------------------------------------------------------------------------
# Inference: KV-cache prefill + single-token decode
# (reference analog: the vLLM engine the reference wraps for serving,
# python/ray/llm/_internal/serve/engines/vllm/ — here the engine is
# in-tree and TPU-native: static-shape caches, jitted decode over the
# whole batch, continuous batching handled by ray_tpu.llm.engine)
# ---------------------------------------------------------------------------

def lora_init(rng, config: LlamaConfig, rank: int = 8,
              alpha: float = 16.0) -> Dict[str, Any]:
    """A LoRA adapter on the q/v projections (the classic placement).
    B starts at zero so a fresh adapter is the identity; ``alpha/rank``
    scaling is folded into B so inference needs no extra multiply."""
    c = config
    hd = c.head_dim
    kq, kv = jax.random.split(rng)
    scale = alpha / rank

    def a(key):
        # A maps dim -> rank regardless of the projection's output size
        return (jax.random.normal(key, (c.n_layers, c.dim, rank),
                                  dtype=jnp.float32)
                * (c.dim ** -0.5)).astype(c.dtype)

    return {
        "A_q": a(kq),
        "B_q": jnp.zeros((c.n_layers, rank, c.n_heads * hd), c.dtype),
        "A_v": a(kv),
        "B_v": jnp.zeros((c.n_layers, rank, c.n_kv_heads * hd), c.dtype),
        "scale": jnp.asarray(scale, c.dtype),
    }


def _lora_delta(h, a, b):
    """h @ A @ B with a possibly per-slot-gathered A/B.
    h: [B, S, D]; a: [D, r] or [B, D, r]; b: [r, H] or [B, r, H]."""
    if a.ndim == 2:
        return (h @ a) @ b
    t = jnp.einsum("bsd,bdr->bsr", h, a)
    return jnp.einsum("bsr,brh->bsh", t, b)


def llama_init_cache(config: LlamaConfig, batch: int, max_seq: int):
    """KV cache pair, each [L, B, S, KVH, HD] in the model dtype."""
    c = config
    shape = (c.n_layers, batch, max_seq, c.n_kv_heads, c.head_dim)
    return jnp.zeros(shape, c.dtype), jnp.zeros(shape, c.dtype)


def llama_prefill(params, tokens, config: LlamaConfig, lora=None):
    """Forward over a padded prompt, keeping per-layer K/V.

    tokens: [B, S] int32 -> (logits [B, S, vocab] f32,
    k [L, B, S, KVH, HD], v [L, B, S, KVH, HD]). Positions are arange;
    junk K/V at padding positions is never attended later because decode
    masks by true position. ``lora``: optional adapter pytree
    (lora_init) applied to the whole (batch-1) prefill — per-request
    multi-LoRA happens at decode via the bank path.
    """
    c = config
    hd = c.head_dim
    b, s = tokens.shape
    x = params["embedding"][tokens].astype(c.dtype)
    cos, sin = rope_frequencies(hd, s, c.rope_theta)

    if lora is None:
        def body(x, layer_params):
            x, kv, _aux = _block(layer_params, x, cos, sin, c, None)
            return x, kv

        x, (ks, vs) = jax.lax.scan(body, x, params["layers"])
    else:
        def body(x, layer):
            layer_params, a_q, b_q, a_v, b_v = layer
            x, kv, _aux = _block(
                layer_params, x, cos, sin, c, None,
                lora=(a_q, b_q, a_v, b_v, lora["scale"]))
            return x, kv

        x, (ks, vs) = jax.lax.scan(
            body, x, (params["layers"], lora["A_q"], lora["B_q"],
                      lora["A_v"], lora["B_v"]))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, ks, vs


def llama_decode_step(params, token, cache_k, cache_v, pos,
                      config: LlamaConfig, lora_bank=None, lora_idx=None):
    """One token for every sequence in the batch.

    token: [B] int32 (the token at position `pos`); pos: [B] int32;
    cache_k/v: [L, B, S, KVH, HD]. Returns (logits [B, vocab] f32,
    cache_k, cache_v) with the new K/V written at `pos`.

    Multi-LoRA: ``lora_bank`` stacks adapters on a leading axis
    ({A_q: [N, L, D, r], ...}; index 0 all-zero = no adapter) and
    ``lora_idx`` [B] picks one per slot — the vLLM-style batched-gather
    design, TPU-friendly because N and r are static and tiny.
    """
    c = config
    n_layers, b, s, kvh, hd = cache_k.shape
    n_rep = c.n_heads // c.n_kv_heads
    x = params["embedding"][token][:, None, :].astype(c.dtype)  # [B,1,D]
    cos, sin = rope_frequencies(hd, s, c.rope_theta)
    pos_2d = pos[:, None]                                       # [B,1]
    # causal visibility: this token may attend to cache slots <= pos
    visible = jnp.arange(s)[None, :] <= pos_2d                  # [B,S]
    if lora_bank is not None:
        # [N, L, ...] -> [L, N, ...] so the layer scan consumes them
        bank = {k2: jnp.swapaxes(v2, 0, 1)
                for k2, v2 in lora_bank.items() if k2 != "scale"}
        lora_scale = lora_bank["scale"]

    def body(x, layer):
        if lora_bank is not None:
            layer_params, ck, cv, a_q, b_q, a_v, b_v = layer
        else:
            layer_params, ck, cv = layer                        # ck [B,S,KVH,HD]
        h = rms_norm(x, layer_params["attn_norm"], c.norm_eps)
        q = (h @ layer_params["wq"]).reshape(b, 1, c.n_heads, hd)
        k = (h @ layer_params["wk"]).reshape(b, 1, kvh, hd)
        v = (h @ layer_params["wv"]).reshape(b, 1, kvh, hd)
        if lora_bank is not None:
            dq = _lora_delta(h, a_q[lora_idx], b_q[lora_idx])
            dv = _lora_delta(h, a_v[lora_idx], b_v[lora_idx])
            q = q + (lora_scale * dq).reshape(b, 1, c.n_heads, hd)
            v = v + (lora_scale * dv).reshape(b, 1, kvh, hd)
        q = apply_rope(q, cos, sin, positions=pos_2d)
        k = apply_rope(k, cos, sin, positions=pos_2d)
        write = jax.vmap(
            lambda cache, new, p: jax.lax.dynamic_update_slice(
                cache, new, (p, 0, 0)))
        ck = write(ck, k, pos)
        cv = write(cv, v, pos)
        kk = jnp.repeat(ck, n_rep, axis=2) if n_rep > 1 else ck
        vv = jnp.repeat(cv, n_rep, axis=2) if n_rep > 1 else cv
        scores = jnp.einsum("bqhd,bshd->bhqs", q, kk).astype(jnp.float32)
        scores = scores * (hd ** -0.5)
        scores = jnp.where(visible[:, None, None, :], scores, -1e30)
        weights = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
        attn = jnp.einsum("bhqs,bshd->bqhd", weights, vv)
        x = x + attn.reshape(b, 1, c.n_heads * hd) @ layer_params["wo"]
        h = rms_norm(x, layer_params["mlp_norm"], c.norm_eps)
        y, _aux = _ffn(layer_params, h, c)  # MoE-aware (decode too)
        return x + y, (ck, cv)

    if lora_bank is not None:
        xs = (params["layers"], cache_k, cache_v,
              bank["A_q"], bank["B_q"], bank["A_v"], bank["B_v"])
    else:
        xs = (params["layers"], cache_k, cache_v)
    x, (new_k, new_v) = jax.lax.scan(body, x, xs)
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    logits = (x[:, 0] @ params["lm_head"]).astype(jnp.float32)
    return logits, new_k, new_v


def llama_verify_step(params, tokens, cache_k, cache_v, pos,
                      config: LlamaConfig):
    """Score a G-token speculative chunk in ONE target forward.

    tokens: [B, G] int32 — the current token followed by G-1 draft
    proposals; pos: [B] int32 chunk start positions; cache_k/v:
    [L, B, S, KVH, HD]. Returns (logits [B, G, vocab] f32, cache_k,
    cache_v) with the chunk's K/V written at pos..pos+G-1 per slot.
    logits[:, g] is the target's distribution for the token AFTER
    chunk input g — the verifier for draft g+1 (speculative decoding,
    Leviathan et al. 2023; reference analog: vLLM's spec-decode
    scorer). G is static, so XLA sees one fixed-shape program per
    chunk width.
    """
    c = config
    n_layers, b, s, kvh, hd = cache_k.shape
    g = tokens.shape[1]
    n_rep = c.n_heads // c.n_kv_heads
    x = params["embedding"][tokens].astype(c.dtype)           # [B,G,D]
    cos, sin = rope_frequencies(hd, s, c.rope_theta)
    positions = pos[:, None] + jnp.arange(g)[None, :]         # [B,G]
    # chunk position i attends cache slot t iff t <= pos+i (the write
    # below lands the chunk's own K/V inside that window)
    visible = (jnp.arange(s)[None, None, :]
               <= positions[:, :, None])                      # [B,G,S]

    def body(x, layer):
        layer_params, ck, cv = layer                # ck [B,S,KVH,HD]
        h = rms_norm(x, layer_params["attn_norm"], c.norm_eps)
        q = (h @ layer_params["wq"]).reshape(b, g, c.n_heads, hd)
        k = (h @ layer_params["wk"]).reshape(b, g, kvh, hd)
        v = (h @ layer_params["wv"]).reshape(b, g, kvh, hd)
        q = apply_rope(q, cos, sin, positions=positions)
        k = apply_rope(k, cos, sin, positions=positions)
        write = jax.vmap(
            lambda cache, new, p: jax.lax.dynamic_update_slice(
                cache, new, (p, 0, 0)))
        ck = write(ck, k, pos)
        cv = write(cv, v, pos)
        kk = jnp.repeat(ck, n_rep, axis=2) if n_rep > 1 else ck
        vv = jnp.repeat(cv, n_rep, axis=2) if n_rep > 1 else cv
        scores = jnp.einsum("bghd,bshd->bhgs", q, kk).astype(jnp.float32)
        scores = scores * (hd ** -0.5)
        scores = jnp.where(visible[:, None, :, :], scores, -1e30)
        weights = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
        attn = jnp.einsum("bhgs,bshd->bghd", weights, vv)
        x = x + attn.reshape(b, g, c.n_heads * hd) @ layer_params["wo"]
        h = rms_norm(x, layer_params["mlp_norm"], c.norm_eps)
        y, _aux = _ffn(layer_params, h, c)
        return x + y, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache_k, cache_v))
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, new_k, new_v
