"""Checkpoints: directories of files, with sharded-pytree save/restore.

reference: python/ray/train/_checkpoint.py (Checkpoint = directory on an
fsspec filesystem) + SURVEY.md §5.4 — the TPU equivalent of torch
checkpointing is orbax-style sharded array checkpointing; restore placing
shards directly on their target devices (no host round-trip of the full
tree).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional


class Checkpoint:
    """A directory of checkpoint data."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @staticmethod
    def from_directory(path: str) -> "Checkpoint":
        return Checkpoint(path)

    @contextmanager
    def as_directory(self):
        yield self.path

    def to_directory(self, dest: Optional[str] = None) -> str:
        if dest is None:
            dest = tempfile.mkdtemp(prefix="rtpu_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


def save_pytree(tree: Any, directory: str, name: str = "state") -> None:
    """Save a (possibly sharded) jax pytree via orbax; host arrays fall
    back to pickle. Multi-host: every process participates (orbax
    coordinates)."""
    os.makedirs(directory, exist_ok=True)
    try:
        import jax
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        target = os.path.join(directory, name)
        if os.path.exists(target):
            shutil.rmtree(target)
        ckptr.save(target, tree)
    except Exception:
        with open(os.path.join(directory, name + ".pkl"), "wb") as f:
            pickle.dump(tree, f)


def load_pytree(directory: str, name: str = "state",
                target: Any = None) -> Any:
    """Restore a pytree; with ``target`` (a pytree of ShapeDtypeStruct or
    arrays with shardings) orbax restores shards onto devices directly."""
    pkl = os.path.join(directory, name + ".pkl")
    if os.path.exists(pkl):
        with open(pkl, "rb") as f:
            return pickle.load(f)
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    path = os.path.join(directory, name)
    if target is not None:
        try:
            return ckptr.restore(path, item=target)
        except TypeError:
            return ckptr.restore(path)
    return ckptr.restore(path)


def save_sharded_state(directory: str, rank: int, world_size: int,
                       state: Any, *, step: int = 0,
                       background: bool = False, keep: int = 2):
    """Per-rank sharded checkpoint write (reference: orbax async
    multi-host checkpointing + SURVEY §5.4). Every rank writes only its
    own shard into a per-step subdirectory, so a crash mid-save can
    never produce a torn cross-rank checkpoint — load falls back to the
    newest step with a complete shard set. ``background=True`` returns
    a started ``threading.Thread``; the caller overlaps the write with
    compute and joins before the next save (async checkpointing).
    Rank 0 prunes steps older than the newest ``keep``.
    """
    step_dir = os.path.join(directory, f"step_{step:010d}")
    os.makedirs(step_dir, exist_ok=True)
    if rank == 0:
        meta_path = os.path.join(step_dir, "sharded_meta.json")
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"world_size": world_size, "step": step}, f)
        os.replace(tmp, meta_path)

    def write():
        # world size in the FILENAME: a zombie rank from a killed gang
        # (kill delivery lags under load) writing its old-world shard
        # into the same step dir must never satisfy the new gang's
        # completeness check — caught live by
        # tests/test_train_failures.py resize-up under full-suite load
        final = os.path.join(step_dir,
                             f"shard_{rank:05d}_of_{world_size:05d}.pkl")
        tmp = final + f".tmp{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(state, f)
        except FileNotFoundError:
            # rank 0's prune raced this lagging rank's write: recreate
            # the step dir and retry once (the age guard below makes
            # this window small)
            os.makedirs(step_dir, exist_ok=True)
            with open(tmp, "wb") as f:
                pickle.dump(state, f)
        os.replace(tmp, final)
        if rank == 0 and keep:
            steps = sorted(d for d in os.listdir(directory)
                           if d.startswith("step_"))
            now = time.time()
            for old in steps[:-keep]:
                path = os.path.join(directory, old)
                try:
                    if now - os.path.getmtime(path) < 30.0:
                        continue  # a lagging rank may still be writing
                except OSError:
                    continue
                shutil.rmtree(path, ignore_errors=True)

    if background:
        import threading
        thread = threading.Thread(target=write, daemon=True)
        thread.start()
        return thread
    write()
    return None


def _complete_shard_set(step_dir: str) -> Optional[list]:
    meta_path = os.path.join(step_dir, "sharded_meta.json")
    if not os.path.exists(meta_path):
        return None
    with open(meta_path) as f:
        world_size = json.load(f)["world_size"]
    paths = [os.path.join(step_dir,
                          f"shard_{r:05d}_of_{world_size:05d}.pkl")
             for r in range(world_size)]
    if not all(os.path.exists(p) for p in paths):
        # pre-world-qualified layout (shard_NNNNN.pkl): loadable, else
        # an upgrade would silently resume every older run from step 0
        paths = [os.path.join(step_dir, f"shard_{r:05d}.pkl")
                 for r in range(world_size)]
    if not all(os.path.exists(p) for p in paths):
        return None
    out = []
    for path in paths:
        with open(path, "rb") as f:
            out.append(pickle.load(f))
    return out


def load_sharded_state(directory: str,
                       timeout: float = 5.0) -> Optional[list]:
    """Restore [state_rank0, state_rank1, ...] from the NEWEST step
    whose shard set is complete (older complete steps shadow torn
    newer ones). The caller re-shards for its current world size —
    resuming 4-way state on a 3-worker gang re-partitions via
    ``reshard_states``, not orbax."""
    deadline = time.time() + timeout
    while True:
        if os.path.isdir(directory):
            steps = sorted((d for d in os.listdir(directory)
                            if d.startswith("step_")), reverse=True)
            for step_name in steps:
                states = _complete_shard_set(
                    os.path.join(directory, step_name))
                if states is not None:
                    return states
            if not steps:
                return None  # nothing ever saved here
        else:
            return None
        if time.time() > deadline:
            return None
        time.sleep(0.05)


def reshard_states(states: list, new_world_size: int,
                   concat=None, split=None) -> list:
    """Re-partition per-rank states for a different gang size.

    Default treats each state as a pytree of numpy/jax arrays sharded on
    axis 0: shards are concatenated and re-split as evenly as possible.
    Custom ``concat``/``split`` hooks override for other layouts."""
    import numpy as np

    if len(states) == new_world_size:
        return list(states)
    if concat is None:
        def concat(shards):
            import jax
            return jax.tree.map(
                lambda *xs: np.concatenate([np.asarray(x) for x in xs],
                                           axis=0), *shards)
    if split is None:
        def split(full, n):
            import jax
            outs = []
            for i in range(n):
                outs.append(jax.tree.map(
                    lambda x: np.array_split(np.asarray(x), n)[i], full))
            return outs
    return split(concat(states), new_world_size)


class CheckpointManager:
    """Tracks latest/best checkpoints under the run's storage path.

    reference: train/v2/_internal/execution/checkpoint/checkpoint_manager.py
    """

    def __init__(self, storage_path: str, num_to_keep: Optional[int] = None):
        self.storage_path = storage_path
        self.num_to_keep = num_to_keep
        self._index = 0
        self._checkpoints: list = []  # (path, metrics)
        os.makedirs(storage_path, exist_ok=True)

    def register(self, source_dir: str, metrics: Dict[str, Any]) -> Checkpoint:
        if os.path.abspath(source_dir).startswith(
                os.path.abspath(self.storage_path)):
            # Already persisted at report() time — record, don't re-copy.
            self._checkpoints.append((os.path.abspath(source_dir), metrics))
            if self.num_to_keep and len(self._checkpoints) > self.num_to_keep:
                old, _ = self._checkpoints.pop(0)
                shutil.rmtree(old, ignore_errors=True)
            return Checkpoint(source_dir)
        self._index += 1
        dest = os.path.join(self.storage_path,
                            f"checkpoint_{self._index:06d}")
        shutil.copytree(source_dir, dest, dirs_exist_ok=True)
        with open(os.path.join(dest, ".metrics.json"), "w") as f:
            json.dump(_jsonable(metrics), f)
        self._checkpoints.append((dest, metrics))
        if self.num_to_keep and len(self._checkpoints) > self.num_to_keep:
            old, _ = self._checkpoints.pop(0)
            shutil.rmtree(old, ignore_errors=True)
        return Checkpoint(dest)

    def latest(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return self._find_on_disk()
        return Checkpoint(self._checkpoints[-1][0])

    def _find_on_disk(self) -> Optional[Checkpoint]:
        """Resume discovery after a controller restart."""
        if not os.path.isdir(self.storage_path):
            return None
        found = sorted(
            d for d in os.listdir(self.storage_path)
            if d.startswith("checkpoint_"))
        if not found:
            return None
        return Checkpoint(os.path.join(self.storage_path, found[-1]))


def _jsonable(metrics: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for key, value in metrics.items():
        try:
            json.dumps(value)
            out[key] = value
        except (TypeError, ValueError):
            out[key] = float(value) if hasattr(value, "__float__") else str(value)
    return out
