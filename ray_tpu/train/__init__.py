"""Distributed training (Train-v2 equivalent).

reference: python/ray/train/v2 — DataParallelTrainer
(api/data_parallel_trainer.py:152), TrainController state machine
(_internal/execution/controller/controller.py:100), JAX backend
(v2/jax/jax_trainer.py:19), report/get_checkpoint train-fn utils
(api/train_fn_utils.py)."""

from ray_tpu.train.checkpoint import (
    Checkpoint,
    load_pytree,
    load_sharded_state,
    reshard_states,
    save_pytree,
    save_sharded_state,
)
from ray_tpu.train.config import (
    CheckpointConfig,
    ElasticScalingPolicy,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
    ScalingPolicy,
)
from ray_tpu.train.context import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.trainer import JaxTrainer

# DataParallelTrainer is the generic name in the reference; JaxTrainer is
# the (only) backend here — alias for API familiarity.
DataParallelTrainer = JaxTrainer
