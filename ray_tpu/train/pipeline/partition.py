"""Stage partitioner: layered model → N contiguous stage slices.

The model contract mirrors the transformer-block shape used across
``ray_tpu.parallel``: a list of per-layer parameter pytrees plus one
``apply_layer(layer_params, x) -> y`` with x/y of matching leading
batch dim, and a ``loss_fn(output, target) -> scalar`` evaluated only
by the last stage. Shape-changing embed/unembed layers are just layers
here — contiguity keeps activations a single tensor per boundary.

Stages are contiguous layer ranges balanced by *parameter count* (not
layer count): with heterogeneous layers, equal-layer splits leave the
fattest stage as the pipeline's critical path. The partitioner
minimizes the maximum stage parameter count over contiguous splits via
the classic linear-partition DP — exact, and at pipeline scale
(layers ≤ a few hundred, stages ≤ tens) effectively free.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple


@dataclass
class LayeredModel:
    """Driver-side model description handed to the partitioner.

    ``layer_params``: one parameter pytree per layer (picklable —
    numpy / jax arrays both fine); ``apply_layer``: pure fn applied by
    every stage; ``loss_fn``: applied by the last stage only.
    """

    layer_params: List[Any]
    apply_layer: Callable[[Any, Any], Any]
    loss_fn: Callable[[Any, Any], Any]

    @property
    def num_layers(self) -> int:
        return len(self.layer_params)


@dataclass
class StagePlan:
    """One stage's share of the model: contiguous ``[start, stop)``
    layer range plus the parameter pytrees for those layers."""

    stage_id: int
    num_stages: int
    start: int
    stop: int
    layer_params: List[Any] = field(default_factory=list)

    @property
    def is_first(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last(self) -> bool:
        return self.stage_id == self.num_stages - 1


def _leaf_count(tree: Any) -> int:
    import jax
    import numpy as np
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(np.asarray(leaf).size)
    return total


def balanced_ranges(weights: List[int],
                    num_stages: int) -> List[Tuple[int, int]]:
    """Contiguous split of ``weights`` into ``num_stages`` ranges
    minimizing the maximum range sum (linear-partition DP). Every
    range is non-empty; requires ``len(weights) >= num_stages``."""
    n = len(weights)
    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    if n < num_stages:
        raise ValueError(
            f"cannot split {n} layers into {num_stages} non-empty "
            "stages")
    prefix = [0] * (n + 1)
    for i, w in enumerate(weights):
        prefix[i + 1] = prefix[i] + w

    def range_sum(i: int, j: int) -> int:
        return prefix[j] - prefix[i]

    INF = float("inf")
    # cost[k][j]: best max-sum splitting weights[:j] into k ranges
    cost = [[INF] * (n + 1) for _ in range(num_stages + 1)]
    split = [[0] * (n + 1) for _ in range(num_stages + 1)]
    cost[0][0] = 0
    for k in range(1, num_stages + 1):
        for j in range(k, n + 1):
            for i in range(k - 1, j):
                c = max(cost[k - 1][i], range_sum(i, j))
                if c < cost[k][j]:
                    cost[k][j] = c
                    split[k][j] = i
    # walk back the split points
    bounds = [n]
    j = n
    for k in range(num_stages, 0, -1):
        j = split[k][j]
        bounds.append(j)
    bounds.reverse()
    return [(bounds[i], bounds[i + 1]) for i in range(num_stages)]


def partition_model(model: LayeredModel, num_stages: int,
                    weights: Optional[List[int]] = None
                    ) -> List[StagePlan]:
    """Split ``model`` into ``num_stages`` contiguous StagePlans,
    balanced by per-layer parameter count (override with explicit
    ``weights``, e.g. measured per-layer step times)."""
    if weights is None:
        weights = [_leaf_count(p) for p in model.layer_params]
    if len(weights) != model.num_layers:
        raise ValueError(
            f"{len(weights)} weights for {model.num_layers} layers")
    ranges = balanced_ranges(weights, num_stages)
    return [
        StagePlan(stage_id=i, num_stages=num_stages, start=start,
                  stop=stop,
                  layer_params=model.layer_params[start:stop])
        for i, (start, stop) in enumerate(ranges)
    ]


def stitch_params(plans_params: List[List[Any]]) -> List[Any]:
    """Inverse of partitioning: per-stage layer lists → the flat
    per-layer list, for parity checks against a reference model."""
    out: List[Any] = []
    for stage_layers in plans_params:
        out.extend(stage_layers)
    return out
