"""Static microbatch schedules for MPMD pipeline stages.

Each stage executes a fixed instruction list per optimizer step —
compiled once, replayed every step by the stage actor inside the
compiled-DAG loop. Two schedules (arXiv 2412.14374 §3):

- ``"1f1b"`` — stage ``s`` of ``S`` runs ``min(S - s, M)`` warmup
  forwards, then alternates one-backward-one-forward in steady state,
  then drains the remaining backwards. In-flight activations per stage
  are bounded by the warmup depth (≤ S), independent of M.
- ``"gpipe"`` — fill-drain: all M forwards, then all M backwards.
  Simpler, but all M activations are live at the fill/drain boundary,
  so a bounded activation channel (capacity < M) stalls the upstream
  stage — the measured bubble exceeds 1F1B's on the same config.

Both end with one ``STEP`` (gradient apply). The theoretical bubble
fraction for either fill-drain schedule is ``(S-1) / (M + S - 1)``;
the executor additionally *measures* bubble as 1 - compute/wall per
stage, which is where the schedules separate under finite channel
capacity.

Instruction ops (the DAG-loop ISA of the issue):

- ``FWD k``  — run this stage's forward on microbatch ``k``
- ``BWD k``  — run this stage's backward on microbatch ``k``
- ``RECV k`` — block on the upstream/downstream channel (``kind`` says
  whether an activation or a gradient arrives)
- ``SEND k`` — write to the adjacent channel (``kind`` as above)
- ``STEP``   — apply the accumulated gradient

Pure Python, no jax/actors: golden tests and the devtools.check smoke
step consume this module directly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ray_tpu.devtools import collsan

SCHEDULES = ("1f1b", "gpipe")

# instruction ops
FWD = "FWD"
BWD = "BWD"
RECV = "RECV"
SEND = "SEND"
STEP = "STEP"

# what a RECV/SEND carries
ACT = "act"    # forward activation, flowing stage s -> s+1
GRAD = "grad"  # backward gradient, flowing stage s+1 -> s


@dataclass(frozen=True)
class Instruction:
    op: str          # FWD | BWD | RECV | SEND | STEP
    mb: int = -1     # microbatch id (-1 for STEP)
    kind: str = ""   # "act" | "grad" for RECV/SEND, else ""
    phase: str = ""  # warmup | steady | drain | step

    def __repr__(self) -> str:  # compact golden-test form
        if self.op == STEP:
            return "STEP"
        if self.op in (RECV, SEND):
            return f"{self.op}({self.kind},{self.mb})"
        return f"{self.op}({self.mb})"


def bubble_fraction(num_stages: int, num_microbatches: int,
                    schedule: str = "1f1b") -> float:
    """Theoretical pipeline-bubble fraction: idle ticks / total ticks.

    With uniform per-microbatch stage time t for fwd and bwd, a step
    spans (M + S - 1) fwd ticks + (M + S - 1) bwd ticks of which each
    stage computes 2M — bubble = (S-1)/(M+S-1) for both fill-drain
    schedules (1F1B's win over GPipe is activation memory and, under
    bounded channels, the absence of fill-phase backpressure stalls).
    """
    _check_args(num_stages, num_microbatches, schedule)
    s, m = num_stages, num_microbatches
    return (s - 1) / (m + s - 1)


def _check_args(num_stages: int, num_microbatches: int,
                schedule: str) -> None:
    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    if num_microbatches < 1:
        raise ValueError(
            f"num_microbatches must be >= 1, got {num_microbatches}")
    if schedule not in SCHEDULES:
        raise ValueError(
            f"unknown schedule {schedule!r}; expected one of {SCHEDULES}")


def warmup_depth(stage: int, num_stages: int,
                 num_microbatches: int) -> int:
    """1F1B warmup forwards for ``stage`` (0-indexed): ``S - stage``
    capped at M — the last stage runs exactly one forward before its
    first backward; stage 0 fills the whole pipeline."""
    return min(num_stages - stage, num_microbatches)


def _fwd_block(stage: int, num_stages: int, k: int,
               phase: str) -> List[Instruction]:
    out = []
    if stage > 0:
        out.append(Instruction(RECV, k, ACT, phase))
    out.append(Instruction(FWD, k, "", phase))
    if stage < num_stages - 1:
        out.append(Instruction(SEND, k, ACT, phase))
    return out


def _bwd_block(stage: int, num_stages: int, k: int,
               phase: str) -> List[Instruction]:
    out = []
    if stage < num_stages - 1:
        out.append(Instruction(RECV, k, GRAD, phase))
    out.append(Instruction(BWD, k, "", phase))
    if stage > 0:
        out.append(Instruction(SEND, k, GRAD, phase))
    return out


def stage_schedule(stage: int, num_stages: int, num_microbatches: int,
                   schedule: str = "1f1b") -> List[Instruction]:
    """The static instruction list stage ``stage`` replays every step."""
    _check_args(num_stages, num_microbatches, schedule)
    if not 0 <= stage < num_stages:
        raise ValueError(
            f"stage {stage} out of range for {num_stages} stages")
    s, m = num_stages, num_microbatches
    instrs: List[Instruction] = []
    if schedule == "gpipe":
        for k in range(m):
            instrs += _fwd_block(stage, s, k, "warmup")
        for k in range(m):
            instrs += _bwd_block(stage, s, k, "drain")
    else:  # 1f1b
        warm = warmup_depth(stage, s, m)
        for k in range(warm):
            instrs += _fwd_block(stage, s, k, "warmup")
        # steady state: BWD (k - warm) then FWD k, keeping exactly
        # ``warm`` microbatches in flight on this stage
        for k in range(warm, m):
            instrs += _bwd_block(stage, s, k - warm, "steady")
            instrs += _fwd_block(stage, s, k, "steady")
        for k in range(m - warm, m):
            instrs += _bwd_block(stage, s, k, "drain")
    instrs.append(Instruction(STEP, -1, "", "step"))
    return instrs


def build_schedule(num_stages: int, num_microbatches: int,
                   schedule: str = "1f1b") -> List[List[Instruction]]:
    """Instruction lists for every stage, index = stage id."""
    return [stage_schedule(s, num_stages, num_microbatches, schedule)
            for s in range(num_stages)]


def max_in_flight(instrs: List[Instruction]) -> int:
    """Peak number of microbatches with a live forward (FWD seen, BWD
    not yet) — the stage's activation-memory high-water mark."""
    live = 0
    peak = 0
    for ins in instrs:
        if ins.op == FWD:
            live += 1
            peak = max(peak, live)
        elif ins.op == BWD:
            live -= 1
    return peak


def validate_schedule(num_stages: int, num_microbatches: int,
                      schedule: str = "1f1b") -> None:
    """Structural invariants, used by golden tests and the
    ``devtools.check`` pipeline smoke step. Raises AssertionError with
    the violated property."""
    per_stage = build_schedule(num_stages, num_microbatches, schedule)
    s, m = num_stages, num_microbatches
    for stage, instrs in enumerate(per_stage):
        fwds = [i.mb for i in instrs if i.op == FWD]
        bwds = [i.mb for i in instrs if i.op == BWD]
        assert fwds == list(range(m)), \
            f"stage {stage}: forwards {fwds} != 0..{m - 1} in order"
        assert bwds == list(range(m)), \
            f"stage {stage}: backwards {bwds} != 0..{m - 1} in order"
        assert instrs[-1].op == STEP, f"stage {stage}: missing STEP"
        assert sum(1 for i in instrs if i.op == STEP) == 1, \
            f"stage {stage}: more than one STEP"
        # every FWD on mb k precedes its BWD on mb k
        for k in range(m):
            fi = next(n for n, i in enumerate(instrs)
                      if i.op == FWD and i.mb == k)
            bi = next(n for n, i in enumerate(instrs)
                      if i.op == BWD and i.mb == k)
            assert fi < bi, f"stage {stage}: BWD {k} before FWD {k}"
        if schedule == "1f1b":
            warm = warmup_depth(stage, s, m)
            # warmup depth: first `warm` compute ops are forwards
            compute = [i for i in instrs if i.op in (FWD, BWD)]
            head = [i.op for i in compute[:warm]]
            assert head == [FWD] * warm, \
                (f"stage {stage}: warmup depth {warm} violated "
                 f"(head={head})")
            # steady state: strict BWD/FWD alternation until the drain
            steady = [i.op for i in compute[warm:warm + 2 * (m - warm)]]
            assert steady == [BWD, FWD] * (m - warm), \
                f"stage {stage}: steady-state alternation violated"
            # drain: the rest are backwards
            tail = [i.op for i in compute[warm + 2 * (m - warm):]]
            assert tail == [BWD] * warm, \
                f"stage {stage}: drain should be {warm} BWDs, got {tail}"
            assert max_in_flight(instrs) == warm, \
                (f"stage {stage}: in-flight {max_in_flight(instrs)} != "
                 f"warmup depth {warm}")
    # channel-order invariant: the SEND sequence on every edge matches
    # the RECV sequence of its peer (channels are FIFO per edge).
    # Checked through collsan's pure program checker — the same
    # contract the resharding planner emits into.
    violations = collsan.verify_program(
        schedule_program(per_stage), world=s)
    assert not violations, "; ".join(violations)


def schedule_program(per_stage: List[List[Instruction]]):
    """Lower a built schedule into the ``collsan.verify_program``
    op-list form: SEND/RECV become p2p ops on a per-edge FIFO channel
    (``"act 0->1"``, ``"grad 1->0"``) keyed by microbatch id; compute
    ops carry no cross-rank contract and are omitted."""
    program = {}
    for stage, instrs in enumerate(per_stage):
        ops = []
        for ins in instrs:
            if ins.op not in (SEND, RECV):
                continue
            if ins.kind == ACT:
                src = stage if ins.op == SEND else stage - 1
                chan = f"act {src}->{src + 1}"
            else:
                src = stage if ins.op == SEND else stage + 1
                chan = f"grad {src}->{src - 1}"
            ops.append({"op": ins.op.lower(), "chan": chan,
                        "key": ins.mb})
        program[stage] = ops
    return program
