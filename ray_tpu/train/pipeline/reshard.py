"""Stage-boundary activation resharding.

When adjacent pipeline stages run different intra-stage sharding
(stage i holds activations split over ``src_parts`` ranks, stage i+1
expects ``dst_parts``), the boundary transfer must redistribute the
batch dimension. Following "Memory-efficient array redistribution
through portable collective communication" (arXiv 2112.01075), the
redistribution is expressed over the portable host collectives in
``parallel/collective.py`` — all-gather to materialize the boundary
tensor, then slice this rank's destination shard — rather than a
bespoke point-to-point exchange. (The all-gather→slice pair is the
always-correct baseline of the paper's search space; with equal part
counts it degenerates to the identity and is skipped entirely.)

Two paths share the slicing math:

- **collective**: inside a collective group (``group_name`` set), ring
  all-gather the flat activation over the group, reassemble, slice.
- **local**: given the full list of source shards (single-process
  tests, or a stage actor that already holds them), pure numpy.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def _dst_bounds(total: int, dst_parts: int) -> List[int]:
    """Batch-dim split points for the destination sharding; matches
    collective._chunk_bounds semantics (remainder spread over the
    first ranks)."""
    base, rem = divmod(total, dst_parts)
    bounds = [0]
    for r in range(dst_parts):
        bounds.append(bounds[-1] + base + (1 if r < rem else 0))
    return bounds


def reshard_slice(full: np.ndarray, dst_rank: int,
                  dst_parts: int) -> np.ndarray:
    """``dst_rank``'s shard of the assembled boundary tensor (batch
    dim 0)."""
    bounds = _dst_bounds(full.shape[0], dst_parts)
    return full[bounds[dst_rank]:bounds[dst_rank + 1]]


def reshard_boundary(shard: np.ndarray, *, src_parts: int,
                     dst_parts: int, dst_rank: int,
                     group_name: Optional[str] = None,
                     all_shards: Optional[Sequence[np.ndarray]] = None
                     ) -> np.ndarray:
    """Redistribute a batch-sharded activation across the boundary.

    ``shard``: this rank's piece under the source sharding (batch dim
    0). With ``src_parts == dst_parts`` the boundary shardings agree
    and the input is returned untouched (the degenerate identity). The
    collective path rides ``allgather_flat`` over ``group_name``; the
    local path assembles ``all_shards`` directly.
    """
    if src_parts < 1 or dst_parts < 1:
        raise ValueError(
            f"part counts must be >= 1 (src={src_parts}, "
            f"dst={dst_parts})")
    if not 0 <= dst_rank < dst_parts:
        raise ValueError(
            f"dst_rank {dst_rank} out of range for {dst_parts} parts")
    shard = np.asarray(shard)
    if src_parts == dst_parts:
        return shard
    if all_shards is not None:
        full = np.concatenate([np.asarray(s) for s in all_shards],
                              axis=0)
        return reshard_slice(full, dst_rank, dst_parts)
    if group_name is None:
        raise ValueError(
            "resharding across unequal part counts needs either a "
            "collective group_name or the explicit all_shards list")
    from ray_tpu.parallel import collective
    # All-gather the flat payload over the stage group; shards may be
    # unevenly sized (remainder batches), and allgather_flat
    # concatenates in rank order, which is exactly batch order here.
    flat = shard.astype(np.float32, copy=False).ravel()
    full_flat = collective.allgather_flat(flat, group_name=group_name)
    per_item = int(np.prod(shard.shape[1:], dtype=np.int64)) or 1
    total_batch = full_flat.size // per_item
    full = np.asarray(full_flat, dtype=np.float32).reshape(
        (total_batch,) + tuple(shard.shape[1:]))
    return reshard_slice(full, dst_rank, dst_parts).astype(
        shard.dtype, copy=False)
