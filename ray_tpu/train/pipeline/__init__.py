"""MPMD pipeline-parallel training over the compiled DAG.

Grounding: "Scaling Deep Learning Training with MPMD Pipeline
Parallelism" (arXiv 2412.14374) for the stage/schedule split, and
"Memory-efficient array redistribution through portable collective
communication" (arXiv 2112.01075) for the stage-boundary reshard.

The subsystem has four layers:

- :mod:`schedule` — static per-stage instruction lists (1F1B and the
  GPipe fill-drain fallback) plus the bubble-fraction math; pure
  Python, golden-testable without actors.
- :mod:`partition` — splits a layered model into contiguous stage
  slices balanced by parameter count, and builds each stage's
  fwd/bwd closures.
- :mod:`reshard` — the boundary all-gather→slice used when adjacent
  stages disagree on intra-stage sharding, expressed over the host
  collective primitives.
- :mod:`executor` — stage actors and the driver-side
  :class:`PipelineRunner` that compiles them into one DAG; forward
  activations and backward grads stream stage-to-stage over
  bounded-capacity channels (shm or TCP), providing backpressure.

Selected from the trainer via ``ScalingConfig(pipeline_stages=N,
microbatches=M, schedule="1f1b")`` — see ``train/trainer.py``.
"""

from ray_tpu.train.pipeline.schedule import (  # noqa: F401
    Instruction,
    bubble_fraction,
    build_schedule,
    stage_schedule,
    validate_schedule,
)
from ray_tpu.train.pipeline.partition import (  # noqa: F401
    LayeredModel,
    StagePlan,
    balanced_ranges,
    partition_model,
)
from ray_tpu.train.pipeline.reshard import reshard_boundary  # noqa: F401
from ray_tpu.train.pipeline.executor import (  # noqa: F401
    PipelineRunner,
    PipelineStage,
)

__all__ = [
    "Instruction", "bubble_fraction", "build_schedule", "stage_schedule",
    "validate_schedule", "LayeredModel", "StagePlan", "balanced_ranges",
    "partition_model", "reshard_boundary", "PipelineRunner",
    "PipelineStage",
]
