"""Pipeline stage actors + the driver-side runner.

Execution shape: every stage is one actor; the compiled DAG fans the
step input out to all stages (``MultiOutputNode`` collects every
stage's per-step report), so the per-actor resident loops run
concurrently. Within a step, each stage replays its static
instruction list (``schedule.py``); forward activations and backward
grads do NOT ride the DAG edges — they stream stage-to-stage over
dedicated bounded-capacity channels (``dag/channel.py`` shm rings on
one node, ``dag/tcp_channel.py`` native-wire links across nodes), so
channel capacity is the pipeline's backpressure bound.

Failure semantics: a stage raising mid-step becomes an
``_ErrorToken`` in its output channel; ``CompiledDAGRef.get()``
raises ``DAGExecutionError`` whose message names the stage. Peers
blocked on the dead stage's channels time out with a
``PipelineStallError`` (also naming themselves), so the DAG never
wedges silently.

Data-parallel composition: replicas of the same stage form one
collective group ("stage group"); at ``STEP`` the accumulated
gradient is allreduce-averaged over that group (optionally block-
quantized via ``grad_compression``) before the local update — the
DDP×pipeline shape of the trainer's ``ScalingConfig``.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.dag.channel import ChannelSpec, ChannelTimeoutError
from ray_tpu.train.pipeline import schedule as sched_mod
from ray_tpu.train.pipeline.partition import (
    LayeredModel, StagePlan, partition_model, stitch_params)
from ray_tpu.util import flight_recorder as _flight
from ray_tpu.util.metrics import Counter, Gauge, Histogram

logger = logging.getLogger(__name__)

_STEP_BOUNDS = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0]

PIPELINE_BUBBLE = Gauge(
    "ray_tpu_train_pipeline_bubble_ratio",
    "Measured per-stage pipeline bubble (1 - compute/wall) for the "
    "last step", tag_keys=("stage", "schedule"))
STAGE_STEP_SECONDS = Histogram(
    "ray_tpu_train_pipeline_stage_step_seconds",
    "Per-instruction compute time by stage and schedule phase",
    boundaries=_STEP_BOUNDS, tag_keys=("stage", "phase"))
ACTIVATION_BYTES = Counter(
    "ray_tpu_train_pipeline_activation_bytes_total",
    "Bytes moved over pipeline stage-boundary channels",
    tag_keys=("edge",))


class PipelineStallError(RuntimeError):
    """A stage timed out waiting on an adjacent stage's channel."""


def _tree_add(a, b):
    import jax
    return jax.tree_util.tree_map(lambda x, y: x + y, a, b)


def _tree_scale(t, s):
    import jax
    return jax.tree_util.tree_map(lambda x: x * s, t)


class PipelineStage:
    """One MPMD stage: owns its layer slice, optimizer state, and the
    four channel endpoints (fwd in/out, grad in/out)."""

    def __init__(self, config_blob: bytes):
        from ray_tpu.core import serialization
        cfg = serialization.loads(config_blob)
        self.stage_id: int = cfg["stage_id"]
        self.num_stages: int = cfg["num_stages"]
        self.num_microbatches: int = cfg["num_microbatches"]
        self.schedule_name: str = cfg["schedule"]
        self.lr: float = cfg["lr"]
        self.recv_timeout_s: float = cfg["recv_timeout_s"]
        self.plan: StagePlan = cfg["plan"]
        self._apply_layer = cfg["apply_layer"]
        self._loss_fn = cfg["loss_fn"]
        self.grad_compression: Optional[str] = cfg.get("grad_compression")
        self._dp: Optional[Tuple[str, int, int]] = cfg.get("dp")
        self._instrs = sched_mod.stage_schedule(
            self.stage_id, self.num_stages, self.num_microbatches,
            self.schedule_name)
        import jax.numpy as jnp
        self.params = [
            __import__("jax").tree_util.tree_map(jnp.asarray, lp)
            for lp in self.plan.layer_params]
        # channel endpoints, bound in connect_channels()
        self._fwd_in = self._fwd_out = None
        self._grad_in = self._grad_out = None
        self._adopt_tokens: Dict[str, str] = {}
        self._step_idx = 0
        self._fail_next = False
        if self._dp is not None:
            group_name, dp_world, dp_rank = self._dp
            from ray_tpu.parallel import collective
            collective.init_collective_group(dp_world, dp_rank,
                                             group_name)

    # -- channel wiring (driver-orchestrated, pre-compile) -------------
    def pipe_create_listener(self, token: str):
        """TCP transport: bind this stage's reader-side listener and
        return its address (driver hands it to the writing peer)."""
        from ray_tpu.dag.tcp_channel import create_listener
        return create_listener(token)

    def connect_channels(self, endpoints: Dict[str, Any]) -> bool:
        """Bind channel endpoints. Each entry is either
        ``("shm", ChannelSpec, reader_idx_or_None)`` or
        ``("tcp_reader", token)`` / ``("tcp_writer", [addr], cap)``.
        Keys: fwd_in, fwd_out, grad_in, grad_out (absent at the
        pipeline's ends)."""
        from ray_tpu.dag.channel import ChannelReader, ChannelWriter

        def build(entry, reading: bool):
            kind = entry[0]
            if kind == "shm":
                spec: ChannelSpec = entry[1]
                return (ChannelReader(spec, entry[2]) if reading
                        else ChannelWriter(spec))
            if kind == "tcp_reader":
                # defer adoption to the run loop: the listener was
                # created in this process and adopt is process-local
                self._adopt_tokens[entry[1]] = entry[1]
                return ("tcp_pending", entry[1])
            from ray_tpu.dag.tcp_channel import TcpChannelWriter
            return TcpChannelWriter(list(entry[1]), entry[2])

        self._fwd_in = (build(endpoints["fwd_in"], True)
                        if "fwd_in" in endpoints else None)
        self._fwd_out = (build(endpoints["fwd_out"], False)
                         if "fwd_out" in endpoints else None)
        self._grad_in = (build(endpoints["grad_in"], True)
                         if "grad_in" in endpoints else None)
        self._grad_out = (build(endpoints["grad_out"], False)
                          if "grad_out" in endpoints else None)
        return True

    def _adopt(self, endpoint):
        if (isinstance(endpoint, tuple)
                and endpoint[0] == "tcp_pending"):
            from ray_tpu.dag.tcp_channel import adopt_listener
            return adopt_listener(endpoint[1])
        return endpoint

    # -- test hooks ----------------------------------------------------
    def fail_next_step(self) -> bool:
        """Inject a mid-step stage death on the next run_step."""
        self._fail_next = True
        return True

    def get_params(self):
        """Stage params as numpy trees (callable only while the actor
        is NOT parked in a compiled-DAG loop — i.e. after teardown; the
        in-band path is the runner's ``fetch_params``)."""
        import jax
        return [jax.tree_util.tree_map(np.asarray, lp)
                for lp in self.params]

    # -- the per-step instruction interpreter --------------------------
    def run_step(self, batch):
        """Execute this stage's full instruction list for one step.
        ``batch`` = ("step", x, y): stage 0 consumes x, the last stage
        y. The actor is parked inside the compiled-DAG resident loop,
        so control-plane requests ride the same channel as steps:
        ("fetch", None, None) returns this stage's params in-band.
        Returns the stage's step report dict."""
        import jax

        cmd, x, y = batch
        if cmd == "fetch":
            return {"stage": self.stage_id, "params": self.get_params()}
        if cmd == "fail":
            # test hook riding the DAG: arm a mid-step death for the
            # next ("step", ...) on the targeted stage
            if int(x) == self.stage_id:
                self._fail_next = True
            return {"stage": self.stage_id, "armed": self._fail_next}
        self._fwd_in = self._adopt(self._fwd_in)
        self._grad_in = self._adopt(self._grad_in)
        m = self.num_microbatches
        sid = self.stage_id
        x_mbs = (np.array_split(np.asarray(x), m, axis=0)
                 if self.plan.is_first else [None] * m)
        y_mbs = (np.array_split(np.asarray(y), m, axis=0)
                 if self.plan.is_last else [None] * m)

        recv_act: Dict[int, Any] = {}
        recv_grad: Dict[int, Any] = {}
        outputs: Dict[int, Any] = {}
        pullbacks: Dict[int, Any] = {}
        grads_accum = None
        loss_sum = 0.0
        live = peak_live = 0
        compute_s = 0.0
        edge_bytes: Dict[str, int] = {}
        hist_items: List[tuple] = []
        base = self._step_idx * m
        t_wall0 = time.perf_counter()
        rec = _flight.RECORDER
        step_t0_ns = rec.clock() if rec is not None else 0

        def stage_forward(layer_list, h):
            for lp in layer_list:
                h = self._apply_layer(lp, h)
            return h

        def _read(endpoint, seq, what):
            try:
                value = endpoint.read(seq, timeout=self.recv_timeout_s)
            except ChannelTimeoutError as exc:
                err = PipelineStallError(
                    f"pipeline stage {sid} stalled waiting for {what} "
                    f"(seq {seq}); an adjacent stage likely died")
                # post-mortem: ship this stage's final moments with the
                # error (rides the pickled exception to the driver)
                _flight.attach_tail(err)
                raise err from exc
            if not getattr(endpoint, "owned_reads", False):
                value = np.array(value, copy=True)
            endpoint.ack(seq)
            return value

        def _write(endpoint, value, seq, edge):
            arr = np.asarray(value)
            edge_bytes[edge] = edge_bytes.get(edge, 0) + arr.nbytes
            try:
                endpoint.write(arr, seq, timeout=self.recv_timeout_s)
            except ChannelTimeoutError as exc:
                err = PipelineStallError(
                    f"pipeline stage {sid} stalled writing to edge "
                    f"{edge} (seq {seq}); the peer stage likely died")
                _flight.attach_tail(err)
                raise err from exc

        for ins in self._instrs:
            if self._fail_next and ins.op == sched_mod.FWD:
                self._fail_next = False
                err = RuntimeError(
                    f"pipeline stage {sid} died mid-step (injected "
                    "failure)")
                _flight.attach_tail(err)  # post-mortem journal tail
                raise err
            ins_t0_ns = rec.clock() if rec is not None else 0
            if ins.op == sched_mod.RECV:
                if ins.kind == sched_mod.ACT:
                    recv_act[ins.mb] = _read(
                        self._fwd_in, base + ins.mb,
                        f"activation mb {ins.mb} from stage {sid - 1}")
                else:
                    recv_grad[ins.mb] = _read(
                        self._grad_in, base + ins.mb,
                        f"gradient mb {ins.mb} from stage {sid + 1}")
                if rec is not None:
                    rec.record("pipeline", ins.op, ins_t0_ns,
                               rec.clock() - ins_t0_ns,
                               {"stage": sid, "step": self._step_idx,
                                "mb": ins.mb, "kind": ins.kind,
                                "phase": ins.phase})
                continue
            if ins.op == sched_mod.SEND:
                if ins.kind == sched_mod.ACT:
                    _write(self._fwd_out, outputs.pop(ins.mb),
                           base + ins.mb, f"{sid}->{sid + 1}")
                else:
                    _write(self._grad_out, recv_grad.pop(ins.mb),
                           base + ins.mb, f"{sid}->{sid - 1}")
                if rec is not None:
                    rec.record("pipeline", ins.op, ins_t0_ns,
                               rec.clock() - ins_t0_ns,
                               {"stage": sid, "step": self._step_idx,
                                "mb": ins.mb, "kind": ins.kind,
                                "phase": ins.phase})
                continue

            t0 = time.perf_counter()
            if ins.op == sched_mod.FWD:
                k = ins.mb
                h_in = (x_mbs[k] if self.plan.is_first
                        else recv_act.pop(k))
                h_in = jax.numpy.asarray(h_in)
                if self.plan.is_last:
                    target = jax.numpy.asarray(y_mbs[k])
                    loss, pull = jax.vjp(
                        lambda p, h: self._loss_fn(
                            stage_forward(p, h), target),
                        self.params, h_in)
                    loss_sum += float(loss)
                else:
                    out, pull = jax.vjp(stage_forward, self.params,
                                        h_in)
                    outputs[k] = out
                pullbacks[k] = pull
                live += 1
                peak_live = max(peak_live, live)
            elif ins.op == sched_mod.BWD:
                k = ins.mb
                seed = (1.0 if self.plan.is_last
                        else jax.numpy.asarray(recv_grad[k]))
                gp, gx = pullbacks.pop(k)(seed)
                grads_accum = (gp if grads_accum is None
                               else _tree_add(grads_accum, gp))
                live -= 1
                if not self.plan.is_first:
                    # overwrite in place: SEND(grad, k) picks it up
                    recv_grad[k] = gx
                else:
                    recv_grad.pop(k, None)
            elif ins.op == sched_mod.STEP:
                grads = _tree_scale(grads_accum, 1.0 / m)
                if self._dp is not None:
                    grads = self._dp_allreduce(grads)
                self.params = jax.tree_util.tree_map(
                    lambda p, g: p - self.lr * g, self.params, grads)
            dt = time.perf_counter() - t0
            compute_s += dt
            if rec is not None:
                rec.record("pipeline", ins.op, ins_t0_ns,
                           rec.clock() - ins_t0_ns,
                           {"stage": sid, "step": self._step_idx,
                            "mb": ins.mb, "phase": ins.phase})
            hist_items.append((
                "histogram", "ray_tpu_train_pipeline_stage_step_seconds",
                {"stage": str(sid), "phase": ins.phase}, dt,
                _STEP_BOUNDS))

        wall_s = time.perf_counter() - t_wall0
        bubble = max(0.0, 1.0 - compute_s / wall_s) if wall_s > 0 else 0.0
        if rec is not None:
            # the per-step envelope span: whereis derives measured
            # bubble from (1 - compute/wall) of exactly these numbers —
            # the same formula the live report uses
            rec.record("pipeline", "stage_step", step_t0_ns,
                       rec.clock() - step_t0_ns,
                       {"stage": sid, "step": self._step_idx,
                        "schedule": self.schedule_name,
                        "S": self.num_stages,
                        "m": self.num_microbatches,
                        "wall_s": round(wall_s, 6),
                        "compute_s": round(compute_s, 6)})
        self._step_idx += 1
        self._flush_metrics(bubble, edge_bytes, hist_items)
        report = {
            "stage": sid,
            "wall_s": wall_s,
            "compute_s": compute_s,
            "bubble": bubble,
            "max_live": peak_live,
            "edge_bytes": edge_bytes,
        }
        if self.plan.is_last:
            report["loss"] = loss_sum / m
        return report

    def _dp_allreduce(self, grads):
        """Average the stage gradient across this stage's data-parallel
        replica group (quantized when grad_compression is set)."""
        import jax
        group_name, _, _ = self._dp
        from ray_tpu.parallel import collective
        flat, treedef = jax.tree_util.tree_flatten(grads)
        reduced = [
            collective.allreduce(
                np.asarray(leaf), op="mean", group_name=group_name,
                compression=self.grad_compression,
                ef_key=(f"pipe/{self.stage_id}/{i}"
                        if self.grad_compression else None))
            for i, leaf in enumerate(flat)
        ]
        return jax.tree_util.tree_unflatten(treedef, reduced)

    def _flush_metrics(self, bubble: float, edge_bytes: Dict[str, int],
                       hist_items: List[tuple]) -> None:
        """One record_batch per step: gauge + per-instruction histogram
        observations + edge byte counters (a worker-side batch rides a
        single control-plane RPC)."""
        from ray_tpu.util.metrics import record_batch
        items = list(hist_items)
        items.append((
            "gauge", "ray_tpu_train_pipeline_bubble_ratio",
            {"stage": str(self.stage_id),
             "schedule": self.schedule_name}, bubble, None))
        for edge, nbytes in edge_bytes.items():
            items.append((
                "counter",
                "ray_tpu_train_pipeline_activation_bytes_total",
                {"edge": edge}, float(nbytes), None))
        try:
            record_batch(items)
        except Exception:  # noqa: BLE001 — observability must not
            logger.debug("pipeline metrics not recorded",  # fail a step
                         exc_info=True)


class PipelineRunner:
    """Driver handle: partitions the model, spawns stage actors, wires
    the activation channels, compiles the fan-out DAG, and exposes
    ``step()``/``fetch_params()``/``shutdown()``."""

    def __init__(self, model: LayeredModel, *, num_stages: int,
                 num_microbatches: int, schedule: str = "1f1b",
                 transport: str = "shm", channel_capacity: int = 4,
                 lr: float = 0.05, recv_timeout_s: float = 30.0,
                 grad_compression: Optional[str] = None,
                 dp_group: Optional[Tuple[str, int, int]] = None,
                 actor_options: Optional[dict] = None):
        import ray_tpu
        from ray_tpu.core import serialization
        from ray_tpu.dag import InputNode, MultiOutputNode

        if transport not in ("shm", "tcp"):
            raise ValueError(f"unknown transport {transport!r}")
        sched_mod.validate_schedule(num_stages, num_microbatches,
                                    schedule)
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.schedule = schedule
        self.theoretical_bubble = sched_mod.bubble_fraction(
            num_stages, num_microbatches, schedule)
        plans = partition_model(model, num_stages)

        StageActor = ray_tpu.remote(PipelineStage)
        opts = dict(actor_options or {})
        self._actors = []
        for plan in plans:
            cfg = {
                "stage_id": plan.stage_id, "num_stages": num_stages,
                "num_microbatches": num_microbatches,
                "schedule": schedule, "lr": lr,
                "recv_timeout_s": recv_timeout_s, "plan": plan,
                "apply_layer": model.apply_layer,
                "loss_fn": model.loss_fn,
                "grad_compression": grad_compression,
                "dp": (None if dp_group is None else
                       (f"{dp_group[0]}/stage{plan.stage_id}",
                        dp_group[1], dp_group[2])),
            }
            actor = (StageActor.options(**opts).remote(
                serialization.dumps(cfg)) if opts
                else StageActor.remote(serialization.dumps(cfg)))
            self._actors.append(actor)

        # --- wire the boundary channels (edge i: stage i <-> i+1) ----
        endpoints: List[Dict[str, Any]] = [dict() for _ in plans]
        if transport == "shm":
            import os as _os
            for i in range(num_stages - 1):
                fwd = ChannelSpec(channel_id=_os.urandom(8),
                                  num_readers=1,
                                  capacity=channel_capacity)
                bwd = ChannelSpec(channel_id=_os.urandom(8),
                                  num_readers=1,
                                  capacity=channel_capacity)
                endpoints[i]["fwd_out"] = ("shm", fwd, None)
                endpoints[i + 1]["fwd_in"] = ("shm", fwd, 0)
                endpoints[i + 1]["grad_out"] = ("shm", bwd, None)
                endpoints[i]["grad_in"] = ("shm", bwd, 0)
        else:
            # reader-side listeners first, so writer connects can't race
            tokens = {}
            listen_refs = []
            for i in range(num_stages - 1):
                t_fwd = f"pipe:{id(self)}:fwd:{i}"
                t_bwd = f"pipe:{id(self)}:bwd:{i}"
                tokens[i] = (t_fwd, t_bwd)
                listen_refs.append(
                    self._actors[i + 1].pipe_create_listener.remote(
                        t_fwd))
                listen_refs.append(
                    self._actors[i].pipe_create_listener.remote(t_bwd))
            addrs = ray_tpu.get(listen_refs)
            for i in range(num_stages - 1):
                t_fwd, t_bwd = tokens[i]
                fwd_addr = tuple(addrs[2 * i])
                bwd_addr = tuple(addrs[2 * i + 1])
                endpoints[i]["fwd_out"] = ("tcp_writer", [fwd_addr],
                                           channel_capacity)
                endpoints[i + 1]["fwd_in"] = ("tcp_reader", t_fwd)
                endpoints[i + 1]["grad_out"] = ("tcp_writer",
                                                [bwd_addr],
                                                channel_capacity)
                endpoints[i]["grad_in"] = ("tcp_reader", t_bwd)
        ray_tpu.get([a.connect_channels.remote(e)
                     for a, e in zip(self._actors, endpoints)])

        with InputNode() as inp:
            outs = [a.run_step.bind(inp) for a in self._actors]
            dag = MultiOutputNode(outs)
        self._compiled = dag.experimental_compile(
            buffer_capacity=channel_capacity)

    # -- driving -------------------------------------------------------
    def execute_async(self, x, y):
        """Non-blocking: enqueue one step; ``ref.get()`` returns the
        per-stage report list (last entry carries the loss)."""
        return self._compiled.execute(
            ("step", np.asarray(x), np.asarray(y)))

    def step(self, x, y, timeout: Optional[float] = 120.0
             ) -> Dict[str, Any]:
        reports = self.execute_async(x, y).get(timeout)
        out = {"loss": reports[-1].get("loss"),
               "reports": reports,
               "bubble": (sum(r["bubble"] for r in reports)
                          / len(reports)),
               "theoretical_bubble": self.theoretical_bubble}
        return out

    def inject_failure(self, stage_id: int) -> None:
        """Test hook: arm a mid-step death on ``stage_id`` for the next
        step. Rides the DAG input channel — the stage actors are parked
        in their resident loops, so an out-of-band actor call would
        never execute."""
        self._compiled.execute(("fail", stage_id, None)).get(30.0)

    def fetch_params(self) -> List[Any]:
        """Current per-layer params, stitched back into model order.
        Rides the DAG (the stage actors are parked in their resident
        loops, so an out-of-band actor call would never run)."""
        reports = self._compiled.execute(
            ("fetch", None, None)).get(60.0)
        return stitch_params([r["params"] for r in reports])

    def shutdown(self) -> None:
        import ray_tpu
        try:
            self._compiled.teardown()
        finally:
            for a in self._actors:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001 — already gone
                    logger.debug("pipeline stage kill failed",
                                 exc_info=True)
