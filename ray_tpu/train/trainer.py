"""JaxTrainer: controller + worker group + failure policy.

reference: python/ray/train/v2 — the controller state machine
(_internal/execution/controller/controller.py:100, state.py:89-154:
Initializing→Scheduling→Running→Restarting→Finished/Errored), the
worker group (execution/worker_group/worker_group.py), the JAX backend
(v2/jax/jax_trainer.py:19, config.py:29 jax.distributed bootstrap), and
TPU slice reservation (TPUReservationCallback + reserve_tpu_slice,
_private/accelerators/tpu.py:145).

Workers are actors on the core runtime ("tpu" worker profile when
use_tpu — they see the chips; the controller and plain tasks don't).
Inside each worker the user's train_loop_per_worker runs with the
TrainContext set, so report()/get_checkpoint()/get_dataset_shard() work,
and a collective group "<run>/train" is pre-initialized for host-side
allreduce/barrier (in-graph math should use the mesh instead).
"""

from __future__ import annotations

import logging
import os
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core import serialization
from ray_tpu.exceptions import ActorError, RayTpuError, TaskError, WorkerCrashedError
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import Result, RunConfig, ScalingConfig
from ray_tpu.util.placement_group import placement_group, remove_placement_group

logger = logging.getLogger(__name__)


class _TrainWorker:
    """Actor hosting one rank of the gang (runs in a 'tpu'-profile
    worker process when TPU resources are requested)."""

    def __init__(self, rank: int, world_size: int, storage_path: str,
                 group_name: str, jax_env: Optional[dict] = None,
                 grad_compression: Optional[str] = None,
                 zero1: bool = False, pipeline_stages: int = 1,
                 microbatches: int = 1, schedule: str = "1f1b"):
        self.rank = rank
        self.world_size = world_size
        self.storage_path = storage_path
        self.group_name = group_name
        self.grad_compression = grad_compression
        self.zero1 = zero1
        # pipeline topology: stage-major rank layout (adjacent ranks =
        # adjacent stages of one replica), gradient sync per stage
        self.pipeline_stages = max(1, pipeline_stages)
        self.microbatches = microbatches
        self.schedule = schedule
        self.pipeline_stage = rank % self.pipeline_stages
        self.pipeline_replica = rank // self.pipeline_stages
        self.stage_group_name: Optional[str] = None
        if jax_env:
            # Multi-host bootstrap (reference: _setup_jax_tpu_environment).
            # The coordinator must bind on RANK 0's host (on a pod that's
            # a slice host the head can't predict), so rank 0 picks a
            # local port and publishes it through the GCS KV; the rest
            # of the gang polls for it.
            if jax_env.get("coordinator_address") is None:
                jax_env = dict(jax_env)
                jax_env["coordinator_address"] = \
                    self._rendezvous_coordinator(
                        jax_env.get("process_id", 0))
            from ray_tpu.parallel.mesh import initialize_distributed
            initialize_distributed(**jax_env)
        from ray_tpu.parallel import collective
        collective.init_collective_group(world_size, rank, group_name)
        if self.pipeline_stages > 1:
            # cross-replica group per stage: DDP/ZeRO-1 allreduce of a
            # stage's grads only involves the replicas holding that
            # stage's parameters
            dp_world = world_size // self.pipeline_stages
            self.stage_group_name = \
                f"{group_name}/stage{self.pipeline_stage}"
            collective.init_collective_group(
                dp_world, self.pipeline_replica, self.stage_group_name)

    def _rendezvous_coordinator(self, process_id: int) -> str:
        import socket as _socket
        import time as _time

        from ray_tpu.core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        key = f"jaxcoord/{self.group_name}".encode()
        if process_id == 0:
            try:
                host = _socket.gethostbyname(_socket.gethostname())
            except OSError:
                host = "127.0.0.1"
            probe = _socket.socket()
            probe.bind((host, 0))
            address = f"{host}:{probe.getsockname()[1]}"
            probe.close()
            rt.gcs_call("kv_put", key, address.encode(), "train")
            return address
        deadline = _time.monotonic() + 120.0
        while _time.monotonic() < deadline:
            value = rt.gcs_call("kv_get", key, "train")
            if value:
                return value.decode()
            _time.sleep(0.05)
        raise TimeoutError(
            "rank 0 never published the jax.distributed coordinator "
            f"address for group {self.group_name}")

    def run(self, loop_blob: bytes, loop_config: Optional[dict],
            resume_path: Optional[str], datasets_blob: Optional[bytes]):
        from ray_tpu.train import context as ctx_mod
        loop = serialization.loads(loop_blob)
        datasets = serialization.loads(datasets_blob) if datasets_blob else {}
        ctx = ctx_mod.TrainContext(
            world_size=self.world_size, world_rank=self.rank,
            storage_path=self.storage_path,
            resume_checkpoint=Checkpoint(resume_path) if resume_path else None,
            datasets=datasets, group_name=self.group_name,
            grad_compression=self.grad_compression, zero1=self.zero1,
            pipeline_stages=self.pipeline_stages,
            microbatches=self.microbatches, schedule=self.schedule,
            pipeline_stage=self.pipeline_stage,
            pipeline_replica=self.pipeline_replica,
            stage_group_name=self.stage_group_name)
        ctx_mod.set_context(ctx)
        try:
            if loop_config is not None:
                loop(loop_config)
            else:
                try:
                    loop()
                except TypeError:
                    loop({})
        finally:
            ctx_mod.set_context(None)
        return ctx.reported

    def ping(self):
        return self.rank


class JaxTrainer:
    """Gang-scheduled SPMD training driver.

    The DDP/FSDP/TP modes are not wrapper classes: the train loop builds
    a mesh (`ray_tpu.parallel.mesh`) and shards params with
    `llama_sharding_rules`/`ShardingConfig`; XLA inserts the gradient
    collectives (SURVEY.md §2.3 X2/X3).
    """

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.state_history: List[str] = ["INITIALIZING"]

    def _transition(self, state: str) -> None:
        self.state_history.append(state)
        # publish run state for the dashboard's train module
        # (reference: dashboard/modules/train — run states from the
        # controller); best-effort: observability must not fail a run
        try:
            import time as _time

            from ray_tpu.core import runtime as runtime_mod
            from ray_tpu.core import serialization as _ser
            rt = runtime_mod.get_runtime_or_none()
            if rt is None:
                return
            if not hasattr(self, "_run_record_id"):
                # unique per trainer: same-named (or unnamed) runs must
                # not clobber each other's dashboard records
                import uuid as _uuid
                self._run_record_id = _uuid.uuid4().hex[:8]
            record = _ser.dumps({
                "name": self.run_config.name or "train_run",
                "run_id": self._run_record_id,
                "state": state,
                "history": list(self.state_history),
                "num_workers": self.scaling_config.num_workers,
                "use_tpu": bool(getattr(self.scaling_config,
                                        "use_tpu", False)),
                "updated_at": _time.time(),
            })
            key = (f"{self.run_config.name or 'train_run'}"
                   f":{self._run_record_id}").encode()
            if rt.is_driver:
                rt.gcs.kv.put(key, record, namespace="train_runs")
                # Retention: keep the newest 50 records. Pruning only on
                # TERMINAL transitions keeps the hot path N+1-free, and
                # skipping our own key means an old-but-active run can't
                # be evicted by a flood of quick newer runs.
                if state in ("FINISHED", "ERRORED", "ABORTED"):
                    keys = rt.gcs.kv.keys(namespace="train_runs")
                    if len(keys) > 50:
                        aged = []
                        for k in keys:
                            if k == key:
                                continue
                            blob = rt.gcs.kv.get(k,
                                                 namespace="train_runs")
                            if blob is None:
                                continue
                            aged.append(
                                (_ser.loads(blob).get("updated_at", 0),
                                 k))
                        aged.sort()
                        for _ts, k in aged[:len(aged) - 49]:
                            rt.gcs.kv.delete(k, namespace="train_runs")
            else:
                rt.gcs_call("kv_put", key, record, "train_runs")
        except Exception:  # noqa: BLE001 — dashboard record is best-effort
            logger.debug("train run-state record not published",
                         exc_info=True)

    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        storage = self.run_config.resolved_storage_path()
        manager = CheckpointManager(
            storage, self.run_config.checkpoint_config.num_to_keep)
        max_failures = self.run_config.failure_config.max_failures
        loop_blob = serialization.dumps(self.train_loop)
        last_error: Optional[Exception] = None

        policy = self.scaling_config.resolved_scaling_policy()
        world = self.scaling_config.num_workers
        for attempt in range(max_failures + 1):
            self._transition("SCHEDULING" if attempt == 0 else "RESTARTING")
            try:
                workers, pg, reservation = self._create_worker_group(
                    storage, world)
            except (ActorError, WorkerCrashedError, TaskError, RayTpuError,
                    TimeoutError, RuntimeError) as e:
                last_error = e
                world = self._resize_after_failure(policy, world)
                if world is None:
                    break
                continue
            resume = manager.latest()
            try:
                self._transition("RUNNING")
                # Split streaming datasets ONCE here and ship each rank
                # its own iterator: n workers each calling
                # streaming_split would spin up n coordinators, each
                # executing the whole dataset. Rebuilt per attempt so an
                # elastic resize re-splits at the new world size.
                datasets_blobs = self._rank_datasets_blobs(len(workers))
                refs = [
                    w.run.remote(loop_blob, self.train_loop_config,
                                 resume.path if resume else None,
                                 datasets_blobs[rank])
                    for rank, w in enumerate(workers)
                ]
                all_reports = ray_tpu.get(refs)
                self._transition("FINISHED")
                return self._build_result(all_reports, manager, storage)
            except (ActorError, WorkerCrashedError, TaskError,
                    RayTpuError) as e:
                last_error = e
            finally:
                for w in workers:
                    try:
                        ray_tpu.kill(w)
                    except Exception:  # noqa: BLE001 — already torn down
                        logger.debug("train worker kill failed during "
                                     "group teardown", exc_info=True)
                if pg is not None:
                    remove_placement_group(pg)
                if reservation is not None:
                    reservation.release()
            # Decide the next gang size only after the failed group's
            # reservations are released — the policy reads available
            # cluster resources.
            world = self._resize_after_failure(policy, world)
            if world is None:
                break
        self._transition("ERRORED")
        final = manager.latest()
        return Result(metrics={}, checkpoint=final, path=storage,
                      error=last_error)

    def _resize_after_failure(self, policy, world: int):
        """Scaling-policy hook: pick the next gang size (None = stop).
        A shrink is the elastic Resizing transition; training resumes
        from the last checkpoint at the new world size."""
        new_world = policy.world_size_after_failure(
            world, runtime_mod.get_runtime())
        if new_world is None or new_world < 1:
            return None
        stages = max(1, self.scaling_config.pipeline_stages)
        if stages > 1:
            # elastic shrink must keep whole pipeline replicas
            new_world -= new_world % stages
            if new_world < stages:
                return None
        if new_world != world:
            self._transition("RESIZING")
            from ray_tpu.core import events
            events.emit("TRAIN_RESIZED", "WARNING",
                        message=f"elastic resize {world} -> {new_world}",
                        data={"from": world, "to": new_world})
        return new_world

    def _rank_datasets_blobs(self, world: int) -> List[Optional[bytes]]:
        """Per-rank serialized datasets dicts: streaming datasets are
        split once driver-side into per-rank iterators sharing ONE
        coordinator/execution; non-splittable values ship whole."""
        if not self.datasets:
            return [None] * world
        per_rank: List[Dict[str, Any]] = [{} for _ in range(world)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                shards = ds.streaming_split(world)
                for rank in range(world):
                    per_rank[rank][name] = shards[rank]
            else:
                for rank in range(world):
                    per_rank[rank][name] = ds
        return [serialization.dumps(d) for d in per_rank]

    def _create_worker_group(self, storage: str,
                             num_workers: Optional[int] = None):
        scaling = self.scaling_config
        if num_workers is None:
            num_workers = scaling.num_workers
        stages = max(1, scaling.pipeline_stages)
        if stages > 1:
            from ray_tpu.train.pipeline.schedule import SCHEDULES
            if scaling.schedule not in SCHEDULES:
                raise ValueError(
                    f"unknown pipeline schedule {scaling.schedule!r}; "
                    f"expected one of {SCHEDULES}")
            if num_workers % stages:
                raise ValueError(
                    f"num_workers={num_workers} is not divisible by "
                    f"pipeline_stages={stages}: every data-parallel "
                    "replica needs a full set of stage workers")
            if scaling.microbatches < 1:
                raise ValueError(
                    f"microbatches must be >= 1, got "
                    f"{scaling.microbatches}")
        res = scaling.worker_resources()
        # Multi-host slice gang: reserve a whole slice via its head
        # resource, then pin every worker to that slice's hosts with the
        # slice-name resource + STRICT_SPREAD (one worker per host) —
        # the reference's JaxTrainer shape (reference: reserve_tpu_slice
        # tpu.py:145 + TPUReservationCallback).
        slice_name = None
        slice_reservation = None
        if (scaling.use_tpu and scaling.topology
                and scaling.accelerator_type):
            from ray_tpu.accelerators.tpu import reserve_tpu_slice
            slice_reservation = reserve_tpu_slice(scaling.topology,
                                                  scaling.accelerator_type)
            if slice_reservation is not None:
                slice_name = slice_reservation.name
                res[slice_name] = 1.0
        # Gang reservation: one bundle per worker. PACK fallback keeps
        # single-node dev boxes working.
        pg = None
        strategy = (("STRICT_SPREAD" if slice_name
                     else scaling.placement_strategy)
                    if num_workers > 1 else "PACK")
        try:
            pg = placement_group([dict(res)] * num_workers,
                                 strategy=strategy)
            # Creation queues (never raises) when the gang doesn't fit
            # yet; give the reservation a short window, then fall back
            # to loose scheduling so single-node dev boxes still train
            # (an unready queued PG must be removed, or it would grab
            # resources later with no owner).
            # NOTE: uses the module-level remove_placement_group — a
            # function-local import here would shadow it for the whole
            # function scope and break the later failure-path call.
            if not pg.ready(timeout=2.0):
                remove_placement_group(pg)
                pg = None
        except Exception:
            pg = None
        group_name = f"train/{os.path.basename(storage)}/{time.time_ns()}"
        WorkerActor = ray_tpu.remote(_TrainWorker)
        workers = []
        for rank in range(num_workers):
            opts = {"num_cpus": res.get("CPU", 1)}
            if "TPU" in res:
                opts["num_tpus"] = res["TPU"]
            if slice_name is not None:
                opts["resources"] = {slice_name: 1.0}
            if pg is not None:
                # Place each worker INSIDE its reserved bundle rather
                # than double-booking from the free pool (reference:
                # PlacementGroupSchedulingStrategy per worker rank).
                from ray_tpu.util.placement_group import (
                    PlacementGroupSchedulingStrategy)
                opts["scheduling_strategy"] = \
                    PlacementGroupSchedulingStrategy(
                        placement_group=pg,
                        placement_group_bundle_index=rank)
            env = None
            if num_workers > 1 and scaling.use_tpu:
                # coordinator_address resolves inside the gang: rank 0
                # binds locally and publishes via the GCS KV (see
                # _TrainWorker) — the head can't pick it, because on a
                # real pod rank 0 lives on a slice host, not here.
                env = {"num_processes": num_workers,
                       "process_id": rank}
            workers.append(
                WorkerActor.options(**opts).remote(
                    rank, num_workers, storage, group_name,
                    jax_env=env,
                    grad_compression=scaling.grad_compression,
                    zero1=scaling.zero1,
                    pipeline_stages=stages,
                    microbatches=scaling.microbatches,
                    schedule=scaling.schedule))
        # Fail fast if any worker can't construct — and release every
        # reservation on the way out, or the next (resized) attempt sees
        # the failed gang still holding the cluster's resources.
        try:
            ray_tpu.get([w.ping.remote() for w in workers])
        except BaseException:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:  # noqa: BLE001 — fail-fast cleanup
                    logger.debug("train worker kill failed during "
                                 "fail-fast cleanup", exc_info=True)
            if pg is not None:
                remove_placement_group(pg)
            if slice_reservation is not None:
                slice_reservation.release()
            raise
        return workers, pg, slice_reservation

    def _build_result(self, all_reports, manager: CheckpointManager,
                      storage: str) -> Result:
        rank0 = all_reports[0] if all_reports else []
        checkpoint = None
        history = []
        for metrics, ckpt_path in rank0:
            history.append(metrics)
            if ckpt_path:
                checkpoint = manager.register(ckpt_path, metrics)
        final_metrics = history[-1] if history else {}
        return Result(metrics=final_metrics, checkpoint=checkpoint,
                      path=storage, metrics_history=history,
                      all_reports=list(all_reports))
