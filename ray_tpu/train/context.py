"""In-train-loop API: report(), get_context(), get_checkpoint().

reference: python/ray/train/v2/api/train_fn_utils.py (report,
get_checkpoint, get_dataset_shard) and train/v2/api/context.py.
The context is process-global inside a train worker; report() buffers
metrics for the controller and persists checkpoints rank-coordinated
(rank 0 registers; others just sync).
"""

from __future__ import annotations

import threading

import logging

from ray_tpu.devtools import locktrace
from typing import Any, Dict, Iterable, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.util.metrics import Gauge

logger = logging.getLogger(__name__)

# Train-loop instrumentation (reference: Podracer-style TPU training
# leans on step-time + duty-cycle visibility; PAPERS.md). Step time is
# the interval between successive report() calls; MFU is estimated when
# the loop reports its per-step flops (``flops_per_step``) and a peak
# is known (``peak_flops_per_s`` in the report, or the
# RTPU_PEAK_FLOPS_PER_S env var on the worker).
TRAIN_STEP_SECONDS = Gauge(
    "ray_tpu_train_step_seconds",
    "Wall time between successive train.report() calls",
    tag_keys=("run", "rank"))
TRAIN_MFU = Gauge(
    "ray_tpu_train_mfu_ratio",
    "Estimated model flops utilization (0-1)",
    tag_keys=("run", "rank"))
TRAIN_REPORTED_STEPS = Gauge(
    "ray_tpu_train_reported_steps",
    "report() calls seen this run", tag_keys=("run", "rank"))


class TrainContext:
    def __init__(self, world_size: int, world_rank: int,
                 storage_path: str, resume_checkpoint: Optional[Checkpoint],
                 datasets: Optional[Dict[str, Any]] = None,
                 group_name: str = "train",
                 grad_compression: Optional[str] = None,
                 zero1: bool = False, pipeline_stages: int = 1,
                 microbatches: int = 1, schedule: str = "1f1b",
                 pipeline_stage: int = 0, pipeline_replica: int = 0,
                 stage_group_name: Optional[str] = None):
        self.world_size = world_size
        self.world_rank = world_rank
        self.storage_path = storage_path
        self.resume_checkpoint = resume_checkpoint
        self.datasets = datasets or {}
        self.group_name = group_name
        # gradient-sync flags from ScalingConfig, read by
        # train.collective.allreduce_gradients / make_optimizer
        self.grad_compression = grad_compression
        self.zero1 = zero1
        # pipeline topology (ScalingConfig.pipeline_stages > 1): this
        # worker's stage/replica, plus the cross-replica per-stage
        # collective group that gradient sync scopes itself to
        self.pipeline_stages = pipeline_stages
        self.microbatches = microbatches
        self.schedule = schedule
        self.pipeline_stage = pipeline_stage
        self.pipeline_replica = pipeline_replica
        self.stage_group_name = stage_group_name
        self.reported: list = []
        self.pending_checkpoint_dirs: list = []
        self._lock = locktrace.traced_lock("train.context")

    # reference API surface
    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.world_rank  # one worker per host in this runtime

    def get_pipeline_stage(self) -> int:
        return self.pipeline_stage

    def sync_group_name(self) -> str:
        """The group gradient sync should run in: the per-stage
        cross-replica group under pipeline parallelism (replicas of the
        SAME stage hold the same parameters), the run group otherwise."""
        return self.stage_group_name or self.group_name

    def get_experiment_name(self) -> str:
        return self.storage_path.rsplit("/", 1)[-1]


_context: Optional[TrainContext] = None


def set_context(ctx: Optional[TrainContext]) -> None:
    global _context
    _context = ctx


def get_context() -> TrainContext:
    if _context is None:
        raise RuntimeError(
            "not inside a train loop (get_context/report are only valid "
            "inside train_loop_per_worker)")
    return _context


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (+ optional checkpoint dir) from the train loop.

    All ranks should call report with the same cadence; only rank 0's
    checkpoint is persisted — and it is persisted HERE, at report time,
    so a later crash still leaves every reported checkpoint on storage
    for the failure-policy restart to resume from
    (reference: ray.train.report + sync_actor rank coordination).
    """
    import json
    import os
    import shutil
    import time

    ctx = get_context()
    persisted = None
    if checkpoint is not None and ctx.world_rank == 0:
        persisted = os.path.join(ctx.storage_path,
                                 f"checkpoint_{time.time_ns():019d}")
        shutil.copytree(checkpoint.path, persisted, dirs_exist_ok=True)
        try:
            with open(os.path.join(persisted, ".metrics.json"), "w") as f:
                json.dump({k: v for k, v in metrics.items()
                           if isinstance(v, (int, float, str, bool))}, f)
        except OSError:
            pass
    with ctx._lock:
        ctx.reported.append((dict(metrics), persisted))
        n_reports = len(ctx.reported)
        prev = getattr(ctx, "_last_report_t", None)
        now = time.perf_counter()
        ctx._last_report_t = now
    try:
        tags = {"run": ctx.get_experiment_name(),
                "rank": str(ctx.world_rank)}
        TRAIN_REPORTED_STEPS.set(float(n_reports), tags=tags)
        if prev is not None and now > prev:
            step_s = now - prev
            TRAIN_STEP_SECONDS.set(step_s, tags=tags)
            # estimated MFU: either reported directly, or derived from
            # flops_per_step against the hardware peak
            mfu = metrics.get("mfu")
            if mfu is None:
                flops = metrics.get("flops_per_step")
                peak = metrics.get("peak_flops_per_s") or float(
                    os.environ.get("RTPU_PEAK_FLOPS_PER_S", 0) or 0)
                if flops and peak:
                    mfu = float(flops) / (step_s * float(peak))
            if mfu is not None:
                TRAIN_MFU.set(min(max(float(mfu), 0.0), 1.0), tags=tags)
    except Exception:  # noqa: BLE001 — observability must not fail a run
        logger.debug("train step gauges not recorded", exc_info=True)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_context().resume_checkpoint


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a dataset passed to the trainer
    (reference: streaming_split per-worker iterators, data/dataset.py:1853)."""
    ctx = get_context()
    ds = ctx.datasets.get(name)
    if ds is None:
        return None
    from ray_tpu.data.iterator import DataIterator
    if isinstance(ds, DataIterator):
        # Already this rank's split — the trainer splits once
        # driver-side; splitting again here would execute the whole
        # dataset once per worker.
        return ds
    if hasattr(ds, "streaming_split"):
        return ds.streaming_split(ctx.world_size)[ctx.world_rank]
    return ds
