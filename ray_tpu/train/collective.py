"""Control-plane collectives for train workers.

reference: python/ray/train/collective/collectives.py:16,32 (barrier,
broadcast_from_rank_zero via SynchronizationActor) — here implemented
over the GCS-KV collective backend (ray_tpu/parallel/collective.py),
scoped to the run's pre-initialized group.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ray_tpu.core import serialization
from ray_tpu.parallel import collective
from ray_tpu.train.context import get_context


def barrier() -> None:
    ctx = get_context()
    collective.barrier(group_name=ctx.group_name)


def broadcast_from_rank_zero(data: Any) -> Any:
    """Broadcast an arbitrary picklable value from rank 0 to all ranks."""
    ctx = get_context()
    if ctx.world_rank == 0:
        payload = np.frombuffer(serialization.pack(data), dtype=np.uint8)
    else:
        payload = None
    out = collective.broadcast(
        payload if payload is not None else np.zeros(0, dtype=np.uint8),
        src_rank=0, group_name=ctx.group_name)
    return serialization.unpack(out.tobytes())


def allreduce_gradients(grads, op: str = "mean"):
    """Host-side gradient allreduce for DDP loops whose math runs on a
    single local device per worker (the multi-process CPU/dev path).
    On a pod, shard over the mesh instead — XLA's psum rides ICI."""
    ctx = get_context()
    import jax
    flat, treedef = jax.tree_util.tree_flatten(grads)
    reduced = [
        collective.allreduce(np.asarray(leaf), op=op,
                             group_name=ctx.group_name)
        for leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, reduced)
