"""Control-plane collectives for train workers.

reference: python/ray/train/collective/collectives.py:16,32 (barrier,
broadcast_from_rank_zero via SynchronizationActor) — here implemented
over the GCS-KV collective backend (ray_tpu/parallel/collective.py),
scoped to the run's pre-initialized group.

Round 7 adds the two gradient-sync cost levers (EQuARX + cross-replica
weight-update sharding, PAPERS.md):

* ``allreduce_gradients(..., compression="int8"|"fp8")`` — block-
  quantized transport with a persistent per-leaf error-feedback
  residual, ~4x fewer wire bytes;
* ``Zero1Optimizer`` — reduce-scatter grads → local optimizer step on
  this rank's 1/world_size flat shard → all-gather params, so
  optimizer-state memory per replica is ~1/world_size of the model
  (ZeRO-1 / "Automatic Cross-Replica Sharding of Weight Update").

Both are selected by ``ScalingConfig(grad_compression=..., zero1=...)``
and read off the TrainContext via ``make_optimizer``.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ray_tpu.core import serialization
from ray_tpu.devtools import collsan as _collsan
from ray_tpu.parallel import collective
from ray_tpu.train.context import get_context
from ray_tpu.util import flight_recorder as _flight


def _csan_enter(group: str, op_kind: str, leaves: int,
                compression: Optional[str]):
    """Envelope fingerprint for an optimizer-level gradient sync — the
    per-leaf collectives inside stamp their own, this one asserts every
    rank runs the same *wrapper* with the same leaf count and
    compression. None (and nothing recorded) when collsan is off."""
    led = _collsan.LEDGER
    if led is None:
        return None
    info = collective._groups.get(group)
    if info is None:
        return None
    return led.record_enter(
        group, info.rank, info.world_size,
        _collsan.fingerprint(op_kind, "", leaves, (), compression))


def _csan_exit(group: str, token, op_kind: str) -> None:
    led = _collsan.LEDGER
    if led is None or token is None:
        return
    info = collective._groups.get(group)
    if info is not None:
        led.record_exit(group, info.rank, info.world_size, token,
                        op_kind)


def barrier() -> None:
    ctx = get_context()
    collective.barrier(group_name=ctx.group_name)


def broadcast_from_rank_zero(data: Any) -> Any:
    """Broadcast an arbitrary picklable value from rank 0 to all ranks."""
    ctx = get_context()
    if ctx.world_rank == 0:
        payload = np.frombuffer(serialization.pack(data), dtype=np.uint8)
    else:
        payload = None
    out = collective.broadcast(
        payload if payload is not None else np.zeros(0, dtype=np.uint8),
        src_rank=0, group_name=ctx.group_name)
    return serialization.unpack(out.tobytes())


def allreduce_gradients(grads, op: str = "mean",
                        compression: Optional[str] = None):
    """Host-side gradient allreduce for DDP loops whose math runs on a
    single local device per worker (the multi-process CPU/dev path).
    On a pod, shard over the mesh instead — XLA's psum rides ICI.

    ``compression`` (default: the run's ``grad_compression`` flag):
    "int8"/"fp8" block-quantizes every ring hop and keeps a persistent
    error-feedback residual per leaf, so repeated rounds converge
    instead of accumulating quantization bias."""
    ctx = get_context()
    if compression is None:
        compression = getattr(ctx, "grad_compression", None)
    # under pipeline parallelism, sync within the per-stage group:
    # only the replicas of THIS stage hold these parameters
    group = _sync_group(ctx)
    import jax
    flat, treedef = jax.tree_util.tree_flatten(grads)
    rec = _flight.RECORDER
    t0_ns = rec.clock() if rec is not None else 0
    token = _csan_enter(group, "allreduce_gradients", len(flat),
                        compression)
    try:
        reduced = [
            collective.allreduce(np.asarray(leaf), op=op,
                                 group_name=group,
                                 compression=compression,
                                 ef_key=f"grad/{i}" if compression
                                 else None)
            for i, leaf in enumerate(flat)
        ]
    finally:
        _csan_exit(group, token, "allreduce_gradients")
    if rec is not None:
        # envelope over the whole gradient sync (per-leaf hop spans are
        # recorded inside collective.allreduce)
        rec.record("collective", "allreduce_gradients", t0_ns,
                   rec.clock() - t0_ns,
                   {"leaves": len(flat),
                    "compression": compression or "none"})
    return jax.tree_util.tree_unflatten(treedef, reduced)


def _sync_group(ctx) -> str:
    return getattr(ctx, "stage_group_name", None) or ctx.group_name


def _flatten_to_vector(tree):
    """Pytree → (flat f32 vector, treedef, leaf shapes, leaf dtypes)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrs = [np.asarray(l) for l in leaves]
    vec = (np.concatenate([a.ravel().astype(np.float32) for a in arrs])
           if arrs else np.zeros(0, np.float32))
    return vec, treedef, [a.shape for a in arrs], [a.dtype for a in arrs]


def _unflatten_from_vector(vec, treedef, shapes, dtypes):
    import jax
    leaves = []
    off = 0
    for shape, dtype in zip(shapes, dtypes):
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        leaves.append(np.asarray(vec[off:off + n], dtype=np.float32)
                      .reshape(shape).astype(dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, leaves)


class DDPOptimizer:
    """Replicated (plain DDP) optimizer step over the host collective:
    allreduce-mean the gradients, then every rank runs the full optax
    update. Same ``step(params, grads)`` surface as Zero1Optimizer so
    the train loop toggles between them with one flag."""

    def __init__(self, optimizer, params, *,
                 grad_compression: Optional[str] = None,
                 group_name: Optional[str] = None):
        self.optimizer = optimizer
        self.grad_compression = grad_compression
        self.group_name = group_name or _sync_group(get_context())
        self._opt_state = optimizer.init(params)

    def optimizer_state_bytes(self) -> int:
        import jax
        return sum(np.asarray(l).nbytes
                   for l in jax.tree_util.tree_leaves(self._opt_state))

    def step(self, params, grads):
        import jax
        import optax
        flat, treedef = jax.tree_util.tree_flatten(grads)
        token = _csan_enter(self.group_name, "ddp_step", len(flat),
                            self.grad_compression)
        try:
            reduced = [
                collective.allreduce(
                    np.asarray(leaf), op="mean",
                    group_name=self.group_name,
                    compression=self.grad_compression,
                    ef_key=f"ddp/{i}" if self.grad_compression else None)
                for i, leaf in enumerate(flat)
            ]
        finally:
            _csan_exit(self.group_name, token, "ddp_step")
        grads = jax.tree_util.tree_unflatten(treedef, reduced)
        updates, self._opt_state = self.optimizer.update(
            grads, self._opt_state, params)
        return optax.apply_updates(params, updates)


class Zero1Optimizer:
    """ZeRO-1 cross-replica sharded weight update (PAPERS.md: "Automatic
    Cross-Replica Sharding of Weight Update in Data-Parallel Training").

    Each step: ring reduce-scatter the FLAT gradient vector (each rank
    receives the exact f32 mean of its 1/world_size chunk — half the
    wire bytes of a full allreduce, quantizable with error feedback),
    run the optax update on that shard only, then ring all-gather the
    updated parameter shards. Optimizer state (adam m/v) exists ONLY
    for this rank's shard — per-replica optimizer memory is
    ~1/world_size of the replicated DDP equivalent.

    The update must be elementwise over the flat vector for shard-wise
    ≡ full-tree equivalence (adam/adamw/sgd/lamb-without-layer-norms
    qualify; anything needing per-leaf structure or cross-parameter
    norms does not).
    """

    def __init__(self, optimizer, params, *,
                 grad_compression: Optional[str] = None,
                 group_name: Optional[str] = None):
        self.optimizer = optimizer
        self.grad_compression = grad_compression
        self.group_name = group_name or _sync_group(get_context())
        self.world = collective.get_collective_group_size(self.group_name)
        self.rank = collective.get_rank(self.group_name)
        vec, _, _, _ = _flatten_to_vector(params)
        bounds = collective._chunk_bounds(vec.size, self.world)
        self._lo, self._hi = bounds[self.rank], bounds[self.rank + 1]
        self._opt_state = optimizer.init(vec[self._lo:self._hi])

    def optimizer_state_bytes(self) -> int:
        import jax
        return sum(np.asarray(l).nbytes
                   for l in jax.tree_util.tree_leaves(self._opt_state))

    def step(self, params, grads):
        import optax
        gvec, treedef, shapes, dtypes = _flatten_to_vector(grads)
        token = _csan_enter(self.group_name, "zero1_step", gvec.size,
                            self.grad_compression)
        try:
            grad_shard, off = collective.reduce_scatter_flat(
                gvec, op="mean", group_name=self.group_name,
                compression=self.grad_compression,
                ef_key="zero1/grads" if self.grad_compression else None)
            if off != self._lo or off + grad_shard.size != self._hi:
                raise ValueError(
                    "gradient pytree size changed under Zero1Optimizer "
                    f"(shard [{off}, {off + grad_shard.size}) vs "
                    f"optimizer state for [{self._lo}, {self._hi}))")
            pvec, _, _, _ = _flatten_to_vector(params)
            pshard = pvec[self._lo:self._hi]
            updates, self._opt_state = self.optimizer.update(
                np.asarray(grad_shard, dtype=np.float32),
                self._opt_state, pshard)
            new_shard = optax.apply_updates(pshard, updates)
            full = collective.allgather_flat(np.asarray(new_shard),
                                             group_name=self.group_name)
        finally:
            _csan_exit(self.group_name, token, "zero1_step")
        return _unflatten_from_vector(full, treedef, shapes, dtypes)


def make_optimizer(optimizer, params, *,
                   zero1: Optional[bool] = None,
                   grad_compression: Optional[str] = None,
                   group_name: Optional[str] = None):
    """Build the gradient-sync/update wrapper the run's flags ask for:
    ``ScalingConfig(zero1=True)`` → Zero1Optimizer, else DDPOptimizer;
    ``grad_compression`` defaults from the TrainContext the same way."""
    if zero1 is None or grad_compression is None or group_name is None:
        ctx = get_context()
        if zero1 is None:
            zero1 = getattr(ctx, "zero1", False)
        if grad_compression is None:
            grad_compression = getattr(ctx, "grad_compression", None)
        if group_name is None:
            group_name = _sync_group(ctx)
    cls = Zero1Optimizer if zero1 else DDPOptimizer
    return cls(optimizer, params, grad_compression=grad_compression,
               group_name=group_name)
