"""XLA/JAX profiler hooks for train workers.

Capability parity with the reference's profiler runtime-env plugins
(reference: python/ray/_private/runtime_env/nsight.py, rocprof_sys.py —
per-worker profiler attachment; SURVEY.md §5.1 names jax.profiler as
the TPU equivalent). Captures an XLA trace (HLO timelines, host events)
viewable in TensorBoard or Perfetto.

Usage inside a train loop::

    from ray_tpu.train.profiler import xla_profile
    with xla_profile("/tmp/prof", rank0_only=True):
        for step in range(k):
            train_step(...)

or step-windowed::

    prof = StepProfiler("/tmp/prof", start_step=10, num_steps=5)
    for step in range(n):
        prof.on_step(step)
        train_step(...)
    prof.close()
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Optional


def _rank() -> int:
    try:
        from ray_tpu.train.context import get_context
        return get_context().get_world_rank()
    except Exception:  # noqa: BLE001 — outside a train worker
        return 0


@contextmanager
def xla_profile(logdir: str, rank0_only: bool = True):
    """Capture a jax.profiler trace for the with-block. ``rank0_only``
    keeps multi-host runs to one trace (the usual want: every host's
    programs are the same SPMD program)."""
    if rank0_only and _rank() != 0:
        yield
        return
    import jax
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepProfiler:
    """Trace a window of steps [start_step, start_step + num_steps) —
    skipping warmup/compile steps, the standard profiling recipe."""

    def __init__(self, logdir: str, start_step: int = 2,
                 num_steps: int = 3, rank0_only: bool = True):
        self._logdir = logdir
        self._start = start_step
        self._stop = start_step + num_steps
        self._enabled = not (rank0_only and _rank() != 0)
        self._active = False

    def on_step(self, step: int) -> None:
        if not self._enabled:
            return
        import jax
        # range check, not equality: resumed loops start at arbitrary
        # step counters and must still hit the window
        if self._start <= step < self._stop and not self._active:
            os.makedirs(self._logdir, exist_ok=True)
            jax.profiler.start_trace(self._logdir)
            self._active = True
        elif step >= self._stop and self._active:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            import jax
            jax.profiler.stop_trace()
            self._active = False
