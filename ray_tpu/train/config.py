"""Train configuration objects.

reference: python/ray/train/v2/api/config.py (ScalingConfig, RunConfig,
FailureConfig, CheckpointConfig) and python/ray/air/config.py.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    # chips each worker should see (sets the TPU resource request;
    # reference: resources={"TPU": chips_per_host} per worker,
    # jax_trainer.py + tpu.py:283 visible-chips plumbing)
    tpu_chips_per_worker: int = 1
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    # chip topology for slice gang scheduling, e.g. "2x2x4" — with
    # accelerator_type set, the trainer reserves a whole slice via its
    # head resource and places one worker per slice host (reference:
    # reserve_tpu_slice, _private/accelerators/tpu.py:145)
    topology: Optional[str] = None
    # TPU generation, e.g. "TPU-V4" / "TPU-V5P"
    accelerator_type: Optional[str] = None
    placement_strategy: str = "SPREAD"

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker)
        if self.use_tpu:
            res.setdefault("TPU", float(self.tpu_chips_per_worker))
        res.setdefault("CPU", 1.0)
        return res


@dataclass
class FailureConfig:
    max_failures: int = 0  # worker-group rebuilds before giving up


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results")
        name = self.name or "train_run"
        path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)
        return path


@dataclass
class Result:
    """reference: python/ray/air/result.py"""
    metrics: Dict[str, Any]
    checkpoint: Optional[Any]
    path: str
    error: Optional[Exception] = None
    metrics_history: list = field(default_factory=list)
    # per-rank report lists [(metrics, checkpoint_path), ...]
    all_reports: list = field(default_factory=list)
