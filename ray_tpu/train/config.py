"""Train configuration objects.

reference: python/ray/train/v2/api/config.py (ScalingConfig, RunConfig,
FailureConfig, CheckpointConfig) and python/ray/air/config.py.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    # chips each worker should see (sets the TPU resource request;
    # reference: resources={"TPU": chips_per_host} per worker,
    # jax_trainer.py + tpu.py:283 visible-chips plumbing)
    tpu_chips_per_worker: int = 1
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    # chip topology for slice gang scheduling, e.g. "2x2x4" — with
    # accelerator_type set, the trainer reserves a whole slice via its
    # head resource and places one worker per slice host (reference:
    # reserve_tpu_slice, _private/accelerators/tpu.py:145)
    topology: Optional[str] = None
    # TPU generation, e.g. "TPU-V4" / "TPU-V5P"
    accelerator_type: Optional[str] = None
    placement_strategy: str = "SPREAD"
    # Elastic training: with min_workers set, a worker-group failure
    # rebuilds the gang at whatever size the cluster can still schedule
    # (>= min_workers) instead of failing, resuming from the last
    # checkpoint (reference: Resizing state + scaling policies,
    # train/v2/_internal/execution/controller/state.py:125).
    min_workers: Optional[int] = None
    scaling_policy: Optional[Any] = None
    # Gradient-sync cost levers (see train/collective.py): block-
    # quantized allreduce transport ("int8" | "fp8" | None) and the
    # ZeRO-1 cross-replica sharded optimizer update. Read off the
    # TrainContext by allreduce_gradients()/make_optimizer().
    grad_compression: Optional[str] = None
    zero1: bool = False
    # Pipeline parallelism (train/pipeline): stages per replica,
    # microbatch count, and schedule ("1f1b" | "gpipe"). num_workers
    # must be divisible by pipeline_stages; rank -> (stage = rank %
    # pipeline_stages, replica = rank // pipeline_stages), and DDP /
    # ZeRO-1 gradient sync runs within each stage's cross-replica
    # group instead of the whole-world group.
    pipeline_stages: int = 1
    microbatches: int = 1
    schedule: str = "1f1b"

    def resolved_scaling_policy(self):
        if self.scaling_policy is not None:
            return self.scaling_policy
        if self.min_workers is not None:
            return ElasticScalingPolicy(self.min_workers,
                                        self.worker_resources())
        return ScalingPolicy()

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker)
        if self.use_tpu:
            res.setdefault("TPU", float(self.tpu_chips_per_worker))
        res.setdefault("CPU", 1.0)
        return res


class ScalingPolicy:
    """Decides the gang size after a failure (reference:
    train/v2/_internal/execution/scaling_policy/ + the Resizing
    controller state, controller/state.py:125). Return None to stop
    retrying at a new size (the failure policy's max_failures still
    governs same-size retries)."""

    def world_size_after_failure(self, current_world: int,
                                 runtime) -> "int | None":
        return current_world  # fixed-size: retry at the same size


class ElasticScalingPolicy(ScalingPolicy):
    """Shrink to what the cluster can currently schedule, bounded below
    by ``min_workers`` — the elastic-training shape: lose a host, keep
    training smaller from the last checkpoint."""

    def __init__(self, min_workers: int, resources_per_worker=None):
        self.min_workers = min_workers
        self.resources_per_worker = dict(resources_per_worker or {})

    def world_size_after_failure(self, current_world: int,
                                 runtime) -> "int | None":
        # The dead gang's resource releases land asynchronously (worker
        # kills are observed by node IO threads); poll briefly and take
        # the best feasible size seen instead of aborting on a
        # transiently-empty cluster.
        import time as _time

        best = 0
        deadline = _time.monotonic() + 3.0
        while _time.monotonic() < deadline:
            available = runtime.available_resources()
            feasible = current_world
            for key, need in self.resources_per_worker.items():
                if need > 0:
                    feasible = min(feasible,
                                   int(available.get(key, 0.0) // need))
            best = max(best, min(feasible, current_world))
            if best >= current_world:
                break
            _time.sleep(0.1)
        if best < self.min_workers:
            return None
        return best


@dataclass
class FailureConfig:
    max_failures: int = 0  # worker-group rebuilds before giving up


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results")
        name = self.name or "train_run"
        path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)
        return path


@dataclass
class Result:
    """reference: python/ray/air/result.py"""
    metrics: Dict[str, Any]
    checkpoint: Optional[Any]
    path: str
    error: Optional[Exception] = None
    metrics_history: list = field(default_factory=list)
    # per-rank report lists [(metrics, checkpoint_path), ...]
    all_reports: list = field(default_factory=list)
