"""Round-7 gradient-sync tests: ring allreduce (vs the binomial tree,
bitwise), int8/fp8 block-quantized transport with error feedback,
ZeRO-1 sharded optimizer parity + memory, collective byte counters at
/metrics, and the jit-side quantized collectives on a forced 8-device
CPU backend (run in a subprocess so this process's JAX state stays
untouched — see the `multidevice` marker in pytest.ini)."""

import os
import subprocess
import sys
import textwrap
import urllib.request

import numpy as np
import pytest

import ray_tpu

WORLD = 4


def _spawn_group(n, group="qgrp"):
    @ray_tpu.remote(num_cpus=0)
    class SyncWorker:
        def __init__(self, rank, world):
            from ray_tpu.parallel import collective
            self.rank, self.world = rank, world
            self.group = group
            collective.init_collective_group(world, rank, group)

        def ring_vs_tree(self):
            from ray_tpu.parallel import collective
            # integer-valued floats: fp32 addition is exact, so any
            # mismatch is an algorithm bug, not roundoff
            x = np.arange(self.rank, self.rank + 5000, dtype=np.float32)
            ring = collective.allreduce(x, "sum", self.group,
                                        algorithm="ring")
            tree = collective.allreduce(x, "sum", self.group,
                                        algorithm="tree")
            mean = collective.allreduce(x, "mean", self.group,
                                        algorithm="ring")
            return (bool((ring == tree).all()),
                    bool(np.allclose(mean, tree / self.world)),
                    ring[:4].tolist())

        def quantized_error(self, compression):
            from ray_tpu.parallel import collective
            rng = np.random.default_rng(self.rank)
            g = rng.standard_normal(4097).astype(np.float32)
            exact = collective.allreduce(g, "sum", self.group)
            quant = collective.allreduce(g, "sum", self.group,
                                         compression=compression)
            rel = float(np.abs(quant - exact).max()
                        / np.abs(exact).max())
            return rel, quant[:4].tolist()

        def ef_convergence(self, rounds):
            """Repeatedly allreduce the SAME tensor; the time-averaged
            result converges to the truth only with error feedback —
            naive quantization repeats the same biased rounding every
            round."""
            from ray_tpu.parallel import collective
            rng = np.random.default_rng(self.rank)
            g = rng.standard_normal(2048).astype(np.float32)
            truth = collective.allreduce(g, "mean", self.group)
            naive = np.zeros_like(g)
            ef = np.zeros_like(g)
            for _ in range(rounds):
                naive += collective.allreduce(g, "mean", self.group,
                                              compression="int8")
                ef += collective.allreduce(g, "mean", self.group,
                                           compression="int8",
                                           ef_key="efleaf")
            naive_bias = float(np.abs(naive / rounds - truth).max())
            ef_bias = float(np.abs(ef / rounds - truth).max())
            return naive_bias, ef_bias

        def zero1_vs_ddp(self, steps):
            """Same grads through Zero1Optimizer and DDPOptimizer must
            land on the same params; ZeRO-1's adam state is ~1/world of
            DDP's."""
            import jax
            import optax
            from ray_tpu.train.collective import (DDPOptimizer,
                                                  Zero1Optimizer)
            params = {
                "w": np.linspace(-1.0, 1.0, 1003,
                                 dtype=np.float32).reshape(17, 59),
                "b": np.zeros(59, dtype=np.float32),
            }
            z1 = Zero1Optimizer(optax.adam(0.05), params,
                                group_name=self.group)
            ddp = DDPOptimizer(optax.adam(0.05), params,
                               group_name=self.group)
            p_z1 = jax.tree_util.tree_map(np.array, params)
            p_ddp = jax.tree_util.tree_map(np.array, params)
            rng = np.random.default_rng(100 + self.rank)
            for _ in range(steps):
                grads = {
                    "w": rng.standard_normal((17, 59)).astype(np.float32),
                    "b": rng.standard_normal(59).astype(np.float32),
                }
                p_z1 = z1.step(p_z1, grads)
                p_ddp = ddp.step(p_ddp, grads)
            diff = max(
                float(np.abs(np.asarray(p_z1[k])
                             - np.asarray(p_ddp[k])).max())
                for k in params)
            return (diff, z1.optimizer_state_bytes(),
                    ddp.optimizer_state_bytes())

        def bytes_for(self, compression):
            from ray_tpu.parallel import collective
            g = np.ones(65536, dtype=np.float32)
            collective.allreduce(g, "sum", self.group,
                                 compression=compression)
            return True

        def roundtrip_flat(self):
            from ray_tpu.parallel import collective
            g = np.arange(1025, dtype=np.float32) * (self.rank + 1)
            truth = collective.allreduce(g, "mean", self.group)
            shard, off = collective.reduce_scatter_flat(
                g, "mean", self.group)
            full = collective.allgather_flat(shard, self.group)
            return (float(np.abs(full - truth).max()), int(off),
                    int(shard.size))

        def destroy(self):
            from ray_tpu.parallel import collective
            collective.destroy_collective_group(self.group)

    return [SyncWorker.remote(i, n) for i in range(n)]


def test_ring_allreduce_matches_tree_bitwise(ray_start_regular):
    workers = _spawn_group(WORLD)
    out = ray_tpu.get([w.ring_vs_tree.remote() for w in workers])
    assert all(bitwise for bitwise, _, _ in out)
    assert all(mean_ok for _, mean_ok, _ in out)
    # every rank returns the identical reduced tensor
    assert len({tuple(head) for _, _, head in out}) == 1
    ray_tpu.get([w.destroy.remote() for w in workers])


def test_odd_world_ring(ray_start_regular):
    workers = _spawn_group(3)
    out = ray_tpu.get([w.ring_vs_tree.remote() for w in workers])
    assert all(bitwise for bitwise, _, _ in out)
    ray_tpu.get([w.destroy.remote() for w in workers])


@pytest.mark.parametrize("compression,bound", [("int8", 0.02),
                                               ("fp8", 0.15)])
def test_quantized_allreduce_error_bounded(ray_start_regular,
                                           compression, bound):
    workers = _spawn_group(WORLD)
    out = ray_tpu.get(
        [w.quantized_error.remote(compression) for w in workers])
    for rel, _head in out:
        assert rel < bound, f"{compression} rel error {rel} > {bound}"
    # ranks decode the same wire bytes -> identical outputs
    assert len({tuple(head) for _, head in out}) == 1
    ray_tpu.get([w.destroy.remote() for w in workers])


@pytest.mark.watchdog(300)
def test_error_feedback_converges_where_naive_drifts(ray_start_regular):
    workers = _spawn_group(WORLD)
    out = ray_tpu.get([w.ef_convergence.remote(50) for w in workers])
    for naive_bias, ef_bias in out:
        # naive quantization repeats the same deterministic rounding ->
        # constant bias; EF compensates it away round over round
        assert ef_bias < naive_bias / 3
        assert ef_bias < 2e-3
    ray_tpu.get([w.destroy.remote() for w in workers])


@pytest.mark.watchdog(300)
def test_zero1_matches_ddp_and_shrinks_opt_state(ray_start_regular):
    workers = _spawn_group(WORLD)
    out = ray_tpu.get([w.zero1_vs_ddp.remote(5) for w in workers])
    n_params = 1003 + 59
    for diff, z1_bytes, ddp_bytes in out:
        assert diff < 1e-5, f"zero1 diverged from ddp by {diff}"
        # adam keeps mu+nu (f32): DDP holds them for every param,
        # ZeRO-1 only for this rank's 1/world flat shard
        assert ddp_bytes >= 2 * 4 * n_params
        ratio = ddp_bytes / max(z1_bytes, 1)
        assert WORLD * 0.7 < ratio < WORLD * 1.4, (
            f"opt-state shrink {ratio} not ~{WORLD}x")
    ray_tpu.get([w.destroy.remote() for w in workers])


def test_reduce_scatter_allgather_flat_roundtrip(ray_start_regular):
    workers = _spawn_group(WORLD)
    out = ray_tpu.get([w.roundtrip_flat.remote() for w in workers])
    offs = sorted((off, size) for _, off, size in out)
    assert offs[0][0] == 0
    assert sum(size for _, size in offs) == 1025
    for err, _, _ in out:
        assert err < 1e-6
    ray_tpu.get([w.destroy.remote() for w in workers])


def test_kv_wait_timeout_names_missing_rank(ray_start_regular):
    """A rank whose peer never shows up gets a timeout that says WHICH
    rank it was waiting for (satellite: backoff _kv_wait with a hard
    deadline and a named-rank error)."""
    from ray_tpu.exceptions import GetTimeoutError
    from ray_tpu.parallel import collective
    collective.init_collective_group(2, 0, "lonely")
    try:
        with pytest.raises(GetTimeoutError) as exc:
            collective.allreduce(np.ones(4, np.float32), "sum", "lonely",
                                 timeout=1.5)
        msg = str(exc.value)
        assert "rank 1" in msg
        assert "lonely" in msg
    finally:
        collective._groups.pop("lonely", None)


def test_ef_residual_reset_and_inspection(ray_start_regular):
    from ray_tpu.parallel import collective
    collective.init_collective_group(1, 0, "solo")
    try:
        g = np.linspace(-1, 1, 512).astype(np.float32)
        collective.allreduce(g, "sum", "solo", compression="int8",
                             ef_key="leaf")
        # world==1 short-circuits before quantization: no residual
        assert collective.error_feedback_residual("solo", "leaf") is None
        collective.reset_error_feedback("solo")
    finally:
        collective._groups.pop("solo", None)


@pytest.fixture
def metrics_runtime():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    rt = ray_tpu.init(num_cpus=4, include_dashboard=True)
    yield rt
    ray_tpu.shutdown()


def _scrape_text(url):
    with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
        return resp.read().decode()


def _bytes_series_sum(body, dtype):
    total = 0.0
    for line in body.splitlines():
        if (line.startswith("ray_tpu_train_collective_bytes_total")
                and f'dtype="{dtype}"' in line
                and 'op="allreduce"' in line):
            total += float(line.rsplit(" ", 1)[1])
    return total


@pytest.mark.watchdog(300)
def test_collective_bytes_counter_and_compression_ratio(metrics_runtime):
    """The GL006-named transport metrics appear at /metrics, and the
    byte counters prove int8 moves >=3.5x fewer payload bytes than fp32
    for the same gradient tensor (acceptance criterion). Deltas, not
    absolutes: the driver-side registry outlives ray_tpu.shutdown(), so
    earlier tests' collectives are already in the counters."""
    workers = _spawn_group(WORLD, group="mgrp")
    base = _scrape_text(metrics_runtime.dashboard_url)
    ray_tpu.get([w.bytes_for.remote(None) for w in workers])
    mid = _scrape_text(metrics_runtime.dashboard_url)
    ray_tpu.get([w.bytes_for.remote("int8") for w in workers])
    ray_tpu.get([w.destroy.remote() for w in workers])
    body = _scrape_text(metrics_runtime.dashboard_url)

    fp32_bytes = (_bytes_series_sum(mid, "float32")
                  - _bytes_series_sum(base, "float32"))
    int8_bytes = (_bytes_series_sum(body, "int8")
                  - _bytes_series_sum(mid, "int8"))
    assert fp32_bytes > 0, f"no fp32 byte series in:\n{body[:2000]}"
    assert int8_bytes > 0
    assert fp32_bytes / int8_bytes >= 3.5, (
        f"int8 only moved {fp32_bytes / int8_bytes:.2f}x fewer bytes")
    # the ratio gauge is exported and agrees
    gauges = [
        float(line.rsplit(" ", 1)[1])
        for line in body.splitlines()
        if line.startswith("ray_tpu_train_collective_compression_ratio")
        and 'dtype="int8"' in line
    ]
    assert gauges and max(gauges) >= 3.5


@pytest.mark.watchdog(300)
def test_trainer_zero1_flags_plumbed(ray_start_regular, tmp_path):
    """ScalingConfig(grad_compression=..., zero1=...) reaches the
    TrainContext; make_optimizer picks Zero1Optimizer and synced
    updates keep ranks identical."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def train_loop(config):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax
        import ray_tpu.train as train
        from ray_tpu.train.collective import (Zero1Optimizer,
                                              make_optimizer)

        ctx = train.get_context()
        assert ctx.grad_compression == "int8"
        assert ctx.zero1 is True
        params = {"w": np.linspace(-1, 1, 600,
                                   dtype=np.float32).reshape(20, 30)}
        stepper = make_optimizer(optax.adam(0.05), params)
        assert isinstance(stepper, Zero1Optimizer)
        rng = np.random.default_rng(ctx.world_rank)
        for _ in range(3):
            grads = {"w": rng.standard_normal((20, 30))
                     .astype(np.float32)}
            params = stepper.step(params, grads)
        checksum = float(np.sum(np.asarray(params["w"])))
        train.report({"checksum": checksum,
                      "opt_bytes": stepper.optimizer_state_bytes()})

    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(num_workers=2,
                                     grad_compression="int8",
                                     zero1=True),
        run_config=RunConfig(name="zero1_test",
                             storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    checksums = {
        reports[-1][0]["checksum"] for reports in result.all_reports}
    assert len(checksums) == 1, "ranks diverged under zero1"


_MULTIDEVICE_SCRIPT = textwrap.dedent("""
    import numpy as np
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from ray_tpu.parallel import collective as C

    assert jax.device_count() == 8, jax.devices()
    mesh = Mesh(np.array(jax.devices()), ("d",))
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((8, 1000)).astype(np.float32)
    truth = xs.sum(0)

    def run(fn, *args):
        specs = tuple(P("d") for _ in args)
        return np.asarray(shard_map(fn, mesh=mesh, in_specs=specs,
                                    out_specs=P("d"),
                                    check_rep=False)(*args))

    out = run(lambda x: C.quantized_psum(x, "d", dtype="int8"), xs)
    rel = np.abs(out[0] - truth).max() / np.abs(truth).max()
    assert rel < 0.02, f"int8 psum rel {rel}"
    assert (out == out[0]).all(), "replicas disagree"

    out8 = run(lambda x: C.quantized_psum(x, "d", dtype="fp8"), xs)
    rel8 = np.abs(out8[0] - truth).max() / np.abs(truth).max()
    assert rel8 < 0.1, f"fp8 psum rel {rel8}"

    # the error-feedback pair returns the residual of THIS round
    def ef(x, e):
        return C.quantized_psum(x, "d", dtype="int8", error=e)[1]
    res = run(ef, xs, np.zeros_like(xs))
    assert res.shape == xs.shape
    assert np.abs(res).max() > 0  # quantization error is nonzero

    # quantized reduce-scatter: shards concatenate to the sum
    ys = rng.standard_normal((8, 4096)).astype(np.float32)
    t2 = ys.sum(0)
    sh = run(lambda y: C.quantized_reduce_scatter(
        y.reshape(-1), "d", dtype="int8"), ys).reshape(-1)
    rel2 = np.abs(sh - t2).max() / np.abs(t2).max()
    assert rel2 < 0.02, f"qrs rel {rel2}"
    print("MULTIDEVICE_OK")
""")


@pytest.mark.multidevice
@pytest.mark.watchdog(300)
def test_jit_quantized_collectives_eight_devices():
    """jit-side quantized_psum / quantized_reduce_scatter numerics on a
    forced 8-device CPU backend — in a SUBPROCESS (cpu_mesh_env(8)) so
    the tier-1 process's own JAX backend is never reconfigured."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from __graft_entry__ import cpu_mesh_env
    proc = subprocess.run(
        [sys.executable, "-c", _MULTIDEVICE_SCRIPT],
        env=cpu_mesh_env(8), capture_output=True, text=True,
        timeout=240,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, (proc.stdout[-2000:]
                                  + proc.stderr[-2000:])
    assert "MULTIDEVICE_OK" in proc.stdout
