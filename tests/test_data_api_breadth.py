"""Dataset API breadth: splits, block-order shuffle, refs exports,
write_numpy/write_images, input_files, names/types, explain
(reference: python/ray/data/tests/test_split.py, test_numpy.py,
test_image.py, test_consumption.py)."""

import glob
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module", autouse=True)
def _rt():
    rt = ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


# ------------------------------------------------------------- splits

def test_split_at_indices():
    parts = rd.range(10).split_at_indices([3, 7])
    assert [p.count() for p in parts] == [3, 4, 3]
    assert [r["id"] for r in parts[0].take_all()] == [0, 1, 2]
    assert [r["id"] for r in parts[1].take_all()] == [3, 4, 5, 6]
    assert [r["id"] for r in parts[2].take_all()] == [7, 8, 9]


def test_split_at_indices_edges():
    parts = rd.range(5).split_at_indices([0, 5])
    assert [p.count() for p in parts] == [0, 5, 0]
    with pytest.raises(ValueError):
        rd.range(5).split_at_indices([3, 1])
    with pytest.raises(ValueError):
        rd.range(5).split_at_indices([-1])


def test_split_proportionately():
    parts = rd.range(10).split_proportionately([0.2, 0.3])
    assert [p.count() for p in parts] == [2, 3, 5]
    with pytest.raises(ValueError):
        rd.range(10).split_proportionately([0.5, 0.6])
    with pytest.raises(ValueError):
        rd.range(10).split_proportionately([])


def test_train_test_split_fraction_and_count():
    train, test = rd.range(10).train_test_split(0.25)
    assert train.count() == 7 and test.count() == 3
    # int form: exact test rows off the tail
    train, test = rd.range(10).train_test_split(4)
    assert train.count() == 6 and test.count() == 4
    assert [r["id"] for r in test.take_all()] == [6, 7, 8, 9]
    # shuffled split keeps the partition sizes but mixes rows
    train, test = rd.range(100).train_test_split(0.5, shuffle=True,
                                                 seed=7)
    assert train.count() == 50 and test.count() == 50
    assert sorted(r["id"] for r in train.take_all()) != list(range(50))


def test_randomize_block_order():
    ds = rd.range(100, parallelism=10)
    shuffled = ds.randomize_block_order(seed=3)
    assert shuffled.count() == 100
    # rows within blocks keep order; block order changes for some seed
    ids = [r["id"] for r in shuffled.take_all()]
    assert sorted(ids) == list(range(100))
    assert ids != list(range(100))


# -------------------------------------------------------- refs exports

def test_to_pandas_refs():
    refs = rd.range(20, parallelism=4).to_pandas_refs()
    dfs = ray_tpu.get(refs)
    assert sum(len(df) for df in dfs) == 20
    assert all(list(df.columns) == ["id"] for df in dfs)


def test_to_numpy_refs():
    refs = rd.range(12, parallelism=3).to_numpy_refs(column="id")
    arrs = ray_tpu.get(refs)
    assert sorted(np.concatenate(arrs).tolist()) == list(range(12))
    # dict form without a column
    refs = rd.range(4, parallelism=1).to_numpy_refs()
    (d,) = ray_tpu.get(refs)
    assert set(d) == {"id"}


# ------------------------------------------------- file sinks + sources

def test_write_read_numpy(tmp_path):
    path = str(tmp_path / "np_out")
    rd.range_tensor(8, shape=(2, 2), parallelism=2).write_numpy(
        path, column="data")
    files = sorted(glob.glob(os.path.join(path, "*.npy")))
    assert len(files) == 2
    total = sum(np.load(f).shape[0] for f in files)
    assert total == 8
    assert np.load(files[0]).shape[1:] == (2, 2)


def test_write_images_roundtrip(tmp_path):
    path = str(tmp_path / "imgs")
    imgs = np.random.randint(0, 255, size=(5, 8, 8, 3), dtype=np.uint8)
    ds = rd.from_numpy(imgs)
    ds.map_batches(lambda b: {"image": b["data"]},
                   batch_format="numpy").write_images(path)
    files = sorted(glob.glob(os.path.join(path, "*.png")))
    assert len(files) == 5
    back = rd.read_images(files).take_all()
    assert len(back) == 5
    first = np.asarray(back[0]["image"])
    assert first.shape == (8, 8, 3)
    # PNG is lossless: pixel payload must round-trip exactly. Row order
    # across files is lexical (expand_paths sorts), but the write stem
    # is random — compare as multisets of flattened images.
    want = {imgs[i].tobytes() for i in range(5)}
    got = {np.asarray(r["image"]).astype(np.uint8).tobytes()
           for r in back}
    assert got == want


def test_input_files(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    for i in range(3):
        pq.write_table(pa.table({"x": [i]}),
                       str(tmp_path / f"part-{i}.parquet"))
    ds = rd.read_parquet(str(tmp_path))
    files = ds.input_files()
    assert len(files) == 3
    assert all(f.endswith(".parquet") for f in files)
    # survives downstream transforms
    assert len(ds.map(lambda r: r).input_files()) == 3
    # non-file datasets report none
    assert rd.range(3).input_files() == []


# ----------------------------------------------------- schema + plan

def test_names_types_and_name():
    ds = rd.from_items([{"a": 1, "b": "x"}])
    assert ds.names() == ["a", "b"]
    types = ds.types()
    assert len(types) == 2
    assert ds.name is None
    ds.set_name("my_ds")
    assert ds.name == "my_ds"


def test_explain_renders_plan(capsys):
    ds = rd.range(10).map(lambda r: r).limit(5)
    text = ds.explain()
    out = capsys.readouterr().out
    assert text in out
    assert "Limit" in text or "limit" in text.lower()


# ------------------------------------------------- random access

def test_random_access_dataset():
    ds = rd.from_items([{"id": i * 2, "val": f"v{i}"} for i in range(50)],
                       parallelism=5)
    rad = ds.to_random_access_dataset("id", num_workers=2)
    # hits
    assert ray_tpu.get(rad.get_async(0))["val"] == "v0"
    assert ray_tpu.get(rad.get_async(98))["val"] == "v49"
    assert ray_tpu.get(rad.get_async(48))["val"] == "v24"
    # misses: odd keys, out of range
    assert ray_tpu.get(rad.get_async(49)) is None
    assert ray_tpu.get(rad.get_async(-2)) is None
    assert ray_tpu.get(rad.get_async(1000)) is None
    # batched, order-preserving, with misses interleaved
    got = rad.multiget([4, 5, 96, -1, 0])
    assert [r["val"] if r else None for r in got] == \
        ["v2", None, "v48", None, "v0"]
    s = rad.stats()
    assert "workers=2" in s and "gets" in s


def test_random_access_unsorted_input():
    # input arrives unsorted; the index must sort it first
    import random
    items = [{"k": i, "x": i * i} for i in range(30)]
    random.Random(7).shuffle(items)
    rad = rd.from_items(items, parallelism=4).to_random_access_dataset(
        "k", num_workers=3)
    assert ray_tpu.get(rad.get_async(17))["x"] == 289
    assert rad.multiget([0, 29])[1]["x"] == 841
