"""Autoscaler tests (reference: autoscaler unit tests driving
StandardAutoscaler.update with a fake provider,
python/ray/tests/test_autoscaler.py + FakeMultiNodeProvider)."""

import time
import urllib.parse

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalerConfig, FakeMultiNodeProvider, NodeTypeConfig,
    StandardAutoscaler)


@pytest.fixture
def small_head():
    rt = ray_tpu.init(num_cpus=1)
    yield rt
    ray_tpu.shutdown()


def _autoscaler(rt, **cfg_kw):
    config = AutoscalerConfig(**cfg_kw)
    provider = FakeMultiNodeProvider(rt)
    return StandardAutoscaler(config, provider, rt), provider


def _wait_demand(rt, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if rt.resource_demand():
            return True
        time.sleep(0.02)
    return False


def test_scale_up_on_backlog(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt, node_types=[NodeTypeConfig("cpu2", {"CPU": 2.0},
                                       max_workers=4)])

    @ray_tpu.remote(num_cpus=2)
    def work(x):
        time.sleep(0.2)
        return x + 1

    refs = [work.remote(i) for i in range(3)]
    assert _wait_demand(rt)
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) >= 1
    # more rounds may be needed while tasks queue
    for _ in range(5):
        autoscaler.update()
        time.sleep(0.05)
    assert ray_tpu.get(refs, timeout=60) == [1, 2, 3]


def test_infeasible_tpu_demand_launches_tpu_node(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt, node_types=[
            NodeTypeConfig("cpu2", {"CPU": 2.0}, max_workers=2),
            NodeTypeConfig("v5p-host", {"CPU": 8.0, "TPU": 4.0},
                           max_workers=2,
                           labels={"tpu-pod-type": "v5p-8"}),
        ])

    @ray_tpu.remote(resources={"TPU": 4})
    def on_tpu():
        return "ok"

    ref = on_tpu.remote()
    assert _wait_demand(rt)
    launched = autoscaler.update()
    assert launched.get("v5p-host") == 1
    assert ray_tpu.get(ref, timeout=60) == "ok"


def test_min_workers_floor(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt, node_types=[NodeTypeConfig("cpu1", {"CPU": 1.0},
                                       min_workers=2, max_workers=4)])
    autoscaler.update()
    nodes = provider.non_terminated_nodes()
    assert sum(1 for t in nodes.values() if t == "cpu1") == 2


def test_max_workers_cap(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt, node_types=[NodeTypeConfig("cpu2", {"CPU": 2.0},
                                       max_workers=2)])

    @ray_tpu.remote(num_cpus=2)
    def work():
        time.sleep(0.5)

    refs = [work.remote() for _ in range(8)]
    assert _wait_demand(rt)
    for _ in range(4):
        autoscaler.update()
    nodes = provider.non_terminated_nodes()
    assert sum(1 for t in nodes.values() if t == "cpu2") <= 2
    ray_tpu.get(refs, timeout=60)


def test_idle_nodes_terminated(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt,
        node_types=[NodeTypeConfig("cpu2", {"CPU": 2.0}, max_workers=2)],
        idle_timeout_s=0.1)

    @ray_tpu.remote(num_cpus=2)
    def work():
        return 1

    ref = work.remote()
    assert _wait_demand(rt)
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 1
    assert ray_tpu.get(ref, timeout=60) == 1
    time.sleep(0.3)
    autoscaler.update()  # marks idle
    time.sleep(0.3)
    autoscaler.update()  # past idle_timeout -> terminate
    assert len(provider.non_terminated_nodes()) == 0


def test_background_loop(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt,
        node_types=[NodeTypeConfig("cpu1", {"CPU": 1.0}, min_workers=1,
                                   max_workers=2)],
        update_interval_s=0.05)
    autoscaler.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if provider.non_terminated_nodes():
                break
            time.sleep(0.02)
        assert provider.non_terminated_nodes()
    finally:
        autoscaler.stop()


# --- GCE TPU slice provider + gang (placement-group) provisioning -------

class _FakeTpuApi:
    """Hermetic stand-in for tpu.googleapis.com: records requests and
    'boots' slice hosts into the live runtime on create, the way real
    hosts join via their startup script."""

    def __init__(self, rt, hosts_per_slice=2,
                 host_resources=None):
        self.rt = rt
        self.hosts_per_slice = hosts_per_slice
        self.host_resources = host_resources or {"CPU": 1.0, "TPU": 4.0}
        self.requests = []
        self.nodes = {}          # provider_id -> node_type name
        self.runtime_nodes = {}  # provider_id -> [NodeID]
        self.fail_next_list = False
        self.page_size = 0       # >0: serve GETs in pages w/ tokens

    def __call__(self, method, url, body):
        from ray_tpu.autoscaler.gce import (
            NODE_TYPE_LABEL, PROVIDER_ID_LABEL)
        self.requests.append((method, url))
        if method == "POST":
            pid = url.rsplit("nodeId=", 1)[-1]
            node_type = body["labels"]["ray-tpu-node-type"]
            assert "startup-script" in body["metadata"]
            assert "ray-tpu start --address" in body["metadata"]["startup-script"]
            self.nodes[pid] = node_type
            joined = []
            for _ in range(self.hosts_per_slice):
                nid = self.rt.add_node(
                    resources=dict(self.host_resources),
                    labels={PROVIDER_ID_LABEL: pid,
                            NODE_TYPE_LABEL: node_type})
                joined.append(nid)
            self.runtime_nodes[pid] = joined
            return 200, {"name": f"operations/op-{pid}"}
        if method == "DELETE":
            pid = url.rsplit("/", 1)[-1]
            self.nodes.pop(pid, None)
            for nid in self.runtime_nodes.pop(pid, []):
                self.rt.remove_node(nid)
            return 200, {}
        if method == "GET":
            if self.fail_next_list:
                self.fail_next_list = False
                return 503, {"error": "backend unavailable"}
            entries = [
                {"name": f"projects/p/locations/z/nodes/{pid}",
                 "state": "READY",
                 "labels": {"ray-tpu-node-type": t}}
                for pid, t in self.nodes.items()]
            if not self.page_size:
                return 200, {"nodes": entries}
            # Paged listing: opaque token = start index (with reserved
            # chars, so the client must URL-encode it).
            start = 0
            if "pageToken=" in url:
                token = urllib.parse.unquote(
                    url.rsplit("pageToken=", 1)[-1])
                assert token.startswith("idx+&/")
                start = int(token[len("idx+&/"):])
            page = entries[start:start + self.page_size]
            out = {"nodes": page}
            if start + self.page_size < len(entries):
                out["nextPageToken"] = f"idx+&/{start + self.page_size}"
            return 200, out
        raise AssertionError(f"unexpected {method} {url}")


def test_pending_strict_spread_pg_satisfied_by_slice_launch(small_head):
    """VERDICT round-2 item 4 done-criterion: a queued STRICT_SPREAD
    slice PG is satisfied by the autoscaler 'launching' mocked TPU
    hosts through the GCE slice provider."""
    from ray_tpu.autoscaler import GceTpuSliceNodeProvider
    from ray_tpu.util.placement_group import (
        placement_group, remove_placement_group)

    rt = small_head
    fake_api = _FakeTpuApi(rt, hosts_per_slice=2)
    provider = GceTpuSliceNodeProvider(
        "proj", "us-central2-b", "head:6379", runtime=rt,
        http_request=fake_api, name_prefix="ray-tpu")
    slice_type = NodeTypeConfig(
        "v5e-slice", {"CPU": 1.0, "TPU": 4.0}, max_workers=4, count=2,
        provider_params={"accelerator_type": "v5litepod-8"})
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types=[slice_type], idle_timeout_s=0.0),
        provider, rt)

    pg = placement_group([{"TPU": 4.0}] * 2, strategy="STRICT_SPREAD")
    assert not pg.ready(timeout=0.2)  # queued: no TPU hosts exist

    autoscaler.update()
    # One slice (2 hosts) launched, gang reserved on distinct hosts.
    assert len(fake_api.nodes) == 1
    assert pg.ready(timeout=5)
    assert len(set(n.hex() for n in pg.bundle_node_ids())) == 2

    # Reserved (but task-idle) slice must NOT be culled even with a
    # zero idle timeout, and repeated rounds must not re-launch.
    autoscaler.update()
    autoscaler.update()
    assert len(fake_api.nodes) == 1

    # Releasing the gang makes the slice idle: it is terminated.
    remove_placement_group(pg)
    autoscaler.update()
    autoscaler.update()
    assert len(fake_api.nodes) == 0


class _FakeKubeApi:
    """Hermetic Kubernetes API server for a KubeRay RayCluster: serves
    GET/PATCH on the CR, materializes worker-group replicas as pods
    (numOfHosts pods per replica, replicaIndex labels — the GKE TPU
    webhook convention), boots their hosts into the live runtime, and
    honors workersToDelete on scale-down."""

    def __init__(self, rt, groups, hosts_per_replica=2,
                 host_resources=None):
        from ray_tpu.autoscaler.gke import CRD_PATH
        self.rt = rt
        self.hosts_per_replica = hosts_per_replica
        self.host_resources = host_resources or {"CPU": 1.0, "TPU": 4.0}
        self.requests = []
        self.crd_path = CRD_PATH.format(ns="ray", name="tpu-cluster")
        self.cluster = {"spec": {"workerGroupSpecs": [
            {"groupName": g, "replicas": 0,
             "numOfHosts": hosts_per_replica,
             "scaleStrategy": {"workersToDelete": []}}
            for g in groups]}}
        self.pods = {}           # pod name -> pod dict
        self.runtime_nodes = {}  # provider id -> [NodeID]
        self.page_size = 0

    def _reconcile(self):
        from ray_tpu.autoscaler.gce import (
            NODE_TYPE_LABEL, PROVIDER_ID_LABEL)
        for spec in self.cluster["spec"]["workerGroupSpecs"]:
            group = spec["groupName"]
            doomed = set(spec["scaleStrategy"].get("workersToDelete",
                                                   ()))
            for name in list(self.pods):
                if name in doomed:
                    pod = self.pods.pop(name)
                    pid = pod["metadata"]["labels"]["replicaIndex"]
                    for nid in self.runtime_nodes.pop(pid, []):
                        self.rt.remove_node(nid)
            spec["scaleStrategy"]["workersToDelete"] = []
            live = {p["metadata"]["labels"]["replicaIndex"]
                    for p in self.pods.values()
                    if p["metadata"]["labels"]["ray.io/group"] == group}
            idx = 0
            while len(live) < spec["replicas"]:
                pid = f"{group}-{idx}"
                if pid in live:
                    idx += 1
                    continue
                live.add(pid)
                joined = []
                for h in range(self.hosts_per_replica):
                    name = f"{pid}-host-{h}"
                    self.pods[name] = {
                        "metadata": {"name": name, "labels": {
                            "ray.io/cluster": "tpu-cluster",
                            "ray.io/group": group,
                            "replicaIndex": pid}},
                        "status": {"phase": "Running"}}
                    joined.append(self.rt.add_node(
                        resources=dict(self.host_resources),
                        labels={PROVIDER_ID_LABEL: pid,
                                NODE_TYPE_LABEL: group}))
                self.runtime_nodes[pid] = joined

    def __call__(self, method, path, body):
        self.requests.append((method, path))
        if path.startswith(self.crd_path):
            if method == "GET":
                return 200, self.cluster
            if method == "PATCH":
                for op in body:
                    parts = op["path"].strip("/").split("/")
                    target = self.cluster
                    for p in parts[:-1]:
                        target = (target[int(p)]
                                  if p.isdigit() else target[p])
                    target[parts[-1]] = op["value"]
                self._reconcile()
                return 200, self.cluster
        if method == "GET" and "/pods" in path:
            items = sorted(self.pods.values(),
                           key=lambda p: p["metadata"]["name"])
            return 200, {"items": items, "metadata": {}}
        raise AssertionError(f"unexpected {method} {path}")


def test_gke_kuberay_gang_provisioning(small_head):
    """VERDICT r3 item 6 done-criterion: a queued STRICT_SPREAD slice
    PG drives the GKE provider to scale a RayCluster worker group
    (replicas PATCH -> pods -> hosts join), and idle scale-down removes
    exact replicas via workersToDelete (reference:
    autoscaler/_private/kuberay/node_provider.py)."""
    from ray_tpu.autoscaler import GkeKubeRayNodeProvider
    from ray_tpu.util.placement_group import (
        placement_group, remove_placement_group)

    rt = small_head
    fake = _FakeKubeApi(rt, groups=["v5e-slice"], hosts_per_replica=2)
    provider = GkeKubeRayNodeProvider(
        "ray", "tpu-cluster", runtime=rt, http_request=fake)
    slice_type = NodeTypeConfig(
        "v5e-slice", {"CPU": 1.0, "TPU": 4.0}, max_workers=4, count=2)
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types=[slice_type], idle_timeout_s=0.0),
        provider, rt)

    pg = placement_group([{"TPU": 4.0}] * 2, strategy="STRICT_SPREAD")
    assert not pg.ready(timeout=0.2)  # queued: no TPU hosts exist

    autoscaler.update()
    # one replica (= slice of 2 hosts) launched via CR PATCH
    spec = fake.cluster["spec"]["workerGroupSpecs"][0]
    assert spec["replicas"] == 1
    assert pg.ready(timeout=5)
    assert len(set(n.hex() for n in pg.bundle_node_ids())) == 2
    assert provider.non_terminated_nodes() == {"v5e-slice-0":
                                               "v5e-slice"}
    assert len(provider.runtime_node_ids("v5e-slice-0")) == 2

    # reserved slice is never idle-culled; repeated rounds don't
    # relaunch
    autoscaler.update()
    autoscaler.update()
    assert spec["replicas"] == 1

    # release the PG: the now-idle slice scales down through
    # workersToDelete and its hosts leave the runtime
    remove_placement_group(pg)
    deadline = time.time() + 10
    while spec["replicas"] > 0 and time.time() < deadline:
        autoscaler.update()
        time.sleep(0.05)
    assert spec["replicas"] == 0
    assert provider.non_terminated_nodes() == {}
    assert provider.runtime_node_ids("v5e-slice-0") == []


def test_gce_provider_api_shapes(small_head):
    """Provider unit contract: URLs, accelerator type plumb-through,
    list filtering, and the local-view fallback on an API hiccup."""
    from ray_tpu.autoscaler import GceTpuSliceNodeProvider

    rt = small_head
    fake_api = _FakeTpuApi(rt, hosts_per_slice=1)
    provider = GceTpuSliceNodeProvider(
        "proj", "us-central2-b", "head:6379", runtime=rt,
        http_request=fake_api)
    nt = NodeTypeConfig("v5p-host", {"CPU": 1.0, "TPU": 4.0},
                        provider_params={"accelerator_type": "v5p-8"})
    pid = provider.create_node(nt)
    method, url = fake_api.requests[0]
    assert method == "POST"
    assert url.startswith("https://tpu.googleapis.com/v2/projects/proj"
                          "/locations/us-central2-b/nodes?nodeId=")
    assert provider.non_terminated_nodes() == {pid: "v5p-host"}
    assert len(provider.runtime_node_ids(pid)) == 1

    # API hiccup on list: fall back to the local view, no relaunch.
    fake_api.fail_next_list = True
    assert provider.non_terminated_nodes() == {pid: "v5p-host"}

    provider.terminate_node(pid)
    assert provider.non_terminated_nodes() == {}
    assert provider.runtime_node_ids(pid) == []


def test_gce_provider_paginated_listing(small_head):
    """nodes.list pagination: all pages are accumulated (tokens with
    reserved chars must be URL-encoded), and a mid-pagination failure
    falls back to the full local view instead of a truncated page."""
    from ray_tpu.autoscaler import GceTpuSliceNodeProvider

    rt = small_head
    fake_api = _FakeTpuApi(rt, hosts_per_slice=1)
    fake_api.page_size = 2
    provider = GceTpuSliceNodeProvider(
        "proj", "us-central2-b", "head:6379", runtime=rt,
        http_request=fake_api)
    nt = NodeTypeConfig("v5p-host", {"CPU": 1.0, "TPU": 4.0},
                        provider_params={"accelerator_type": "v5p-8"})
    pids = {provider.create_node(nt) for _ in range(5)}

    listed = provider.non_terminated_nodes()
    assert set(listed) == pids          # pages 1-3 merged, none dropped
    assert all(t == "v5p-host" for t in listed.values())
    gets = [u for m, u in fake_api.requests if m == "GET"]
    assert len(gets) == 3               # 2 + 2 + 1 rows
    assert any("pageToken=idx%2B%26%2F" in u for u in gets)  # encoded

    # Failure on page 2 of a later poll: full local view, not 2 rows.
    def fail_second(method, url, body, _n=[0]):
        if method == "GET":
            _n[0] += 1
            if _n[0] == 2:
                return 503, {"error": "hiccup"}
        return fake_api(method, url, body)

    provider._http = fail_second
    assert set(provider.non_terminated_nodes()) == pids
    provider._http = fake_api
