"""Autoscaler tests (reference: autoscaler unit tests driving
StandardAutoscaler.update with a fake provider,
python/ray/tests/test_autoscaler.py + FakeMultiNodeProvider)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalerConfig, FakeMultiNodeProvider, NodeTypeConfig,
    StandardAutoscaler)


@pytest.fixture
def small_head():
    rt = ray_tpu.init(num_cpus=1)
    yield rt
    ray_tpu.shutdown()


def _autoscaler(rt, **cfg_kw):
    config = AutoscalerConfig(**cfg_kw)
    provider = FakeMultiNodeProvider(rt)
    return StandardAutoscaler(config, provider, rt), provider


def _wait_demand(rt, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if rt.resource_demand():
            return True
        time.sleep(0.02)
    return False


def test_scale_up_on_backlog(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt, node_types=[NodeTypeConfig("cpu2", {"CPU": 2.0},
                                       max_workers=4)])

    @ray_tpu.remote(num_cpus=2)
    def work(x):
        time.sleep(0.2)
        return x + 1

    refs = [work.remote(i) for i in range(3)]
    assert _wait_demand(rt)
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) >= 1
    # more rounds may be needed while tasks queue
    for _ in range(5):
        autoscaler.update()
        time.sleep(0.05)
    assert ray_tpu.get(refs, timeout=60) == [1, 2, 3]


def test_infeasible_tpu_demand_launches_tpu_node(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt, node_types=[
            NodeTypeConfig("cpu2", {"CPU": 2.0}, max_workers=2),
            NodeTypeConfig("v5p-host", {"CPU": 8.0, "TPU": 4.0},
                           max_workers=2,
                           labels={"tpu-pod-type": "v5p-8"}),
        ])

    @ray_tpu.remote(resources={"TPU": 4})
    def on_tpu():
        return "ok"

    ref = on_tpu.remote()
    assert _wait_demand(rt)
    launched = autoscaler.update()
    assert launched.get("v5p-host") == 1
    assert ray_tpu.get(ref, timeout=60) == "ok"


def test_min_workers_floor(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt, node_types=[NodeTypeConfig("cpu1", {"CPU": 1.0},
                                       min_workers=2, max_workers=4)])
    autoscaler.update()
    nodes = provider.non_terminated_nodes()
    assert sum(1 for t in nodes.values() if t == "cpu1") == 2


def test_max_workers_cap(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt, node_types=[NodeTypeConfig("cpu2", {"CPU": 2.0},
                                       max_workers=2)])

    @ray_tpu.remote(num_cpus=2)
    def work():
        time.sleep(0.5)

    refs = [work.remote() for _ in range(8)]
    assert _wait_demand(rt)
    for _ in range(4):
        autoscaler.update()
    nodes = provider.non_terminated_nodes()
    assert sum(1 for t in nodes.values() if t == "cpu2") <= 2
    ray_tpu.get(refs, timeout=60)


def test_idle_nodes_terminated(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt,
        node_types=[NodeTypeConfig("cpu2", {"CPU": 2.0}, max_workers=2)],
        idle_timeout_s=0.1)

    @ray_tpu.remote(num_cpus=2)
    def work():
        return 1

    ref = work.remote()
    assert _wait_demand(rt)
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 1
    assert ray_tpu.get(ref, timeout=60) == 1
    time.sleep(0.3)
    autoscaler.update()  # marks idle
    time.sleep(0.3)
    autoscaler.update()  # past idle_timeout -> terminate
    assert len(provider.non_terminated_nodes()) == 0


def test_background_loop(small_head):
    rt = small_head
    autoscaler, provider = _autoscaler(
        rt,
        node_types=[NodeTypeConfig("cpu1", {"CPU": 1.0}, min_workers=1,
                                   max_workers=2)],
        update_interval_s=0.05)
    autoscaler.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if provider.non_terminated_nodes():
                break
            time.sleep(0.02)
        assert provider.non_terminated_nodes()
    finally:
        autoscaler.stop()
