"""Llama model tests: forward/loss/grad, sharded-vs-unsharded parity."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models.llama import (
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_loss,
    llama_sharding_rules,
)
from ray_tpu.parallel.mesh import MeshSpec, make_mesh
from ray_tpu.parallel.sharding import shard_pytree


def _data(cfg, batch=4, seq=32):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0,
                                 cfg.vocab_size)
    return tokens, targets


def test_forward_shapes():
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    tokens, _ = _data(cfg)
    logits = llama_forward(params, tokens, cfg)
    assert logits.shape == (4, 32, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_gqa_head_counts():
    cfg = LlamaConfig.tiny(n_heads=4, n_kv_heads=1)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    tokens, targets = _data(cfg)
    loss = llama_loss(params, tokens, targets, cfg)
    assert bool(jnp.isfinite(loss))


def test_sharded_matches_unsharded():
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    tokens, targets = _data(cfg, batch=8)
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, model=2))
    sharded = shard_pytree(params, mesh, llama_sharding_rules("fsdp_tp"))
    batch_sh = NamedSharding(mesh, P(("data", "fsdp")))
    t_s = jax.device_put(tokens, batch_sh)
    y_s = jax.device_put(targets, batch_sh)
    loss_sharded = jax.jit(
        lambda p, t, y: llama_loss(p, t, y, cfg))(sharded, t_s, y_s)
    loss_ref = llama_loss(params, tokens, targets, cfg)
    np.testing.assert_allclose(float(loss_sharded), float(loss_ref),
                               rtol=1e-4)


def test_grad_step_improves_loss():
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    tokens, targets = _data(cfg)

    @jax.jit
    def step(p):
        loss, grads = jax.value_and_grad(
            lambda p_: llama_loss(p_, tokens, targets, cfg))(p)
        p = jax.tree.map(lambda a, g: a - 0.1 * g, p, grads)
        return p, loss

    losses = []
    for _ in range(5):
        params, loss = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_param_count_formula():
    cfg = LlamaConfig.tiny()
    params = llama_init(jax.random.PRNGKey(0), cfg)
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert actual == cfg.num_params()


def test_chunked_cross_entropy_matches_dense():
    """Chunked CE (no [B,S,V] materialization) must match the dense
    loss in value AND gradients, with and without a mask."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from ray_tpu.models.llama import LlamaConfig, llama_init, llama_loss

    cfg = LlamaConfig.tiny(vocab_size=97)  # odd vocab, exercises padding
    cfg_chunked = dataclasses.replace(cfg, ce_chunk_tokens=13)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 21), 0,
                                cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (2, 21), 0,
                                 cfg.vocab_size)
    mask = (jax.random.uniform(jax.random.PRNGKey(3), (2, 21))
            > 0.3).astype(jnp.float32)

    for m in (None, mask):
        dense, dense_grads = jax.value_and_grad(
            lambda p: llama_loss(p, tokens, targets, cfg, mask=m))(params)
        chunked, chunked_grads = jax.value_and_grad(
            lambda p: llama_loss(p, tokens, targets, cfg_chunked,
                                 mask=m))(params)
        assert jnp.allclose(dense, chunked, rtol=2e-4, atol=2e-4), (
            float(dense), float(chunked), m is not None)
        flat_d = ravel_pytree(dense_grads)[0]
        flat_c = ravel_pytree(chunked_grads)[0]
        assert jnp.allclose(flat_d, flat_c, rtol=5e-3, atol=5e-4), (
            "grad mismatch", float(jnp.abs(flat_d - flat_c).max()))
