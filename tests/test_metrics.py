"""Metrics registry + exposition tests (reference model:
python/ray/util/metrics + the dashboard metrics agent's Prometheus
exposition)."""

import pytest

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util.metrics import (
    Counter,
    Gauge,
    Histogram,
    prometheus_text,
    remove_series,
)


def _series(text):
    """Parse exposition text into {series_line_key: float}."""
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        key, value = line.rsplit(" ", 1)
        out[key] = float(value)
    return out


def test_histogram_bucket_math_and_headers():
    h = Histogram("ray_tpu_test_hist_seconds",
                  "A test histogram", boundaries=[0.1, 1.0, 10.0],
                  tag_keys=("op",))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v, tags={"op": "x"})
    text = prometheus_text()
    assert "# HELP ray_tpu_test_hist_seconds A test histogram" in text
    assert "# TYPE ray_tpu_test_hist_seconds histogram" in text
    # headers once per family, not per series
    assert text.count("# TYPE ray_tpu_test_hist_seconds histogram") == 1
    s = _series(text)
    name = "ray_tpu_test_hist_seconds"
    # cumulative le buckets: 0.1 -> 1 | 1.0 -> 3 | 10.0 -> 4 | +Inf -> 5
    assert s[f'{name}_bucket{{op="x",le="0.1"}}'] == 1
    assert s[f'{name}_bucket{{op="x",le="1.0"}}'] == 3
    assert s[f'{name}_bucket{{op="x",le="10.0"}}'] == 4
    assert s[f'{name}_bucket{{op="x",le="+Inf"}}'] == 5
    assert s[f'{name}_count{{op="x"}}'] == 5
    assert s[f'{name}_sum{{op="x"}}'] == pytest.approx(56.05)
    remove_series(name, {"op": "x"})


def test_boundary_value_lands_in_its_bucket():
    # Prometheus buckets are le (inclusive upper bound): an observation
    # exactly on a boundary counts in that boundary's bucket.
    h = Histogram("ray_tpu_test_edge_seconds", "edge",
                  boundaries=[1.0, 2.0])
    h.observe(1.0)
    s = _series(prometheus_text())
    assert s['ray_tpu_test_edge_seconds_bucket{le="1.0"}'] == 1
    remove_series("ray_tpu_test_edge_seconds", {})


def test_label_escaping():
    g = Gauge("ray_tpu_test_escape", "escapes", tag_keys=("k",))
    g.set(1.0, tags={"k": 'a\\b"c\nd'})
    text = prometheus_text()
    line = next(l for l in text.splitlines()
                if l.startswith("ray_tpu_test_escape{"))
    assert r'a\\b' in line and r'\"c' in line and r'\nd' in line
    assert "\n" not in line  # the newline itself must be escaped away
    remove_series("ray_tpu_test_escape", {"k": 'a\\b"c\nd'})


def test_remove_series_drops_headers_with_last_series():
    g = Gauge("ray_tpu_test_zombie", "zombie gauge", tag_keys=("node",))
    g.set(1.0, tags={"node": "a"})
    g.set(2.0, tags={"node": "b"})
    remove_series("ray_tpu_test_zombie", {"node": "a"})
    text = prometheus_text()
    # one series left: headers stay
    assert "# TYPE ray_tpu_test_zombie gauge" in text
    assert 'ray_tpu_test_zombie{node="b"}' in text
    remove_series("ray_tpu_test_zombie", {"node": "b"})
    text = prometheus_text()
    # last series gone: no dangling HELP/TYPE header
    assert "ray_tpu_test_zombie" not in text


def test_counter_accumulates_and_help_survives_blank_redefinition():
    c = Counter("ray_tpu_test_counter_total", "counts things")
    c.inc()
    c.inc(2.5)
    # a second definition with no description must not clobber the help
    Counter("ray_tpu_test_counter_total")
    text = prometheus_text()
    assert "# HELP ray_tpu_test_counter_total counts things" in text
    assert _series(text)["ray_tpu_test_counter_total"] == 3.5
    remove_series("ray_tpu_test_counter_total", {})


def test_worker_to_driver_forwarding(ray_start_regular):
    @ray_tpu.remote
    def bump():
        from ray_tpu.util.metrics import Counter
        Counter("ray_tpu_test_worker_total", "worker-side counter",
                tag_keys=("who",)).inc(tags={"who": "w"})
        return 1

    assert sum(ray_tpu.get([bump.remote() for _ in range(3)])) == 3
    s = _series(prometheus_text())
    assert s['ray_tpu_test_worker_total{who="w"}'] == 3
    remove_series("ray_tpu_test_worker_total", {"who": "w"})


def test_record_batch_applies_all_kinds(ray_start_regular):
    metrics_mod.record_batch([
        ("counter", "ray_tpu_test_batch_total", {}, 2.0, None),
        ("gauge", "ray_tpu_test_batch_gauge", {"g": "x"}, 7.0, None),
        ("histogram", "ray_tpu_test_batch_hist", {}, 0.5, [1.0]),
    ])

    @ray_tpu.remote
    def bump():
        from ray_tpu.util import metrics
        metrics.record_batch([
            ("counter", "ray_tpu_test_batch_total", {}, 3.0, None)])
        return 1

    assert ray_tpu.get(bump.remote()) == 1
    s = _series(prometheus_text())
    assert s["ray_tpu_test_batch_total"] == 5.0
    assert s['ray_tpu_test_batch_gauge{g="x"}'] == 7.0
    assert s['ray_tpu_test_batch_hist_bucket{le="1.0"}'] == 1
    for name, tags in (("ray_tpu_test_batch_total", {}),
                       ("ray_tpu_test_batch_gauge", {"g": "x"}),
                       ("ray_tpu_test_batch_hist", {})):
        remove_series(name, tags)


# The metric-naming drift guard that used to live here (a fresh-
# interpreter registry sweep) is now graftlint rule GL006, enforced by
# tests/test_lint_clean.py over every source file — including metrics
# defined in modules this list would have missed.
