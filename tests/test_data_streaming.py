"""Streaming data plane tests: pipelined shuffle (first output before
last map, bounded in-flight, seed-stable permutation), prefetch overlap,
zero-copy shm block transport, empty-join schema survival, and the
data-plane metrics exported at /metrics.
"""

import time
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.context import DataContext


@pytest.fixture(scope="module", autouse=True)
def _rt():
    rt = ray_tpu.init(num_cpus=8, include_dashboard=True,
                      ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


@pytest.fixture
def shuffle_ctx():
    """Small shuffle knobs so streaming behavior is observable, restored
    afterwards (the context is thread-local and shared by the module)."""
    ctx = DataContext.get_current()
    saved = (ctx.shuffle_reduce_fanin, ctx.max_shuffle_blocks_in_flight,
             ctx.shuffle_num_reducers)
    ctx.shuffle_reduce_fanin = 2
    ctx.max_shuffle_blocks_in_flight = 4
    ctx.shuffle_num_reducers = 4
    yield ctx
    (ctx.shuffle_reduce_fanin, ctx.max_shuffle_blocks_in_flight,
     ctx.shuffle_num_reducers) = saved


def _shuffle_state(ds):
    ex = ds._last_executor
    states = list(ex.shuffle_states.values())
    assert len(states) == 1
    return states[0]


def test_shuffle_first_output_before_last_map(shuffle_ctx):
    """Streaming proof (a): the first reduce output lands while maps are
    still running — the old implementation was a barrier that launched
    zero reducers until every map shard existed."""
    ds = rd.range(640, parallelism=32).random_shuffle(seed=11)
    out = list(ds.iter_internal_ref_bundles())
    ss = _shuffle_state(ds)
    assert ss.n_maps == 32
    assert ss.first_output_maps_done is not None
    assert ss.first_output_maps_done < ss.n_maps, (
        f"first reduce output only after {ss.first_output_maps_done}/"
        f"{ss.n_maps} maps — shuffle did not stream")
    assert ss.outputs_emitted == len(out)
    # output orders are dense, so downstream in-order consumption works
    assert sorted(b.order for b in out) == list(range(len(out)))


def test_shuffle_bounded_in_flight(shuffle_ctx):
    """Streaming proof (b): peak in-flight blocks (buffered shard sets +
    running maps + running reduces) stays within the configured bound on
    a dataset far larger than the bound — no stage materializes its full
    input."""
    ctx = shuffle_ctx
    ds = rd.range(640, parallelism=64).random_shuffle(seed=5)
    rows = [r["id"] for r in ds.take_all()]
    assert sorted(rows) == list(range(640))
    ss = _shuffle_state(ds)
    bound = ss.window + ctx.max_tasks_in_flight_per_op
    assert ss.n_maps == 64
    assert ss.n_maps > bound, "dataset must dwarf the in-flight bound"
    assert 0 < ss.peak_in_flight_blocks <= bound, (
        f"peak {ss.peak_in_flight_blocks} blocks in flight exceeds "
        f"window({ss.window}) + reduce cap")


def test_shuffle_seed_stable_permutation(shuffle_ctx):
    """Streaming proof (d): same seed -> identical output (regardless of
    task completion order), output is a permutation of the input, and a
    different seed gives a different permutation."""
    def run(seed):
        return [r["id"] for r in rd.range(300, parallelism=16)
                .random_shuffle(seed=seed).take_all()]

    a, b, c = run(7), run(7), run(8)
    assert a == b, "same seed must be reproducible"
    assert sorted(a) == list(range(300)), "must be a permutation"
    assert a != list(range(300)), "must actually shuffle"
    assert a != c, "different seed must permute differently"


def test_shuffle_num_outputs_knob(shuffle_ctx):
    ds = rd.range(100, parallelism=8).random_shuffle(seed=1, num_blocks=3)
    out = list(ds.iter_internal_ref_bundles())
    ss = _shuffle_state(ds)
    assert ss.n_out == 3
    assert sum(b.metadata.num_rows for b in out) == 100


def test_iter_device_batches_overlap():
    """Streaming proof (c): device staging runs on a producer thread, so
    a slow consumer does not inflate the producer's wall-time."""
    ds = rd.range(256, parallelism=4)
    t0 = time.monotonic()
    it = ds.iter_device_batches(batch_size=32, prefetch=8)
    n = 0
    for _ in it:
        time.sleep(0.05)  # slow consumer (releases the GIL)
        n += 1
    assert n == 8
    consumer_time = time.monotonic() - t0
    produce_time = it.producer_done_time - t0
    # with depth >= batch count the producer never waits for the
    # consumer; 0.75x the consumer's sleep budget leaves slack for the
    # single-core CI box
    assert produce_time < 0.75 * consumer_time, (
        f"producer took {produce_time:.3f}s vs consumer "
        f"{consumer_time:.3f}s — staging did not overlap consumption")


def test_iter_batches_prefetch_thread_overlap():
    """Host-side prefetch: batch production overlaps a slow consumer and
    results match the synchronous path exactly."""
    sync = [b["id"].tolist()
            for b in rd.range(128, parallelism=4).iter_batches(
                batch_size=16, prefetch_batches=0)]
    it = rd.range(128, parallelism=4).iter_batches(
        batch_size=16, prefetch_batches=4)
    pre = []
    for b in it:
        time.sleep(0.02)
        pre.append(b["id"].tolist())
    assert pre == sync
    assert it.wait_seconds_total >= 0.0  # stat is tracked


def test_iter_batches_prefetch_propagates_udf_error():
    def boom(batch):
        raise RuntimeError("udf exploded")

    ds = rd.range(64, parallelism=2).map_batches(boom)
    with pytest.raises(Exception, match="udf exploded"):
        list(ds.iter_batches(batch_size=8, prefetch_batches=2))


def test_block_get_is_zero_copy_from_shm():
    """A large Arrow block round-trips through the shm object store and
    the gotten table's data buffer points INTO the mapped arena — no
    serialize/copy on the node-local path."""
    from ray_tpu.core import runtime as rtm
    store = rtm.get_runtime().nodes[rtm.get_runtime().head_node_id].store
    lo, hi = store.arena_range()
    big = pa.table({"v": pa.array(np.arange(200_000, dtype=np.int64))})
    ref = ray_tpu.put(big)
    got = ray_tpu.get(ref)
    assert got.num_rows == 200_000
    buf = got.column("v").chunks[0].buffers()[1]
    assert lo <= buf.address < hi, (
        "block data buffer lives on the heap, not in the shm arena — "
        "the zero-copy read path regressed")
    del buf, got  # drop arena views before module teardown closes shm


def test_join_empty_but_schemad_side():
    """An empty-but-schema'd Arrow side joins cleanly (regression: the
    executor used to demand materialization for any empty side)."""
    left = rd.from_items([{"k": 1, "a": 10}, {"k": 2, "a": 20}])
    empty = pa.table({"k": pa.array([], type=pa.int64()),
                      "b": pa.array([], type=pa.int64())})
    right = rd.from_arrow(empty)
    out = left.join(right, on=["k"], how="left").take_all()
    assert sorted(r["k"] for r in out) == [1, 2]
    assert all(r["b"] is None for r in out)


def test_chained_join_through_empty_intermediate():
    """A join with an entirely-empty result now emits one schema'd empty
    bundle, so a downstream outer join against it works instead of
    raising the unknown-schema error."""
    a = rd.from_items([{"k": 1, "x": 1}])
    b = rd.from_items([{"k": 2, "y": 2}])
    inner = a.join(b, on=["k"], how="inner")  # empty result, schema known
    assert inner.count() == 0
    c = rd.from_items([{"k": 3, "z": 9}])
    out = c.join(inner, on=["k"], how="left").take_all()
    assert len(out) == 1
    assert out[0]["k"] == 3 and out[0]["z"] == 9
    assert out[0]["x"] is None and out[0]["y"] is None


def test_trainer_splits_datasets_once_driver_side(tmp_path):
    """JaxTrainer ships each rank a per-rank split iterator sharing ONE
    coordinator — not the dataset itself (which every worker would
    re-execute through its own coordinator)."""
    from ray_tpu.core import serialization
    from ray_tpu.data.iterator import _SplitIterator
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ds = rd.range(64, parallelism=4)
    trainer = JaxTrainer(
        lambda config: None,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="split_once", storage_path=str(tmp_path)),
        datasets={"train": ds})
    blobs = trainer._rank_datasets_blobs(2)
    shards = [serialization.loads(b)["train"] for b in blobs]
    assert all(isinstance(s, _SplitIterator) for s in shards)
    assert shards[0]._idx == 0 and shards[1]._idx == 1
    # both ranks talk to the SAME coordinator actor
    assert (shards[0]._coord._actor_id == shards[1]._coord._actor_id)
    # and get_dataset_shard returns a prebuilt iterator untouched
    from ray_tpu.train import context as tctx
    ctx = tctx.TrainContext(world_size=2, world_rank=0,
                            storage_path=str(tmp_path),
                            resume_checkpoint=None,
                            datasets={"train": shards[0]})
    tctx.set_context(ctx)
    try:
        assert tctx.get_dataset_shard("train") is shards[0]
    finally:
        tctx.set_context(None)
    rows = sorted(v for it in shards
                  for b in it.iter_batches(batch_size=None,
                                           prefetch_batches=0)
                  for v in b["id"].tolist())
    assert rows == list(range(64))


def test_data_metrics_exported(_rt):
    """The data-plane metrics land at /metrics after a real workload."""
    ctx = DataContext.get_current()
    saved = ctx.shuffle_reduce_fanin
    ctx.shuffle_reduce_fanin = 2
    try:
        ds = rd.range(512, parallelism=8).random_shuffle(seed=3)
        list(ds.iter_batches(batch_size=64, prefetch_batches=2))
    finally:
        ctx.shuffle_reduce_fanin = saved
    with urllib.request.urlopen(_rt.dashboard_url + "/metrics",
                                timeout=30) as resp:
        text = resp.read().decode()
    shuffle_lines = [l for l in text.splitlines()
                     if l.startswith("ray_tpu_data_shuffle_bytes_total")]
    stages = {l for l in shuffle_lines for s in ("map", "reduce")
              if f'stage="{s}"' in l}
    assert len(stages) == 2, f"missing shuffle stage series: {shuffle_lines}"
    for line in shuffle_lines:
        assert float(line.rsplit(" ", 1)[1]) > 0
    assert "ray_tpu_data_blocks_in_flight" in text
    assert "ray_tpu_data_prefetch_wait_seconds_bucket" in text
