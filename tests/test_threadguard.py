"""Runtime half of threadguard: @loop_only affinity assertion, the
loop-stall watchdog, and the zero-overhead-when-disabled contract.

The decorator checks RAY_TPU_THREADGUARD at *decoration* time, so the
enabled-path tests set the env var first and then define their classes
(and build private IOLoop instances, so the watchdog attaches).
"""

import threading
import time

import pytest

from ray_tpu.devtools import threadguard


# -- disabled by default: plain functions ------------------------------

def test_disabled_decorator_is_identity(monkeypatch):
    monkeypatch.delenv("RAY_TPU_THREADGUARD", raising=False)
    assert not threadguard.enabled()

    def fn(self):
        return 42

    assert threadguard.loop_only(fn) is fn
    assert threadguard.loop_only(loop_attr="conn._loop")(fn) is fn
    assert fn._tg_loop_only is True  # static marker still applied


def test_loop_owned_is_declarative_and_merges_bases():
    @threadguard.loop_owned("a", "b")
    class Base:
        pass

    @threadguard.loop_owned("c")
    class Child(Base):
        pass

    assert Base._tg_loop_owned == frozenset({"a", "b"})
    assert Child._tg_loop_owned == frozenset({"a", "b", "c"})
    # no runtime wrapping: attribute access stays plain
    Child().a = 1


# -- enabled: affinity enforcement -------------------------------------

@pytest.fixture
def private_loop(monkeypatch):
    monkeypatch.setenv("RAY_TPU_THREADGUARD", "1")
    monkeypatch.setenv("RAY_TPU_THREADGUARD_STALL_S", "0.1")
    threadguard.reset()
    from ray_tpu.core.io_loop import IOLoop
    loop = IOLoop(name="rtpu-io-loop-tgtest")
    yield loop
    loop.stop()
    threadguard.reset()


def test_loop_only_raises_off_thread_with_diagnostic(private_loop):
    class Proto:
        def __init__(self, loop):
            self._io = loop
            self.hits = []

        @threadguard.loop_only
        def _drain(self):
            self.hits.append(threading.current_thread().name)

    p = Proto(private_loop)
    with pytest.raises(threadguard.LoopAffinityError) as exc:
        p._drain()
    msg = str(exc.value)
    assert "Proto._drain" in msg
    assert "rtpu-io-loop-tgtest" in msg           # owning loop thread
    assert threading.current_thread().name in msg  # offending thread
    assert "call_soon" in msg                      # remediation hint

    # the same call routed through the loop is fine
    done = threading.Event()
    private_loop.call_soon(lambda: (p._drain(), done.set()))
    assert done.wait(5.0)
    assert p.hits == ["rtpu-io-loop-tgtest"]


def test_loop_only_explicit_loop_attr_path(private_loop):
    class Holder:
        pass

    class Proto:
        def __init__(self, loop):
            self.conn = Holder()
            self.conn._loop = loop

        @threadguard.loop_only(loop_attr="conn._loop")
        def _on_msg(self):
            return "ok"

    p = Proto(private_loop)
    with pytest.raises(threadguard.LoopAffinityError):
        p._on_msg()

    # unresolvable loop -> guard passes through rather than guessing
    q = Proto(private_loop)
    del q.conn._loop
    assert q._on_msg() == "ok"


# -- enabled: stall watchdog -------------------------------------------

def test_watchdog_reports_blocking_frame(private_loop):
    """A 300ms+ sleep inside a dispatched callback (vs the 0.1s
    threshold) must produce a stall report naming the blocking frame."""

    def _slow_handler():
        time.sleep(0.35)

    private_loop.call_soon(_slow_handler)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not threadguard.stall_reports():
        time.sleep(0.02)
    reports = threadguard.stall_reports()
    assert reports, "watchdog produced no stall report"
    rep = reports[0]
    assert rep["thread"] == "rtpu-io-loop-tgtest"
    assert rep["stalled_s"] >= 0.1
    # the sampled stack names the blocking frame (the handler sitting
    # in its sleep), not just the dispatch machinery
    assert "_slow_handler" in rep["stack"]
    assert "time.sleep(0.35)" in rep["stack"]


def test_watchdog_quiet_for_fast_dispatches(private_loop):
    done = threading.Event()
    for _ in range(50):
        private_loop.call_soon(lambda: None)
    private_loop.call_soon(done.set)
    assert done.wait(5.0)
    time.sleep(0.3)  # several watchdog polls
    assert threadguard.stall_reports() == []
