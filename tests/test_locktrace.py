"""locktrace runtime lock-order detector: a real A->B / B->A inversion
across two threads must produce a cycle in the lock-order graph."""

import threading

from ray_tpu.devtools import locktrace


def fresh_tracer(**kwargs):
    return locktrace.LockTracer(**kwargs)


def test_inversion_across_two_threads_detected():
    tracer = fresh_tracer()
    a = locktrace.TracedLock("lock.a", tracer=tracer)
    b = locktrace.TracedLock("lock.b", tracer=tracer)

    # Serialize the two threads with events so both orders actually
    # happen (no real deadlock: each thread fully releases before the
    # other starts its nested acquisition).
    t1_done = threading.Event()

    def t1():  # acquires A then B
        with a:
            with b:
                pass
        t1_done.set()

    def t2():  # acquires B then A — the inversion
        t1_done.wait(5.0)
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1, daemon=True)
    th2 = threading.Thread(target=t2, daemon=True)
    th1.start()
    th2.start()
    th1.join(5.0)
    th2.join(5.0)

    assert ("lock.a", "lock.b") in tracer.edges()
    assert ("lock.b", "lock.a") in tracer.edges()
    cycles = tracer.cycles()
    assert cycles, "A->B / B->A inversion must be reported as a cycle"
    assert sorted(cycles[0]) == ["lock.a", "lock.b"]

    report = tracer.report()
    assert report["cycles"] == cycles
    # each edge carries a sample stack for the report
    assert tracer.edge_stack("lock.a", "lock.b")


def test_consistent_order_is_not_a_cycle():
    tracer = fresh_tracer()
    a = locktrace.TracedLock("lock.a", tracer=tracer)
    b = locktrace.TracedLock("lock.b", tracer=tracer)

    def worker():
        with a:
            with b:
                pass

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5.0)

    assert tracer.edges() == [("lock.a", "lock.b")]
    assert tracer.cycles() == []


def test_long_hold_reported():
    tracer = fresh_tracer(hold_threshold_s=0.0)
    lock = locktrace.TracedLock("lock.slow", tracer=tracer)
    with lock:
        pass
    holds = tracer.long_holds()
    assert holds and holds[0]["lock"] == "lock.slow"
    assert holds[0]["held_s"] >= 0.0


def test_reentrant_lock_supported():
    tracer = fresh_tracer()
    r = locktrace.TracedLock("lock.r", reentrant=True, tracer=tracer)
    with r:
        with r:  # same lock: must not self-edge
            pass
    assert tracer.edges() == []
    assert tracer.cycles() == []


def test_factories_are_plain_locks_when_disabled(monkeypatch):
    monkeypatch.delenv("RAY_TPU_LOCKTRACE", raising=False)
    assert not locktrace.enabled()
    lock = locktrace.traced_lock("x")
    assert not isinstance(lock, locktrace.TracedLock)
    rlock = locktrace.traced_rlock("x")
    assert not isinstance(rlock, locktrace.TracedLock)


def test_factories_trace_when_enabled(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCKTRACE", "1")
    assert locktrace.enabled()
    lock = locktrace.traced_lock("traced.x")
    assert isinstance(lock, locktrace.TracedLock)
    with lock:
        assert lock.locked()
    assert not lock.locked()


def test_reset_clears_state():
    tracer = fresh_tracer(hold_threshold_s=0.0)
    a = locktrace.TracedLock("a", tracer=tracer)
    b = locktrace.TracedLock("b", tracer=tracer)
    with a:
        with b:
            pass
    assert tracer.edges() and tracer.long_holds()
    tracer.reset()
    assert tracer.edges() == []
    assert tracer.long_holds() == []
    assert tracer.report()["cycles"] == []
