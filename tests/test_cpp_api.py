"""C++ client API tests (reference model: cpp/ public API tests —
put/get/call through a non-Python client).

Compiles cpp/ with g++ and runs the test binary against a live head:
binary TLV over the same TCP listener node daemons use."""

import os
import subprocess

import pytest

import ray_tpu
from ray_tpu import capi

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# toolchain-dependent tests skip (not fail) where g++ is absent
import shutil  # noqa: E402

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="g++ unavailable")


def _build_binary(tmp_path) -> str:
    out = str(tmp_path / "capi_test")
    cmd = [
        "g++", "-O1", "-g", "-std=c++17", "-Wall",
        "-I", os.path.join(_REPO, "cpp", "include"),
        os.path.join(_REPO, "cpp", "src", "capi_client.cc"),
        os.path.join(_REPO, "cpp", "test", "capi_client_test_main.cc"),
        "-o", out,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    return out


@needs_gxx
def test_cpp_client_end_to_end(tmp_path):
    binary = _build_binary(tmp_path)
    rt = ray_tpu.init(num_cpus=4, head_port=0)
    try:
        capi.register_function("double", lambda b: b * 2)
        host, port = rt.head_address.split(":")
        proc = subprocess.run([binary, host, port], capture_output=True,
                              text=True, timeout=120)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "CPP-OK" in proc.stdout
    finally:
        ray_tpu.shutdown()


def test_capi_objects_visible_to_python_tasks(tmp_path):
    """A C-put object is an ordinary cluster object: Python tasks can
    consume it (here simulated with the Python framing of the same
    protocol, so the test runs without the C++ toolchain)."""
    import socket
    import struct

    from ray_tpu.core.protocol import recv_frame, send_frame

    rt = ray_tpu.init(num_cpus=2, head_port=0)
    try:
        host, port = rt.head_address.split(":")
        sock = socket.create_connection((host, int(port)), timeout=10)
        send_frame(sock, b"CAPI" + struct.pack("<I", 1))
        assert recv_frame(sock)[0] == 0
        send_frame(sock, bytes([2]) + b"payload-from-c")
        reply = recv_frame(sock)
        assert reply[0] == 0
        oid_bytes = reply[1:]

        from ray_tpu.core.ids import ObjectID
        from ray_tpu.core.object_ref import ObjectRef

        @ray_tpu.remote
        def consume(value):
            # the C-put object arrives resolved, like any task arg
            return value.decode().upper()

        ref = ObjectRef(ObjectID(oid_bytes))
        assert ray_tpu.get(consume.remote(ref),
                           timeout=60) == "PAYLOAD-FROM-C"

        # version skew is rejected cleanly
        sock2 = socket.create_connection((host, int(port)), timeout=10)
        send_frame(sock2, b"CAPI" + struct.pack("<I", 999))
        assert recv_frame(sock2)[0] == 1
        sock2.close()
        sock.close()
    finally:
        ray_tpu.shutdown()


# --- C++ WORKER-side tasks/actors (round 3; reference capability:
#     cpp/include/ray/api.h running C++ tasks/actors in C++ workers) ----

def _build_worker_binary(tmp_path) -> str:
    out = str(tmp_path / "cpp_worker")
    cmd = [
        "g++", "-O1", "-g", "-std=c++17", "-Wall",
        "-I", os.path.join(_REPO, "cpp", "include"),
        os.path.join(_REPO, "cpp", "src", "worker_runtime.cc"),
        os.path.join(_REPO, "cpp", "test", "worker_test_main.cc"),
        "-o", out,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    return out


def _wait_worker_registered(rt, timeout=30.0):
    import time
    manager = capi.get_cpp_worker_manager(rt)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with manager._lock:
            if manager._workers:
                return manager
        time.sleep(0.05)
    raise TimeoutError("C++ worker never registered")


@needs_gxx
def test_cpp_worker_tasks_and_actors(tmp_path):
    binary = _build_worker_binary(tmp_path)
    rt = ray_tpu.init(num_cpus=2, head_port=0)
    worker = None
    try:
        host, port = rt.head_address.split(":")
        worker = subprocess.Popen([binary, host, port])
        _wait_worker_registered(rt)

        # task: executed by compiled C++ code in the worker process
        ref = capi.cpp_task("Add", b"40,2")
        assert ray_tpu.get(ref, timeout=30) == b"42"

        # C++ exception -> Python-side CppWorkerError with the message
        with pytest.raises(capi.CppWorkerError, match="intentional"):
            ray_tpu.get(capi.cpp_task("Fail", b"boom"), timeout=30)

        # after a failure, the worker keeps serving
        assert ray_tpu.get(capi.cpp_task("Add", b"1,2"), timeout=30) == b"3"

        # stateful actor: ordered methods on one instance
        counter = capi.cpp_actor("Counter")
        refs = [counter.call("incr", b"5"), counter.call("incr", b"7")]
        assert [ray_tpu.get(r, timeout=30) for r in refs] == [b"5", b"12"]
        assert ray_tpu.get(counter.call("get"), timeout=30) == b"12"

        # a second instance is independent state
        other = capi.cpp_actor("Counter")
        assert ray_tpu.get(other.call("get"), timeout=30) == b"0"
        counter.kill()
        with pytest.raises(capi.CppWorkerError):
            ray_tpu.get(counter.call("get"), timeout=30)

        # unknown function: routed nowhere, clear error
        with pytest.raises(capi.CppWorkerError, match="no connected"):
            capi.cpp_task("Nope", b"")
    finally:
        if worker is not None:
            worker.kill()
            worker.wait(timeout=10)
        ray_tpu.shutdown()


@needs_gxx
def test_cpp_worker_death_fails_inflight(tmp_path):
    import time

    binary = _build_worker_binary(tmp_path)
    rt = ray_tpu.init(num_cpus=2, head_port=0)
    try:
        host, port = rt.head_address.split(":")
        worker = subprocess.Popen([binary, host, port])
        _wait_worker_registered(rt)
        counter = capi.cpp_actor("Counter")
        assert ray_tpu.get(counter.call("incr", b"1"), timeout=30) == b"1"
        # kill mid-flight: a pending call must fail, not hang. "slow"
        # parks the worker, so the call is deterministically still
        # pending when the kill lands (an instant method could win the
        # race and legitimately reply first).
        ref = counter.call("slow", b"")
        worker.kill()
        worker.wait(timeout=10)
        time.sleep(0.5)  # let the head observe the EOF
        with pytest.raises(capi.CppWorkerError):
            ray_tpu.get(ref, timeout=30)
    finally:
        ray_tpu.shutdown()
