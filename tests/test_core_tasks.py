"""Task API tests (reference model: python/ray/tests/test_basic.py)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, TaskError


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_many_tasks(ray_start_regular):
    @ray_tpu.remote
    def square(x):
        return x * x

    refs = [square.remote(i) for i in range(50)]
    assert ray_tpu.get(refs) == [i * i for i in range(50)]


def test_kwargs_and_defaults(ray_start_regular):
    @ray_tpu.remote
    def f(a, b=10, *, c=100):
        return a + b + c

    assert ray_tpu.get(f.remote(1)) == 111
    assert ray_tpu.get(f.remote(1, b=2, c=3)) == 6


def test_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_dependency_chain(ray_start_regular):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(5):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref) == 6


def test_nested_task_submission(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(10)) == 21


def test_large_array_roundtrip(ray_start_regular):
    @ray_tpu.remote
    def make():
        return np.arange(300_000, dtype=np.float32)

    @ray_tpu.remote
    def total(a):
        return float(a.sum())

    ref = make.remote()
    arr = ray_tpu.get(ref)
    assert arr.shape == (300_000,)
    assert ray_tpu.get(total.remote(ref)) == pytest.approx(arr.sum())


def test_error_propagation(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(TaskError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_error_through_dependency(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise RuntimeError("first failure")

    @ray_tpu.remote
    def consume(x):
        return x

    # The dependent task fails because its dependency errored.
    with pytest.raises(TaskError):
        ray_tpu.get(consume.remote(boom.remote()))


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return 1

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.2)


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        time.sleep(3)
        return "slow"

    f, s = fast.remote(), slow.remote()
    done, rest = ray_tpu.wait([f, s], num_returns=1, timeout=2.0)
    assert done == [f]
    assert rest == [s]


def test_retry_exceptions(ray_start_regular):
    @ray_tpu.remote(retry_exceptions=True, max_retries=5)
    def flaky(key):
        # Use the KV store to count attempts across retries.
        rt = __import__("ray_tpu.core.runtime", fromlist=["runtime"]).get_runtime()
        n = rt.gcs_call("kv_get", key.encode(), "")
        n = int(n or 0) + 1
        rt.gcs_call("kv_put", key.encode(), str(n).encode(), "")
        if n < 3:
            raise RuntimeError(f"attempt {n} fails")
        return n

    assert ray_tpu.get(flaky.remote("flaky_counter")) == 3


def test_put_get_roundtrip(ray_start_regular):
    obj = {"a": [1, 2, 3], "b": "text", "c": np.ones(10)}
    ref = ray_tpu.put(obj)
    out = ray_tpu.get(ref)
    assert out["a"] == [1, 2, 3]
    assert out["b"] == "text"
    np.testing.assert_array_equal(out["c"], np.ones(10))


def test_object_ref_in_collection_passthrough(ray_start_regular):
    # Refs nested in containers are passed through (not auto-resolved),
    # matching the reference's semantics.
    @ray_tpu.remote
    def identity(x):
        return x

    inner_ref = ray_tpu.put(42)
    out = ray_tpu.get(identity.remote([inner_ref]))
    assert isinstance(out[0], ray_tpu.ObjectRef)
    assert ray_tpu.get(out[0]) == 42


def test_cluster_resources(ray_start_regular):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 4.0


def test_arg_embedded_ref_pinned(ray_start_regular):
    # An ObjectRef embedded inside a serialized argument is containment-
    # pinned by the task spec: the caller dropping its handle while the
    # task is queued must not delete the inner object.
    import gc

    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.core.remote_function import value_to_arg

    rt = runtime_mod.get_runtime()
    inner = ray_tpu.put(np.arange(100_000))  # large -> shm store
    oid = inner.id
    arg = value_to_arg({"payload": inner}, rt)
    del inner
    gc.collect()
    assert rt.reference_counter.count(oid) > 0, (
        "embedded ref dropped while the arg still pins it")
    del arg
    gc.collect()

    # End-to-end: inner ref's only handle dies right after submission.
    @ray_tpu.remote
    def read_inner(box):
        return ray_tpu.get(box["ref"]) + 1

    inner2 = ray_tpu.put(41)
    fut = read_inner.remote({"ref": inner2})
    del inner2
    gc.collect()
    assert ray_tpu.get(fut) == 42


def test_nested_submission_under_pool_cap():
    """A parent task blocked in get(child) must not deadlock a node
    whose worker pool is at its cap: blocked workers leave the cap
    accounting so a replacement spawns (reference: workers blocked in
    ray.get release their CPU)."""
    import ray_tpu

    ray_tpu.shutdown()
    rt = ray_tpu.init(num_cpus=1,
                      system_config={"task_max_retries": 0,
                                     "max_workers_per_node": 1})

    @ray_tpu.remote(num_cpus=0)
    def child(x):
        return x + 1

    @ray_tpu.remote(num_cpus=0)
    def parent():
        import ray_tpu as r
        return r.get(child.remote(41))

    assert ray_tpu.get(parent.remote(), timeout=60) == 42

    # two levels deep for good measure
    @ray_tpu.remote(num_cpus=0)
    def grandparent():
        import ray_tpu as r
        return r.get(parent.remote()) + 1

    assert ray_tpu.get(grandparent.remote(), timeout=60) == 43
    ray_tpu.shutdown()


# --- burst grants (lease reuse) --------------------------------------------

def _scheduler_fully_released(rt) -> bool:
    snap = rt.scheduler.snapshot()
    return all(res.available == res.total for res in snap.values())


def test_burst_grant_flood_releases_all_resources(ray_start_regular):
    """A homogeneous flood rides burst grants; after draining, the
    scheduler's availability must equal totals exactly — the
    marker-consumption invariant across the completion path."""
    from ray_tpu.core import runtime as runtime_mod

    @ray_tpu.remote
    def f(i):
        return i

    assert ray_tpu.get([f.remote(i) for i in range(500)],
                       timeout=120) == list(range(500))
    rt = runtime_mod.get_runtime()
    deadline = time.time() + 10
    while time.time() < deadline:
        if _scheduler_fully_released(rt) and not rt._overcommitted:
            return
        time.sleep(0.1)
    raise AssertionError(
        (rt.scheduler.snapshot(), len(rt._overcommitted)))


def test_burst_grant_crash_retry_releases_all_resources(
        ray_start_regular, tmp_path):
    """Worker crash mid-flood: burst-granted tasks retry through the
    normal path; resource accounting must still balance (covers the
    crash + retry release paths)."""
    import os as _os

    from ray_tpu.core import runtime as runtime_mod

    flag = str(tmp_path / "died")

    @ray_tpu.remote(max_retries=3)
    def maybe_crash(i, flag=flag):
        if i == 250 and not _os.path.exists(flag):
            open(flag, "w").close()
            _os._exit(1)
        return i

    out = ray_tpu.get([maybe_crash.remote(i) for i in range(500)],
                      timeout=120)
    assert out == list(range(500))
    rt = runtime_mod.get_runtime()
    deadline = time.time() + 10
    while time.time() < deadline:
        if _scheduler_fully_released(rt) and not rt._overcommitted:
            return
        time.sleep(0.1)
    raise AssertionError(
        (rt.scheduler.snapshot(), len(rt._overcommitted)))


def test_cancel_burst_queued_task(ray_start_regular):
    """A burst-granted spec parked in the node's dispatch queue must
    cancel immediately with TaskCancelledError (queued semantics),
    releasing its accounting."""
    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.exceptions import TaskCancelledError

    @ray_tpu.remote
    def slow():
        time.sleep(0.4)
        return 1

    # flood so followers queue at the node behind busy workers
    refs = [slow.remote() for _ in range(60)]
    victim = refs[-1]
    ray_tpu.cancel(victim)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(victim, timeout=60)
    # everything else still completes and accounting balances
    rest = [r for r in refs[:-1]]
    assert ray_tpu.get(rest, timeout=120) == [1] * 59
    rt = runtime_mod.get_runtime()
    deadline = time.time() + 10
    while time.time() < deadline:
        if _scheduler_fully_released(rt) and not rt._overcommitted:
            return
        time.sleep(0.1)
    raise AssertionError(rt.scheduler.snapshot())
