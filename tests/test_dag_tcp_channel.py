"""Unit tests for the cross-node DAG channel transport
(reference model: experimental/channel tests — bounded-buffer
semantics over a P2P link)."""

import threading
import time

import pytest

from ray_tpu.dag.channel import ChannelTimeoutError
from ray_tpu.dag.tcp_channel import (
    TcpChannelListener, TcpChannelReader, TcpChannelWriter,
    adopt_listener, create_listener)


def test_roundtrip_and_order():
    listener = TcpChannelListener(host="127.0.0.1")
    reader = TcpChannelReader(listener)
    writer = TcpChannelWriter([listener.address], capacity=4)
    try:
        got = []
        def consume():
            for seq in range(8):
                got.append(reader.read(seq, timeout=30))
                reader.ack(seq)
        t = threading.Thread(target=consume)
        t.start()
        for seq in range(8):
            writer.write({"seq": seq, "blob": b"x" * 1000}, seq)
        t.join(30)
        assert [g["seq"] for g in got] == list(range(8))
    finally:
        writer.close()
        reader.close()


def test_capacity_backpressure():
    """The writer must block after `capacity` unacked items."""
    listener = TcpChannelListener(host="127.0.0.1")
    reader = TcpChannelReader(listener)
    writer = TcpChannelWriter([listener.address], capacity=2)
    try:
        # reader accepts the connection but consumes nothing yet
        threading.Thread(target=lambda: reader.read(0, timeout=30),
                         daemon=True).start()
        time.sleep(0.2)
        writer.write("a", 0)
        writer.write("b", 1)
        with pytest.raises(ChannelTimeoutError):
            writer.write("c", 2, timeout=0.5)  # window full: blocks
        reader.ack(0)  # one credit frees the window
        writer.write("c", 2, timeout=10)
    finally:
        writer.close()
        reader.close()


def test_fanout_two_readers():
    l1 = TcpChannelListener(host="127.0.0.1")
    l2 = TcpChannelListener(host="127.0.0.1")
    r1, r2 = TcpChannelReader(l1), TcpChannelReader(l2)
    writer = TcpChannelWriter([l1.address, l2.address], capacity=4)
    try:
        out = {}
        def consume(name, r):
            vals = []
            for seq in range(4):
                vals.append(r.read(seq, timeout=30))
                r.ack(seq)
            out[name] = vals
        ts = [threading.Thread(target=consume, args=("a", r1)),
              threading.Thread(target=consume, args=("b", r2))]
        for t in ts:
            t.start()
        for seq in range(4):
            writer.write(seq * 10, seq)
        for t in ts:
            t.join(30)
        assert out["a"] == out["b"] == [0, 10, 20, 30]
    finally:
        writer.close()
        r1.close()
        r2.close()


def test_registry_create_adopt():
    addr = create_listener("tok-1")
    assert isinstance(addr, tuple) and addr[1] > 0
    writer = TcpChannelWriter([("127.0.0.1", addr[1])], capacity=2)
    reader = adopt_listener("tok-1")
    try:
        writer.write("hello", 0)
        assert reader.read(0, timeout=10) == "hello"
        reader.ack(0)
    finally:
        writer.close()
        reader.close()


def test_reader_disconnect_surfaces():
    listener = TcpChannelListener(host="127.0.0.1")
    reader = TcpChannelReader(listener)
    writer = TcpChannelWriter([listener.address], capacity=1)

    def accept_then_die():
        # reader.close() below severs the link mid-read: the expected
        # ChannelTimeoutError must not escape the helper thread (pytest
        # records unhandled thread exceptions as a suite warning)
        try:
            reader.read(0, timeout=10)
        except ChannelTimeoutError:
            pass

    threading.Thread(target=accept_then_die, daemon=True).start()
    time.sleep(0.2)
    writer.write("x", 0)
    reader.close()
    time.sleep(0.2)
    with pytest.raises(ChannelTimeoutError):
        # window is full and the reader is gone: must error, not hang
        writer.write("y", 1, timeout=2)
    writer.close()
