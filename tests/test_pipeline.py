"""Pipeline-parallel training: 1F1B/GPipe schedule goldens, stage
partitioning, boundary reshard math, and end-to-end MPMD execution
over the compiled DAG (parity vs a single-process reference, bounded
in-flight under capacity-1 channels, stage-death error propagation,
and the DDP x pipeline composition)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from ray_tpu.train.pipeline import schedule as sched
from ray_tpu.train.pipeline.partition import (
    LayeredModel, balanced_ranges, partition_model)
from ray_tpu.train.pipeline.reshard import reshard_boundary


# ----------------------------------------------------------------------
# schedule goldens (pure python, no actors)
# ----------------------------------------------------------------------

def _ops(instrs):
    return [i.op for i in instrs if i.op in (sched.FWD, sched.BWD)]


def test_1f1b_warmup_depth_per_stage():
    """Warmup depth is min(stages - stage, microbatches): the last
    stage runs exactly one forward before its first backward, stage 0
    fills the whole pipeline."""
    s, m = 4, 8
    for stage in range(s):
        depth = sched.warmup_depth(stage, s, m)
        assert depth == min(s - stage, m)
        compute = _ops(sched.stage_schedule(stage, s, m, "1f1b"))
        assert compute[:depth] == [sched.FWD] * depth
        assert compute[depth] == sched.BWD
    assert sched.warmup_depth(s - 1, s, m) == 1


def test_1f1b_golden_middle_stage():
    """Exact instruction stream for stage 1 of (3 stages, 4 mb)."""
    got = [repr(i) for i in sched.stage_schedule(1, 3, 4, "1f1b")]
    assert got == [
        # warmup: two forwards
        "RECV(act,0)", "FWD(0)", "SEND(act,0)",
        "RECV(act,1)", "FWD(1)", "SEND(act,1)",
        # steady: strict BWD/FWD alternation
        "RECV(grad,0)", "BWD(0)", "SEND(grad,0)",
        "RECV(act,2)", "FWD(2)", "SEND(act,2)",
        "RECV(grad,1)", "BWD(1)", "SEND(grad,1)",
        "RECV(act,3)", "FWD(3)", "SEND(act,3)",
        # drain: the remaining backwards
        "RECV(grad,2)", "BWD(2)", "SEND(grad,2)",
        "RECV(grad,3)", "BWD(3)", "SEND(grad,3)",
        "STEP",
    ]


def test_1f1b_steady_alternation_and_drain():
    s, m = 3, 6
    for stage in range(s):
        warm = sched.warmup_depth(stage, s, m)
        compute = _ops(sched.stage_schedule(stage, s, m, "1f1b"))
        steady = compute[warm:warm + 2 * (m - warm)]
        assert steady == [sched.BWD, sched.FWD] * (m - warm)
        assert compute[warm + 2 * (m - warm):] == [sched.BWD] * warm


def test_gpipe_fill_drain():
    instrs = sched.stage_schedule(1, 3, 4, "gpipe")
    compute = _ops(instrs)
    assert compute == [sched.FWD] * 4 + [sched.BWD] * 4
    assert sched.max_in_flight(instrs) == 4  # all mbs live at the turn
    assert instrs[-1].op == sched.STEP


def test_1f1b_in_flight_bounded_by_warmup():
    """1F1B's activation-memory bound: peak live microbatches equals
    the warmup depth, independent of M."""
    for s, m in [(2, 8), (3, 12), (4, 16)]:
        for stage in range(s):
            instrs = sched.stage_schedule(stage, s, m, "1f1b")
            assert sched.max_in_flight(instrs) == \
                sched.warmup_depth(stage, s, m)


def test_validate_schedule_many_configs():
    for s, m in [(1, 1), (2, 2), (3, 4), (4, 8), (5, 5), (3, 12),
                 (8, 8), (4, 2)]:
        for name in sched.SCHEDULES:
            sched.validate_schedule(s, m, name)


def test_bubble_fraction():
    assert sched.bubble_fraction(3, 4) == pytest.approx(2 / 6)
    assert sched.bubble_fraction(1, 4) == 0.0
    # more microbatches amortize the fill/drain ramps
    assert (sched.bubble_fraction(4, 16)
            < sched.bubble_fraction(4, 4))


def test_schedule_arg_errors():
    with pytest.raises(ValueError, match="unknown schedule"):
        sched.stage_schedule(0, 2, 2, "interleaved")
    with pytest.raises(ValueError, match="out of range"):
        sched.stage_schedule(2, 2, 2, "1f1b")
    with pytest.raises(ValueError, match="num_microbatches"):
        sched.build_schedule(2, 0, "1f1b")


# ----------------------------------------------------------------------
# partitioner + reshard math
# ----------------------------------------------------------------------

def test_balanced_ranges_minimizes_max():
    # equal-layer split would put both fat layers in one stage
    ranges = balanced_ranges([5, 1, 1, 1, 5, 1], 3)
    assert ranges[0][0] == 0 and ranges[-1][1] == 6
    assert all(b > a for a, b in ranges)
    weights = [5, 1, 1, 1, 5, 1]
    max_sum = max(sum(weights[a:b]) for a, b in ranges)
    assert max_sum == 6  # [5,1] [1,1] [5,1] (or equivalent)
    with pytest.raises(ValueError, match="non-empty"):
        balanced_ranges([1, 2], 3)


def test_partition_model_contiguous_and_stitched():
    layers = [{"w": np.ones((4, 4), np.float32) * i} for i in range(6)]
    model = LayeredModel(layers, lambda p, x: x, lambda o, t: 0.0)
    plans = partition_model(model, 3)
    assert [p.stage_id for p in plans] == [0, 1, 2]
    assert plans[0].is_first and plans[-1].is_last
    assert plans[0].start == 0 and plans[-1].stop == 6
    seen = [lp for p in plans for lp in p.layer_params]
    assert len(seen) == 6
    for i, lp in enumerate(seen):
        assert float(lp["w"][0, 0]) == float(i)


def test_reshard_boundary_local_paths():
    full = np.arange(24, dtype=np.float32).reshape(8, 3)
    shards2 = [full[:4], full[4:]]
    # identity when part counts agree
    out = reshard_boundary(shards2[0], src_parts=2, dst_parts=2,
                           dst_rank=0)
    np.testing.assert_array_equal(out, shards2[0])
    # 2 -> 4: every dst rank gets its quarter of the batch dim
    for r in range(4):
        out = reshard_boundary(shards2[0], src_parts=2, dst_parts=4,
                               dst_rank=r, all_shards=shards2)
        np.testing.assert_array_equal(out, full[2 * r:2 * r + 2])
    with pytest.raises(ValueError, match="group_name"):
        reshard_boundary(shards2[0], src_parts=2, dst_parts=4,
                         dst_rank=0)


# ----------------------------------------------------------------------
# end-to-end over the compiled DAG
# ----------------------------------------------------------------------

_D, _L = 8, 6


def _make_layers(seed=0):
    rng = np.random.RandomState(seed)
    return [{"w": rng.randn(_D, _D).astype(np.float32) * 0.3,
             "b": np.zeros(_D, dtype=np.float32)} for _ in range(_L)]


def _model_fns():
    """Stage fwd/loss as CLOSURES: worker processes can't import the
    test module, so the functions must pickle by value."""
    def apply_layer(p, x):
        import jax.numpy as jnp
        return jnp.tanh(x @ p["w"] + p["b"])

    def loss_fn(out, tgt):
        import jax.numpy as jnp
        return jnp.mean((out - tgt) ** 2)

    return apply_layer, loss_fn


def _reference_run(layers, x, y, steps, microbatches, lr=0.05,
                   fns=None):
    """Single-process microbatched-SGD reference: per-step mean loss
    (at pre-update params) and the final per-layer params."""
    import jax
    import jax.numpy as jnp

    apply_layer, loss_fn = fns or _model_fns()
    params = [dict(w=jnp.asarray(l["w"]), b=jnp.asarray(l["b"]))
              for l in layers]

    def full_loss(ps, xb, yb):
        h = jnp.asarray(xb)
        for p in ps:
            h = apply_layer(p, h)
        return loss_fn(h, jnp.asarray(yb))

    losses = []
    for _ in range(steps):
        gacc, lsum = None, 0.0
        for xm, ym in zip(np.array_split(x, microbatches),
                          np.array_split(y, microbatches)):
            loss, g = jax.value_and_grad(full_loss)(params, xm, ym)
            lsum += float(loss)
            gacc = (g if gacc is None else jax.tree_util.tree_map(
                lambda a, b: a + b, gacc, g))
        losses.append(lsum / microbatches)
        g = jax.tree_util.tree_map(lambda a: a / microbatches, gacc)
        params = jax.tree_util.tree_map(lambda p, gg: p - lr * gg,
                                        params, g)
    return losses, params


def _assert_param_parity(ref_params, got_params, atol=1e-5):
    assert len(ref_params) == len(got_params)
    for ref, got in zip(ref_params, got_params):
        np.testing.assert_allclose(np.asarray(ref["w"]),
                                   np.asarray(got["w"]), atol=atol)
        np.testing.assert_allclose(np.asarray(ref["b"]),
                                   np.asarray(got["b"]), atol=atol)


@pytest.mark.watchdog(300)
def test_1f1b_parity_with_single_process_reference(ray_start_regular):
    """10 steps of a 3-stage / 4-microbatch 1F1B pipeline land on the
    same losses and parameters (<1e-5) as single-process microbatched
    SGD."""
    from ray_tpu.train.pipeline import PipelineRunner

    layers = _make_layers()
    rng = np.random.RandomState(1)
    x = rng.randn(16, _D).astype(np.float32)
    y = rng.randn(16, _D).astype(np.float32)

    runner = PipelineRunner(
        LayeredModel(layers, *_model_fns()),
        num_stages=3, num_microbatches=4, schedule="1f1b",
        recv_timeout_s=15.0)
    try:
        results = [runner.step(x, y) for _ in range(10)]
        losses = [r["loss"] for r in results]
        ref_losses, ref_params = _reference_run(layers, x, y, 10, 4)
        assert losses[-1] < losses[0]
        np.testing.assert_allclose(losses, ref_losses, atol=1e-5)
        _assert_param_parity(ref_params, runner.fetch_params())
        # every report carries the measured bubble + live bound
        for r in results:
            assert 0.0 <= r["bubble"] <= 1.0
            assert r["theoretical_bubble"] == pytest.approx(2 / 6)
    finally:
        runner.shutdown()


@pytest.mark.watchdog(300)
def test_tcp_transport_parity(ray_start_regular):
    """The same pipeline over native-wire TCP channels (loop-registered,
    no per-connection reader threads) reproduces the reference losses."""
    import threading

    from ray_tpu.train.pipeline import PipelineRunner

    layers = _make_layers()
    rng = np.random.RandomState(2)
    x = rng.randn(16, _D).astype(np.float32)
    y = rng.randn(16, _D).astype(np.float32)

    before = threading.active_count()
    runner = PipelineRunner(
        LayeredModel(layers, *_model_fns()),
        num_stages=3, num_microbatches=4, schedule="1f1b",
        transport="tcp", recv_timeout_s=15.0)
    try:
        losses = [runner.step(x, y)["loss"] for _ in range(3)]
        ref_losses, _ = _reference_run(layers, x, y, 3, 4)
        np.testing.assert_allclose(losses, ref_losses, atol=1e-5)
        # O(1) thread topology: the DRIVER process gained no reader
        # threads for the 4 TCP links (all IO rides the shared loop)
        assert threading.active_count() <= before + 2
    finally:
        runner.shutdown()


@pytest.mark.watchdog(300)
def test_capacity_one_channel_bounds_in_flight(ray_start_regular):
    """With capacity-1 activation channels the pipeline still completes,
    and each stage's live-microbatch peak equals its 1F1B warmup depth
    (the schedule's memory bound, enforced under real backpressure)."""
    from ray_tpu.train.pipeline import PipelineRunner

    layers = _make_layers()
    rng = np.random.RandomState(3)
    x = rng.randn(12, _D).astype(np.float32)
    y = rng.randn(12, _D).astype(np.float32)

    runner = PipelineRunner(
        LayeredModel(layers, *_model_fns()),
        num_stages=3, num_microbatches=6, schedule="1f1b",
        channel_capacity=1, recv_timeout_s=15.0)
    try:
        result = runner.step(x, y)
        assert result["loss"] is not None
        for report in result["reports"]:
            warm = sched.warmup_depth(report["stage"], 3, 6)
            assert report["max_live"] == warm
    finally:
        runner.shutdown()


@pytest.mark.watchdog(300)
def test_stage_death_surfaces_dag_error(ray_start_regular):
    """A stage dying mid-step propagates as DAGExecutionError from
    CompiledDAGRef.get(), naming the stage."""
    from ray_tpu.dag import DAGExecutionError
    from ray_tpu.train.pipeline import PipelineRunner

    layers = _make_layers()
    rng = np.random.RandomState(4)
    x = rng.randn(8, _D).astype(np.float32)
    y = rng.randn(8, _D).astype(np.float32)

    runner = PipelineRunner(
        LayeredModel(layers, *_model_fns()),
        num_stages=3, num_microbatches=4, schedule="1f1b",
        recv_timeout_s=3.0)
    try:
        assert runner.step(x, y)["loss"] is not None  # healthy first
        runner.inject_failure(1)
        with pytest.raises(DAGExecutionError, match="pipeline stage"):
            runner.execute_async(x, y).get(60.0)
    finally:
        runner.shutdown()


_DDP_PIPELINE_SCRIPT = r"""
import numpy as np
import ray_tpu
from ray_tpu.train.pipeline import LayeredModel, PipelineRunner
import jax.numpy as jnp

def apply_layer(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])

def loss_fn(out, tgt):
    return jnp.mean((out - tgt) ** 2)

D, L, M = 8, 4, 2
rng = np.random.RandomState(0)
layers = [{"w": rng.randn(D, D).astype(np.float32) * 0.3,
           "b": np.zeros(D, dtype=np.float32)} for _ in range(L)]

ray_tpu.init(num_cpus=8, system_config={"task_max_retries": 0})
model = LayeredModel(layers, apply_layer, loss_fn)
# two data-parallel replicas of a 2-stage pipeline: replicas of the
# same stage share a collective group and allreduce at STEP
runners = [
    PipelineRunner(model, num_stages=2, num_microbatches=M,
                   schedule="1f1b", recv_timeout_s=20.0,
                   dp_group=("ddp", 2, r))
    for r in range(2)
]
xs = [rng.randn(8, D).astype(np.float32) for _ in range(2)]
ys = [rng.randn(8, D).astype(np.float32) for _ in range(2)]
for _ in range(3):
    # both replicas must be in flight before either result is awaited:
    # the per-stage allreduce blocks until its peer arrives
    refs = [r.execute_async(x, y) for r, x, y in zip(runners, xs, ys)]
    reports = [ref.get(90.0) for ref in refs]
    assert all(rep[-1]["loss"] is not None for rep in reports)

p0, p1 = runners[0].fetch_params(), runners[1].fetch_params()
for a, b in zip(p0, p1):
    np.testing.assert_allclose(np.asarray(a["w"]), np.asarray(b["w"]),
                               atol=1e-6)
for r in runners:
    r.shutdown()
ray_tpu.shutdown()
print("DDP-PIPE-OK")
"""


@pytest.mark.multidevice
@pytest.mark.watchdog(300)
def test_ddp_pipeline_composition():
    """DDP x pipeline: two data-parallel replicas of a 2-stage pipeline
    allreduce within per-stage groups and stay bitwise-synchronized.
    Runs in a subprocess (cpu_mesh_env) per the multidevice contract."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from __graft_entry__ import cpu_mesh_env
    proc = subprocess.run(
        [sys.executable, "-c", _DDP_PIPELINE_SCRIPT],
        env=cpu_mesh_env(2), capture_output=True, text=True,
        timeout=240,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, (proc.stdout[-2000:]
                                  + proc.stderr[-2000:])
    assert "DDP-PIPE-OK" in proc.stdout


@pytest.mark.slow
@pytest.mark.watchdog(400)
def test_measured_bubble_1f1b_below_gpipe(ray_start_regular):
    """Under capacity-1 channels GPipe's fill phase stalls on
    backpressure (all M activations want to be in flight); 1F1B keeps
    at most warmup-depth in flight, so its measured bubble is lower on
    the same config."""
    from ray_tpu.train.pipeline import PipelineRunner

    layers = _make_layers()
    rng = np.random.RandomState(5)
    x = rng.randn(32, _D).astype(np.float32)
    y = rng.randn(32, _D).astype(np.float32)

    bubbles = {}
    for name in ("gpipe", "1f1b"):
        runner = PipelineRunner(
            LayeredModel(layers, *_model_fns()),
            num_stages=3, num_microbatches=8, schedule=name,
            channel_capacity=1, recv_timeout_s=20.0)
        try:
            runner.step(x, y)  # warm the jit caches
            vals = [runner.step(x, y)["bubble"] for _ in range(3)]
            bubbles[name] = sum(vals) / len(vals)
        finally:
            runner.shutdown()
    assert bubbles["1f1b"] < bubbles["gpipe"], bubbles
