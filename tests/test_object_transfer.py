"""Object-plane admission control + RPC retry/chaos.

Covers reference capabilities: pull admission control with prioritized
queues (reference: src/ray/object_manager/pull_manager.h:50), in-flight
byte budget (reference: push_manager.h:28), retryable idempotent RPC
(reference: src/ray/rpc/retryable_grpc_client.h), and env-gated fault
injection (reference: src/ray/rpc/rpc_chaos.h:24-46).
"""

import os
import threading
import time

import pytest

from ray_tpu.core import object_transfer, protocol
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_transfer import (
    PRIORITY_BACKGROUND,
    PRIORITY_GET,
    PRIORITY_TASK_ARG,
    ObjectServer,
    PullManager,
    _ByteBudget,
    pull_object,
)


class _Store:
    """In-memory store satisfying both the ObjectServer source side
    (get_buffer/release) and the pull destination side
    (contains/create/seal/delete)."""

    def __init__(self):
        self._bufs = {}
        self._sealed = {}
        self._lock = threading.Lock()

    def put(self, oid, payload: bytes):
        with self._lock:
            self._bufs[oid] = bytearray(payload)
            self._sealed[oid] = True

    def contains(self, oid):
        with self._lock:
            return self._sealed.get(oid, False)

    def create(self, oid, size):
        with self._lock:
            if oid in self._bufs:
                raise FileExistsError(oid.hex())
            self._bufs[oid] = bytearray(size)
            self._sealed[oid] = False
            return memoryview(self._bufs[oid])

    def seal(self, oid):
        with self._lock:
            self._sealed[oid] = True

    def delete(self, oid):
        with self._lock:
            self._bufs.pop(oid, None)
            self._sealed.pop(oid, None)

    def get_buffer(self, oid, timeout_s=0.0):
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                if self._sealed.get(oid):
                    return memoryview(self._bufs[oid])
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.005)

    def release(self, oid):
        pass


@pytest.fixture
def server_store():
    store = _Store()
    server = ObjectServer(lambda oid: store if store.contains(oid) else None)
    yield server, store
    server.stop()


def test_pull_roundtrip(server_store):
    server, store = server_store
    oid = ObjectID.from_random()
    payload = os.urandom(2 * 1024 * 1024 + 17)
    store.put(oid, payload)
    dest = _Store()
    assert pull_object(server.address, oid, dest)
    buf = dest.get_buffer(oid, timeout_s=1.0)
    assert bytes(buf) == payload


def test_byte_budget_invariant():
    """Concurrent charges never exceed the cap (except a lone oversize
    charge), and waiters make progress."""
    budget = _ByteBudget(16 * 1024 * 1024)
    peak = [0]
    peak_lock = threading.Lock()

    def worker():
        for _ in range(5):
            budget.charge(8 * 1024 * 1024)
            with peak_lock:
                peak[0] = max(peak[0], budget.inflight_bytes)
            time.sleep(0.002)
            budget.release(8 * 1024 * 1024)

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert peak[0] <= 16 * 1024 * 1024
    assert budget.inflight_bytes == 0


def test_byte_budget_oversize_admitted_alone():
    budget = _ByteBudget(1024)
    budget.charge(10_000)  # must not deadlock: sole pull always admitted
    assert budget.inflight_bytes == 10_000
    budget.release(10_000)
    assert budget.inflight_bytes == 0


def test_pull_manager_budget_respected(server_store):
    """N concurrent pulls of real objects keep in-flight bytes under the
    budget (VERDICT round-2 item 7 done-criterion, scaled down)."""
    server, store = server_store
    size = 4 * 1024 * 1024
    oids = []
    for _ in range(6):
        oid = ObjectID.from_random()
        store.put(oid, os.urandom(size))
        oids.append(oid)
    mgr = PullManager(max_concurrent=6, max_inflight_bytes=2 * size)
    peak = [0]
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            peak[0] = max(peak[0], mgr.budget.inflight_bytes)
            time.sleep(0.0005)

    sampler_t = threading.Thread(target=sampler, daemon=True)
    sampler_t.start()
    results = [None] * len(oids)
    dests = [_Store() for _ in oids]

    def do_pull(i):
        results[i] = mgr.pull(server.address, oids[i], dests[i],
                              priority=PRIORITY_GET)

    threads = [threading.Thread(target=do_pull, args=(i,))
               for i in range(len(oids))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    sampler_t.join()
    assert all(results)
    assert peak[0] <= 2 * size


def test_pull_manager_priority_order(monkeypatch):
    """With one slot busy, a later TASK_ARG pull is admitted before an
    earlier-queued BACKGROUND pull."""
    order = []
    release_first = threading.Event()
    entered_first = threading.Event()

    def fake_pull(addr, oid, dest, timeout=30.0, budget=None):
        if not entered_first.is_set():
            entered_first.set()
            release_first.wait(5.0)
        order.append(oid)
        return True

    monkeypatch.setattr(object_transfer, "pull_object", fake_pull)
    mgr = PullManager(max_concurrent=1)
    dest = _Store()
    oid_hold, oid_bg, oid_arg = (ObjectID.from_random() for _ in range(3))
    threads = [threading.Thread(
        target=mgr.pull, args=(("h", 0), oid_hold, dest),
        kwargs={"priority": PRIORITY_GET})]
    threads[0].start()
    assert entered_first.wait(5.0)
    # Queue background first, then task-arg; both wait on the one slot.
    threads.append(threading.Thread(
        target=mgr.pull, args=(("h", 0), oid_bg, dest),
        kwargs={"priority": PRIORITY_BACKGROUND}))
    threads[1].start()
    time.sleep(0.1)
    threads.append(threading.Thread(
        target=mgr.pull, args=(("h", 0), oid_arg, dest),
        kwargs={"priority": PRIORITY_TASK_ARG}))
    threads[2].start()
    time.sleep(0.1)
    release_first.set()
    for t in threads:
        t.join(10.0)
    assert order == [oid_hold, oid_arg, oid_bg]


def test_retry_call_recovers():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("transient")
        return "ok"

    assert protocol.retry_call(flaky, attempts=4, backoff_s=0.001) == "ok"
    assert len(calls) == 3


def test_retry_call_exhausts():
    def always_down():
        raise ConnectionResetError("down")

    with pytest.raises(ConnectionResetError):
        protocol.retry_call(always_down, attempts=2, backoff_s=0.001)


def test_chaos_injected_pull_failure_recovered(server_store, monkeypatch):
    """RTPU_RPC_CHAOS drops the first two PULL sends; the PullManager's
    bounded retry still lands the object (reference: rpc_chaos.h +
    retryable_grpc_client.h interplay)."""
    server, store = server_store
    oid = ObjectID.from_random()
    payload = os.urandom(128 * 1024)
    store.put(oid, payload)
    monkeypatch.setenv("RTPU_RPC_CHAOS", "PULL=fail:2")
    try:
        mgr = PullManager(max_concurrent=2)
        dest = _Store()
        assert mgr.pull(server.address, oid, dest, attempts=3)
        assert bytes(dest.get_buffer(oid, timeout_s=1.0)) == payload
    finally:
        monkeypatch.delenv("RTPU_RPC_CHAOS")
        protocol._maybe_chaos(None)  # reset cached spec


def test_chaos_delay(monkeypatch):
    monkeypatch.setenv("RTPU_RPC_CHAOS", "PING=delay:30")
    try:
        t0 = time.perf_counter()
        protocol._maybe_chaos("PING")
        assert time.perf_counter() - t0 >= 0.025
        t0 = time.perf_counter()
        protocol._maybe_chaos("OTHER")
        assert time.perf_counter() - t0 < 0.02
    finally:
        monkeypatch.delenv("RTPU_RPC_CHAOS")
        protocol._maybe_chaos(None)
