"""ray_tpu.data tests.

Models the reference's data test strategy (reference: python/ray/data/tests —
deterministic execution over synthetic datasets, per-op unit coverage).
"""

import os

import numpy as np
import pyarrow as pa
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module", autouse=True)
def _rt():
    rt = ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield rt
    ray_tpu.shutdown()


def test_range_count_take():
    ds = rd.range(100)
    assert ds.count() == 100
    rows = ds.take(5)
    assert rows == [{"id": i} for i in range(5)]


def test_from_items_and_schema():
    ds = rd.from_items([{"x": i, "y": str(i)} for i in range(10)])
    assert ds.count() == 10
    assert set(ds.columns()) == {"x", "y"}
    assert ds.take_all()[-1]["y"] == "9"


def test_map_and_filter_and_flat_map():
    ds = rd.range(20).map(lambda r: {"id": r["id"] * 2})
    assert ds.take(3) == [{"id": 0}, {"id": 2}, {"id": 4}]
    ds2 = rd.range(20).filter(lambda r: r["id"] % 5 == 0)
    assert sorted(r["id"] for r in ds2.take_all()) == [0, 5, 10, 15]
    ds3 = rd.from_items([{"v": 1}, {"v": 2}]).flat_map(
        lambda r: [{"v": r["v"]}, {"v": -r["v"]}])
    assert sorted(r["v"] for r in ds3.take_all()) == [-2, -1, 1, 2]


def test_map_batches_numpy():
    ds = rd.range(32).map_batches(
        lambda b: {"id": b["id"] + 100}, batch_size=8)
    out = sorted(r["id"] for r in ds.take_all())
    assert out == list(range(100, 132))


def test_map_batches_pandas_format():
    def add_col(df):
        df = df.copy()
        df["double"] = df["id"] * 2
        return df

    ds = rd.range(10).map_batches(add_col, batch_format="pandas")
    row = ds.take(1)[0]
    assert row == {"id": 0, "double": 0}


def test_map_batches_callable_class_actors():
    class Doubler:
        def __init__(self):
            self.calls = 0

        def __call__(self, batch):
            self.calls += 1
            return {"id": batch["id"] * 2}

    ds = rd.range(16).map_batches(Doubler, batch_size=4, concurrency=2)
    assert sorted(r["id"] for r in ds.take_all()) == [2 * i for i in range(16)]


def test_fusion_single_stage():
    ds = rd.range(8).map(lambda r: {"id": r["id"] + 1}).filter(
        lambda r: r["id"] > 4).map(lambda r: {"id": r["id"] * 10})
    # One fused physical map stage.
    from ray_tpu.data.planner import Planner
    phys = Planner(ds.context).plan(ds._plan)
    from ray_tpu.data.execution import MapPhysicalOp
    assert isinstance(phys, MapPhysicalOp)
    assert len(phys.transforms) == 3
    assert sorted(r["id"] for r in ds.take_all()) == [50, 60, 70, 80]


def test_repartition():
    ds = rd.range(100, parallelism=4).repartition(10)
    mat = ds.materialize()
    assert mat.num_blocks() == 10
    assert mat.count() == 100
    assert sorted(r["id"] for r in mat.take_all()) == list(range(100))


def test_random_shuffle_deterministic_seed():
    a = rd.range(50).random_shuffle(seed=7).take_all()
    b = rd.range(50).random_shuffle(seed=7).take_all()
    assert a == b
    assert sorted(r["id"] for r in a) == list(range(50))
    assert [r["id"] for r in a] != list(range(50))


def test_sort():
    ds = rd.from_items([{"k": i % 7, "v": i} for i in range(30)]).sort("k")
    ks = [r["k"] for r in ds.take_all()]
    assert ks == sorted(ks)
    ds_desc = rd.range(25).sort("id", descending=True)
    ids = [r["id"] for r in ds_desc.take_all()]
    assert ids == list(reversed(sorted(ids)))


def test_groupby_aggregate():
    ds = rd.from_items([{"g": i % 3, "v": float(i)} for i in range(12)])
    out = ds.groupby("g").aggregate(rd.Count(), rd.Sum("v"),
                                    rd.Mean("v")).take_all()
    by_g = {r["g"]: r for r in out}
    assert by_g[0]["count()"] == 4
    assert by_g[0]["sum(v)"] == 0 + 3 + 6 + 9
    assert by_g[1]["mean(v)"] == (1 + 4 + 7 + 10) / 4


def test_global_aggregate():
    ds = rd.range(10)
    assert ds.sum("id") == 45
    assert ds.min("id") == 0
    assert ds.max("id") == 9
    assert ds.mean("id") == 4.5


def test_limit_and_union_and_zip():
    assert rd.range(1000).limit(7).count() == 7
    u = rd.range(5).union(rd.range(3))
    assert u.count() == 8
    z = rd.range(6).zip(rd.range(6).map(lambda r: {"b": r["id"] * 2}))
    rows = sorted(z.take_all(), key=lambda r: r["id"])
    assert rows[3] == {"id": 3, "b": 6}


def test_iter_batches_sizes_and_drop_last():
    ds = rd.range(25)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=10)]
    assert sorted(sizes, reverse=True) == [10, 10, 5]
    sizes = [len(b["id"])
             for b in ds.iter_batches(batch_size=10, drop_last=True)]
    assert sizes == [10, 10]


def test_iter_torch_batches():
    import torch
    ds = rd.range(8)
    batches = list(ds.iter_torch_batches(batch_size=4))
    assert len(batches) == 2
    assert isinstance(batches[0]["id"], torch.Tensor)


def test_iter_device_batches():
    import jax.numpy as jnp
    ds = rd.range(16)
    batches = list(ds.iter_device_batches(batch_size=8, dtypes=jnp.int32))
    assert len(batches) == 2
    assert batches[0]["id"].dtype == jnp.int32


def test_local_shuffle():
    rows = [b["id"].tolist() for b in rd.range(64, parallelism=2).iter_batches(
        batch_size=64, local_shuffle_buffer_size=64, local_shuffle_seed=3)]
    flat = [x for b in rows for x in b]
    assert sorted(flat) == list(range(64))
    assert flat != list(range(64))


def test_write_read_parquet(tmp_path):
    path = str(tmp_path / "pq")
    rd.range(40, parallelism=4).write_parquet(path)
    back = rd.read_parquet(path)
    assert back.count() == 40
    assert sorted(r["id"] for r in back.take_all()) == list(range(40))


def test_write_read_csv_json(tmp_path):
    p1 = str(tmp_path / "csv")
    rd.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}]).write_csv(p1)
    assert rd.read_csv(p1).count() == 2
    p2 = str(tmp_path / "json")
    rd.from_items([{"a": 1}, {"a": 2}, {"a": 3}]).write_json(p2)
    assert rd.read_json(p2).sum("a") == 6


def test_from_pandas_to_pandas():
    import pandas as pd
    df = pd.DataFrame({"x": [1, 2, 3], "y": ["a", "b", "c"]})
    ds = rd.from_pandas(df)
    out = ds.to_pandas()
    assert list(out["x"]) == [1, 2, 3]


def test_split():
    parts = rd.range(40, parallelism=8).split(2)
    assert sum(p.count() for p in parts) == 40


def test_streaming_split_two_consumers():
    splits = rd.range(40, parallelism=8).streaming_split(2)
    seen = []
    for it in splits:
        for b in it.iter_batches(batch_size=None):
            seen.extend(b["id"].tolist())
    assert sorted(seen) == list(range(40))


def test_add_select_drop_rename():
    ds = rd.range(5).add_column("sq", lambda b: b["id"] ** 2)
    assert ds.take(3) == [{"id": 0, "sq": 0}, {"id": 1, "sq": 1},
                          {"id": 2, "sq": 4}]
    assert rd.range(5).add_column("z", lambda b: b["id"]).select_columns(
        ["z"]).columns() == ["z"]
    assert rd.range(5).rename_columns({"id": "n"}).columns() == ["n"]


def test_udf_error_propagates():
    def boom(row):
        raise ValueError("bad row")

    with pytest.raises(Exception):
        rd.range(4).map(boom).take_all()


def test_groupby_map_groups():
    ds = rd.from_items([{"g": i % 2, "v": i} for i in range(10)])
    out = ds.groupby("g").map_groups(
        lambda b: {"g": [int(b["g"][0])], "total": [int(b["v"].sum())]})
    rows = sorted(out.take_all(), key=lambda r: r["g"])
    assert rows == [{"g": 0, "total": 0 + 2 + 4 + 6 + 8},
                    {"g": 1, "total": 1 + 3 + 5 + 7 + 9}]


# --- join -----------------------------------------------------------------

def _join_reference(left_rows, right_rows, on, how):
    """Plain-python join oracle."""
    import collections
    right_by_key = collections.defaultdict(list)
    for r in right_rows:
        right_by_key[tuple(r[k] for k in on)].append(r)
    out = []
    matched_right = set()
    for l in left_rows:
        key = tuple(l[k] for k in on)
        matches = right_by_key.get(key, [])
        if matches:
            for r in matches:
                matched_right.add(id(r))
                row = dict(l)
                for k, v in r.items():
                    if k not in on:
                        row[k + "_r" if k in l else k] = v
                out.append(row)
        elif how in ("left", "outer"):
            out.append(dict(l))
    if how in ("right", "outer"):
        for rows in right_by_key.values():
            for r in rows:
                if id(r) not in matched_right:
                    out.append({k: v for k, v in r.items()})
    return out


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_join(how):
    left_rows = [{"k": i % 5, "lv": i} for i in range(12)]
    right_rows = [{"k": i, "rv": i * 10} for i in range(3, 8)]
    left = rd.from_items(left_rows, parallelism=3)
    right = rd.from_items(right_rows, parallelism=2)
    got = left.join(right, on="k", how=how, num_partitions=4).take_all()
    want = _join_reference(left_rows, right_rows, ["k"], how)

    def norm(rows):
        return sorted(
            (tuple(sorted((k, v) for k, v in r.items() if v is not None)))
            for r in rows)
    assert norm(got) == norm(want), (how, len(got), len(want))


def test_join_multi_key_and_suffix():
    left = rd.from_items(
        [{"a": i % 2, "b": i % 3, "v": i} for i in range(12)])
    right = rd.from_items(
        [{"a": i % 2, "b": i % 3, "v": 100 + i} for i in range(6)])
    out = left.join(right, on=["a", "b"], how="inner").take_all()
    assert out, "multi-key inner join produced nothing"
    assert all("v" in r and "v_r" in r for r in out)


def test_join_empty_side():
    left = rd.from_items([{"k": 1, "v": 2}])
    empty = rd.from_items([{"k": 9, "w": 0}]).filter(lambda r: False)
    assert left.join(empty, on="k", how="inner").take_all() == []


def test_memory_backpressure_budget():
    """A stream over ~8MB of blocks with a 1MB budget must still finish,
    and queued bytes must stay near the budget (sources pause)."""
    from ray_tpu.data.context import DataContext
    ctx = DataContext.get_current()
    old = ctx.memory_budget_bytes
    ctx.memory_budget_bytes = 1 * 1024 * 1024
    try:
        ds = rd.range_tensor(64, shape=(16384,), parallelism=16)  # 8MB
        total = 0
        it = ds.map_batches(lambda b: b, batch_format="numpy")
        executor = None
        for batch in it.iter_batches(batch_size=None):
            total += 1
        assert total > 0
        # peak accounting: rebuild with explicit executor to observe
        from ray_tpu.data.planner import Planner
        from ray_tpu.data.execution import StreamingExecutor
        plan = Planner().plan(ds._plan)
        ex = StreamingExecutor(plan)
        for _ in ex.execute():
            pass
        budget = ex.resource_manager.budget
        # sources pause above budget; in-flight tasks can overshoot by
        # roughly one round of task outputs
        slack = 16 * 128 * 1024  # one block per in-flight task
        assert ex.resource_manager.peak_queued_bytes <= budget + slack, (
            ex.resource_manager.peak_queued_bytes, budget)
    finally:
        ctx.memory_budget_bytes = old


def test_sort_with_tiny_budget_no_deadlock():
    """Barrier ops buffering more than the budget must not deadlock the
    source-pause logic (the barrier can't consume until sources finish)."""
    from ray_tpu.data.context import DataContext
    ctx = DataContext.get_current()
    old = ctx.memory_budget_bytes
    ctx.memory_budget_bytes = 64 * 1024  # far below the dataset size
    try:
        ds = rd.from_items(
            [{"id": i, "pad": "x" * 8192} for i in range(64)],
            parallelism=8)  # 512KB total >> 64KB budget
        out = ds.sort("id").take(3)
        assert [r["id"] for r in out] == [0, 1, 2]
    finally:
        ctx.memory_budget_bytes = old


def test_left_join_empty_left_is_empty():
    left = rd.from_items([{"k": 1, "v": 2}]).filter(lambda r: False)
    right = rd.from_items([{"k": 1, "w": 3}])
    assert left.join(right, on="k", how="left").take_all() == []
    assert right.join(left, on="k", how="right").take_all() == []


def test_actor_pool_autoscaling():
    """concurrency=(min, max): the pool grows under load and stays
    within bounds; results are correct either way."""
    from ray_tpu.data.execution import _ActorPool

    class AddOne:
        def __call__(self, batch):
            return {"v": np.asarray(batch["v"]) + 1}

    ds = rd.from_items([{"v": i} for i in range(64)], parallelism=8)
    out = ds.map_batches(AddOne, compute="actors",
                         concurrency=(1, 3)).take_all()
    assert sorted(r["v"] for r in out) == list(range(1, 65))

    # unit: pick() scales up only while under max and all actors busy
    pool = _ActorPool((1, 2), {"CPU": 0})
    try:
        i0, _ = pool.pick()
        assert len(pool.actors) == 1
        i1, _ = pool.pick()      # first is busy -> grow
        assert len(pool.actors) == 2
        pool.pick()              # both busy, at max -> no growth
        assert len(pool.actors) == 2
        pool.release(i0)
        pool.release(i1)
        # idle reaping respects min_size and the grace period
        pool.IDLE_REAP_S = 0.0
        pool.maybe_scale_down()
        assert len(pool.actors) >= 1
    finally:
        pool.shutdown()


# --- round-3 data breadth: readers, expressions, preprocessors ----------

def test_read_text_and_binary(ray_start_shared, tmp_path):
    from ray_tpu import data as rd
    p1 = tmp_path / "a.txt"
    p1.write_text("alpha\nbeta\ngamma\n")
    p2 = tmp_path / "b.txt"
    p2.write_text("delta\n")
    ds = rd.read_text([str(p1), str(p2)])
    assert sorted(r["text"] for r in ds.take_all()) == [
        "alpha", "beta", "delta", "gamma"]

    blob = bytes(range(256))
    (tmp_path / "x.bin").write_bytes(blob)
    ds = rd.read_binary_files(str(tmp_path / "x.bin"), include_paths=True)
    rows = ds.take_all()
    assert len(rows) == 1
    assert rows[0]["bytes"] == blob
    assert rows[0]["path"].endswith("x.bin")


def test_read_images(ray_start_shared, tmp_path):
    from PIL import Image

    from ray_tpu import data as rd
    for i, color in enumerate([(255, 0, 0), (0, 255, 0)]):
        Image.new("RGB", (8, 6), color).save(tmp_path / f"img{i}.png")
    ds = rd.read_images(str(tmp_path) + "/*.png", size=(3, 4), mode="RGB")
    rows = ds.take_all()
    assert len(rows) == 2
    arrs = [np.asarray(r["image"], np.uint8) for r in rows]
    assert all(a.shape == (3, 4, 3) for a in arrs)  # (h, w, c) resize
    channels = sorted(int(np.argmax(a.mean(axis=(0, 1)))) for a in arrs)
    assert channels == [0, 1]  # one red-dominant, one green-dominant


def test_read_numpy(ray_start_shared, tmp_path):
    from ray_tpu import data as rd
    arr = np.arange(12, dtype=np.float32).reshape(6, 2)
    np.save(tmp_path / "x.npy", arr)
    ds = rd.read_numpy(str(tmp_path / "x.npy"))
    rows = ds.take_all()
    assert len(rows) == 6
    np.testing.assert_allclose(rows[3]["data"], arr[3])


def test_read_numpy_empty_shard(ray_start_shared, tmp_path):
    # A 0-row .npy shard must produce a valid typed 0-row block.
    from ray_tpu import data as rd
    np.save(tmp_path / "e.npy", np.zeros((0, 5), dtype=np.float32))
    assert rd.read_numpy(str(tmp_path / "e.npy")).take_all() == []


def test_tensor_reads_preserve_dtype(ray_start_shared, tmp_path):
    # uint8 images stay uint8 through arrow (reference read_images
    # semantics) instead of widening to int64 nested lists.
    import pyarrow as pa
    from ray_tpu.data.datasource import _ImageRead, _NumpyRead
    from PIL import Image

    Image.new("RGB", (4, 3), (9, 8, 7)).save(tmp_path / "i.png")
    t = _ImageRead(str(tmp_path / "i.png"))()
    assert t.column("image").type == pa.list_(
        pa.list_(pa.list_(pa.uint8())))
    np.save(tmp_path / "h.npy", np.ones((2, 3), dtype=np.float16))
    t = _NumpyRead(str(tmp_path / "h.npy"))()
    assert t.column("data").type == pa.list_(pa.float16())


def test_expressions_with_column_and_filter(ray_start_shared):
    from ray_tpu import data as rd
    from ray_tpu.data import col, lit
    ds = rd.range(10)  # column "id"
    out = (ds.with_column("double", col("id") * 2)
             .with_column("shifted", col("double") + lit(1))
             .filter(expr=(col("shifted") > 9) & (col("id") != 9)))
    rows = out.take_all()
    assert [r["id"] for r in rows] == [5, 6, 7, 8]
    assert [r["shifted"] for r in rows] == [11, 13, 15, 17]


def test_expressions_replace_existing_column(ray_start_shared):
    from ray_tpu import data as rd
    from ray_tpu.data import col
    ds = rd.range(4).with_column("id", col("id") + 100)
    assert [r["id"] for r in ds.take_all()] == [100, 101, 102, 103]


def test_standard_scaler_chained_into_iter_batches(ray_start_shared):
    """VERDICT round-2 item 9 done-criterion: a preprocessor chained
    into iter_batches."""
    from ray_tpu import data as rd
    from ray_tpu.data.preprocessors import StandardScaler
    values = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
    ds = rd.from_items([{"x": float(v), "y": i}
                        for i, v in enumerate(values)])
    scaler = StandardScaler(columns=["x"]).fit(ds)
    got = np.concatenate([b["x"] for b in
                          scaler.transform(ds).iter_batches(batch_size=2)])
    want = (values - values.mean()) / values.std(ddof=1)
    np.testing.assert_allclose(np.sort(got), np.sort(want), rtol=1e-6)


def test_preprocessor_requires_fit(ray_start_shared):
    from ray_tpu import data as rd
    from ray_tpu.data.preprocessors import (
        PreprocessorNotFittedError, StandardScaler)
    ds = rd.range(4)
    with pytest.raises(PreprocessorNotFittedError):
        StandardScaler(columns=["id"]).transform(ds)


def test_encoders_and_concatenator(ray_start_shared):
    from ray_tpu import data as rd
    from ray_tpu.data.preprocessors import (
        Chain, Concatenator, LabelEncoder, MinMaxScaler, OneHotEncoder)
    rows = [{"size": s, "price": p, "label": lab}
            for s, p, lab in [("S", 1.0, "cheap"), ("M", 5.0, "mid"),
                              ("L", 9.0, "dear"), ("M", 5.0, "mid")]]
    ds = rd.from_items(rows)
    chain = Chain(
        OneHotEncoder(columns=["size"]),
        LabelEncoder(label_column="label"),
        MinMaxScaler(columns=["price"]),
        Concatenator(columns=["size_L", "size_M", "size_S", "price"],
                     output_column_name="features"))
    out = chain.fit_transform(ds).take_all()
    feats = [np.asarray(r["features"], np.float32) for r in out]
    assert all(f.shape == (4,) for f in feats)
    by_label = {r["label"] for r in out}
    assert by_label == {0, 1, 2}  # dense codes
    prices = sorted(float(f[3]) for f in feats)
    assert prices[0] == 0.0 and prices[-1] == 1.0  # min-max scaled


# ------------------------------------------------- public Datasource seam
# (reference: datasource/datasource.py:32 Datasource ABC,
#  read_api.py:360,2078,2418,2645 read_datasource/tfrecords/webdataset/sql)


class _SquaresSource(rd.Datasource):
    """User-defined datasource: n rows of squares split across tasks."""

    def __init__(self, n):
        self.n = n

    def get_read_tasks(self, parallelism):
        import functools
        edges = np.linspace(0, self.n, min(parallelism, self.n) + 1,
                            dtype=int)

        def read(lo, hi):
            ids = np.arange(lo, hi, dtype=np.int64)
            return pa.table({"x": pa.array(ids),
                             "sq": pa.array(ids * ids)})

        return [functools.partial(read, int(lo), int(hi))
                for lo, hi in zip(edges[:-1], edges[1:]) if hi > lo]


def test_read_custom_datasource(ray_start_shared):
    ds = rd.read_datasource(_SquaresSource(10), parallelism=3)
    rows = sorted(ds.take_all(), key=lambda r: r["x"])
    assert [r["sq"] for r in rows] == [i * i for i in range(10)]
    # feeds iter_batches and streaming_split like any built-in reader
    n = sum(len(b["x"]) for b in
            rd.read_datasource(_SquaresSource(10)).iter_batches(
                batch_size=4))
    assert n == 10
    splits = rd.read_datasource(_SquaresSource(8)).streaming_split(2)
    got = []
    for it in splits:
        for b in it.iter_batches(batch_size=8):
            got.extend(int(v) for v in b["x"])
    assert sorted(got) == list(range(8))
    with pytest.raises(ValueError, match="Datasource"):
        rd.read_datasource(object())


def test_write_custom_datasink(ray_start_shared, tmp_path):
    # defined inside the test so cloudpickle ships it by value to the
    # write tasks (test modules are not importable in workers)
    class CountingSink(rd.Datasink):
        def __init__(self, path):
            self.path = path

        def write(self, block):
            import uuid
            os.makedirs(self.path, exist_ok=True)
            full = os.path.join(self.path, uuid.uuid4().hex[:8] + ".txt")
            with open(full, "w") as f:
                f.write(str(block.num_rows))
            return full

        def on_write_complete(self, results):
            with open(os.path.join(self.path, "_SUCCESS"), "w") as f:
                f.write(str(len(results)))

    out = str(tmp_path / "sink")
    rd.range(10).write_datasink(CountingSink(out))
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    parts = [f for f in os.listdir(out) if f.endswith(".txt")]
    total = sum(int(open(os.path.join(out, f)).read()) for f in parts)
    assert total == 10


def test_tfrecords_roundtrip(ray_start_shared, tmp_path):
    """write_tfrecords -> read_tfrecords preserves int/float/bytes/str
    features (in-tree Example protobuf codec + crc32c framing)."""
    out = str(tmp_path / "tfr")
    ds = rd.from_items([
        {"idx": i, "score": i * 0.5, "name": f"row{i}",
         "blob": bytes([i, i + 1])}
        for i in range(6)])
    ds.write_tfrecords(out)
    files = [f for f in os.listdir(out) if f.endswith(".tfrecords")]
    assert files
    back = sorted(rd.read_tfrecords(out).take_all(),
                  key=lambda r: r["idx"])
    assert [r["idx"] for r in back] == list(range(6))
    assert back[2]["score"] == pytest.approx(1.0)
    # str round-trips as bytes (tf.train.Example has only bytes_list)
    assert back[3]["name"] == b"row3"
    assert back[1]["blob"] == bytes([1, 2])
    # a feature appearing only in LATER records still gets a column
    from ray_tpu.data.datasource import _TFRecordRead, encode_example, _masked_crc
    import struct
    path2 = str(tmp_path / "late.tfrecords")
    with open(path2, "wb") as f:
        for rec in ({"a": 1}, {"a": 2, "late": b"x"}):
            data = encode_example(rec)
            header = struct.pack("<Q", len(data))
            f.write(header + struct.pack("<I", _masked_crc(header))
                    + data + struct.pack("<I", _masked_crc(data)))
    t = _TFRecordRead(path2)()
    assert set(t.column_names) == {"a", "late"}
    assert t.column("late").to_pylist() == [None, b"x"]


def test_tfrecords_crc_detects_corruption(tmp_path):
    from ray_tpu.data.datasource import read_tfrecord_file
    out = str(tmp_path / "tfr2")
    rd.from_items([{"a": 1}]).write_tfrecords(out)
    path = os.path.join(out, os.listdir(out)[0])
    data = bytearray(open(path, "rb").read())
    data[-1] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(data))
    with pytest.raises(ValueError, match="crc"):
        read_tfrecord_file(path)


def test_read_webdataset(ray_start_shared, tmp_path):
    import io
    import tarfile
    shard = str(tmp_path / "shard-000.tar")
    with tarfile.open(shard, "w") as tar:
        def add(name, payload):
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
        add("sample_a.txt", b"hello")
        add("sample_a.cls", b"3")
        add("sample_b.txt", b"world")
        add("sample_b.cls", b"7")
        add("sample_b.json", b'{"k": 1}')
        # same basename in different subdirs = DISTINCT samples
        add("train/0001.txt", b"t-one")
        add("val/0001.txt", b"v-one")
    rows = sorted(rd.read_webdataset(shard).take_all(),
                  key=lambda r: r["__key__"])
    keys = [r["__key__"] for r in rows]
    assert keys == ["sample_a", "sample_b", "train/0001", "val/0001"]
    assert rows[0]["txt"] == "hello" and rows[0]["cls"] == 3
    assert rows[1]["txt"] == "world" and rows[1]["cls"] == 7
    assert rows[2]["txt"] == "t-one" and rows[3]["txt"] == "v-one"
    # undecoded mode keeps raw bytes
    raw = rd.read_webdataset(shard, decode=False).take_all()
    assert all(isinstance(r["txt"], bytes) for r in raw)


def _sqlite_factory(path):
    import functools
    import sqlite3
    return functools.partial(sqlite3.connect, path)


def test_sql_roundtrip(ray_start_shared, tmp_path):
    import sqlite3
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE pts (x INTEGER, y REAL)")
    conn.commit()
    conn.close()
    factory = _sqlite_factory(db)
    rd.from_items([{"x": i, "y": i * 1.5} for i in range(8)]).write_sql(
        "INSERT INTO pts VALUES (?, ?)", factory)
    ds = rd.read_sql("SELECT x, y FROM pts ORDER BY x", factory)
    rows = ds.take_all()
    assert [r["x"] for r in rows] == list(range(8))
    assert rows[4]["y"] == pytest.approx(6.0)
    # sharded parallel read: one task per parameter tuple
    ds2 = rd.read_sql("SELECT x, y FROM pts WHERE x >= ? AND x < ?",
                      factory, shards=[(0, 4), (4, 8)])
    assert sorted(r["x"] for r in ds2.take_all()) == list(range(8))


def test_from_torch_and_from_huggingface(ray_start_shared):
    import torch.utils.data as tud

    class Squares(tud.Dataset):
        def __len__(self):
            return 5

        def __getitem__(self, i):
            return i * i

    ds = rd.from_torch(Squares())
    assert [r["item"] for r in ds.take_all()] == [0, 1, 4, 9, 16]

    class Streamy(tud.IterableDataset):
        def __iter__(self):
            return iter(["a", "b"])

    assert [r["item"] for r in rd.from_torch(Streamy()).take_all()] \
        == ["a", "b"]

    # huggingface duck-type: arrow-backed fast path + row fallback
    class FakeData:
        def __init__(self, table):
            self.table = table

    class FakeHF:
        def __init__(self, table):
            self.data = FakeData(table)

    t = pa.table({"x": pa.array([1, 2, 3])})
    out = rd.from_huggingface(FakeHF(t)).take_all()
    assert [r["x"] for r in out] == [1, 2, 3]

    class IterHF:
        def __iter__(self):
            return iter([{"x": 1}, {"x": 2}])

    assert [r["x"] for r in rd.from_huggingface(IterHF()).take_all()] \
        == [1, 2]
