"""North-star scale argument: the REAL Llama-2-7B config lowers over a
simulated v5p-32 (16-device) mesh and fits per-chip HBM (VERDICT r4
item 5 — no v5p hardware here, so the claim is compile-only + memory
accounting from the true sharding rules)."""

import pytest


def test_llama2_7b_lowers_and_fits_v5p32():
    import __graft_entry__ as g

    # conftest forces an 8-device CPU mesh in THIS process; the dryrun
    # spawns its own 16-device CPU subprocess (same pattern the driver
    # uses for dryrun_multichip)
    result = g.dryrun_7b_north_star(16)
    assert result["lowered_ok"]
    assert result["fits"]
    assert result["n_devices"] == 16
    assert result["params_total"] > 6.5e9
    gb = result["per_chip_gb"]
    # fsdp-8 x tp-2: ~13.5 GB params+grads+opt state per chip, leaving
    # ample headroom of the 95 GB for activations at batch 16 x 4096
    assert gb["params"] < 2.0
    assert gb["total"] < 40.0
    assert gb["total"] == pytest.approx(
        gb["params"] + gb["grads"] + gb["optimizer"]
        + gb["activations_est"], abs=0.02)
