"""Test fixtures.

Mirrors the reference's test infrastructure (reference:
python/ray/tests/conftest.py:590 ray_start_regular; :680 ray_start_cluster)
and forces JAX onto a virtual 8-device CPU mesh so every sharding test
runs without TPU hardware (SURVEY.md §7 "Testing without TPUs").
"""

import os
import threading

# Must be set before jax initializes a backend. The TPU-image
# sitecustomize imports jax at interpreter start (before pytest), so the
# env vars alone are too late — update the jax config directly; backends
# are still uninitialized at conftest time.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture
def ray_start_regular():
    """A fresh single-node runtime per test."""
    import ray_tpu
    if ray_tpu.is_initialized():
        # a failed test elsewhere must not cascade into fixture errors
        ray_tpu.shutdown()
    rt = ray_tpu.init(num_cpus=4, system_config={"task_max_retries": 0})
    yield rt
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def ray_start_shared():
    """One runtime shared by a whole test module (faster)."""
    import ray_tpu
    rt = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield rt
    try:
        ray_tpu.shutdown()
    finally:
        # a shutdown that raises mid-teardown (hung serve controller,
        # dead node) must not leave the global runtime set — the next
        # module's fixtures would all error with "already initialized"
        from ray_tpu.core import runtime as runtime_mod
        if runtime_mod.get_runtime_or_none() is not None:
            runtime_mod.set_runtime(None)


@pytest.fixture
def ray_start_cluster():
    """A multi-node simulated cluster; tests add nodes declaratively."""
    from ray_tpu.core.cluster_utils import Cluster
    cluster = Cluster(head_node_args={"resources": {"CPU": 2}})
    yield cluster
    cluster.shutdown()


@pytest.fixture(autouse=True)
def _per_test_watchdog(request):
    """Per-test timeout (pytest-timeout isn't in the image): SIGALRM in
    the main thread interrupts Python-level waits, so a flaky hang in a
    get()/wait() fails the one test instead of stalling the whole run
    (reference: pytest.ini's 180 s default timeout). Long-training
    tests opt into a bigger budget with @pytest.mark.watchdog(N)."""
    import signal

    if threading.current_thread() is not threading.main_thread():
        yield
        return

    marker = request.node.get_closest_marker("watchdog")
    budget = int(marker.args[0]) if marker and marker.args else 150

    def _on_alarm(signum, frame):
        import faulthandler
        import sys
        faulthandler.dump_traceback(file=sys.stderr)
        raise TimeoutError(f"test exceeded {budget} s watchdog")

    old = signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(budget)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture
def cpu_mesh8():
    """An 8-device CPU mesh for sharding tests."""
    import jax
    devices = jax.devices("cpu")
    assert len(devices) >= 8, (
        "conftest must set xla_force_host_platform_device_count=8 before "
        "jax import")
    yield devices[:8]
