"""Chaos drills against the cluster event plane + recovery timelines.

Reference models: python/ray/tests/test_multinode_failures.py (node
death drills) — here each drill must additionally leave a queryable
causal chain: death event -> retries -> lease grants -> lineage
reconstruction, folded into per-incident detect/reschedule/reconstruct
durations by ``ray_tpu.devtools.recovery``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.devtools import recovery
from ray_tpu.exceptions import ActorDiedError
from ray_tpu.util import state


def _pin_soft(node_id):
    from ray_tpu.core.task_spec import SchedulingStrategy
    return SchedulingStrategy(kind="NODE_AFFINITY", node_id=node_id,
                              soft=True)


@pytest.fixture
def drill_cluster():
    from ray_tpu.core.cluster_utils import Cluster
    cluster = Cluster(
        head_node_args={"resources": {"CPU": 2}},
        system_config={"head_port": 0, "heartbeat_timeout_s": 2.5,
                       "object_store_memory": 64 * 1024 * 1024})
    yield cluster
    cluster.shutdown()


@pytest.mark.watchdog(300)
def test_node_death_drill_recovery_timeline(drill_cluster):
    """Freeze a node daemon (SIGSTOP: heartbeats stop, TCP stays open)
    so the head declares it dead via the heartbeat timeout — a genuine
    detect phase — then assert the retried task, the reconstructed
    object, and the recovery_report() fold all chain causally from the
    NODE_DEAD event, via the in-process store AND the CLI snapshot."""
    cluster = drill_cluster
    node_id, proc = cluster.add_remote_node(num_cpus=2)
    try:
        @ray_tpu.remote(max_retries=2)
        def produce():
            return np.arange(100_000, dtype=np.float64)  # shm-sized

        obj = produce.options(
            scheduling_strategy=_pin_soft(node_id)).remote()
        ray_tpu.wait([obj], timeout=30)

        @ray_tpu.remote(max_retries=2)
        def slow():
            import time as t

            import ray_tpu as rt
            t.sleep(2.0)
            return rt.get_runtime_context().get_node_id()

        # soft affinity: starts on the doomed node, retry falls back
        ref = slow.options(
            scheduling_strategy=_pin_soft(node_id)).remote()
        time.sleep(0.5)      # let it start there
        t_freeze = time.time()
        os.kill(proc.pid, signal.SIGSTOP)

        # the head must declare the death via the heartbeat timeout
        # (the frozen daemon keeps its TCP socket open)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if state.list_cluster_events(kinds=["NODE_DEAD"]):
                break
            time.sleep(0.1)
        else:
            pytest.fail("frozen node was never declared dead")
        detect_wall = time.time() - t_freeze

        # reschedule: the death-triggered retry lands on the head
        assert ray_tpu.get(ref, timeout=60) == \
            cluster.head_node_id.hex()
        # reconstruct: the only copy died with the node
        value = ray_tpu.get(obj, timeout=60)
        assert float(value.sum()) == float(np.arange(100_000).sum())

        dead = state.list_cluster_events(kinds=["NODE_DEAD"])
        assert len(dead) == 1
        assert dead[0]["node_id"] == node_id.hex()
        # detection had to ride the heartbeat timeout (2.5s), not a
        # connection drop — SIGSTOP keeps the socket open
        assert dead[0]["data"]["detect_s"] > 1.0
        assert detect_wall > 2.0

        report = recovery.recovery_report(journals={})
        incidents = [inc for inc in report["incidents"]
                     if inc["root_kind"] == "NODE_DEAD"]
        assert len(incidents) == 1
        inc = incidents[0]
        # all three recovery phases measured and nonzero
        assert inc["detect_s"] > 1.0
        assert inc["reschedule_s"] > 0.0
        assert inc["reconstruct_s"] > 0.0
        assert inc["mttr_s"] >= inc["detect_s"]
        # causally chained from the death event
        chain_kinds = {ev["kind"] for ev in inc["chain"]}
        assert {"NODE_DEAD", "TASK_RETRY", "LEASE_GRANTED",
                "RECONSTRUCT_START", "RECONSTRUCT_DONE"} <= chain_kinds
        assert inc["chain"][0]["seq"] == inc["root_seq"]
        assert all(ev["caused_by"] is not None
                   for ev in inc["chain"][1:])
        # the heartbeat-miss precursor is attributed, not part of MTTR
        assert inc["precursor"]["kind"] == "NODE_HEARTBEAT_MISS"
        assert node_id.hex() in inc["affected"]["nodes"]
        assert inc["affected"]["objects"]  # the reconstructed oid
        # printable without raising
        assert "NODE_DEAD" in recovery.render(report)

        # same incident through the out-of-process CLI surface
        from ray_tpu.scripts.cli import _load_state
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            snap = _load_state()
            if snap and any(e["kind"] == "NODE_DEAD"
                            for e in snap.get("events", [])):
                break
            time.sleep(0.2)
        else:
            pytest.fail("NODE_DEAD never reached the state snapshot")
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.cli", "events",
             "--kind", "NODE_DEAD"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0 and "NODE_DEAD" in out.stdout
        # ... and the standalone report CLI folds the same snapshot
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.devtools.recovery",
             "--json"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0
        folded = json.loads(out.stdout)
        assert any(i["root_kind"] == "NODE_DEAD"
                   for i in folded["incidents"])
    finally:
        proc.send_signal(signal.SIGKILL)  # kills stopped processes too
        proc.wait(timeout=10)


@pytest.mark.watchdog(120)
def test_actor_kill_drill_attaches_timeline(ray_start_regular):
    """Kill an actor's worker process; a submission to the now-dead
    actor must fail with an ActorDiedError carrying the incident
    timeline, and the ACTOR_DEAD event must chain to the WORKER_EXIT
    that caused it."""
    @ray_tpu.remote(max_restarts=0)
    class Victim:
        def pid(self):
            import os as _os
            return _os.getpid()

        def slow(self):
            import time as t
            t.sleep(30)

    victim = Victim.remote()
    pid = ray_tpu.get(victim.pid.remote(), timeout=30)
    running = victim.slow.remote()
    time.sleep(0.5)
    os.kill(pid, signal.SIGKILL)

    with pytest.raises(Exception):  # in-flight call dies with the worker
        ray_tpu.get(running, timeout=60)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if state.list_cluster_events(kinds=["ACTOR_DEAD"]):
            break
        time.sleep(0.1)
    else:
        pytest.fail("ACTOR_DEAD event never recorded")

    with pytest.raises(ActorDiedError) as err:
        ray_tpu.get(victim.pid.remote(), timeout=60)
    assert "recovery timeline" in str(err.value)
    assert "WORKER_EXIT" in str(err.value)

    dead = state.list_cluster_events(kinds=["ACTOR_DEAD"])
    assert dead and dead[-1]["caused_by"] is not None
    exits = state.list_cluster_events(kinds=["WORKER_EXIT"],
                                      severity="ERROR")
    assert any(e["seq"] == dead[-1]["caused_by"] for e in exits)

    report = recovery.recovery_report(journals={})
    incidents = [inc for inc in report["incidents"]
                 if "ACTOR_DEAD" in {e["kind"] for e in inc["chain"]}]
    assert incidents
    assert incidents[0]["root_kind"] == "WORKER_EXIT"


def test_events_disabled_kill_switch(ray_start_regular):
    from ray_tpu.core.config import get_config
    cfg = get_config()
    before = len(state.list_cluster_events(limit=100_000))
    cfg.cluster_events_enabled = False
    try:
        @ray_tpu.remote
        def f():
            return 1

        ray_tpu.get(f.remote())
        assert len(state.list_cluster_events(limit=100_000)) == before
    finally:
        cfg.cluster_events_enabled = True


@pytest.mark.watchdog(300)
def test_events_overhead_ratio_guard(ray_start_regular):
    """Event-plane-enabled vs disabled wall time on a tight task loop
    must stay under a generous ratio bound (the committed measured row
    lives in BENCH_core.json; see PERF.md round 16)."""
    from ray_tpu.core.config import get_config

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(500)])   # warmup

    def run_loop(n=1500):
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        return time.perf_counter() - t0

    cfg = get_config()
    saved = cfg.cluster_events_enabled
    try:
        timings = {}
        for mode in ("off", "on", "off", "on"):    # interleave: best-of
            cfg.cluster_events_enabled = (mode == "on")
            timings.setdefault(mode, []).append(run_loop())
        ratio = min(timings["on"]) / min(timings["off"])
    finally:
        cfg.cluster_events_enabled = saved
    # generous: shared-CI noise dominates; the emit is ~1.5us
    assert ratio < 2.0, f"event-plane overhead ratio {ratio:.2f} >= 2.0"
