"""Runtime environment tests (reference model:
python/ray/tests/test_runtime_env*.py — env_vars, working_dir,
py_modules, pip, inheritance, caching)."""

import os
import subprocess
import sys

import pytest

import ray_tpu
from ray_tpu.exceptions import RuntimeEnvSetupError
from ray_tpu.runtime_env import (
    RuntimeEnv,
    merge_runtime_envs,
    normalize_runtime_env,
    runtime_env_hash,
)


def test_validation():
    with pytest.raises(ValueError):
        RuntimeEnv(bogus_field=1)
    with pytest.raises(TypeError):
        RuntimeEnv(env_vars={"A": 1})
    env = RuntimeEnv(env_vars={"A": "1"})
    assert env["env_vars"] == {"A": "1"}


def test_merge_semantics():
    parent = {"env_vars": {"A": "1", "B": "2"}, "working_dir": "kv://pkg/x/y"}
    child = {"env_vars": {"B": "3"}}
    merged = merge_runtime_envs(parent, child)
    assert merged["env_vars"] == {"A": "1", "B": "3"}
    assert merged["working_dir"] == "kv://pkg/x/y"
    assert merge_runtime_envs(None, child) == child
    assert merge_runtime_envs(parent, None) == parent


def test_hash_stability():
    a = {"env_vars": {"X": "1", "Y": "2"}}
    b = {"env_vars": {"Y": "2", "X": "1"}}
    assert runtime_env_hash(a) == runtime_env_hash(b)
    assert runtime_env_hash(a) != runtime_env_hash({"env_vars": {"X": "2"}})


def test_env_vars_applied_and_isolated(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_TEST_FLAG": "hello"}})
    def read_env():
        return os.environ.get("RTPU_TEST_FLAG")

    @ray_tpu.remote
    def read_default():
        return os.environ.get("RTPU_TEST_FLAG")

    assert ray_tpu.get(read_env.remote()) == "hello"
    # default-env workers must not see the var (separate worker pool)
    assert ray_tpu.get(read_default.remote()) is None


def test_env_vars_inherited_by_child_tasks(ray_start_regular):
    @ray_tpu.remote
    def child():
        return os.environ.get("RTPU_INHERIT")

    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_INHERIT": "yes"}})
    def parent():
        return ray_tpu.get(child.remote())

    assert ray_tpu.get(parent.remote()) == "yes"


def test_working_dir(tmp_path, ray_start_regular):
    (tmp_path / "data.txt").write_text("payload-42")
    (tmp_path / "helper_mod.py").write_text("VALUE = 1234\n")
    sub = tmp_path / "skipme"
    sub.mkdir()
    (sub / "big.bin").write_text("x")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path),
                                 "excludes": ["skipme"]})
    def use_working_dir():
        import helper_mod
        with open("data.txt") as f:
            content = f.read()
        return content, helper_mod.VALUE, os.path.exists("skipme")

    content, value, has_excluded = ray_tpu.get(use_working_dir.remote())
    assert content == "payload-42"
    assert value == 1234
    assert not has_excluded


def test_py_modules(tmp_path, ray_start_regular):
    mod_dir = tmp_path / "mymodpkg"
    mod_dir.mkdir()
    (mod_dir / "__init__.py").write_text("ANSWER = 7\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod_dir)]})
    def use_module():
        import mymodpkg
        return mymodpkg.ANSWER

    assert ray_tpu.get(use_module.remote()) == 7


def test_actor_runtime_env(tmp_path, ray_start_regular):
    (tmp_path / "actor_data.txt").write_text("actor-sees-me")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
    class Reader:
        def read(self):
            with open("actor_data.txt") as f:
                return f.read()

    actor = Reader.remote()
    assert ray_tpu.get(actor.read.remote()) == "actor-sees-me"


def test_bad_working_dir_fails_task(ray_start_regular):
    @ray_tpu.remote(
        max_retries=0,
        runtime_env={"working_dir": "kv://pkg/deadbeef/nope"})
    def f():
        return 1

    with pytest.raises((RuntimeEnvSetupError, Exception)) as exc_info:
        ray_tpu.get(f.remote(), timeout=60)
    assert "runtime_env" in str(exc_info.value)


def test_package_cache_reuse(tmp_path, ray_start_regular):
    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.runtime_env import packaging

    (tmp_path / "f.txt").write_text("cache-me")
    rt = runtime_mod.get_runtime()
    uri1 = packaging.upload_package(rt, str(tmp_path))
    uri2 = packaging.upload_package(rt, str(tmp_path))
    assert uri1 == uri2  # content-addressed: identical dirs dedupe

    extracted = packaging.fetch_package(
        uri1, lambda key, ns: rt.gcs_call("kv_get", key, ns))
    marker = os.path.join(extracted, "f.txt")
    assert open(marker).read() == "cache-me"
    # second fetch reuses the directory (no re-extract)
    ino = os.stat(extracted).st_ino
    again = packaging.fetch_package(
        uri1, lambda key, ns: rt.gcs_call("kv_get", key, ns))
    assert os.stat(again).st_ino == ino


def _make_trivial_wheel(tmp_path) -> str:
    """Hand-build a minimal wheel (a zip with METADATA + RECORD) so the
    pip test runs fully offline."""
    import zipfile
    name, version = "rtpu_testpkg", "0.1"
    wheel = tmp_path / f"{name}-{version}-py3-none-any.whl"
    dist_info = f"{name}-{version}.dist-info"
    with zipfile.ZipFile(wheel, "w") as zf:
        zf.writestr(f"{name}.py", "MAGIC = 'from-pip-env'\n")
        zf.writestr(
            f"{dist_info}/METADATA",
            f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n")
        zf.writestr(
            f"{dist_info}/WHEEL",
            "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true\n"
            "Tag: py3-none-any\n")
        zf.writestr(f"{dist_info}/RECORD", "")
    return str(wheel)


def test_pip_runtime_env(tmp_path, ray_start_regular):
    try:
        subprocess.run([sys.executable, "-m", "pip", "--version"],
                       check=True, capture_output=True, timeout=30)
        subprocess.run([sys.executable, "-m", "venv", "--help"],
                       check=True, capture_output=True, timeout=30)
    except Exception:
        pytest.skip("pip/venv unavailable")
    wheel = _make_trivial_wheel(tmp_path)

    @ray_tpu.remote(runtime_env={"pip": {
        "packages": [wheel],
        "pip_install_options": ["--no-index", "--no-deps"]}})
    def use_pip_pkg():
        import rtpu_testpkg
        return rtpu_testpkg.MAGIC

    assert ray_tpu.get(use_pip_pkg.remote(), timeout=110) == "from-pip-env"


def test_normalize_empty_is_none(ray_start_regular):
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime()
    assert normalize_runtime_env({}, rt) is None
    assert normalize_runtime_env(None, rt) is None


# --- conda + container (round 3; reference: _private/runtime_env/
#     conda.py:297, image_uri.py:24) -----------------------------------

def _write_exe(path, text):
    path.write_text(text)
    path.chmod(0o755)
    return str(path)


def _fake_conda(tmp_path):
    """A fake conda executable: `env list --json` reports one named env
    whose bin/python is a wrapper that marks the environment, and
    `env create` materializes a content-addressed env dir."""
    import json
    env_dir = tmp_path / "envs" / "myenv"
    (env_dir / "bin").mkdir(parents=True)
    _write_exe(env_dir / "bin" / "python",
               "#!/bin/sh\nexport RTPU_TEST_CONDA=myenv\n"
               f"exec {sys.executable} \"$@\"\n")
    create_log = tmp_path / "creates.log"
    conda = _write_exe(tmp_path / "conda", f"""#!{sys.executable}
import json, os, pathlib, sys
args = sys.argv[1:]
if args[:3] == ["env", "list", "--json"]:
    print(json.dumps({{"envs": [{json.dumps(str(env_dir))}]}}))
elif args[:2] == ["env", "create"]:
    dest = pathlib.Path(args[args.index("-p") + 1])
    (dest / "bin").mkdir(parents=True)
    py = dest / "bin" / "python"
    py.write_text("#!/bin/sh\\nexport RTPU_TEST_CONDA=created\\n"
                  "exec {sys.executable} \\"$@\\"\\n")
    py.chmod(0o755)
    with open({json.dumps(str(create_log))}, "a") as f:
        f.write("create\\n")
else:
    sys.exit(2)
""".replace("{sys.executable}", sys.executable))
    return conda, create_log


def test_conda_named_env_worker_reexec(tmp_path, monkeypatch):
    conda, _ = _fake_conda(tmp_path)
    monkeypatch.setenv("RTPU_CONDA_EXE", conda)
    rt = ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"conda": "myenv"})
        def which_env():
            return os.environ.get("RTPU_TEST_CONDA")

        assert ray_tpu.get(which_env.remote(), timeout=60) == "myenv"
    finally:
        ray_tpu.shutdown()


def test_conda_dict_spec_created_and_cached(tmp_path, monkeypatch):
    from ray_tpu.runtime_env.conda_env import ensure_conda_env
    conda, create_log = _fake_conda(tmp_path)
    monkeypatch.setenv("RTPU_CONDA_EXE", conda)
    monkeypatch.setenv("RTPU_RUNTIME_ENV_CACHE", str(tmp_path / "cache"))
    spec = {"dependencies": ["numpy"]}
    python = ensure_conda_env(spec)
    assert os.path.exists(python)
    python2 = ensure_conda_env(spec)  # cache hit: no second create
    assert python2 == python
    assert create_log.read_text().count("create") == 1


def test_conda_missing_exe_fails_task(tmp_path, monkeypatch):
    monkeypatch.setenv("RTPU_CONDA_EXE", str(tmp_path / "no-such-conda"))
    rt = ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"conda": "whatever"}, max_retries=0)
        def f():
            return 1

        with pytest.raises(RuntimeEnvSetupError):
            ray_tpu.get(f.remote(), timeout=60)
    finally:
        ray_tpu.shutdown()


def test_pip_and_conda_mutually_exclusive():
    with pytest.raises(ValueError):
        RuntimeEnv(pip=["x"], conda="base")


def test_container_worker_command_shape(tmp_path, monkeypatch):
    from ray_tpu.runtime_env.container import container_worker_command
    fake = _write_exe(tmp_path / "podman", "#!/bin/sh\nexit 0\n")
    monkeypatch.setenv("RTPU_CONTAINER_RUNTIME", fake)
    cmd = container_worker_command(
        "registry/img:1", ["python", "-m", "w"],
        {"RTPU_X": "1", "HOME": "/root", "TPU_CHIPS": "0"},
        mounts=["/a:/a", "/b:/b:ro"])
    assert cmd[0] == fake
    assert cmd[1:5] == ["run", "--rm", "--network=host", "--ipc=host"]
    assert "-v" in cmd and "/a:/a" in cmd and "/b:/b:ro" in cmd
    assert "--env" in cmd and "RTPU_X=1" in cmd and "TPU_CHIPS=0" in cmd
    assert "HOME=/root" not in cmd  # only RTPU_/TPU_/JAX_/PYTHON* pass
    img_idx = cmd.index("registry/img:1")
    assert cmd[img_idx + 1:] == ["python", "-m", "w"]


def test_image_uri_worker_with_fake_runtime(tmp_path, monkeypatch):
    """image_uri e2e against a FAKE container runtime that strips the
    container args and execs the worker on the host (VERDICT round-2
    item 10 done-criterion: config-plumbed + fake-runtime tested)."""
    fake = _write_exe(tmp_path / "fakectr", f"""#!{sys.executable}
import os, sys
args = sys.argv[1:]
os.environ["RTPU_TEST_CONTAINER"] = "1"
idx = args.index("fake:img")
os.execvp(args[idx + 1], args[idx + 1:])
""")
    monkeypatch.setenv("RTPU_CONTAINER_RUNTIME", fake)
    rt = ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"image_uri": "fake:img"})
        def inside():
            return os.environ.get("RTPU_TEST_CONTAINER")

        assert ray_tpu.get(inside.remote(), timeout=60) == "1"
    finally:
        ray_tpu.shutdown()
