"""Podracer RL tests: Anakin multi-device parity, the Sebulba
actor–learner loop end to end (mid-flight weight refresh, staleness,
replay backpressure, actor death), and the flight-recorder rl.* spans
landing in the merged timeline."""

import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl.env import Env
from ray_tpu.rl.spaces import Box, Discrete
from ray_tpu.util import flight_recorder as fr


# --- unit: replay queue + weight wire format --------------------------

def test_fragment_replay_backpressure():
    """Depth is bounded by construction: pushes over capacity evict the
    OLDEST fragment and are counted."""
    from ray_tpu.rl.podracer import FragmentReplay

    q = FragmentReplay(capacity=4)
    for i in range(10):
        dropped = q.push(("meta", i))
        assert dropped == (i >= 4)
        assert q.depth() <= 4
    st = q.stats()
    assert st == {"depth": 4, "capacity": 4, "pushed": 10,
                  "dropped": 6, "popped": 0}
    # oldest got evicted: the survivors are the 4 freshest, FIFO order
    assert [m[1] for m in q.pop_many(99)] == [6, 7, 8, 9]
    assert q.pop_many(1) == []
    assert q.stats()["popped"] == 4


def test_weight_quantize_roundtrip():
    """int8 block quantization of a params pytree survives the wire
    with per-block error, not per-tensor error."""
    import jax
    from ray_tpu.rl.podracer import dequantize_params, quantize_params
    from ray_tpu.rl.rl_module import RLModuleSpec

    spec = RLModuleSpec(Box(-np.ones(4, np.float32),
                            np.ones(4, np.float32)),
                        Discrete(2), (16,))
    params = spec.init(jax.random.PRNGKey(0))
    payload = quantize_params(params)
    rebuilt = dequantize_params(params, payload)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(rebuilt)):
        a, b = np.asarray(a), np.asarray(b)
        assert b.shape == a.shape and b.dtype == a.dtype
        scale = max(np.abs(a).max(), 1e-6)
        assert np.abs(a - b).max() / scale < 0.02

    with pytest.raises(ValueError, match="out of sync"):
        dequantize_params(params, payload[:-1])


def test_weight_push_reaches_saturated_replica():
    """A replica pegged at max_ongoing_requests sheds data-plane
    requests with the Rejected sentinel — which only the router path
    retries — so a weight push through handle_request would silently
    no-op exactly when admission control is active. The control-plane
    entry point must bypass the gate."""
    from ray_tpu.core import serialization
    from ray_tpu.serve.replica import Rejected, Replica

    class _Policy:
        def __init__(self):
            self.version = -1

        def set_weights(self, version, payload):
            self.version = int(version)
            return int(version)

    rep = Replica("d", "d#0", serialization.dumps(_Policy),
                  serialization.dumps(((), {})), max_ongoing_requests=0)
    blob = serialization.dumps(((7, None), {}))
    # saturated data plane: the generic entry point sheds ...
    assert isinstance(rep.handle_request("set_weights", blob), Rejected)
    assert rep.callable.version == -1
    # ... the control plane applies the push anyway
    assert rep.handle_control_request("set_weights", blob) == 7
    assert rep.callable.version == 7


# --- Anakin: multi-device parity --------------------------------------

_ANAKIN_PARITY_SCRIPT = textwrap.dedent("""
    import jax
    import numpy as np
    from ray_tpu.rl.env import make_jax_env
    from ray_tpu.rl.podracer.anakin import (
        AXIS_NAME, AnakinConfig, build_step, init_shard, make_optimizer)
    from ray_tpu.rl.rl_module import RLModuleSpec

    assert jax.device_count() == 8, jax.devices()
    D = 8
    cfg = AnakinConfig(num_envs_per_device=4, rollout_len=8,
                       hidden=(16,), seed=0)
    env = make_jax_env(cfg.env)
    spec = RLModuleSpec(env.observation_space, env.action_space,
                        cfg.hidden)
    step = build_step(env, spec, cfg)

    key = jax.random.PRNGKey(cfg.seed)
    k_model, k_env, k_run = jax.random.split(key, 3)
    params = spec.init(k_model)
    opt_state = make_optimizer(cfg).init(params)
    p_params = jax.device_put_replicated(params, jax.devices())
    p_opt = jax.device_put_replicated(opt_state, jax.devices())
    env_keys = jax.random.split(k_env, D)
    p_env, p_obs = jax.pmap(
        lambda k: init_shard(env, spec, cfg, k))(env_keys)

    # vmap reference: identical math, identical axis_name semantics,
    # one device. Same stacked inputs, same keys.
    v_step = jax.jit(jax.vmap(step, axis_name=AXIS_NAME))
    v_params = jax.tree.map(lambda x: np.asarray(x), p_params)
    v_opt = jax.tree.map(lambda x: np.asarray(x), p_opt)
    v_env = jax.tree.map(lambda x: np.asarray(x), p_env)
    v_obs = np.asarray(p_obs)

    p_step = jax.pmap(step, axis_name=AXIS_NAME)
    k = k_run
    for i in range(10):
        k, sub = jax.random.split(k)
        keys = jax.random.split(sub, D)
        p_params, p_opt, p_env, p_obs, pm = p_step(
            p_params, p_opt, p_env, p_obs, keys)
        v_params, v_opt, v_env, v_obs, vm = v_step(
            v_params, v_opt, v_env, v_obs, keys)

    for a, b in zip(jax.tree_util.tree_leaves(p_params),
                    jax.tree_util.tree_leaves(v_params)):
        a, b = np.asarray(a), np.asarray(b)
        # every shard identical (pmean-synced) ...
        assert np.abs(a - a[0]).max() == 0.0, "shards diverged"
        # ... and equal to the single-device vmap reference
        err = np.abs(a - b).max()
        assert err < 1e-5, f"pmap/vmap divergence {err}"
    assert abs(float(np.asarray(pm["total_loss"])[0])
               - float(np.asarray(vm["total_loss"])[0])) < 1e-5
    print("MULTIDEVICE_OK")
""")


@pytest.mark.multidevice
@pytest.mark.watchdog(300)
def test_anakin_multidevice_parity():
    """10 fused Anakin updates on 8 pmapped CPU devices match the
    single-device vmap reference to <1e-5 — in a SUBPROCESS
    (cpu_mesh_env(8)) so the tier-1 process's JAX state is untouched."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    from __graft_entry__ import cpu_mesh_env
    proc = subprocess.run(
        [sys.executable, "-c", _ANAKIN_PARITY_SCRIPT],
        env=cpu_mesh_env(8), capture_output=True, text=True,
        timeout=240, cwd=root)
    assert proc.returncode == 0, (proc.stdout[-2000:]
                                  + proc.stderr[-2000:])
    assert "MULTIDEVICE_OK" in proc.stdout


# --- Sebulba: end to end ----------------------------------------------

class _BanditEnv(Env):
    """Trivial learnable env: action 0 pays +1, action 1 pays -1; a
    policy that learns anything at all drives returns from ~0 to +len.
    Lives in the test module on purpose — it ships to the env-runner
    actors by value (cloudpickle), proving test-defined envs work."""

    observation_space = Box(-np.ones(3, np.float32),
                            np.ones(3, np.float32))
    action_space = Discrete(2)
    _LEN = 8

    def __init__(self):
        self._t = 0

    def reset(self, *, seed=None):
        self._t = 0
        return np.ones(3, np.float32), {}

    def step(self, action):
        self._t += 1
        reward = 1.0 if int(action) == 0 else -1.0
        return (np.ones(3, np.float32), reward,
                self._t >= self._LEN, False, {})


@pytest.fixture
def podracer_cluster():
    """Fresh runtime with the flight recorder on (fast journal flush so
    the merged-timeline assertions see worker spans promptly)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, system_config={
        "flight_recorder_enabled": True,
        "flight_flush_interval_s": 0.05,
        "task_max_retries": 0,
    })
    yield
    from ray_tpu import serve
    serve.shutdown()
    ray_tpu.shutdown()


def _merged_rl_spans(deadline_s=10.0, want=()):
    """Poll merged journals until every wanted rl span name appears
    (worker journals flush on an interval)."""
    deadline = time.time() + deadline_s
    names = set()
    merged = {}
    while time.time() < deadline:
        merged = fr.merged_journals()
        names = {ev[4] for events in merged.values()
                 for ev in events if ev[3] == "rl"}
        if set(want) <= names:
            break
        time.sleep(0.1)
    return merged, names


@pytest.mark.watchdog(300)
def test_sebulba_e2e_weight_refresh_and_learning(podracer_cluster):
    from ray_tpu.devtools import whereis
    from ray_tpu.rl.podracer import Sebulba, SebulbaConfig

    cfg = SebulbaConfig(
        env_creator=_BanditEnv, num_actors=2, num_envs_per_actor=2,
        rollout_len=8, hidden=(16,), lr=3e-2, entropy_coeff=0.0,
        fragments_per_step=2, weight_push_interval=1,
        max_staleness=50, seed=0)
    s = Sebulba(cfg)
    try:
        out = s.train(12, step_timeout_s=60.0)
    finally:
        s.shutdown()

    learner = out["learner"]
    assert learner["num_updates"] == 12
    # >=2 mid-flight version-tagged weight refreshes, every one of
    # them confirmed by every replica (control-plane path, never shed)
    assert learner["weight_pushes"] >= 2
    assert learner["push_failures"] == 0
    # ... actually observed by the actors, in order, while sampling
    all_versions = set()
    for actor_id, versions in out["versions_by_actor"].items():
        assert versions == sorted(versions), (
            f"actor {actor_id} saw versions go backwards: {versions}")
        all_versions.update(versions)
    assert len(all_versions) >= 3, (
        f"expected >=2 refreshes observed (3 distinct versions), "
        f"got {sorted(all_versions)}")
    # sampling never paused: fragments kept flowing the whole run
    assert out["fragments"] >= 2 * cfg.num_actors
    assert out["env_steps_sampled"] >= out["fragments"] * 16
    # staleness is measured and bounded
    assert learner["version_lag_max"] <= cfg.max_staleness
    # replay depth stayed within its bound
    assert out["replay"]["depth"] <= cfg.replay_capacity
    # the learner actually learned the trivial env through the full
    # actor->inference->replay->learner->broadcast loop
    returns = out["episode_returns"]
    assert len(returns) >= 8
    third = max(len(returns) // 3, 1)
    assert np.mean(returns[-third:]) > np.mean(returns[:third]), returns

    # rl.* spans all land in the merged, clock-aligned timeline
    want = {"rollout", "infer_batch", "replay_wait", "learn_step",
            "weight_push"}
    merged, names = _merged_rl_spans(want=want)
    assert want <= names, f"missing rl spans: {want - names}"
    report = whereis.attribution(merged)
    rl = report["rl"]
    assert rl is not None
    fracs = rl["fractions"]
    assert set(fracs) == {"acting", "inference_wait", "learning",
                          "weight_sync"}
    assert abs(sum(fracs.values()) - 1.0) < 0.01
    assert rl["acting_s"] > 0 and rl["learning_s"] > 0
    assert rl["env_steps"] > 0
    assert "rl:" in whereis.render(report)


@pytest.mark.watchdog(300)
def test_sebulba_actor_death_mid_rollout(podracer_cluster):
    """Killing an env-runner mid-run costs its in-flight fragment and
    nothing else: the learner finishes every update, the surviving
    actor keeps the replay queue fed."""
    from ray_tpu.rl.podracer import Sebulba, SebulbaConfig

    cfg = SebulbaConfig(
        env_creator=_BanditEnv, num_actors=2, num_envs_per_actor=2,
        rollout_len=8, hidden=(16,), fragments_per_step=1,
        weight_push_interval=2, max_staleness=50, seed=1)
    s = Sebulba(cfg)
    doomed = s.actors[0]
    timer = threading.Timer(1.0, lambda: ray_tpu.kill(doomed))
    timer.start()
    try:
        out = s.train(8, step_timeout_s=60.0)
    finally:
        timer.cancel()
        s.shutdown()

    assert out["actor_deaths"] == 1
    assert len(s.actors) == 1
    assert out["learner"]["num_updates"] == 8
    # the survivor kept the replay queue fed throughout
    assert out["fragments"] >= 4


@pytest.mark.watchdog(300)
def test_sebulba_replay_backpressure_bounds_depth(podracer_cluster):
    """Actors outrunning a deliberately absent learner: the replay
    queue evicts oldest instead of growing — depth never exceeds
    capacity while pushes keep landing."""
    from ray_tpu.core import serialization
    from ray_tpu.rl.podracer.inference import build_inference_app
    from ray_tpu.rl.podracer.replay import create_replay_actor
    from ray_tpu.rl.podracer.sebulba import _SebulbaActorImpl
    from ray_tpu.rl.rl_module import RLModuleSpec
    from ray_tpu import serve

    spec = RLModuleSpec(_BanditEnv.observation_space,
                        _BanditEnv.action_space, (16,))
    handle = serve.run(build_inference_app(spec), name="bp",
                       route_prefix=None)
    replay = create_replay_actor(3, name="bp:replay")
    blob = serialization.dumps({
        "actor_id": 0, "env_creator": _BanditEnv, "num_envs": 2,
        "rollout_len": 4, "seed": 0, "handle": handle,
        "replay_name": "bp:replay", "infer_timeout_s": 30.0})
    actor = ray_tpu.remote(_SebulbaActorImpl).options(
        num_cpus=0).remote(blob)
    metas = [ray_tpu.get(actor.sample_fragment.remote())
             for _ in range(8)]
    st = ray_tpu.get(replay.stats.remote())
    assert st["pushed"] == 8
    assert st["depth"] == 3          # bounded, not 8
    assert st["dropped"] == 5        # evictions were counted
    assert any(m["dropped"] for m in metas)  # producers saw the signal
    # the queue kept the FRESHEST fragments
    items = ray_tpu.get(replay.pop_many.remote(99))
    fresh = [ray_tpu.get(refs[0]) for _meta, refs in items]
    assert len(fresh) == 3
    assert all(f["obs"].shape == (4, 2, 3) for f in fresh)
    ray_tpu.kill(actor)
    ray_tpu.kill(replay)


@pytest.mark.watchdog(120)
def test_fragment_refs_survive_producer_turnover(podracer_cluster):
    """Fragment liveness must not depend on producer-side state: a
    producer that drops its refs the moment push() returns (and keeps
    producing) leaves queued fragments pinned solely by the replay
    actor's borrowed refs, and popped fragments pinned by task-return
    containment — a late get (past the 2s borrow grace window) still
    resolves every queued fragment."""
    from ray_tpu.rl.podracer.replay import create_replay_actor

    class _Producer:
        def __init__(self, replay_name):
            self._replay = ray_tpu.get_actor(replay_name)

        def produce(self, n, tag0):
            import gc
            for i in range(n):
                ref = ray_tpu.put({"tag": tag0 + i,
                                   "data": np.arange(2048)})
                ray_tpu.get(self._replay.push.remote(
                    ({"tag": tag0 + i}, [ref])))
                del ref  # no keep-alive: the borrow chain must pin
            gc.collect()
            return True

    replay = create_replay_actor(4, name="pin:replay")
    prod = ray_tpu.remote(_Producer).options(num_cpus=0).remote(
        "pin:replay")
    # 12 fragments through a capacity-4 queue: 8 evicted (freed — that
    # is the point of drop-oldest), 4 survivors pinned only by borrows
    ray_tpu.get(prod.produce.remote(12, 0))
    items = ray_tpu.get(replay.pop_many.remote(99))
    assert [m["tag"] for m, _refs in items] == [8, 9, 10, 11]
    time.sleep(3.0)  # outlast the borrow grace window before the gets
    for meta, refs in items:
        frag = ray_tpu.get(refs[0], timeout=10)
        assert frag["tag"] == meta["tag"]
        assert frag["data"].shape == (2048,)
    ray_tpu.kill(prod)
    ray_tpu.kill(replay)
