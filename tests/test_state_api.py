"""State API / metrics / jobs / CLI tests (reference:
python/ray/tests/test_state_api.py shape — run work, then introspect)."""

import json
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.util import metrics, state


def test_list_tasks_and_summary(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x

    ray_tpu.get([f.remote(i) for i in range(3)])

    tasks = state.list_tasks()
    assert len(tasks) == 3
    assert all(t["state"] == "FINISHED" for t in tasks)
    assert state.summarize_tasks() == {"FINISHED": 3}

    @ray_tpu.remote
    def boom():
        raise ValueError("x")

    with pytest.raises(Exception):
        ray_tpu.get(boom.remote())
    summary = state.summarize_tasks()
    assert summary.get("FAILED") == 1
    failed = state.list_tasks(filters={"state": "FAILED"})
    assert len(failed) == 1 and failed[0]["error"]


def test_list_actors_nodes_objects(ray_start_regular):
    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    ray_tpu.get(a.ping.remote())
    actors = state.list_actors()
    assert len(actors) == 1 and actors[0]["state"] == "ALIVE"

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["is_head"]
    assert nodes[0]["resources_total"]["CPU"] == 4

    ref = ray_tpu.put(list(range(100)))
    objects = state.list_objects()
    assert any(o["object_id"] == ref.id.hex() for o in objects)


def test_timeline_export(ray_start_regular, tmp_path):
    @ray_tpu.remote
    def f():
        time.sleep(0.05)
        return 1

    ray_tpu.get([f.remote() for _ in range(4)])
    out = tmp_path / "trace.json"
    events = state.timeline(str(out))
    assert len(events) == 4
    data = json.loads(out.read_text())
    assert all(e["ph"] == "X" and e["dur"] > 0 for e in data)


def test_metrics_counter_gauge_histogram(ray_start_regular):
    c = metrics.Counter("reqs_total", "requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    g = metrics.Gauge("queue_depth")
    g.set(7.0)
    h = metrics.Histogram("latency_s", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = metrics.prometheus_text()
    assert 'reqs_total{route="/a"} 3.0' in text
    assert "queue_depth 7.0" in text
    assert 'latency_s_bucket{le="0.1"} 1' in text
    assert 'latency_s_bucket{le="+Inf"} 3' in text
    assert "latency_s_count 3" in text


def test_metrics_from_worker(ray_start_regular):
    @ray_tpu.remote
    def work():
        from ray_tpu.util import metrics as m
        m.Counter("worker_side").inc(5.0)
        return True

    assert ray_tpu.get(work.remote())
    assert "worker_side 5.0" in metrics.prometheus_text()


def test_job_submission(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('job says hi')\"",
        runtime_env={"env_vars": {"MARKER": "42"}})
    assert client.wait_until_finish(job_id, timeout=60) == \
        JobStatus.SUCCEEDED
    assert "job says hi" in client.get_job_logs(job_id)
    infos = client.list_jobs()
    assert len(infos) == 1 and infos[0]["submission_id"] == job_id


def test_job_failure_and_stop(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient
    client = JobSubmissionClient()
    bad = client.submit_job(entrypoint=f"{sys.executable} -c 'exit(3)'")
    assert client.wait_until_finish(bad, timeout=60) == JobStatus.FAILED
    assert "exit code 3" in client.get_job_info(bad)["message"]

    slow = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'")
    deadline = time.monotonic() + 30
    while (client.get_job_status(slow) == JobStatus.PENDING
           and time.monotonic() < deadline):
        time.sleep(0.02)
    assert client.stop_job(slow)
    assert client.wait_until_finish(slow, timeout=30) == JobStatus.STOPPED


def test_cli_status_reads_snapshot(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    # wait for the dumper's 2s tick
    from ray_tpu.scripts.cli import _load_state
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        snap = _load_state()
        if snap and snap.get("task_summary", {}).get("FINISHED"):
            break
        time.sleep(0.2)
    else:
        pytest.fail("state snapshot never appeared")
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "status"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    assert "Cluster status" in proc.stdout
    assert "CPU" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "list", "nodes"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    assert json.loads(proc.stdout)[0]["is_head"]


def test_prometheus_label_escaping(ray_start_regular):
    c = metrics.Counter("esc_total", tag_keys=("path",))
    c.inc(tags={"path": 'a"b\\c\nd'})
    text = metrics.prometheus_text()
    assert 'esc_total{path="a\\"b\\\\c\\nd"} 1.0' in text


def test_job_table_shared_between_clients(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient
    first = JobSubmissionClient()
    job_id = first.submit_job(
        entrypoint=f"{sys.executable} -c \"print('shared')\"")
    second = JobSubmissionClient()
    assert second.wait_until_finish(job_id, timeout=60) == \
        JobStatus.SUCCEEDED
    assert any(j["submission_id"] == job_id for j in second.list_jobs())
    assert "shared" in second.get_job_logs(job_id)
    # state API sees submission jobs alongside driver jobs
    from ray_tpu.util import state
    jobs = state.list_jobs()
    assert any(j.get("job_id") == job_id and j["type"] == "submission"
               for j in jobs)


def test_list_cluster_events_and_filters(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get(f.remote())
    evs = state.list_cluster_events()
    kinds = {e["kind"] for e in evs}
    assert {"NODE_ADDED", "WORKER_STARTED", "LEASE_GRANTED"} <= kinds
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)

    # kind filter
    only = state.list_cluster_events(kinds=["LEASE_GRANTED"])
    assert only and all(e["kind"] == "LEASE_GRANTED" for e in only)
    # severity is a MINIMUM: routine grants are DEBUG noise
    warn_up = state.list_cluster_events(severity="WARNING")
    assert all(e["severity"] in ("WARNING", "ERROR") for e in warn_up)
    # entity filter round-trips the hex id
    node_id = only[-1]["node_id"]
    assert node_id
    scoped = state.list_cluster_events(node_id=node_id)
    assert scoped and all(e["node_id"] == node_id for e in scoped)
    # --follow cursor semantics
    cursor = evs[-1]["seq"]
    assert state.list_cluster_events(since_seq=cursor) == []


def test_cli_events_reads_snapshot(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    t0 = time.time()
    ray_tpu.get(f.remote())
    from ray_tpu.scripts.cli import _load_state
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        snap = _load_state()
        # a stale snapshot from a previous session may still be on
        # disk: require a dump from THIS session
        if snap and snap.get("events") and snap["timestamp"] >= t0:
            break
        time.sleep(0.2)
    else:
        pytest.fail("events never reached the state snapshot")
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "events"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    assert "LEASE_GRANTED" in proc.stdout
    assert "WORKER_STARTED" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "events",
         "--kind", "NODE_ADDED", "--limit", "5"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert lines and all("NODE_ADDED" in ln for ln in lines)


def test_state_snapshot_without_driver():
    proc = subprocess.run(
        [sys.executable, "-c",
         "import json; from ray_tpu.util import state; "
         "print(json.dumps(state.state_snapshot()))"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    snap = json.loads(proc.stdout)
    assert snap["driver"] is False
    assert snap["nodes"] == [] and snap["events"] == []
    assert snap["timestamp"] > 0


def test_timeline_inflight_open_span(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(30)

    ref = slow.remote()
    # in-flight tasks report SCHEDULED (RUNNING is recorded with the
    # worker's result message); the timeline must still show them
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        rows = [t for t in state.list_tasks()
                if t["state"] == "SCHEDULED"]
        if rows:
            break
        time.sleep(0.05)
    else:
        pytest.fail("task never reached SCHEDULED")
    trace = state.timeline()
    open_spans = [ev for ev in trace
                  if ev["args"]["state"] == "RUNNING"]
    assert open_spans, "in-flight task missing from the timeline"
    span = open_spans[0]
    assert span["ph"] == "X" and span["dur"] >= 1.0
    # clipped at now: the span must not extend into the future
    assert span["ts"] + span["dur"] <= time.time() * 1e6 + 1e6
    ray_tpu.cancel(ref)
