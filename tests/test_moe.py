"""Mixture-of-Experts: gating, dispatch/combine, Llama-MoE, EP sharding.

No reference analog (the reference outsources MoE to vLLM/DeepSpeed);
tested against the dense FFN as ground truth and on the virtual
8-device mesh per SURVEY §7.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models.llama import (
    LlamaConfig,
    llama_init,
    llama_loss,
    llama_sharding_rules,
)
from ray_tpu.parallel.mesh import MeshSpec, make_mesh
from ray_tpu.parallel.moe import moe_dispatch, moe_ffn, top_k_gating
from ray_tpu.parallel.sharding import shard_pytree


def _dense_swiglu(x, w1, w3, w2):
    gate = jax.nn.silu(x @ w1)
    return (gate * (x @ w3)) @ w2


def test_top_k_gating_shapes_and_normalization():
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (32, 16))
    router = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
    gates, idx, aux = top_k_gating(x, router, k=2)
    assert gates.shape == (32, 4) and idx.shape == (32, 2)
    # gates nonzero only at the top-k experts, summing to 1 per token
    np.testing.assert_allclose(np.asarray(gates.sum(axis=-1)), 1.0,
                               rtol=1e-5)
    assert float(aux) >= 1.0 - 1e-5  # minimized at 1.0 (uniform)


def test_dispatch_respects_capacity():
    # 8 tokens all routed to expert 0, capacity 4: half are dropped
    gates = jnp.zeros((8, 2)).at[:, 0].set(1.0)
    idx = jnp.zeros((8, 1), dtype=jnp.int32)
    dispatch, combine = moe_dispatch(gates, idx, num_experts=2, capacity=4)
    assert float(dispatch.sum()) == 4.0  # only 4 slots filled
    # each filled slot occupied exactly once
    assert float(dispatch[:, 0, :].sum(axis=0).max()) == 1.0


def test_moe_equals_dense_with_identical_experts():
    """top-1 routing into experts with IDENTICAL weights must reproduce
    the dense FFN exactly (ample capacity)."""
    rng = jax.random.PRNGKey(0)
    d, h, e = 16, 32, 4
    x = jax.random.normal(rng, (2, 8, d))
    w1 = jax.random.normal(jax.random.PRNGKey(1), (d, h)) * 0.1
    w3 = jax.random.normal(jax.random.PRNGKey(2), (d, h)) * 0.1
    w2 = jax.random.normal(jax.random.PRNGKey(3), (h, d)) * 0.1
    router = jax.random.normal(jax.random.PRNGKey(4), (d, e))
    ew1 = jnp.stack([w1] * e)
    ew3 = jnp.stack([w3] * e)
    ew2 = jnp.stack([w2] * e)
    y, aux = moe_ffn(x, router, ew1, ew3, ew2, top_k=1,
                     capacity_factor=float(e))  # capacity = all tokens
    expected = _dense_swiglu(x, w1, w3, w2)
    np.testing.assert_allclose(np.asarray(y), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_moe_top2_mixes_experts():
    """With distinct experts and top-2 routing, the output is the
    gate-weighted mixture of the two selected experts' outputs."""
    d, h, e = 8, 16, 2
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 4, d))
    w1 = jax.random.normal(jax.random.PRNGKey(1), (e, d, h)) * 0.1
    w3 = jax.random.normal(jax.random.PRNGKey(2), (e, d, h)) * 0.1
    w2 = jax.random.normal(jax.random.PRNGKey(3), (e, h, d)) * 0.1
    router = jax.random.normal(jax.random.PRNGKey(4), (d, e))
    y, _ = moe_ffn(x, router, w1, w3, w2, top_k=2, capacity_factor=4.0)
    tokens = x.reshape(-1, d)
    gates, _, _ = top_k_gating(tokens, router, 2)
    expected = sum(
        gates[:, i][:, None] * _dense_swiglu(tokens, w1[i], w3[i], w2[i])
        for i in range(e))
    np.testing.assert_allclose(np.asarray(y.reshape(-1, d)),
                               np.asarray(expected), rtol=2e-4, atol=2e-5)


def test_llama_moe_trains(cpu_mesh8):
    cfg = LlamaConfig.tiny(moe_experts=4, moe_top_k=2)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    assert params["layers"]["w1"].shape == (
        cfg.n_layers, 4, cfg.dim, cfg.hidden_dim)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                                 cfg.vocab_size)

    @jax.jit
    def step(p):
        loss, grads = jax.value_and_grad(
            lambda q: llama_loss(q, tokens, targets, cfg))(p)
        return loss, grads

    loss, grads = step(params)
    assert bool(jnp.isfinite(loss))
    # gradients flow into expert weights and the router
    assert float(jnp.abs(grads["layers"]["w1"]).sum()) > 0
    assert float(jnp.abs(grads["layers"]["router"]).sum()) > 0


def test_llama_moe_expert_parallel_matches_replicated(cpu_mesh8):
    """EP over the virtual mesh: loss with expert-sharded weights equals
    the unsharded loss (GSPMD inserts the all-to-alls; math unchanged)."""
    devices = cpu_mesh8
    mesh = make_mesh(MeshSpec(data=2, expert=4), devices)
    cfg = LlamaConfig.tiny(moe_experts=4, moe_top_k=2)
    params = llama_init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0,
                                 cfg.vocab_size)
    baseline = float(llama_loss(params, tokens, targets, cfg))

    sharded = shard_pytree(params, mesh, llama_sharding_rules("ep"))
    tok_sharded = jax.device_put(tokens, NamedSharding(mesh, P("data")))
    tgt_sharded = jax.device_put(targets, NamedSharding(mesh, P("data")))

    @jax.jit
    def loss_fn(p, t, y):
        return llama_loss(p, t, y, cfg)

    ep_loss = float(loss_fn(sharded, tok_sharded, tgt_sharded))
    assert ep_loss == pytest.approx(baseline, rel=1e-4)
