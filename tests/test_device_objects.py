"""Device-object (RDT) tests (reference:
python/ray/tests/gpu_objects/test_gpu_objects_gloo.py shape: produce on
one actor, consume on another, payload stays out of the object plane)."""

import numpy as np

import ray_tpu
from ray_tpu.experimental import device_objects


@ray_tpu.remote
class Producer:
    def make(self, n):
        import jax.numpy as jnp
        arr = jnp.arange(n, dtype=jnp.float32)
        self.ref = device_objects.put(arr)
        return self.ref

    def local_roundtrip(self):
        # same-process get returns the live array, no transfer
        arr = device_objects.get(self.ref)
        return float(arr[1])


@ray_tpu.remote
class Consumer:
    def total(self, ref):
        arr = device_objects.get(ref)
        return float(arr.sum())


def test_driver_put_get(ray_start_regular):
    import jax.numpy as jnp
    ref = device_objects.put(jnp.ones((8,), jnp.float32) * 3)
    out = device_objects.get(ref)
    np.testing.assert_allclose(np.asarray(out), 3.0)


def test_actor_to_driver(ray_start_regular):
    p = Producer.remote()
    ref = ray_tpu.get(p.make.remote(16))
    arr = device_objects.get(ref)
    np.testing.assert_allclose(np.asarray(arr), np.arange(16))


def test_actor_to_actor(ray_start_regular):
    p = Producer.remote()
    c = Consumer.remote()
    ref = ray_tpu.get(p.make.remote(10))
    assert ray_tpu.get(c.total.remote(ref)) == 45.0


def test_same_process_no_transfer(ray_start_regular):
    p = Producer.remote()
    ray_tpu.get(p.make.remote(4))
    assert ray_tpu.get(p.local_roundtrip.remote()) == 1.0


def test_free(ray_start_regular):
    import pytest
    p = Producer.remote()
    ref = ray_tpu.get(p.make.remote(4))
    device_objects.free(ref)
    with pytest.raises(Exception):
        device_objects.get(ref)


def test_driver_put_to_actor(ray_start_regular):
    import jax.numpy as jnp
    c = Consumer.remote()
    ref = device_objects.put(jnp.full((5,), 2.0, jnp.float32))
    assert ray_tpu.get(c.total.remote(ref)) == 10.0
