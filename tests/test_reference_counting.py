"""Borrowed-reference tests: worker-held refs must pin objects at the
owner even when the driver drops its own ref (reference:
reference_counter.h:43 borrowing)."""

import gc

import ray_tpu


@ray_tpu.remote
class Holder:
    def make(self):
        self.ref = ray_tpu.put({"x": 1})
        return self.ref

    def readback(self):
        return ray_tpu.get(self.ref, timeout=10)["x"]

    def drop(self):
        del self.ref


def test_worker_held_ref_pins_object(ray_start_regular):
    h = Holder.remote()
    # driver deliberately discards its copy of the ref
    ray_tpu.get(h.make.remote())
    gc.collect()
    assert ray_tpu.get(h.readback.remote(), timeout=30) == 1
    # still alive for a second read
    assert ray_tpu.get(h.readback.remote(), timeout=30) == 1


def test_returned_ref_pinned_until_container_dies(ray_start_regular):
    """A `return ray_tpu.put(x)` pattern: the worker drops its local ref
    right after the task, but containment pinning keeps the inner object
    alive while the un-deserialized result exists (well past the grace
    window)."""
    import time

    @ray_tpu.remote
    def make():
        return ray_tpu.put({"y": 7})  # worker drops its ref immediately

    outer = make.remote()
    time.sleep(3.5)  # longer than the 2s borrow grace window
    inner = ray_tpu.get(outer, timeout=30)
    assert ray_tpu.get(inner, timeout=30) == {"y": 7}


def test_nested_ref_in_driver_put(ray_start_regular):
    """A put whose value embeds another ref pins the inner object."""
    import gc as _gc

    inner = ray_tpu.put([1, 2, 3])
    outer = ray_tpu.put({"inner": inner})
    inner_copy_id = inner.id
    del inner
    _gc.collect()
    got = ray_tpu.get(outer, timeout=10)
    assert got["inner"].id == inner_copy_id
    assert ray_tpu.get(got["inner"], timeout=10) == [1, 2, 3]
