"""Multi-host control plane: node daemons as separate OS processes over TCP.

Reference models: python/ray/tests/test_multinode_failures.py and the
raylet-joins-GCS flow (src/ray/raylet/main.cc:180). Every test here runs
the head with a TCP listener and node daemons as real subprocesses on
localhost — the same wire path a TPU pod uses across hosts, minus DCN
latency.
"""

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.cluster_utils import Cluster
from ray_tpu.exceptions import ActorDiedError, WorkerCrashedError


@pytest.fixture
def tcp_cluster():
    cluster = Cluster(
        head_node_args={"resources": {"CPU": 2}},
        system_config={"head_port": 0, "heartbeat_timeout_s": 3.0,
                       "object_store_memory": 64 * 1024 * 1024})
    yield cluster
    cluster.shutdown()


def _kill_daemon(proc):
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)


def test_remote_node_runs_tasks(tcp_cluster):
    node_id, proc = tcp_cluster.add_remote_node(
        num_cpus=2, resources={"spot": 1.0})
    try:
        @ray_tpu.remote(resources={"spot": 0.1})
        def where():
            import os
            import ray_tpu as rt
            return rt.get_runtime_context().get_node_id(), os.getpid()

        nid, pid = ray_tpu.get(where.remote(), timeout=30)
        assert nid == node_id.hex()
        assert pid != os.getpid()  # genuinely another process tree
    finally:
        _kill_daemon(proc)


def test_object_transfer_chunked_roundtrip(tcp_cluster):
    """Driver put -> remote task consumes (pull) -> large remote result
    -> driver get (pull back). Both directions cross the object servers
    in chunks (object_chunk_size defaults to 1 MiB; array is ~8 MiB)."""
    node_id, proc = tcp_cluster.add_remote_node(
        num_cpus=2, resources={"spot": 1.0})
    try:
        big = np.arange(1_000_000, dtype=np.float64)
        ref = ray_tpu.put(big)

        @ray_tpu.remote(resources={"spot": 0.1})
        def double(x):
            return x * 2.0

        out = ray_tpu.get(double.remote(ref), timeout=60)
        np.testing.assert_allclose(out, big * 2.0)
    finally:
        _kill_daemon(proc)


def test_remote_to_remote_transfer(tcp_cluster):
    """An object produced on daemon A is consumed on daemon B: the head
    only brokers the holder address; bytes move node-to-node."""
    node_a, proc_a = tcp_cluster.add_remote_node(
        num_cpus=1, resources={"a": 1.0})
    node_b, proc_b = tcp_cluster.add_remote_node(
        num_cpus=1, resources={"b": 1.0})
    try:
        @ray_tpu.remote(resources={"a": 0.5})
        def produce():
            return np.ones(500_000, dtype=np.float64)  # ~4 MiB -> shm

        @ray_tpu.remote(resources={"b": 0.5})
        def consume(x):
            return float(x.sum())

        assert ray_tpu.get(consume.remote(produce.remote()),
                           timeout=60) == 500_000.0
    finally:
        _kill_daemon(proc_a)
        _kill_daemon(proc_b)


def test_remote_actor_lifecycle(tcp_cluster):
    node_id, proc = tcp_cluster.add_remote_node(
        num_cpus=2, resources={"spot": 1.0})
    try:
        @ray_tpu.remote(resources={"spot": 0.1})
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self, k=1):
                self.n += k
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.incr.remote(), timeout=30) == 1
        assert ray_tpu.get(c.incr.remote(5), timeout=30) == 6
        ray_tpu.kill(c)
        with pytest.raises(ActorDiedError):
            ray_tpu.get(c.incr.remote(), timeout=30)
    finally:
        _kill_daemon(proc)


def test_nested_submission_from_remote(tcp_cluster):
    node_id, proc = tcp_cluster.add_remote_node(
        num_cpus=2, resources={"spot": 1.0})
    try:
        @ray_tpu.remote
        def inner(x):
            return x + 1

        @ray_tpu.remote(resources={"spot": 0.1})
        def outer():
            import ray_tpu as rt
            return rt.get(inner.remote(41))

        assert ray_tpu.get(outer.remote(), timeout=60) == 42
    finally:
        _kill_daemon(proc)


def test_object_transfer_survives_rpc_chaos(tcp_cluster, monkeypatch):
    """With RTPU_RPC_CHAOS dropping the first PULL sends in the head
    process, a cross-node object transfer still completes through the
    PullManager's bounded retry (reference: rpc_chaos.h:24-46 +
    retryable_grpc_client.h)."""
    from ray_tpu.core import protocol

    node_id, proc = tcp_cluster.add_remote_node(
        num_cpus=2, resources={"spot": 1.0})
    monkeypatch.setenv("RTPU_RPC_CHAOS", "PULL=fail:2")
    try:
        @ray_tpu.remote(resources={"spot": 0.1})
        def produce():
            return np.arange(500_000, dtype=np.float64)  # ~4 MiB -> pull

        # The head pulls the remote result; the first two PULL sends in
        # this process raise injected ConnectionResetError.
        out = ray_tpu.get(produce.remote(), timeout=60)
        assert out[-1] == 499_999.0
    finally:
        monkeypatch.delenv("RTPU_RPC_CHAOS", raising=False)
        protocol._maybe_chaos(None)  # drop cached chaos spec
        _kill_daemon(proc)


def test_daemon_process_kill_retries_elsewhere(tcp_cluster):
    """Kill the remote node PROCESS mid-task; the head detects the death
    (connection drop / missed heartbeats) and retries the task, which
    lands on the surviving head node (VERDICT round-1 item 2)."""
    marker_res = {"anywhere": 1.0}
    # Head can also run it: give the head node the resource too.
    tcp_cluster.runtime.scheduler.add_node_resources(
        tcp_cluster.head_node_id, marker_res)
    node_id, proc = tcp_cluster.add_remote_node(
        num_cpus=2, resources={"anywhere": 100.0})

    @ray_tpu.remote(resources={"anywhere": 1.0}, max_retries=2)
    def slow():
        import time as t
        import ray_tpu as rt
        t.sleep(1.5)
        return rt.get_runtime_context().get_node_id()

    # Overwhelmingly prefers the remote node (100 vs 1 available).
    ref = slow.remote()
    time.sleep(0.5)  # let it start on the remote node
    _kill_daemon(proc)
    nid = ray_tpu.get(ref, timeout=60)
    assert nid == tcp_cluster.head_node_id.hex()
    # The dead node is gone from the control plane.
    assert node_id not in tcp_cluster.runtime.nodes


def test_daemon_death_without_retries_fails_task(tcp_cluster):
    node_id, proc = tcp_cluster.add_remote_node(
        num_cpus=2, resources={"spot": 1.0})

    @ray_tpu.remote(resources={"spot": 0.1}, max_retries=0)
    def slow():
        import time as t
        t.sleep(30)

    ref = slow.remote()
    time.sleep(0.5)
    _kill_daemon(proc)
    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(ref, timeout=60)


def test_auth_token_gates_cross_host_connections(monkeypatch, tmp_path):
    """Shared-secret auth (reference: src/ray/rpc/authentication/):
    with RTPU_AUTH_TOKEN set on the head, daemons and clients carrying
    the wrong token are rejected at the handshake; matching tokens
    join normally."""
    import json
    import subprocess
    import sys

    import ray_tpu

    monkeypatch.setenv("RTPU_AUTH_TOKEN", "s3cret")
    rt = ray_tpu.init(num_cpus=1, head_port=0)
    try:
        base_env = dict(os.environ)
        base_env["PYTHONPATH"] = os.getcwd()

        # wrong token: daemon registration rejected, process exits != 0
        bad = dict(base_env, RTPU_AUTH_TOKEN="wrong")
        proc = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.cli", "start",
             "--address", rt.head_address,
             "--resources", json.dumps({"CPU": 1})],
            env=bad, capture_output=True, text=True, timeout=60)
        assert proc.returncode != 0
        assert "authentication failed" in (proc.stderr + proc.stdout)
        assert len(rt.nodes) == 1  # nothing joined

        # Unauthenticated bytes are NEVER unpickled: a pickle whose
        # loads() would have side effects must leave no trace (pickle
        # from an untrusted peer is code execution; the auth gate runs
        # on the plaintext frame first).
        import pickle
        import socket as socket_mod

        from ray_tpu.core.protocol import recv_frame, send_frame

        class _Canary:
            def __reduce__(self):
                return (open, (str(tmp_path / "pwned"), "w"))

        host, port_str = rt.head_address.split(":")
        sock = socket_mod.create_connection((host, int(port_str)),
                                            timeout=10)
        send_frame(sock, pickle.dumps({"kind": "NODE_REGISTER",
                                       "canary": _Canary()}))
        reply = recv_frame(sock)  # rejected (pickled reply is fine out)
        sock.close()
        assert reply is not None and b"authentication failed" in reply
        assert not (tmp_path / "pwned").exists(), \
            "head unpickled bytes from an unauthenticated peer"

        # wrong token: client rejected too
        client_probe = (
            "import ray_tpu\n"
            f"ray_tpu.init(address={rt.head_address!r})\n")
        proc = subprocess.run([sys.executable, "-c", client_probe],
                              env=bad, capture_output=True, text=True,
                              timeout=60)
        assert proc.returncode != 0
        assert "authentication failed" in (proc.stderr + proc.stdout)

        # matching token: joins and runs work
        good = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.scripts.cli", "start",
             "--address", rt.head_address,
             "--resources", json.dumps({"CPU": 1, "authed": 1.0})],
            env=base_env)
        try:
            deadline = time.time() + 30
            while len(rt.nodes) < 2 and time.time() < deadline:
                time.sleep(0.1)
            assert len(rt.nodes) == 2

            @ray_tpu.remote(resources={"authed": 0.1})
            def f():
                return "ok"

            assert ray_tpu.get(f.remote(), timeout=30) == "ok"
        finally:
            _kill_daemon(good)
    finally:
        ray_tpu.shutdown()


def test_protocol_minor_negotiation_and_unknown_kind_probe(tcp_cluster):
    """Additive wire-schema evolution (protocol.py policy): the
    REGISTERED handshake advertises (major, minor) + capabilities, and
    a kind the head predates is answered with UNSUPPORTED instead of a
    silent drop — so newer-minor peers can probe and fall back."""
    from ray_tpu.core.protocol import (
        CAPABILITIES, PROTOCOL_MINOR, PROTOCOL_VERSION)

    node_id, proc = tcp_cluster.add_remote_node(
        num_cpus=1, resources={"spot": 1.0})
    try:
        # two-way: the head recorded the daemon's advertised minor
        assert (tcp_cluster.runtime.nodes[node_id].proto_minor
                == PROTOCOL_MINOR)

        # client-side negotiation: a fresh client session sees them
        import subprocess
        import sys
        script = (
            "import ray_tpu\n"
            "from ray_tpu.core.protocol import PROTOCOL_MINOR\n"
            f"rt = ray_tpu.init(address={tcp_cluster.runtime.head_address!r})\n"
            "assert rt.head_proto_minor == PROTOCOL_MINOR\n"
            "assert 'pull-manager' in rt.head_capabilities\n"
            "print('NEGOTIATED-OK')\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = os.getcwd()
        out = subprocess.run([sys.executable, "-c", script], env=env,
                             capture_output=True, text=True, timeout=60)
        assert "NEGOTIATED-OK" in out.stdout, (out.stdout, out.stderr)

        # future-kind probe from a daemon connection: UNSUPPORTED reply
        import socket as socket_mod

        from ray_tpu.core import serialization
        from ray_tpu.core.protocol import recv_frame, send_frame
        host, port = tcp_cluster.runtime.head_address.split(":")
        sock = socket_mod.create_connection((host, int(port)), timeout=10)
        from ray_tpu.core.ids import NodeID
        send_frame(sock, serialization.dumps_fast({
            "kind": "NODE_REGISTER", "proto_version": PROTOCOL_VERSION,
            "node_id": NodeID.from_random().binary(),
            "resources": {"CPU": 0.0}, "labels": {},
            "object_addr": ["127.0.0.1", 1], "address": "probe:0"}))
        reply = serialization.loads(recv_frame(sock))
        assert reply["kind"] == "REGISTERED"
        assert reply["proto_minor"] == PROTOCOL_MINOR
        assert set(CAPABILITIES) <= set(reply["capabilities"])
        send_frame(sock, serialization.dumps_fast(
            {"kind": "FUTURE_FEATURE_KIND", "req_id": 77}))
        reply2 = serialization.loads(recv_frame(sock))
        assert reply2["kind"] == "UNSUPPORTED"
        assert reply2["req_id"] == 77
        sock.close()
    finally:
        _kill_daemon(proc)


def test_reregister_reaps_stale_connection(tcp_cluster):
    """A daemon re-registering the same node id on a NEW connection
    (link blip on a live head) must reap the old record — old socket
    closed, scheduler/GCS adopt the fresh one — and the stale reader's
    late EOF must NOT tear down the new registration (identity guard in
    the head serve loop; reference: raylet re-registration with a live
    GCS, gcs_node_manager.h:47)."""
    import socket as socket_mod

    from ray_tpu.core import serialization
    from ray_tpu.core.ids import NodeID
    from ray_tpu.core.protocol import (PROTOCOL_VERSION, recv_frame,
                                       send_frame)

    rt = tcp_cluster.runtime
    host, port = rt.head_address.split(":")
    nid = NodeID.from_random()

    def register():
        sock = socket_mod.create_connection((host, int(port)), timeout=10)
        send_frame(sock, serialization.dumps_fast({
            "kind": "NODE_REGISTER", "proto_version": PROTOCOL_VERSION,
            "node_id": nid.binary(), "resources": {"CPU": 0.0},
            "labels": {}, "object_addr": ["127.0.0.1", 1],
            "address": "blip:0"}))
        reply = serialization.loads(recv_frame(sock))
        assert reply["kind"] == "REGISTERED"
        return sock

    sock1 = register()
    first = rt.nodes.get(nid)
    assert first is not None
    sock2 = register()  # same node id, old socket still open
    # Reap: the head closed sock1; its recv sees EOF promptly.
    sock1.settimeout(10)
    assert recv_frame(sock1) is None
    sock1.close()
    # The NEW record must be installed and must survive the stale
    # reader thread observing sock1's EOF.
    deadline = time.time() + 5
    while time.time() < deadline and rt.nodes.get(nid) is first:
        time.sleep(0.05)
    second = rt.nodes.get(nid)
    assert second is not None and second is not first
    time.sleep(0.5)  # give a buggy stale-death path time to misfire
    assert rt.nodes.get(nid) is second
    sock2.close()


CLIENT_RESTART_SCRIPT = """
import json
import os
import sys
import time

import ray_tpu
from ray_tpu.exceptions import HeadRestartedError

out = {}
marker_dir = os.environ["MARKER_DIR"]
rt = ray_tpu.init(address=os.environ["RTPU_HEAD_ADDR"])

@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0
    def incr(self):
        self.n += 1
        return self.n

@ray_tpu.remote(resources={"spot": 0.1})
def slow(sec):
    time.sleep(sec)
    return "done"

@ray_tpu.remote(resources={"spot": 0.1})
def quick(tag):
    return tag

# named actor placed on the daemon node; build up in-memory state
h = Counter.options(name="survivor",
                    resources={"spot": 0.1}).remote()
assert ray_tpu.get(h.incr.remote(), timeout=60) == 1
assert ray_tpu.get(h.incr.remote(), timeout=60) == 2
pre_ref = ray_tpu.put({"made": "before-restart"})
inflight = slow.remote(60)
open(os.path.join(marker_dir, "phase1"), "w").write("ok")

# (b) the in-flight get fails with the TYPED error when the head dies
try:
    ray_tpu.get(inflight, timeout=120)
    out["inflight"] = "NO-ERROR"
except HeadRestartedError:
    out["inflight"] = "typed-error"
except Exception as e:
    out["inflight"] = f"WRONG: {type(e).__name__}"

# (c) the client reconnects within client_reconnect_s and resubmits
deadline = time.time() + 60
resubmit = None
while time.time() < deadline:
    try:
        resubmit = ray_tpu.get(quick.remote("retry"), timeout=20)
        break
    except Exception:
        time.sleep(0.5)
out["resubmit"] = resubmit

# pre-restart refs are documented-dead: typed error, immediately
try:
    ray_tpu.get(pre_ref, timeout=10)
    out["pre_ref"] = "NO-ERROR"
except HeadRestartedError:
    out["pre_ref"] = "typed-error"
except Exception as e:
    out["pre_ref"] = f"WRONG: {type(e).__name__}"

# (a) the named actor is re-attachable WITH its in-memory state
deadline = time.time() + 60
out["counter"] = None
while time.time() < deadline:
    try:
        h2 = ray_tpu.get_actor("survivor")
        out["counter"] = ray_tpu.get(h2.incr.remote(), timeout=20)
        break
    except Exception as e:  # ActorUnavailableError until rebind
        out["counter_err"] = f"{type(e).__name__}: {e}"[:200]
        time.sleep(0.5)
open(os.path.join(marker_dir, "phase2"), "w").write(json.dumps(out))
"""


def test_head_restart_user_contract(tmp_path):
    """Head FT slice 2 (VERDICT r3 item 4): across a head crash +
    restart with a journal, (a) a named actor on a surviving daemon is
    re-attachable with its in-memory state intact, (b) the client's
    in-flight get fails with HeadRestartedError (as do gets of
    pre-restart refs), and (c) the reconnected client resubmits work
    successfully (reference: gcs_init_data.cc replay + raylet/worker
    reconnection to a restarted GCS)."""
    import json
    import socket as socket_mod
    import subprocess
    import sys

    import ray_tpu

    s = socket_mod.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    journal = str(tmp_path / "gcs-journal")
    sys_cfg = {"gcs_persistence_path": journal}

    rt = ray_tpu.init(num_cpus=1, head_port=port,
                      system_config=dict(sys_cfg))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd()
    env["RTPU_NODE_RECONNECT_S"] = "60"
    daemon = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "start",
         "--address", f"127.0.0.1:{port}",
         "--resources", json.dumps({"CPU": 2, "spot": 1.0})], env=env)
    client = None
    try:
        deadline = time.time() + 30
        while len(rt.nodes) < 2 and time.time() < deadline:
            time.sleep(0.1)
        assert len(rt.nodes) == 2

        cenv = dict(env)
        cenv["RTPU_HEAD_ADDR"] = f"127.0.0.1:{port}"
        cenv["RTPU_CLIENT_RECONNECT_S"] = "60"
        cenv["MARKER_DIR"] = str(tmp_path)
        client = subprocess.Popen(
            [sys.executable, "-c", CLIENT_RESTART_SCRIPT], env=cenv)
        deadline = time.time() + 60
        while (not (tmp_path / "phase1").exists()
               and time.time() < deadline):
            assert client.poll() is None, "client died in phase 1"
            time.sleep(0.1)
        assert (tmp_path / "phase1").exists()
        time.sleep(0.5)  # let the in-flight get register head-side

        # Head CRASH (no clean STOPs), same choreography as
        # test_daemon_survives_head_restart — plus _stopped first: a
        # real dead process runs NO death handling, but severing the
        # connections in-process wakes EOF readers whose node reaps
        # would mark the actor DEAD and erase its journal entries.
        rt._stopped.set()
        rt.head_server.stop()
        for node in list(rt.nodes.values()):
            if getattr(node, "is_remote", False):
                rt.nodes.pop(node.node_id, None)
                node.close()
        ray_tpu.shutdown()
        time.sleep(1.0)

        rt2 = ray_tpu.init(num_cpus=1, head_port=port,
                           system_config=dict(sys_cfg))
        # The daemon's reconnect window is 60s (env above); the observer
        # must outwait it — a starved box can burn a full 15s register
        # timeout per redial attempt before the rejoin lands.
        deadline = time.time() + 70
        while len(rt2.nodes) < 2 and time.time() < deadline:
            time.sleep(0.2)
        assert len(rt2.nodes) == 2, "daemon did not rejoin"

        deadline = time.time() + 120
        while (not (tmp_path / "phase2").exists()
               and time.time() < deadline):
            assert client.poll() is None, "client died in phase 2"
            time.sleep(0.2)
        assert (tmp_path / "phase2").exists(), "client never finished"
        out = json.loads((tmp_path / "phase2").read_text())
        assert out["inflight"] == "typed-error", out
        assert out["pre_ref"] == "typed-error", out
        assert out["resubmit"] == "retry", out
        # counter was at 2 before the restart; state survived => 3
        assert out["counter"] == 3, out
        client.wait(timeout=30)
        assert client.returncode == 0
    finally:
        for proc in (client, daemon):
            if proc is not None:
                proc.kill()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        ray_tpu.shutdown()


def test_daemon_survives_head_restart(tmp_path):
    """Head-restart tolerance (a slice of head fault tolerance;
    reference: raylets reconnecting to a restarted GCS +
    gcs_init_data.cc replay): a daemon with node_reconnect_s keeps
    retrying after the head crashes, re-registers under its same node
    id with the NEW head on the same address, and serves fresh work."""
    import json
    import socket as socket_mod
    import subprocess
    import sys

    import ray_tpu

    s = socket_mod.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    rt = ray_tpu.init(num_cpus=1, head_port=port)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd()
    env["RTPU_NODE_RECONNECT_S"] = "60"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "start",
         "--address", f"127.0.0.1:{port}",
         "--resources", json.dumps({"CPU": 1, "spot": 1.0})], env=env)
    try:
        deadline = time.time() + 30
        while len(rt.nodes) < 2 and time.time() < deadline:
            time.sleep(0.1)
        assert len(rt.nodes) == 2
        daemon_node_id = next(n for n in rt.nodes
                              if n != rt.head_node_id)

        @ray_tpu.remote(resources={"spot": 0.1})
        def mark(tag):
            return tag

        assert ray_tpu.get(mark.remote("before"), timeout=30) == "before"

        # Simulate a head CRASH: stop accepting FIRST (or the daemon
        # would reconnect to the dying head), then sever daemon links
        # without the STOP frame a clean shutdown would send.
        rt.head_server.stop()
        for node in list(rt.nodes.values()):
            if getattr(node, "is_remote", False):
                rt.nodes.pop(node.node_id, None)
                node.close()
        ray_tpu.shutdown()
        time.sleep(1.0)

        # New head, same address: the daemon must rejoin by itself.
        rt2 = ray_tpu.init(num_cpus=1, head_port=port)
        deadline = time.time() + 40
        while len(rt2.nodes) < 2 and time.time() < deadline:
            time.sleep(0.2)
        assert len(rt2.nodes) == 2, "daemon did not rejoin the new head"
        assert daemon_node_id in rt2.nodes  # SAME node id re-adopted
        assert proc.poll() is None  # daemon process never exited

        @ray_tpu.remote(resources={"spot": 0.1})
        def mark2(tag):
            return tag

        assert ray_tpu.get(mark2.remote("after"), timeout=40) == "after"
    finally:
        proc.kill()
        proc.wait(timeout=10)
        ray_tpu.shutdown()
