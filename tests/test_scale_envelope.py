"""Scale-envelope stress: the single-owner head at its DOCUMENTED
envelope (PARITY.md "Scale envelope"): 64 nodes, 1,000 live actor
records, 32 placement groups, 10k+ tasks/s on one node.

The reference targets 2,000 nodes / 40k actors with a distributed
control plane (release/benchmarks/README.md:11-14); this repo's head
is deliberately a single owner (core/runtime.py design note), so the
envelope is smaller and measured HERE — control-plane bookkeeping at
envelope scale, without spawning a thousand OS processes (worker
execution throughput has its own guards in test_task_throughput.py).
"""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def envelope_head():
    rt = ray_tpu.init(num_cpus=2)
    yield rt
    ray_tpu.shutdown()


def _calibration_rate(n: int = 200_000) -> float:
    t0 = time.perf_counter()
    d = {}
    out = []
    for i in range(n):
        d[i & 1023] = i
        out.append((i, i + 1))
        if len(out) > 1024:
            out.clear()
    return n / (time.perf_counter() - t0)


def test_envelope_64_nodes_1k_actors_pgs(envelope_head):
    rt = envelope_head
    calib = _calibration_rate()

    # --- 64 nodes join the control plane (ledger + GCS) -------------
    # Stub registrations model what REMOTE nodes cost the head: a
    # scheduler ledger row + a GCS record (a daemon's reader thread
    # blocks idle in recv). Full in-process Node objects would instead
    # saddle the one-core head with 64 nodes' worker/log machinery —
    # load real deployments put on 64 separate hosts, not on the head.
    from ray_tpu.core.gcs import NodeRecord
    from ray_tpu.core.ids import NodeID
    t0 = time.perf_counter()
    node_ids = []
    for i in range(64):
        nid = NodeID.from_random()
        rt.scheduler.add_node(
            nid, {"CPU": 4.0, "TPU": 4.0, "envelope": 1.0}, {})
        rt.gcs.register_node(NodeRecord(
            node_id=nid, address=f"stub-host-{i}:0",
            resources_total={"CPU": 4.0, "TPU": 4.0, "envelope": 1.0},
            labels={}, node_manager=None))
        node_ids.append(nid)
    join_s = time.perf_counter() - t0
    assert len(rt.scheduler.snapshot()) >= 65
    assert join_s < 5.0, join_s  # pure bookkeeping, ~2ms quiet-box

    # --- scheduler picks stay fast with 64 nodes in the ledger ------
    from ray_tpu.core.task_spec import SchedulingStrategy, TaskSpec
    from ray_tpu.core.ids import TaskID
    spec = TaskSpec(task_id=TaskID.from_random(), function_id="x",
                    args=[], resources={"CPU": 1.0, "envelope": 0.01},
                    strategy=SchedulingStrategy())
    n_picks = 2_000
    t0 = time.perf_counter()
    for _ in range(n_picks):
        nid = rt.scheduler.pick_node(spec)
        assert nid is not None
        assert rt.scheduler.try_acquire(nid, spec.resources)
        rt.scheduler.release(nid, spec.resources)
    pick_rate = n_picks / (time.perf_counter() - t0)
    # quiet-box ~8.6k pick/acquire/release triples per second over 64
    # nodes (~116us each); guard via the calibration ratio so box load
    # doesn't flake it while a >=2x regression trips it
    assert pick_rate > 0.0008 * calib, (pick_rate, calib)

    # --- 1,000 live actor records + named lookups -------------------
    from ray_tpu.core.gcs import ActorRecord
    from ray_tpu.core.ids import ActorID
    t0 = time.perf_counter()
    aids = []
    for i in range(1_000):
        aid = ActorID.from_random()
        rt.gcs.register_actor(ActorRecord(
            actor_id=aid, name=f"envelope-{i}", namespace="",
            state="ALIVE", node_id=node_ids[i % 64]))
        aids.append(aid)
    reg_s = time.perf_counter() - t0
    # ~0.1ms/record quiet-box; scale the bound with current box speed
    assert reg_s < 1_000 * 0.004 * (5e6 / max(calib, 1e5)), reg_s
    # random named lookups stay fast at 1k actors
    t0 = time.perf_counter()
    for i in range(0, 1_000, 7):
        rec = rt.gcs.get_named_actor(f"envelope-{i}")
        assert rec is not None and rec.state == "ALIVE"
    assert time.perf_counter() - t0 < 1.0

    # --- 32 placement groups solve across the 64 nodes --------------
    from ray_tpu.util.placement_group import (
        placement_group, remove_placement_group)
    pgs = [placement_group([{"TPU": 2.0}] * 2, strategy="SPREAD")
           for _ in range(32)]
    for pg in pgs:
        assert pg.ready(timeout=30)
    # bundles landed across the fleet, not piled on one node
    spread = {nid.hex() for pg in pgs for nid in pg.bundle_node_ids()}
    assert len(spread) >= 16
    for pg in pgs:
        remove_placement_group(pg)

    # --- state surfaces stay responsive at envelope scale -----------
    from ray_tpu.util import state as state_api
    t0 = time.perf_counter()
    nodes = state_api.list_nodes()
    actors = state_api.list_actors(limit=2_000)
    assert len(nodes) >= 65
    assert len(actors) >= 1_000
    assert time.perf_counter() - t0 < 5.0

    # --- real execution still works with the big ledger -------------
    # Pin to the head (stub nodes can't run work) via a marker
    # resource; the scheduler still scans the 65-row ledger per pick.
    rt.scheduler.add_node_resources(rt.head_node_id, {"head_only": 4.0})

    @ray_tpu.remote(resources={"head_only": 0.1}, num_cpus=0)
    def ping(x):
        return x

    assert ray_tpu.get([ping.remote(i) for i in range(100)],
                       timeout=60) == list(range(100))

def test_envelope_8_real_daemon_processes(tmp_path):
    """Anchor for the stub-based 64-node envelope: 8 REAL node-daemon
    subprocesses join over TCP, tasks spread across all of them, and
    the head survives the whole gang disconnecting at once. This is
    the multi-process variant the stub test extrapolates from."""
    import json
    import os
    import signal
    import subprocess
    import sys

    rt = ray_tpu.init(num_cpus=1, head_port=0)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd()
    procs = []
    try:
        for i in range(8):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.scripts.cli", "start",
                 "--address", rt.head_address,
                 "--resources", json.dumps({"CPU": 2, "envd": 1.0})],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
        deadline = time.time() + 120
        while len(rt.nodes) < 9 and time.time() < deadline:
            time.sleep(0.2)
        assert len(rt.nodes) == 9, f"only {len(rt.nodes)} nodes joined"

        @ray_tpu.remote(resources={"envd": 0.05}, num_cpus=0)
        def where():
            import ray_tpu as rtpu
            return rtpu.get_runtime_context().get_node_id()

        hosts = set(ray_tpu.get(
            [where.remote() for _ in range(64)], timeout=180))
        assert len(hosts) >= 4, f"tasks landed on only {len(hosts)} nodes"

        # whole-gang disconnect: the head notices and keeps serving
        for p in procs:
            p.send_signal(signal.SIGKILL)
        for p in procs:
            p.wait(timeout=30)
        deadline = time.time() + 90
        while len(rt.nodes) > 1 and time.time() < deadline:
            time.sleep(0.2)
        assert len(rt.nodes) == 1

        @ray_tpu.remote(num_cpus=1)
        def local():
            return "still-serving"

        assert ray_tpu.get(local.remote(), timeout=60) == "still-serving"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        ray_tpu.shutdown()


# -- serve envelope: load harness + admission + SLO autoscaling ----------
# (ray_tpu/serve/loadgen.py drives the full chain; admission control
# bounds queues; the "slo" policy scales replicas on sustained breach)

@pytest.fixture
def serve_envelope_head():
    # 4 CPU slots: room for max_replicas=3 plus headroom, so the SLO
    # autoscaler's scale-up is placeable (envelope_head's 2 are not)
    rt = ray_tpu.init(num_cpus=4)
    yield rt
    ray_tpu.shutdown()


def _echo_deployment(serve, **opts):
    from ray_tpu.serve.loadgen import EchoServer
    defaults = dict(name="envelope_echo", num_replicas=1,
                    max_ongoing_requests=4, max_queued_requests=64)
    defaults.update(opts)
    return serve.deployment(**defaults)(EchoServer)


def test_serve_envelope_stated_rate_bounded_p99(serve_envelope_head):
    """At the stated rate (30 req/s, 5ms work, 4 slots) nothing
    sheds, p99 stays bounded, and the queue never nears its cap."""
    from ray_tpu import serve
    from ray_tpu.serve.admission import get_admission_controller
    from ray_tpu.serve.loadgen import (
        LoadgenConfig, handle_sender, run_load)

    dep = _echo_deployment(serve)
    try:
        handle = serve.run(dep.bind(5.0), name="envelope")
        handle.remote({"seq": -1}).result(timeout_s=30)  # warm-up
        report = run_load(
            LoadgenConfig(rate=30.0, duration_s=3.0, concurrency=16,
                          timeout_s=20.0),
            handle_sender(handle, timeout_s=20.0),
            admission=get_admission_controller("envelope_echo"))
        assert report.ok > 0
        assert report.shed == 0 and report.errors == 0
        assert report.p99_ms is not None and report.p99_ms < 2_000.0
        assert report.max_queue_depth < 64
    finally:
        serve.shutdown()


def test_serve_envelope_10x_overload_sheds_bounded_queue(serve_envelope_head):
    """At 10x the stated rate the chain sheds (typed BackpressureError
    on the handle path) and the queue NEVER exceeds its cap."""
    from ray_tpu import serve
    from ray_tpu.serve.admission import get_admission_controller
    from ray_tpu.serve.loadgen import (
        LoadgenConfig, handle_sender, run_load)

    cap = 4
    dep = _echo_deployment(serve, max_ongoing_requests=2,
                           max_queued_requests=cap)
    try:
        handle = serve.run(dep.bind(20.0), name="envelope")
        handle.remote({"seq": -1}).result(timeout_s=30)  # warm-up
        report = run_load(
            LoadgenConfig(rate=300.0, duration_s=3.0, concurrency=32,
                          timeout_s=20.0),
            handle_sender(handle, timeout_s=20.0),
            admission=get_admission_controller("envelope_echo"))
        assert report.ok > 0            # still serving under overload
        assert report.shed > 0          # overload WAS shed, not queued
        assert report.errors == 0       # sheds are typed, not failures
        assert report.max_queue_depth <= cap
        # shed clients got a usable backoff hint
        assert report.retry_after_mean_s is not None
        assert report.retry_after_mean_s > 0
    finally:
        serve.shutdown()


def test_serve_envelope_slo_autoscaler_up_then_down(serve_envelope_head):
    """Sustained queue-depth breach scales replicas up; the calm after
    the storm scales back down with hysteresis (one at a time)."""
    import threading as _threading

    from ray_tpu import serve
    from ray_tpu.serve.admission import get_admission_controller
    from ray_tpu.serve.loadgen import (
        LoadgenConfig, handle_sender, run_load)

    dep = _echo_deployment(
        serve, max_ongoing_requests=2, max_queued_requests=200,
        autoscaling_config=dict(
            policy="slo", min_replicas=1, max_replicas=3,
            target_queue_depth=2.0, upscale_delay_s=0.4,
            downscale_delay_s=1.0, slo_stats_staleness_s=2.0))
    try:
        handle = serve.run(dep.bind(40.0), name="envelope")
        handle.remote({"seq": -1}).result(timeout_s=30)  # warm-up

        peak_running = [1]

        def watch():
            while not done.is_set():
                st = serve.status().get("envelope_echo", {})
                peak_running[0] = max(peak_running[0],
                                      st.get("running_replicas", 0))
                done.wait(0.2)

        done = _threading.Event()
        watcher = _threading.Thread(target=watch, daemon=True)
        watcher.start()
        try:
            report = run_load(
                LoadgenConfig(rate=120.0, duration_s=5.0,
                              concurrency=32, timeout_s=30.0),
                handle_sender(handle, timeout_s=30.0),
                admission=get_admission_controller("envelope_echo"))
            # the breach was real: the queue sat past the target
            assert report.max_queue_depth > 2
            deadline = time.time() + 20
            while peak_running[0] < 2 and time.time() < deadline:
                time.sleep(0.2)
        finally:
            done.set()
            watcher.join(timeout=5)
        assert peak_running[0] >= 2, (
            f"SLO policy never scaled up (peak {peak_running[0]})")

        # idle: stats go stale -> sustained calm -> back down to min,
        # one replica per downscale window
        deadline = time.time() + 60
        while time.time() < deadline:
            st = serve.status().get("envelope_echo", {})
            if (st.get("target_replicas") == 1
                    and st.get("running_replicas") == 1):
                break
            time.sleep(0.3)
        st = serve.status().get("envelope_echo", {})
        assert st.get("target_replicas") == 1, st
        assert st.get("running_replicas") == 1, st
    finally:
        serve.shutdown()
