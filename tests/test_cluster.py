"""Multi-node simulation, scheduling policies, placement groups, chaos.

Reference models: python/ray/tests/test_scheduling.py,
test_placement_group.py, test_chaos.py.
"""

import time

import pytest

import ray_tpu
from ray_tpu.core.task_spec import SchedulingStrategy
from ray_tpu.exceptions import (
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.util.placement_group import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)


def test_custom_resources_route_tasks(ray_start_cluster):
    cluster = ray_start_cluster
    tpu_node = cluster.add_node(num_cpus=2, resources={"TPU": 4},
                                labels={"tpu-pod-type": "v5p-8"})

    @ray_tpu.remote(num_tpus=1)
    def where():
        import ray_tpu as rt
        return rt.get_runtime_context().get_node_id()

    node_id = ray_tpu.get(where.remote())
    assert node_id == tpu_node.hex()


def test_node_label_strategy(ray_start_cluster):
    cluster = ray_start_cluster
    labeled = cluster.add_node(num_cpus=2, labels={"zone": "us-central2-b"})
    cluster.add_node(num_cpus=2, labels={"zone": "us-east1-d"})

    @ray_tpu.remote(scheduling_strategy=SchedulingStrategy(
        kind="NODE_LABEL", labels={"zone": "us-central2-b"}))
    def where():
        import ray_tpu as rt
        return rt.get_runtime_context().get_node_id()

    assert ray_tpu.get(where.remote()) == labeled.hex()


def test_spread_strategy(ray_start_cluster):
    cluster = ray_start_cluster
    for _ in range(3):
        cluster.add_node(num_cpus=2)

    @ray_tpu.remote(scheduling_strategy="SPREAD")
    def where():
        import time
        import ray_tpu as rt
        time.sleep(0.2)
        return rt.get_runtime_context().get_node_id()

    nodes = set(ray_tpu.get([where.remote() for _ in range(8)]))
    assert len(nodes) >= 2


def test_placement_group_strict_spread(ray_start_cluster):
    cluster = ray_start_cluster
    node_ids = [cluster.add_node(num_cpus=1, resources={"TPU": 4})
                for _ in range(4)]
    pg = placement_group([{"TPU": 4}] * 4, strategy="STRICT_SPREAD")
    assert pg.ready(timeout=5)
    placed = set(n.hex() for n in pg.bundle_node_ids())
    assert placed == {n.hex() for n in node_ids}
    remove_placement_group(pg)


def test_placement_group_infeasible_queues_pending(ray_start_cluster):
    """Unplaceable PGs queue as PENDING instead of failing fast — the
    autoscaler satisfies them later (reference:
    gcs_placement_group_scheduler.h:281 pending queue). A node joining
    with the needed capacity flips the PG to CREATED."""
    cluster = ray_start_cluster
    pg = placement_group([{"TPU": 8}], strategy="STRICT_PACK")
    assert not pg.ready(timeout=0.2)  # queued, not raised
    cluster.add_node(num_cpus=1, resources={"TPU": 8})
    assert pg.ready(timeout=5)
    remove_placement_group(pg)


def test_placement_group_task_targeting(ray_start_cluster):
    cluster = ray_start_cluster
    tpu_node = cluster.add_node(num_cpus=4, resources={"TPU": 4})
    pg = placement_group([{"TPU": 2}], strategy="PACK")
    assert pg.ready(timeout=5)

    @ray_tpu.remote(num_cpus=0, num_tpus=1,
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=pg, placement_group_bundle_index=0))
    def where():
        import ray_tpu as rt
        return rt.get_runtime_context().get_node_id()

    assert ray_tpu.get(where.remote()) == tpu_node.hex()


def test_worker_crash_retry(ray_start_regular):
    @ray_tpu.remote(max_retries=2)
    def die_once(key):
        import os
        from ray_tpu.core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        n = int(rt.gcs_call("kv_get", key.encode(), "") or 0) + 1
        rt.gcs_call("kv_put", key.encode(), str(n).encode(), "")
        if n == 1:
            os._exit(1)  # simulate hard crash
        return n

    assert ray_tpu.get(die_once.remote("crash_count"), timeout=60) == 2


def test_worker_crash_no_retries_fails(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def die():
        import os
        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=60)


def test_node_removal_chaos(ray_start_cluster, tmp_path):
    import os

    cluster = ray_start_cluster
    doomed = cluster.add_node(num_cpus=2, resources={"DOOMED": 1})
    marker = str(tmp_path / "started")

    @ray_tpu.remote(resources={"DOOMED": 0.1}, max_retries=0)
    def trapped(path):
        import pathlib
        import time
        pathlib.Path(path).write_text("in")
        time.sleep(30)
        return 1

    ref = trapped.remote(marker)
    # wait for POSITIVE confirmation the task is running on the doomed
    # node — a fixed sleep flakes under load: removing the node before
    # dispatch leaves the task queued on a forever-infeasible resource
    # and get() times out instead of raising the crash error
    deadline = time.monotonic() + 30
    while not os.path.exists(marker) and time.monotonic() < deadline:
        time.sleep(0.05)
    assert os.path.exists(marker), "task never started on doomed node"
    cluster.remove_node(doomed)
    with pytest.raises((WorkerCrashedError, TaskError)):
        ray_tpu.get(ref, timeout=60)


def test_object_transfer_between_nodes(ray_start_cluster):
    """An object produced on node A is readable by a task on node B
    (simulated inter-node transfer path)."""
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, resources={"A": 1})
    cluster.add_node(num_cpus=2, resources={"B": 1})
    import numpy as np

    @ray_tpu.remote(resources={"A": 0.1})
    def produce():
        return np.ones(200_000, dtype=np.float32)

    @ray_tpu.remote(resources={"B": 0.1})
    def consume(arr):
        return float(arr.sum())

    assert ray_tpu.get(consume.remote(produce.remote()), timeout=60) == 200_000.0
