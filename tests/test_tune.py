"""Tune: search spaces, grid/random search, schedulers, PBT, restore.

Mirrors the reference's tune test strategy (reference:
python/ray/tune/tests/ — test_tune_restore.py, test_trial_scheduler*.py,
test_searchers.py) at unit scale on the local runtime.
"""

import os
import random

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.tune import experiment as exp_mod


class Quadratic(tune.Trainable):
    """score = -(x - 3)^2 ; best at x = 3."""

    def setup(self, config):
        self.x = config["x"]
        self.state_marker = 0

    def step(self):
        return {"score": -((self.x - 3.0) ** 2)}

    def save_checkpoint(self, checkpoint_dir):
        with open(os.path.join(checkpoint_dir, "state.txt"), "w") as f:
            f.write(f"{self.x},{self.state_marker}")

    def load_checkpoint(self, checkpoint_dir):
        with open(os.path.join(checkpoint_dir, "state.txt")) as f:
            x, marker = f.read().split(",")
        self.x = float(x)
        self.state_marker = int(marker)

    def reset_config(self, new_config):
        self.x = new_config["x"]
        return True


def test_grid_search_finds_best(ray_start_shared, tmp_path):
    tuner = tune.Tuner(
        Quadratic,
        param_space={"x": tune.grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)),
        stop={"training_iteration": 2})
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    assert best.metrics["score"] == 0.0


def test_random_search_and_spaces(ray_start_shared, tmp_path):
    space = {
        "x": tune.uniform(0, 6),
        "lr": tune.loguniform(1e-5, 1e-1),
        "n": tune.randint(1, 10),
        "act": tune.choice(["relu", "gelu"]),
        "double_n": tune.sample_from(lambda cfg: cfg["n"] * 2),
    }

    def fn(config):
        assert 0 <= config["x"] <= 6
        assert 1e-5 <= config["lr"] <= 1e-1
        assert config["double_n"] == config["n"] * 2
        tune.report({"score": -((config["x"] - 3) ** 2)})

    grid = tune.run(fn, config=space, num_samples=5,
                    metric="score", mode="max",
                    run_config=RunConfig(name="rand", storage_path=str(tmp_path)))
    assert len(grid) == 5
    assert grid.get_best_result().metrics["score"] <= 0.0


def test_function_trainable_checkpoint_report(ray_start_shared, tmp_path):
    def fn(config):
        ckpt = tune.get_checkpoint()
        start = 0
        if ckpt:
            with open(os.path.join(ckpt.path, "it.txt")) as f:
                start = int(f.read())
        for it in range(start, 3):
            d = tmp_path / f"w{it}"
            d.mkdir(exist_ok=True)
            (d / "it.txt").write_text(str(it + 1))
            tune.report({"it": it + 1}, checkpoint=Checkpoint(str(d)))

    grid = tune.run(fn, metric="it", mode="max",
                    run_config=RunConfig(name="fnckpt",
                                         storage_path=str(tmp_path)))
    best = grid.get_best_result()
    assert best.metrics["it"] == 3
    assert best.checkpoint is not None
    with open(os.path.join(best.checkpoint.path, "it.txt")) as f:
        assert f.read() == "3"


def test_asha_rung_cutoffs_unit():
    # Deterministic rung-logic check: results arrive in a known order.
    from ray_tpu.tune.experiment import Trial

    sched = tune.ASHAScheduler(grace_period=2, reduction_factor=2, max_t=20)
    sched.set_search_properties("score", "max")
    good = Trial("good", {})
    bad = Trial("bad", {})
    # good reaches rung t=2 first with a high score
    assert sched.on_trial_result(None, good,
                                 {"training_iteration": 2, "score": 10.0}) \
        == tune.TrialScheduler.CONTINUE
    # bad arrives below the rung cutoff -> stopped
    assert sched.on_trial_result(None, bad,
                                 {"training_iteration": 2, "score": 1.0}) \
        == tune.TrialScheduler.STOP
    # good keeps passing later rungs it tops
    assert sched.on_trial_result(None, good,
                                 {"training_iteration": 4, "score": 20.0}) \
        == tune.TrialScheduler.CONTINUE
    # time_attr advancing in jumps still crosses rungs (>=, not ==)
    jumpy = Trial("jumpy", {})
    assert sched.on_trial_result(None, jumpy,
                                 {"training_iteration": 5, "score": 0.5}) \
        == tune.TrialScheduler.STOP
    # and max_t always terminates
    assert sched.on_trial_result(None, good,
                                 {"training_iteration": 20, "score": 99.0}) \
        == tune.TrialScheduler.STOP


def test_asha_integration_smoke(ray_start_shared, tmp_path):
    sched = tune.ASHAScheduler(grace_period=2, reduction_factor=2, max_t=20)
    tuner = tune.Tuner(
        Quadratic,
        param_space={"x": tune.grid_search([0.0, 1.0, 2.5, 3.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=sched),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
        stop={"training_iteration": 20})
    grid = tuner.fit()
    # Every trial terminated cleanly and the best config won.
    assert all(t.status == exp_mod.TERMINATED for t in grid.trials)
    assert grid.get_best_result().metrics["score"] == 0.0


def test_pbt_exploits(ray_start_shared, tmp_path):
    class Learner(tune.Trainable):
        def setup(self, config):
            self.lr = config["lr"]
            self.value = 0.0

        def step(self):
            self.value += 1.0 if 0.05 <= self.lr <= 0.5 else 0.01
            return {"value": self.value, "lr": self.lr}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "v.txt"), "w") as f:
                f.write(str(self.value))

        def load_checkpoint(self, d):
            with open(os.path.join(d, "v.txt")) as f:
                self.value = float(f.read())

        def reset_config(self, new_config):
            self.lr = new_config["lr"]
            return True

    sched = tune.PopulationBasedTraining(
        perturbation_interval=3,
        hyperparam_mutations={"lr": tune.loguniform(1e-3, 1.0)},
        seed=0)
    tuner = tune.Tuner(
        Learner,
        param_space={"lr": tune.grid_search([1e-4, 0.1, 2e-4, 0.2])},
        tune_config=tune.TuneConfig(metric="value", mode="max",
                                    scheduler=sched),
        run_config=RunConfig(name="pbt", storage_path=str(tmp_path)),
        stop={"training_iteration": 12})
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["value"] >= 10.0  # a good-lr lineage survived


def test_trial_failure_retry(ray_start_shared, tmp_path):
    class Flaky(tune.Trainable):
        def setup(self, config):
            self.i = 0

        def step(self):
            self.i += 1
            if self.i == 2 and not os.path.exists(str(tmp_path / "died")):
                (tmp_path / "died").write_text("1")
                os._exit(1)  # hard-crash the trial actor
            return {"i": self.i}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "i.txt"), "w") as f:
                f.write(str(self.i))

        def load_checkpoint(self, d):
            with open(os.path.join(d, "i.txt")) as f:
                self.i = int(f.read())

    tuner = tune.Tuner(
        Flaky, tune_config=tune.TuneConfig(metric="i", mode="max"),
        run_config=RunConfig(name="flaky", storage_path=str(tmp_path)),
        stop={"training_iteration": 4}, max_failures=2, checkpoint_freq=1)
    grid = tuner.fit()
    t = grid.trials[0]
    assert t.status == exp_mod.TERMINATED
    assert t.num_failures == 1
    assert t.last_result["i"] == 4


def test_tuner_restore(ray_start_shared, tmp_path):
    tuner = tune.Tuner(
        Quadratic, param_space={"x": tune.grid_search([1.0, 3.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="res", storage_path=str(tmp_path)),
        stop={"training_iteration": 2})
    grid = tuner.fit()
    exp_dir = grid.experiment_path
    restored = tune.Tuner.restore(
        exp_dir, Quadratic,
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        stop={"training_iteration": 2})
    grid2 = restored.fit()
    assert len(grid2) == 2  # restored, not regenerated
    assert all(t.status == exp_mod.TERMINATED for t in grid2.trials)


def test_tpe_searcher_converges_better_than_random():
    # Pure-searcher unit test: TPE should concentrate samples near the
    # optimum of a smooth 1-d objective versus uniform random.
    space = {"x": tune.uniform(0.0, 10.0)}

    def run_searcher(searcher, n):
        searcher.set_search_properties("score", "max", space)
        best = -1e9
        for i in range(n):
            cfg = searcher.suggest(f"t{i}")
            if cfg is None:
                break
            score = -((cfg["x"] - 7.3) ** 2)
            searcher.on_trial_complete(f"t{i}", {"score": score})
            best = max(best, score)
        return best

    tpe_best = run_searcher(tune.TPESearcher(num_samples=40, seed=1), 40)
    rng = random.Random(1)
    rand_best = max(-((rng.uniform(0, 10) - 7.3) ** 2) for _ in range(40))
    assert tpe_best >= rand_best - 1e-6


def test_tuner_over_jax_trainer(ray_start_shared, tmp_path):
    from ray_tpu.train import JaxTrainer, ScalingConfig
    from ray_tpu.train import context as train_ctx

    def loop(config):
        # metric improves with the right "lr"
        score = -abs(config["lr"] - 0.1)
        train_ctx.report({"score": score})

    trainer = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=1))
    tuner = tune.Tuner(
        trainer, param_space={"lr": tune.grid_search([0.01, 0.1])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="trainer_tune", storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert grid.get_best_result().metrics["score"] == 0.0


# --- external searcher seam (round 3; reference:
#     tune/search/optuna/optuna_search.py:127) --------------------------

class _FakeOptunaTrial:
    def __init__(self, rng):
        self._rng = rng
        self.params = {}

    def suggest_float(self, name, low, high, log=False):
        v = self._rng.uniform(low, high)
        self.params[name] = v
        return v

    def suggest_int(self, name, low, high):
        v = self._rng.randint(low, high)
        self.params[name] = v
        return v

    def suggest_categorical(self, name, choices):
        v = self._rng.choice(list(choices))
        self.params[name] = v
        return v


class _FakeOptunaStudy:
    def __init__(self, rng):
        self._rng = rng
        self.asked = []
        self.told = []

    def ask(self):
        t = _FakeOptunaTrial(self._rng)
        self.asked.append(t)
        return t

    def tell(self, trial, value=None, state=None):
        self.told.append((trial, value, state))


def _install_fake_optuna(monkeypatch):
    import sys as _sys
    import types

    fake = types.ModuleType("optuna")
    fake._studies = []

    def create_study(direction, sampler=None):
        study = _FakeOptunaStudy(random.Random(0))
        study.direction = direction
        fake._studies.append(study)
        return study

    fake.create_study = create_study
    fake.samplers = types.SimpleNamespace(
        TPESampler=lambda seed=None: None)
    fail = types.SimpleNamespace(FAIL="FAIL")
    fake.trial = types.SimpleNamespace(TrialState=fail)
    monkeypatch.setitem(_sys.modules, "optuna", fake)
    return fake


def test_optuna_search_adapter(monkeypatch):
    fake = _install_fake_optuna(monkeypatch)
    searcher = tune.OptunaSearch(num_samples=6, seed=0)
    space = {"lr": tune.loguniform(1e-4, 1e-1),
             "layers": tune.randint(1, 5),
             "act": tune.choice(["relu", "tanh"]),
             "fixed": 7}
    searcher.set_search_properties("score", "max", space)
    for i in range(6):
        cfg = searcher.suggest(f"t{i}")
        assert 1e-4 <= cfg["lr"] <= 1e-1
        assert 1 <= cfg["layers"] <= 4  # [1, 5) exclusive upper
        assert cfg["act"] in ("relu", "tanh")
        assert cfg["fixed"] == 7
        if i == 5:
            searcher.on_trial_complete(f"t{i}", None)  # failure path
        else:
            searcher.on_trial_complete(f"t{i}", {"score": float(i)})
    assert searcher.suggest("t6") is None  # num_samples exhausted
    study = fake._studies[0]
    assert study.direction == "maximize"
    assert len(study.told) == 6
    assert study.told[-1][2] == "FAIL"
    values = [v for _, v, s in study.told if s is None]
    assert values == [0.0, 1.0, 2.0, 3.0, 4.0]


def test_optuna_search_drives_tuner(ray_start_shared, monkeypatch):
    _install_fake_optuna(monkeypatch)

    def loop(config):
        tune.report({"score": -abs(config["x"] - 3.0)})

    results = tune.run(
        loop, config={"x": tune.uniform(0.0, 10.0)},
        metric="score", mode="max", num_samples=4,
        search_alg=tune.OptunaSearch(num_samples=4, seed=0))
    best = results.get_best_result()
    assert best.metrics["score"] <= 0.0
    assert len(results) == 4


def test_bayesopt_searcher_converges_and_mode_min():
    """Native GP searcher (reference: search/bayesopt): beats uniform
    random on a smooth objective with the same budget, and honors
    mode="min"."""
    space = {"x": tune.uniform(0.0, 10.0), "y": tune.uniform(0.0, 4.0)}

    def run(searcher, n, mode):
        searcher.set_search_properties("score", mode, space)
        best = None
        for i in range(n):
            cfg = searcher.suggest(f"t{i}")
            if cfg is None:
                break
            score = (cfg["x"] - 7.3) ** 2 + (cfg["y"] - 1.1) ** 2
            if mode == "max":
                score = -score
            searcher.on_trial_complete(f"t{i}", {"score": score})
            better = (max if mode == "max" else min)
            best = score if best is None else better(best, score)
        return best

    gp_best = run(tune.BayesOptSearcher(num_samples=30, seed=3), 30,
                  "max")
    rng = random.Random(3)
    rand_best = max(
        -((rng.uniform(0, 10) - 7.3) ** 2 + (rng.uniform(0, 4) - 1.1) ** 2)
        for _ in range(30))
    assert gp_best >= rand_best - 1e-6
    # min mode: same objective, un-negated
    gp_min = run(tune.BayesOptSearcher(num_samples=30, seed=4), 30,
                 "min")
    assert gp_min < 4.0  # near the optimum, not a corner
    # exhausts its budget
    s = tune.BayesOptSearcher(num_samples=2, seed=0)
    s.set_search_properties("score", "max", space)
    assert s.suggest("a") is not None
    assert s.suggest("b") is not None
    assert s.suggest("c") is None
