"""Core IO loop tests: fd registration/teardown, partial reads across
frame boundaries, write backpressure, peer-disconnect cleanup — each
run against BOTH wire codecs (the native C codec and the pure-Python
fallback), plus the thread-topology acceptance check that the
per-connection reader threads are really gone."""

import socket
import struct
import threading
import time

import pytest

import ray_tpu
from ray_tpu.core.io_loop import IOLoop, _make_codec
from ray_tpu.core.protocol import FrameReader
from ray_tpu.native import _lib
from ray_tpu.util import metrics

_LEN = struct.Struct("<I")

NATIVE_AVAILABLE = _lib.try_load() is not None


@pytest.fixture(params=["fallback", "native"])
def native(request):
    if request.param == "native" and not NATIVE_AVAILABLE:
        pytest.skip("native wire codec unavailable (no C toolchain)")
    return request.param == "native"


@pytest.fixture
def loop():
    lp = IOLoop(name="test-io-loop", report_metrics=True)
    yield lp
    lp.stop()


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {msg}")


def _gauge(name):
    return metrics._registry.gauges.get((name, ()))


def test_register_teardown_and_fd_gauge(loop, native):
    a, b = socket.socketpair()
    got, closed = [], []
    conn = loop.register(a, lambda c, frames: got.extend(frames),
                         lambda c: closed.append(1),
                         label="t", native=native)
    assert conn.native == native
    assert loop.barrier()
    assert _gauge("ray_tpu_core_io_loop_registered_fds") == 1.0

    b.sendall(_LEN.pack(3) + b"abc")
    _wait(lambda: got == [b"abc"], msg="frame delivery")

    # echo back out through the loop connection
    conn.send_frame(b"reply")
    b.settimeout(5)
    reader, echoed = FrameReader(), []
    while not echoed:
        echoed += reader.feed(b.recv(65536))
    assert echoed == [b"reply"]

    conn.close()
    _wait(lambda: closed == [1], msg="on_close")
    assert conn.closed
    assert loop.barrier()
    assert _gauge("ray_tpu_core_io_loop_registered_fds") == 0.0
    b.close()


def test_partial_reads_across_frame_boundaries(loop, native):
    a, b = socket.socketpair()
    got = []
    loop.register(a, lambda c, frames: got.extend(frames),
                  label="dribble", native=native)
    payloads = [b"x" * 7, b"", b"y", b"z" * 4096, b"w" * 100_000]
    blob = b"".join(_LEN.pack(len(p)) + p for p in payloads)
    # Dribble in splits that land mid-header and mid-payload, with
    # pauses so the loop observes genuinely partial reads.
    for off in range(0, len(blob), 3001):
        b.sendall(blob[off:off + 3001])
        time.sleep(0.002)
    _wait(lambda: len(got) == len(payloads), msg="all frames")
    assert got == payloads
    b.close()


def test_write_backpressure_blocks_then_unblocks(loop, native):
    a, b = socket.socketpair()
    a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 32 * 1024)
    conn = loop.register(a, lambda c, f: None, label="bp", native=native,
                         high_water=64 * 1024, low_water=16 * 1024)
    total, payload = 300, b"p" * 8192
    sent = []

    def producer():
        for _ in range(total):
            conn.send_frame(payload)
            sent.append(1)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.3)
    # ~2.4 MB total vs ~100 KB of queue + kernel buffer: with nobody
    # reading, the producer must be parked on the drain event.
    assert len(sent) < total, "producer never hit backpressure"

    reader, n_rx = FrameReader(), [0]
    b.settimeout(10)

    def drain():
        while n_rx[0] < total:
            n_rx[0] += len(reader.feed(b.recv(256 * 1024)))

    rx_thread = threading.Thread(target=drain, daemon=True)
    rx_thread.start()
    t.join(15)
    rx_thread.join(15)
    assert len(sent) == total, "producer did not unblock after drain"
    assert n_rx[0] == total
    conn.close()
    b.close()


def test_peer_disconnect_cleanup_fires_on_close_once(loop, native):
    a, b = socket.socketpair()
    closed = []
    conn = loop.register(a, lambda c, f: None,
                         lambda c: closed.append(1),
                         label="eof", native=native)
    assert loop.barrier()
    b.close()
    _wait(lambda: conn.closed, msg="teardown on peer EOF")
    assert closed == [1]
    # sends after teardown fail fast instead of hanging
    with pytest.raises(OSError):
        conn.send_frame(b"late")
    # an explicit close after teardown must not re-fire on_close
    conn.close()
    assert loop.barrier()
    assert closed == [1]
    assert _gauge("ray_tpu_core_io_loop_registered_fds") == 0.0


def test_codec_leftover_and_eof(native):
    codec = _make_codec(native=native)
    assert codec.native == native
    a, b = socket.socketpair()
    a.setblocking(False)
    try:
        b.sendall(_LEN.pack(3) + b"abc" + b"\x05\x00")
        time.sleep(0.05)
        frames, status = codec.read(a)
        assert frames == [b"abc"]
        assert status == 0
        # the partial tail is recoverable for protocol handoff
        assert codec.leftover() == b"\x05\x00"
        b.close()
        time.sleep(0.05)
        frames, status = codec.read(a)
        assert frames == []
        assert status == _lib.WIRE_EOF
    finally:
        a.close()


def test_codec_prefeed_then_read(native):
    """Bytes handed over from another parser (feed) come out ahead of
    socket data."""
    codec = _make_codec(native=native)
    a, b = socket.socketpair()
    a.setblocking(False)
    try:
        codec.feed(_LEN.pack(2) + b"hi")
        b.sendall(_LEN.pack(3) + b"you")
        time.sleep(0.05)
        frames, status = codec.read(a)
        assert status == 0
        assert frames == [b"hi", b"you"]
    finally:
        a.close()
        b.close()


def test_runtime_thread_topology():
    """Acceptance: ONE shared selector thread services every runtime
    socket — the per-connection reader threads of the old design must
    not exist, and the loop exports the process thread-count gauge."""
    rt = ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def f(x):
            return x + 1

        assert sum(ray_tpu.get([f.remote(i) for i in range(20)])) == 210
        names = [t.name for t in threading.enumerate()]
        assert names.count("rtpu-io-loop") == 1, names
        legacy = [n for n in names
                  if n.startswith(("client-reader", "head-accept",
                                   "object-server", "node-io"))]
        assert not legacy, f"legacy reader threads still present: {legacy}"
        # The gauge is process-wide and survives shutdown, so a stale
        # value from an earlier runtime in this process may linger until
        # this loop's housekeeper (1 s cadence) refreshes it — wait for
        # it to reflect the topology enumerated above.
        _wait(lambda: (_gauge("ray_tpu_process_thread_count") or 0)
              >= len(names) - 2,
              timeout=10, msg="thread-count gauge refresh")
    finally:
        ray_tpu.shutdown()
