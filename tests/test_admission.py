"""Admission control and backpressure: unit semantics of the
AdmissionController (queue caps, EWMA overload, Retry-After), SLO
autoscaling policy hysteresis, rejection-penalty decay, and the wired
serve chain (503 + Retry-After through the proxy, BackpressureError on
the handle path, sheds excluded from latency histograms).
"""

import json
import math
import threading
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.admission import (
    AdmissionController, BackpressureError, Shed, _Ewma,
    get_admission_controller, reset_admission)


@pytest.fixture
def serve_instance(ray_start_shared):
    yield ray_start_shared
    serve.shutdown()


# -- AdmissionController unit semantics -------------------------------------


def test_cap_zero_sheds_only_when_slots_full():
    ac = AdmissionController("d")
    ac.configure(max_queued=0, capacity=2)
    ac.try_acquire()
    ac.try_acquire()  # both slots busy, no queue allowed ...
    with pytest.raises(BackpressureError) as ei:
        ac.try_acquire()
    assert ei.value.reason == "queue_full"
    assert ei.value.retryable is True
    ac.release()
    ac.try_acquire()  # a freed slot readmits
    assert ac.queue_depth() == 0


def test_cap_one_allows_one_waiter():
    ac = AdmissionController("d")
    ac.configure(max_queued=1, capacity=1)
    ac.try_acquire()           # occupies the slot
    ac.try_acquire()           # the one allowed waiter
    assert ac.queue_depth() == 1
    with pytest.raises(BackpressureError):
        ac.try_acquire()


def test_cap_negative_disables_shedding():
    ac = AdmissionController("d")
    ac.configure(max_queued=-1, capacity=1)
    for _ in range(50):
        ac.try_acquire()
    assert ac.queue_depth() == 49


def test_backpressure_error_pickles_with_fields():
    import pickle
    err = BackpressureError("dep", 2.5, "queue_wait_ewma")
    back = pickle.loads(pickle.dumps(err))
    assert back.deployment == "dep"
    assert back.retry_after_s == 2.5
    assert back.reason == "queue_wait_ewma"
    assert back.retryable is True
    shed = pickle.loads(pickle.dumps(Shed(1.5, "engine_saturated")))
    assert shed.retry_after_s == 1.5 and shed.reason == "engine_saturated"


def test_retry_after_bounded():
    ac = AdmissionController("d")
    ac.configure(max_queued=0, capacity=1)
    ac.note_latency(10_000.0)  # absurd latency must not blow the bound
    ac.try_acquire()
    with pytest.raises(BackpressureError) as ei:
        ac.try_acquire()
    assert 0.1 <= ei.value.retry_after_s <= 30.0


def test_ewma_queue_wait_sheds_then_recovers():
    ac = AdmissionController("d")
    ac.configure(max_queued=100, capacity=4, shed_queue_wait_s=0.05)
    ac._queue_wait = _Ewma(halflife_s=0.05)  # fast decay for the test
    ac.note_queue_wait(5.0)
    with pytest.raises(BackpressureError) as ei:
        ac.try_acquire()
    assert ei.value.reason == "queue_wait_ewma"
    # silence decays the EWMA toward zero -> admission recovers
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        try:
            ac.try_acquire()
            break
        except BackpressureError:
            time.sleep(0.02)
    else:
        pytest.fail("EWMA never decayed below the shed threshold")


def test_take_max_queue_depth_resets_peak():
    ac = AdmissionController("d")
    ac.configure(max_queued=10, capacity=1)
    for _ in range(4):
        ac.try_acquire()
    assert ac.take_max_queue_depth() == 3
    for _ in range(4):
        ac.release()
    # depth was still 3 when the last window was taken, so that is the
    # (true) peak of the second window; the third window starts empty
    assert ac.take_max_queue_depth() == 3
    assert ac.take_max_queue_depth() == 0


def test_registry_is_per_deployment():
    reset_admission()
    a = get_admission_controller("a")
    b = get_admission_controller("b")
    assert a is get_admission_controller("a")
    assert a is not b
    a.configure(max_queued=0, capacity=1)
    a.try_acquire()
    with pytest.raises(BackpressureError):
        a.try_acquire()
    b.try_acquire()  # b's cap is untouched by a's overload
    reset_admission()


# -- histogram percentile readout (util/metrics) ----------------------------


def test_percentile_from_counts_interpolates():
    from ray_tpu.util.metrics import percentile_from_counts
    bounds = [1.0, 2.0, 4.0]
    # 10 obs in (1, 2]: the median interpolates inside that bucket
    assert percentile_from_counts(bounds, [0, 10, 0, 0], 0.5) == \
        pytest.approx(1.5, abs=0.06)
    # overflow bucket clamps to the top bound
    assert percentile_from_counts(bounds, [0, 0, 0, 5], 0.99) == 4.0
    assert percentile_from_counts(bounds, [0, 0, 0, 0], 0.5) is None


def test_histogram_percentile_readout():
    from ray_tpu.util.metrics import Histogram
    h = Histogram("t_adm_pctl_seconds", "t", tag_keys=("k",),
                  boundaries=[0.1, 1.0, 10.0])
    for v in (0.05, 0.5, 0.6, 5.0):
        h.observe(v, tags={"k": "x"})
    p50 = h.percentile(0.5, tags={"k": "x"})
    assert 0.1 <= p50 <= 1.0
    assert h.percentile(0.5, tags={"k": "missing"}) is None
    bounds, buckets, total, count = h.snapshot(tags={"k": "x"})
    assert count == 4 and len(buckets) == len(bounds) + 1


# -- SLO autoscaling policy -------------------------------------------------


def _slo_cfg(**kw):
    from ray_tpu.serve.config import AutoscalingConfig
    defaults = dict(policy="slo", min_replicas=1, max_replicas=4,
                    target_queue_depth=4.0, upscale_delay_s=1.0,
                    downscale_delay_s=2.0, slo_stats_staleness_s=3.0)
    defaults.update(kw)
    return AutoscalingConfig(**defaults)


def test_slo_policy_upscale_needs_sustained_breach():
    from ray_tpu.autoscaler.policy import ReplicaMetrics, make_policy
    pol = make_policy("slo")
    cfg = _slo_cfg()
    m = ReplicaMetrics(running_replicas=1, queue_depth=12.0,
                       stats_age_s=0.0)
    # breach starts: no change before upscale_delay_s elapses
    assert pol.desired_replicas(m, cfg, 1, now=100.0) == 1
    assert pol.desired_replicas(m, cfg, 1, now=100.5) == 1
    # sustained past the delay: proportional step (12/4 -> +2)
    assert pol.desired_replicas(m, cfg, 1, now=101.2) == 3
    # the next step needs its own sustained window (re-armed)
    assert pol.desired_replicas(m, cfg, 3, now=101.3) == 3


def test_slo_policy_downscale_hysteresis():
    from ray_tpu.autoscaler.policy import ReplicaMetrics, make_policy
    pol = make_policy("slo")
    cfg = _slo_cfg()
    calm = ReplicaMetrics(running_replicas=3, queue_depth=0.0,
                          stats_age_s=0.0)
    assert pol.desired_replicas(calm, cfg, 3, now=10.0) == 3
    # a blip above half-threshold resets the calm window
    busyish = ReplicaMetrics(running_replicas=3, queue_depth=3.0,
                             stats_age_s=0.0)
    assert pol.desired_replicas(busyish, cfg, 3, now=11.0) == 3
    assert pol.desired_replicas(calm, cfg, 3, now=11.5) == 3
    assert pol.desired_replicas(calm, cfg, 3, now=13.0) == 3
    # sustained calm: one replica at a time, window re-armed
    assert pol.desired_replicas(calm, cfg, 3, now=13.6) == 2
    assert pol.desired_replicas(calm, cfg, 2, now=14.0) == 2
    assert pol.desired_replicas(calm, cfg, 2, now=15.7) == 1
    # never below min_replicas
    assert pol.desired_replicas(calm, cfg, 1, now=30.0) == 1


def test_slo_policy_stale_stats_never_upscale():
    from ray_tpu.autoscaler.policy import ReplicaMetrics, make_policy
    pol = make_policy("slo")
    cfg = _slo_cfg()
    stale = ReplicaMetrics(running_replicas=1, queue_depth=100.0,
                           stats_age_s=60.0)
    assert pol.desired_replicas(stale, cfg, 1, now=0.0) == 1
    assert pol.desired_replicas(stale, cfg, 1, now=5.0) == 1


def test_slo_policy_p99_term():
    from ray_tpu.autoscaler.policy import ReplicaMetrics, make_policy
    pol = make_policy("slo")
    cfg = _slo_cfg(p99_latency_slo_s=0.5)
    slow = ReplicaMetrics(running_replicas=1, queue_depth=0.0,
                          p99_latency_s=2.0, stats_age_s=0.0)
    assert pol.desired_replicas(slow, cfg, 1, now=0.0) == 1
    assert pol.desired_replicas(slow, cfg, 1, now=1.5) == 2


def test_make_policy_unknown_raises():
    from ray_tpu.autoscaler import make_policy
    with pytest.raises(ValueError):
        make_policy("nope")


# -- rejection-penalty decay (router) ---------------------------------------


def test_rejection_penalty_decays_to_zero():
    from ray_tpu.serve.router import Router
    r = Router("t_penalty_dep", controller=None)
    r.reject_penalty_tau_s = 0.05
    with r._lock:
        r._note_rejection_locked("a")
        r._note_rejection_locked("a")
    assert r.rejection_penalty("a") > 1.0  # gated from affinity
    deadline = time.monotonic() + 5.0
    while r.rejection_penalty("a") > 0.0:
        if time.monotonic() > deadline:
            pytest.fail("penalty never decayed to zero")
        time.sleep(0.02)
    assert "a" not in r._reject_penalty  # entry dropped at the floor


def test_recovered_replica_regains_affinity_share():
    """A cache-affine replica that rejected twice sits out prefix
    routing while its penalty is hot, then wins the prompt again once
    the penalty has decayed (recovery regains traffic share)."""
    from ray_tpu.serve.prefix_router import PrefixAwareRouter

    class _DeadHandle:
        # _queue_len's probe fails fast -> both candidates tie
        def __getattr__(self, name):
            raise AttributeError(name)

    r = PrefixAwareRouter("t_affinity_dep", controller=None)
    r.reject_penalty_tau_s = 0.05
    r._replicas = [("a", _DeadHandle()), ("b", _DeadHandle())]
    prompt = "You are a helpful assistant. Question one" * 3
    r.tree.insert(prompt, "a")
    assert r._choose_for_prompt(prompt)[0] == "a"
    with r._lock:
        r._note_rejection_locked("a")
        r._note_rejection_locked("a")
    assert r.rejection_penalty("a") >= 1.0
    # while hot, affinity is skipped: pow-2 over {a, b} (ties resolve
    # arbitrarily, so only assert the penalty gate is active)
    deadline = time.monotonic() + 5.0
    while r.rejection_penalty("a") > 0.0:
        if time.monotonic() > deadline:
            pytest.fail("penalty never decayed")
        time.sleep(0.02)
    assert r._choose_for_prompt(prompt)[0] == "a"  # share regained


# -- engine reject-before-enqueue -------------------------------------------


def test_engine_sheds_before_enqueue():
    from ray_tpu.llm import (
        ContinuousBatchingEngine, EngineConfig, EngineSaturatedError,
        GenerationRequest)
    from ray_tpu.models.llama import LlamaConfig
    eng = ContinuousBatchingEngine(EngineConfig(
        model=LlamaConfig.tiny(max_seq_len=64, attention="reference",
                               remat=False),
        max_batch=2, max_seq=64, max_waiting_requests=1))
    eng.add_request(GenerationRequest(
        request_id="r1", prompt_ids=[1, 2, 3], max_tokens=1))
    with pytest.raises(EngineSaturatedError) as ei:
        eng.add_request(GenerationRequest(
            request_id="r2", prompt_ids=[1, 2, 3], max_tokens=1))
    assert ei.value.waiting == 1 and ei.value.cap == 1
    assert len(eng.waiting) == 1  # the shed request was NOT enqueued


# -- wired chain (cluster) --------------------------------------------------


def test_handle_sheds_with_backpressure_and_recovers(serve_instance):
    @serve.deployment(max_ongoing_requests=1, max_queued_requests=0)
    class Slow:
        def __call__(self, req):
            time.sleep(req.get("sleep", 0))
            return "done"

    handle = serve.run(Slow.bind(), name="shed_app")
    # warm-up: configures the admission controller from the deployment
    # config (capacity = 1 replica * 1 ongoing, cap 0)
    assert handle.remote({}).result(timeout_s=30) == "done"
    blocker = handle.remote({"sleep": 1.5})
    time.sleep(0.2)  # let the blocker occupy the only slot
    with pytest.raises(BackpressureError) as ei:
        handle.remote({})
    assert ei.value.retryable is True
    assert ei.value.retry_after_s > 0
    assert ei.value.deployment == "Slow"
    assert blocker.result(timeout_s=30) == "done"
    # the slot freed: a retry after the shed now succeeds
    assert handle.remote({}).result(timeout_s=30) == "done"


def test_shed_excluded_from_latency_histogram(serve_instance):
    from ray_tpu.util.metrics import histogram_snapshot

    @serve.deployment(max_ongoing_requests=1, max_queued_requests=0)
    class Slow2:
        def __call__(self, req):
            time.sleep(req.get("sleep", 0))
            return "ok"

    handle = serve.run(Slow2.bind(), name="shed_hist_app")
    handle.remote({}).result(timeout_s=30)
    tags = {"deployment": "Slow2"}

    def latency_count():
        snap = histogram_snapshot(
            "ray_tpu_serve_request_latency_seconds", tags=tags)
        return 0 if snap is None else snap[3]

    before = latency_count()
    blocker = handle.remote({"sleep": 1.0})
    time.sleep(0.2)
    for _ in range(5):
        with pytest.raises(BackpressureError):
            handle.remote({})
    blocker.result(timeout_s=30)
    # only the blocker's completion was observed; 5 sheds were not
    assert latency_count() == before + 1


def test_caps_are_per_deployment(serve_instance):
    @serve.deployment(name="capped", max_ongoing_requests=1,
                      max_queued_requests=0)
    class Capped:
        def __call__(self, req):
            time.sleep(req.get("sleep", 0))
            return "capped"

    @serve.deployment(name="open")
    class Open:
        def __call__(self, req):
            return "open"

    capped = serve.run(Capped.bind(), name="cap_app",
                       route_prefix="/capped")
    opened = serve.run(Open.bind(), name="open_app",
                       route_prefix="/open")
    capped.remote({}).result(timeout_s=30)
    blocker = capped.remote({"sleep": 1.0})
    time.sleep(0.2)
    with pytest.raises(BackpressureError):
        capped.remote({})
    # the other deployment's admission state is independent
    assert opened.remote({}).result(timeout_s=30) == "open"
    blocker.result(timeout_s=30)


def test_http_503_with_retry_after(serve_instance):
    @serve.deployment(max_ongoing_requests=1, max_queued_requests=0)
    class SlowHttp:
        def __call__(self, req):
            time.sleep(float(req.get("sleep", 0)))
            return {"ok": True}

    serve.start(proxy=True, http_options=serve.HTTPOptions(port=0))
    port = serve._proxy.port
    serve.run(SlowHttp.bind(), name="http503_app", route_prefix="/s")

    def post(payload, timeout=30):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/s",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    assert post({}) == {"ok": True}  # warm-up configures admission
    blocker = threading.Thread(target=post, args=({"sleep": 1.5},))
    blocker.start()
    try:
        time.sleep(0.4)
        with pytest.raises(urllib.error.HTTPError) as ei:
            post({})
        err = ei.value
        assert err.code == 503
        retry_after = err.headers.get("Retry-After")
        assert retry_after is not None and int(retry_after) >= 1
        body = json.loads(err.read())
        assert body["deployment"] == "SlowHttp"
        assert body["reason"] == "queue_full"
        assert body["retry_after_s"] > 0
    finally:
        blocker.join(timeout=30)
    assert post({}) == {"ok": True}  # recovered after the blocker
