"""Sharding, ring attention, Ulysses, pipeline tests on the 8-device
CPU mesh (SURVEY.md §7: testing without TPUs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel import _compat
from ray_tpu.parallel.mesh import MeshSpec, make_mesh, mesh_axis_size
from ray_tpu.parallel.pipeline import pipeline
from ray_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
    ulysses_attention,
)

# ring/ulysses/pipeline all lower through shard_map; its import home
# moves across jax versions (see parallel/_compat.py). Skip those tests
# with the detected reason rather than erroring at collection.
needs_shard_map = pytest.mark.skipif(
    not _compat.SHARD_MAP_AVAILABLE,
    reason=_compat.SHARD_MAP_UNAVAILABLE_REASON or "shard_map available")
from ray_tpu.parallel.sharding import (
    ShardingConfig,
    ShardingRules,
    infer_sharding,
    shard_pytree,
)


def test_mesh_spec():
    spec = MeshSpec.for_devices(8, model=2)
    assert spec.data == 4 and spec.model == 2 and spec.size == 8
    mesh = make_mesh(spec)
    assert mesh_axis_size(mesh, "model") == 2
    assert mesh_axis_size(mesh, "data") == 4


def test_sharding_rules_match():
    rules = ShardingRules(rules=[
        (r"dense/kernel", P("fsdp", "model")),
        (r".*", P()),
    ])
    assert rules.spec_for("model/dense/kernel", 2) == P("fsdp", "model")
    assert rules.spec_for("model/bias", 1) == P()
    # Spec longer than ndim gets truncated.
    assert rules.spec_for("dense/kernel", 1) == P("fsdp")


def test_shard_pytree_places_shards(cpu_mesh8):
    mesh = make_mesh(MeshSpec(data=2, model=4), cpu_mesh8)
    tree = {"dense": {"kernel": jnp.ones((8, 16)), "bias": jnp.ones(16)}}
    rules = ShardingConfig(mode="tp").rules()
    # generic tp rules don't match "kernel"; use explicit rules
    rules = ShardingRules(rules=[(r"kernel", P(None, "model")),
                                 (r".*", P())])
    sharded = shard_pytree(tree, mesh, rules)
    assert sharded["dense"]["kernel"].sharding.spec == P(None, "model")


@pytest.mark.parametrize("causal", [True, False])
@needs_shard_map
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh(MeshSpec(seq=4, data=2))
    B, S, H, D = 2, 64, 4, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
    ref = reference_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@needs_shard_map
def test_ulysses_matches_reference():
    mesh = make_mesh(MeshSpec(seq=4, data=2))
    B, S, H, D = 2, 64, 8, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
    ref = reference_attention(q, k, v, causal=True)
    out = ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@needs_shard_map
def test_ring_attention_sharded_inputs():
    """Ring attention with inputs actually sharded over seq."""
    mesh = make_mesh(MeshSpec(seq=8))
    B, S, H, D = 1, 128, 4, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
    sharding = NamedSharding(mesh, P(None, "seq", None, None))
    qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))(qs, ks, vs)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@needs_shard_map
def test_pipeline_matches_sequential():
    mesh = make_mesh(MeshSpec(pipe=4, data=2))
    n_stages, d = 4, 32
    w = jax.random.normal(jax.random.PRNGKey(0), (n_stages, d, d)) * 0.1
    params = {"w": w}

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    x = jax.random.normal(jax.random.PRNGKey(1), (16, d))
    ref = x
    for i in range(n_stages):
        ref = stage_fn({"w": w[i]}, ref)
    out = pipeline(stage_fn, params, x, mesh, num_microbatches=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@needs_shard_map
def test_pipeline_rejects_bad_microbatch():
    mesh = make_mesh(MeshSpec(pipe=4, data=2))
    params = {"w": jnp.zeros((4, 8, 8))}
    x = jnp.zeros((10, 8))
    with pytest.raises(ValueError, match="divisible"):
        pipeline(lambda p, x: x, params, x, mesh, num_microbatches=4)


@needs_shard_map
def test_pipeline_multi_round_and_grad():
    """More microbatches than stages (R=3 rounds of the sharded input
    stream) and gradient flow with remat."""
    mesh = make_mesh(MeshSpec(pipe=4, data=2))
    n_stages, d = 4, 16
    w = jax.random.normal(jax.random.PRNGKey(0), (n_stages, d, d)) * 0.1
    params = {"w": w}

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    x = jax.random.normal(jax.random.PRNGKey(1), (24, d))
    ref = x
    for i in range(n_stages):
        ref = stage_fn({"w": w[i]}, ref)
    out = pipeline(stage_fn, params, x, mesh, num_microbatches=12)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)

    def loss(p, x):
        return jnp.sum(pipeline(stage_fn, p, x, mesh,
                                num_microbatches=12, remat=True) ** 2)

    def ref_loss(p, x):
        h = x
        for i in range(n_stages):
            h = stage_fn({"w": p["w"][i]}, h)
        return jnp.sum(h ** 2)

    g = jax.grad(loss)(params, x)
    g_ref = jax.grad(ref_loss)(params, x)
    np.testing.assert_allclose(np.asarray(g["w"]), np.asarray(g_ref["w"]),
                               atol=2e-4, rtol=2e-4)


@needs_shard_map
def test_pipeline_rejects_uneven_stage_split():
    mesh = make_mesh(MeshSpec(pipe=4, data=2))
    params = {"w": jnp.zeros((4, 8, 8))}
    x = jnp.zeros((12, 8))
    with pytest.raises(ValueError, match="pipe size"):
        pipeline(lambda p, x: x, params, x, mesh, num_microbatches=6)
