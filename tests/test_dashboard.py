"""Dashboard + log streaming tests (reference model:
python/ray/dashboard/ modules + log_monitor tests)."""

import json
import time
import urllib.request

import pytest

import ray_tpu


def _get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


@pytest.fixture
def dash_runtime():
    rt = ray_tpu.init(num_cpus=4, include_dashboard=True)
    yield rt
    ray_tpu.shutdown()


def test_dashboard_index_and_cluster(dash_runtime):
    assert dash_runtime.dashboard_url
    status, body = _get(dash_runtime.dashboard_url + "/")
    assert status == 200 and "ray_tpu dashboard" in body
    status, body = _get(dash_runtime.dashboard_url + "/api/cluster")
    cluster = json.loads(body)
    assert cluster["total"].get("CPU") == 4.0


def test_dashboard_state_routes(dash_runtime):
    @ray_tpu.remote
    def f():
        return 1

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    actor = A.remote()
    assert ray_tpu.get(actor.ping.remote()) == "pong"
    assert ray_tpu.get(f.remote()) == 1

    base = dash_runtime.dashboard_url
    _, body = _get(base + "/api/nodes")
    nodes = json.loads(body)
    assert len(nodes) == 1 and nodes[0]["is_head"]

    _, body = _get(base + "/api/actors")
    actors = json.loads(body)
    assert any(a["state"] == "ALIVE" for a in actors)

    _, body = _get(base + "/api/tasks?limit=10")
    assert isinstance(json.loads(body), list)

    _, body = _get(base + "/api/summary")
    summary = json.loads(body)
    assert summary.get("FINISHED", 0) >= 1

    _, body = _get(base + "/api/jobs")
    assert isinstance(json.loads(body), list)

    _, body = _get(base + "/api/events")
    events = json.loads(body)
    assert {e["kind"] for e in events} >= {"NODE_ADDED", "LEASE_GRANTED"}
    # query params thread through to the store's filters
    _, body = _get(base + "/api/events?kind=LEASE_GRANTED&limit=3")
    rows = json.loads(body)
    assert 0 < len(rows) <= 3
    assert all(e["kind"] == "LEASE_GRANTED" for e in rows)
    _, body = _get(base + "/api/events?severity=ERROR")
    assert all(e["severity"] == "ERROR" for e in json.loads(body))

    status, body = _get(base + "/metrics")
    assert status == 200


def test_worker_logs_served(dash_runtime):
    @ray_tpu.remote
    def noisy():
        print("dashboard-log-line-xyzzy")
        return 1

    assert ray_tpu.get(noisy.remote()) == 1
    base = dash_runtime.dashboard_url
    # logs flush asynchronously; poll briefly
    deadline = time.time() + 10
    found = False
    while time.time() < deadline and not found:
        _, body = _get(base + "/api/logs")
        files = json.loads(body)
        for _dir, names in files.items():
            for name in names:
                _, tail = _get(f"{base}/api/logs/tail?file={name}&lines=50")
                if "dashboard-log-line-xyzzy" in tail:
                    found = True
        if not found:
            time.sleep(0.2)
    assert found, "worker print never appeared in served logs"


def test_log_tail_rejects_traversal(dash_runtime):
    base = dash_runtime.dashboard_url
    try:
        status, _ = _get(base + "/api/logs/tail?file=../../etc/passwd")
    except urllib.error.HTTPError as e:
        status = e.code
    assert status == 404


def test_log_monitor_echoes(tmp_path, capsys):
    from ray_tpu.dashboard.log_monitor import LogMonitor
    log_dir = tmp_path / "logs"
    log_dir.mkdir()
    monitor = LogMonitor([str(log_dir)], echo=True, interval_s=0.05)
    try:
        (log_dir / "worker-abc.log").write_text("hello-from-worker\n")
        deadline = time.time() + 5
        while time.time() < deadline:
            monitor.poll_once()
            out = capsys.readouterr().out
            if "hello-from-worker" in out:
                assert "(worker-abc)" in out
                return
            time.sleep(0.05)
        raise AssertionError("log line never echoed")
    finally:
        monitor.stop()


def test_serve_status_route(dash_runtime):
    base = dash_runtime.dashboard_url
    _, body = _get(base + "/api/serve")
    assert json.loads(body) == {}  # serve not running: empty but valid

    from ray_tpu import serve

    @serve.deployment
    class S:
        def __call__(self, request):
            return {"ok": True}

    try:
        serve.run(S.bind(), name="dashapp", route_prefix="/dash")
        _, body = _get(base + "/api/serve")
        status = json.loads(body)
        assert status, "serve status empty"
        assert any("S" in name for name in status), status
    finally:
        serve.shutdown()


def test_train_status_route(dash_runtime):
    base = dash_runtime.dashboard_url
    _, body = _get(base + "/api/train")
    assert json.loads(body) == []

    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        from ray_tpu import train
        train.report({"loss": 1.0})

    trainer = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="dash-run"))
    trainer.fit()
    _, body = _get(base + "/api/train")
    runs = json.loads(body)
    assert runs and runs[0]["name"] == "dash-run"
    assert runs[0]["state"] == "FINISHED"
    assert "RUNNING" in runs[0]["history"]


def test_metrics_time_series_surface(dash_runtime):
    """The /metrics scrape carries live core gauges (task counters,
    per-node object-store bytes) that the SPA's Metrics view charts,
    and they move with real activity (reference:
    dashboard/modules/metrics)."""
    @ray_tpu.remote
    def work(x):
        return x * 2

    def scrape():
        _, body = _get(dash_runtime.dashboard_url + "/metrics")
        out = {}
        for line in body.splitlines():
            if line.startswith("#") or " " not in line:
                continue
            key, value = line.rsplit(" ", 1)
            out[key] = float(value)
        return out

    first = scrape()
    assert "ray_tpu_tasks_finished_total" in first
    assert any(k.startswith("ray_tpu_object_store_used_bytes")
               for k in first)
    assert ray_tpu.get([work.remote(i) for i in range(20)]) == [
        i * 2 for i in range(20)]
    second = scrape()
    assert (second["ray_tpu_tasks_finished_total"]
            >= first["ray_tpu_tasks_finished_total"] + 20)

    # per-deployment request totals flow replica -> controller ->
    # labeled gauge on the scrape
    from ray_tpu import serve

    @serve.deployment
    class Q:
        def __call__(self, request):
            return {"ok": True}

    try:
        serve.run(Q.bind(), name="qpsapp", route_prefix="/qps")
        handle = serve.get_deployment_handle("Q", app_name="qpsapp")
        for i in range(7):
            assert handle.remote({"i": i}).result(timeout_s=30)["ok"]
        time.sleep(3.1)  # past the serve-totals scrape cache TTL
        labeled = scrape()
        key = next((k for k in labeled
                    if k.startswith("ray_tpu_serve_requests_total")
                    and 'deployment="Q"' in k), None)
        assert key is not None, sorted(labeled)
        assert labeled[key] >= 7
    finally:
        serve.shutdown()

    # the SPA ships the metrics view: nav entry + chart machinery
    _, html = _get(dash_runtime.dashboard_url + "/")
    assert "#/metrics" in html
    for marker in ("viewMetrics", "parsePrometheus", "ratePoints",
                   "sparkline", "ray_tpu_serve_requests_total"):
        assert marker in html, marker
    assert ".innerHTML" not in html  # textContent/SVG-DOM only


def test_web_ui_spa_served(ray_start_shared):
    """The multi-view SPA (reference: dashboard/client React app;
    here vanilla JS) serves from / with every view's API route live."""
    import urllib.request

    from ray_tpu.dashboard import DashboardServer

    dash = DashboardServer(ray_start_shared, port=0)
    try:
        html = urllib.request.urlopen(dash.url + "/",
                                      timeout=30).read().decode()
        # nav covers the reference dashboard's module views
        for view in ("#/overview", "#/nodes", "#/actors", "#/tasks",
                     "#/objects", "#/pgs", "#/jobs", "#/events",
                     "#/serve", "#/train", "#/logs"):
            assert view in html, view
        # rendering is textContent-only (no injection surface); the
        # word appears in a comment stating the rule, never as code
        assert ".innerHTML" not in html
        # every API the SPA polls answers
        import json as _json
        for route in ("/api/cluster", "/api/nodes", "/api/summary",
                      "/api/events", "/api/serve", "/api/train",
                      "/api/logs"):
            _json.load(urllib.request.urlopen(dash.url + route,
                                              timeout=30))
    finally:
        dash.stop()
