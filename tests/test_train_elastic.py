"""Elastic training + sharded checkpoints.

Reference models: train/v2 Resizing controller state + scaling policies
(controller/state.py:116-125, execution/scaling_policy/) and orbax-style
async sharded checkpointing (SURVEY §5.4).
"""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    ElasticScalingPolicy,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    load_sharded_state,
    reshard_states,
    save_sharded_state,
)


def test_sharded_save_load_reshard(tmp_path):
    """Per-rank shards round-trip and re-partition for a new world size."""
    d = str(tmp_path / "ckpt")
    full = np.arange(12, dtype=np.float64)
    shards = np.array_split(full, 4)
    threads = []
    for rank in range(4):
        t = save_sharded_state(d, rank, 4, {"w": shards[rank],
                                            "step": rank},
                               background=(rank % 2 == 0))
        if t is not None:
            threads.append(t)
    for t in threads:
        t.join()
    states = load_sharded_state(d)
    assert len(states) == 4
    merged = np.concatenate([s["w"] for s in states])
    np.testing.assert_array_equal(merged, full)
    # reshard 4 -> 3 (arrays re-split on axis 0; non-arrays like 'step'
    # are re-split too, so drop them first for the default policy)
    arr_states = [{"w": s["w"]} for s in states]
    new = reshard_states(arr_states, 3)
    assert len(new) == 3
    np.testing.assert_array_equal(
        np.concatenate([s["w"] for s in new]), full)


def test_elastic_resume_at_smaller_world(tmp_path):
    """VERDICT item 8 done-criterion: kill one worker of 4 mid-run; the
    controller resizes to world 3 and resumes from the last sharded
    checkpoint."""
    from ray_tpu.core.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"resources": {"CPU": 2}},
                      system_config={"task_max_retries": 0})
    nodes = []
    for _ in range(4):
        nodes.append(cluster.add_node(
            num_cpus=2, resources={"trainslot": 1.0}))
    storage = str(tmp_path / "run")

    def train_loop(config):
        import numpy as np
        import ray_tpu.train as train

        ctx = train.get_context()
        world = ctx.get_world_size()
        rank = ctx.get_world_rank()
        ckpt_dir = os.path.join(ctx.storage_path, "sharded")
        start_step = 0
        full_dim = 12
        states = train.load_sharded_state(ckpt_dir, timeout=1.0)
        if states is not None:
            # reshard the previous gang's shards for THIS world size
            # (all shards are from ONE complete step — per-step dirs)
            start_step = states[0]["step"]
            arrays = [{"w": s["w"]} for s in states]
            mine = train.reshard_states(arrays, world)[rank]["w"]
        else:
            mine = np.array_split(
                np.zeros(full_dim), world)[rank]
        save_thread = None
        for step in range(start_step, 10):
            mine = mine + 1.0  # "training"
            if rank == 0 and step == 4 and world == 4:
                # crash the gang mid-run after a checkpoint exists
                time.sleep(0.3)
                os._exit(1)
            if save_thread is not None:
                save_thread.join()
            save_thread = train.save_sharded_state(
                ckpt_dir, rank, world, {"w": mine, "step": step + 1},
                step=step + 1, background=True)
            train.report({"step": step, "world": world, "rank": rank})
            time.sleep(0.05)
        if save_thread is not None:
            save_thread.join()
        train.report({"done": True, "world": world})

    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(
            num_workers=4, min_workers=2,
            resources_per_worker={"trainslot": 1.0, "CPU": 1.0}),
        run_config=RunConfig(name="elastic", storage_path=storage,
                             failure_config=FailureConfig(max_failures=2)))

    # Kill rank 0's NODE shortly after the run starts so the cluster can
    # only schedule 3 workers afterwards (the elastic policy shrinks).
    def chaos():
        time.sleep(1.0)
        # rank 0's worker crashed itself (os._exit); also remove one
        # node so only 3 trainslots remain
        cluster.remove_node(nodes[0])

    killer = threading.Thread(target=chaos, daemon=True)
    killer.start()
    try:
        result = trainer.fit()
        assert result.error is None, result.error
        finals = [reports[-1][0] for reports in result.all_reports]
        # resumed gang ran at world 3
        assert all(m["world"] == 3 for m in finals)
        assert len(finals) == 3
        assert "RESIZING" in trainer.state_history
        # the checkpointed state survived: total "training" progress
        # accumulated across the resize (10 steps of +1 over 12 elems,
        # modulo the in-flight step lost at the crash)
        states = load_sharded_state(os.path.join(result.path, "sharded"))
        assert states is not None and len(states) == 3
        merged = np.concatenate([s["w"] for s in states])
        assert merged.shape == (12,)
        assert float(merged.min()) >= 9.0  # every element trained
    finally:
        cluster.shutdown()
