"""Client-mode tests (reference model: python/ray/util/client tests —
a remote driver proxied through the cluster's server).

The head runs in this process with a TCP listener; the CLIENT runs in a
real subprocess (its own interpreter, no shared memory with the head)
and drives tasks/actors/objects through ray_tpu.init(address=...).
"""

import os
import subprocess
import sys
import textwrap

import pytest

import ray_tpu

CLIENT_SCRIPT = textwrap.dedent("""
    import os
    import numpy as np
    import ray_tpu

    rt = ray_tpu.init(address=os.environ["RTPU_HEAD_ADDR"])
    assert not rt.is_driver

    # tasks + inline objects
    @ray_tpu.remote
    def add(a, b):
        return a + b
    assert ray_tpu.get(add.remote(2, 3), timeout=60) == 5

    # large object: put ships to the head, get pulls chunked
    big = np.arange(300_000, dtype=np.float64)  # 2.4MB > inline cap
    ref = ray_tpu.put(big)
    back = ray_tpu.get(ref, timeout=60)
    np.testing.assert_array_equal(back, big)

    # large TASK RESULT pulled from the head's arena
    @ray_tpu.remote
    def make_big(n):
        return np.ones(n, dtype=np.float64)
    out = ray_tpu.get(make_big.remote(400_000), timeout=60)
    assert out.shape == (400_000,) and out[0] == 1.0

    # object as task arg (dependency through the head)
    assert ray_tpu.get(add.remote(ref, ref), timeout=60).sum() == 2 * big.sum()

    # actors incl. named lookup
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0
        def incr(self, k=1):
            self.n += k
            return self.n
    c = Counter.options(name="client-counter").remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.incr.remote(4), timeout=60) == 5
    c2 = ray_tpu.get_actor("client-counter")
    assert ray_tpu.get(c2.incr.remote(), timeout=60) == 6

    # wait()
    refs = [add.remote(i, i) for i in range(4)]
    done, rest = ray_tpu.wait(refs, num_returns=4, timeout=60)
    assert len(done) == 4 and not rest

    # streaming generator across the client boundary
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10
    got = [ray_tpu.get(r, timeout=60) for r in gen.remote(4)]
    assert got == [0, 10, 20, 30]

    # error propagation
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("kapow")
    try:
        ray_tpu.get(boom.remote(), timeout=60)
        raise SystemExit("expected failure")
    except Exception as e:
        assert "kapow" in str(e)

    # cluster introspection
    assert ray_tpu.cluster_resources().get("CPU", 0) >= 2
    assert len(ray_tpu.nodes()) >= 1

    ray_tpu.shutdown()
    print("CLIENT-OK")
""")


@pytest.fixture
def head_with_port():
    rt = ray_tpu.init(num_cpus=4, head_port=0)
    yield rt
    ray_tpu.shutdown()


def _run_client(script: str, address: str, timeout: float = 180.0):
    env = dict(os.environ)
    env["RTPU_HEAD_ADDR"] = address
    env["PYTHONPATH"] = (os.getcwd() + os.pathsep
                         + env.get("PYTHONPATH", ""))
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_client_end_to_end(head_with_port):
    proc = _run_client(CLIENT_SCRIPT, head_with_port.head_address)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "CLIENT-OK" in proc.stdout


def test_client_disconnect_releases_refs(head_with_port):
    script = textwrap.dedent("""
        import os
        import numpy as np
        import ray_tpu
        ray_tpu.init(address=os.environ["RTPU_HEAD_ADDR"])
        ref = ray_tpu.put(np.ones(300_000))
        print("OID", ref.hex())
        # exit WITHOUT dropping the ref: disconnect must release it
    """)
    proc = _run_client(script, head_with_port.head_address)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    oid_hex = [line.split()[1] for line in proc.stdout.splitlines()
               if line.startswith("OID")][0]
    import time
    from ray_tpu.core.ids import ObjectID
    oid = ObjectID.from_hex(oid_hex)
    rt = head_with_port
    deadline = time.time() + 15
    while time.time() < deadline:
        with rt.reference_counter._lock:
            if rt.reference_counter._counts.get(oid, 0) == 0:
                return
        time.sleep(0.2)
    raise AssertionError("client refs not released on disconnect")


def test_client_rejected_on_version_skew(head_with_port):
    script = textwrap.dedent("""
        import os
        from ray_tpu.core.protocol import (MessageConnection, connect_tcp,
                                           parse_address)
        host, port = parse_address(os.environ["RTPU_HEAD_ADDR"])
        conn = MessageConnection(connect_tcp(host, port, timeout=10))
        conn.send({"kind": "CLIENT_REGISTER", "proto_version": -1})
        reply = conn.recv()
        assert reply["kind"] == "REGISTER_REJECTED", reply
        print("REJECTED-OK")
    """)
    proc = _run_client(script, head_with_port.head_address)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "REJECTED-OK" in proc.stdout
