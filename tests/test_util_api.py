"""User-facing ray.util parity surface: ActorPool, Queue,
multiprocessing.Pool, scheduling strategies, autoscaler SDK
(reference: python/ray/tests/test_actor_pool.py, test_queue.py,
test_multiprocessing.py, test_scheduling_strategies, autoscaler sdk).
"""

import time

import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Queue
from ray_tpu.util.multiprocessing import Pool
from ray_tpu.util.queue import Empty, Full
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy)


@ray_tpu.remote(num_cpus=0)  # shared fixture: don't exhaust the 4 CPUs
class _Doubler:
    def double(self, v):
        return 2 * v

    def slow_double(self, v):
        time.sleep(0.3)
        return 2 * v


# --------------------------------------------------------------- ActorPool

def test_actor_pool_map_ordered(ray_start_shared):
    pool = ActorPool([_Doubler.remote(), _Doubler.remote()])
    out = list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    assert out == [2, 4, 6, 8]
    # pool is reusable after a full drain
    out = list(pool.map(lambda a, v: a.double.remote(v), [5, 6]))
    assert out == [10, 12]


def test_actor_pool_map_unordered(ray_start_shared):
    pool = ActorPool([_Doubler.remote(), _Doubler.remote()])
    out = list(pool.map_unordered(
        lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    assert sorted(out) == [2, 4, 6, 8]


def test_actor_pool_submit_get_next(ray_start_shared):
    pool = ActorPool([_Doubler.remote()])
    pool.submit(lambda a, v: a.double.remote(v), 1)
    pool.submit(lambda a, v: a.double.remote(v), 2)
    assert pool.has_next()
    assert pool.get_next() == 2
    assert pool.get_next() == 4
    assert not pool.has_next()


def test_actor_pool_get_next_timeout(ray_start_shared):
    pool = ActorPool([_Doubler.remote()])
    pool.submit(lambda a, v: a.slow_double.remote(v), 5)
    with pytest.raises(TimeoutError):
        pool.get_next(timeout=0.01)
    assert pool.get_next(timeout=10) == 10


def test_actor_pool_membership(ray_start_shared):
    a1, a2 = _Doubler.remote(), _Doubler.remote()
    pool = ActorPool([a1])
    assert pool.has_free()
    idle = pool.pop_idle()
    assert idle is a1
    assert not pool.has_free()
    pool.push(a1)
    pool.push(a2)
    with pytest.raises(ValueError):
        pool.push(a2)
    out = list(pool.map(lambda a, v: a.double.remote(v), [1, 2]))
    assert out == [2, 4]


def test_actor_pool_abandoned_map_does_not_pollute_next(ray_start_shared):
    # Abandon a half-consumed map (1-actor pool, values still queued);
    # the next map must return ONLY its own results and the busy actor
    # must come back to the pool.
    pool = ActorPool([_Doubler.remote()])
    it = pool.map(lambda a, v: a.double.remote(v), [1, 2, 3])
    assert next(it) == 2
    out = list(pool.map(lambda a, v: a.double.remote(v), [10]))
    assert out == [20]
    assert pool.has_free()


def test_actor_pool_ordered_after_unordered(ray_start_shared):
    # Divergence from the reference (noted in PARITY.md): interleaving
    # is well-defined here — get_next always yields the earliest
    # outstanding submission instead of raising ValueError.
    pool = ActorPool([_Doubler.remote()])
    for v in (1, 2, 3):
        pool.submit(lambda a, x: a.double.remote(x), v)
    assert pool.get_next_unordered() in (2, 4, 6)
    assert pool.get_next() in (2, 4, 6)
    assert pool.get_next() in (2, 4, 6)
    assert not pool.has_next()


def test_actor_pool_ignore_if_timedout_discards_and_advances(
        ray_start_shared):
    pool = ActorPool([_Doubler.remote()])
    pool.submit(lambda a, x: a.slow_double.remote(x), 7)
    pool.submit(lambda a, x: a.double.remote(x), 8)
    with pytest.raises(TimeoutError, match="discarded"):
        pool.get_next(timeout=0.01, ignore_if_timedout=True)
    # The hung submission was dropped and its actor reclaimed: the next
    # ordered result is the SECOND submission, and the pool drains free.
    assert pool.get_next(timeout=10) == 16
    assert not pool.has_next()
    assert pool.has_free()


def test_actor_pool_queues_excess_submits(ray_start_shared):
    pool = ActorPool([_Doubler.remote()])
    for v in range(5):
        pool.submit(lambda a, x: a.double.remote(x), v)
    assert len(pool._backlog) == 4
    got = [pool.get_next() for _ in range(5)]
    assert got == [0, 2, 4, 6, 8]


# ------------------------------------------------------------------- Queue

def test_queue_fifo_and_sizes(ray_start_shared):
    q = Queue()
    for i in range(5):
        q.put(i)
    assert q.qsize() == 5
    assert not q.empty()
    assert [q.get() for _ in range(5)] == list(range(5))
    assert q.empty()
    q.shutdown()


def test_queue_maxsize_nowait(ray_start_shared):
    q = Queue(maxsize=2)
    q.put_nowait(1)
    q.put_nowait(2)
    assert q.full()
    with pytest.raises(Full):
        q.put_nowait(3)
    assert q.get_nowait() == 1
    q.put(3, block=False)
    assert q.get_nowait_batch(2) == [2, 3]
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


def test_queue_batch_atomicity(ray_start_shared):
    q = Queue(maxsize=3)
    with pytest.raises(Full):
        q.put_nowait_batch([1, 2, 3, 4])
    assert q.qsize() == 0  # nothing partially enqueued
    q.put_nowait_batch([1, 2, 3])
    with pytest.raises(Empty):
        q.get_nowait_batch(4)
    assert q.get_nowait_batch(3) == [1, 2, 3]
    q.shutdown()


def test_queue_blocking_get_timeout(ray_start_shared):
    q = Queue()
    t0 = time.monotonic()
    with pytest.raises(Empty):
        q.get(timeout=0.2)
    assert time.monotonic() - t0 >= 0.15
    q.shutdown()


def test_queue_blocking_put_unblocks_on_get(ray_start_shared):
    q = Queue(maxsize=1)
    q.put("a")

    @ray_tpu.remote
    def producer(q):
        q.put("b")  # blocks until the driver drains "a"
        return True

    ref = producer.remote(q)
    time.sleep(0.2)
    assert q.get() == "a"
    assert ray_tpu.get(ref, timeout=10) is True
    assert q.get(timeout=5) == "b"
    q.shutdown()


def test_queue_many_parked_puts_no_deadlock(ray_start_shared):
    # 10 producers block on a full queue; the driver must still be
    # able to drain (a small actor-concurrency cap would deadlock:
    # every parked put holds a slot and get() could never run).
    q = Queue(maxsize=1)
    q.put("seed")

    @ray_tpu.remote(num_cpus=0)
    def producer(q, i):
        q.put(i)
        return i

    refs = [producer.remote(q, i) for i in range(10)]
    got = [q.get(timeout=30) for _ in range(11)]
    assert got[0] == "seed"
    assert sorted(got[1:]) == list(range(10))
    assert sorted(ray_tpu.get(refs, timeout=30)) == list(range(10))
    q.shutdown()


def test_queue_passes_between_tasks(ray_start_shared):
    q = Queue()

    @ray_tpu.remote
    def consumer(q):
        return q.get(timeout=10)

    ref = consumer.remote(q)
    q.put({"payload": 42})
    assert ray_tpu.get(ref, timeout=10) == {"payload": 42}
    q.shutdown()


# ---------------------------------------------------- multiprocessing.Pool
# NOTE: worker payload functions are defined INSIDE each test so
# cloudpickle ships them by value — workers cannot import the test
# module (reference tests rely on the same local-def idiom).

def _square(x):  # driver-side helper for expected values only
    return x * x


def test_mp_pool_map(ray_start_shared):
    def square(x):
        return x * x

    with Pool(processes=2) as p:
        assert p.map(square, range(8)) == [x * x for x in range(8)]


def test_mp_pool_starmap_apply(ray_start_shared):
    def add(a, b):
        return a + b

    p = Pool(processes=2)
    try:
        assert p.starmap(add, [(1, 2), (3, 4)]) == [3, 7]
        assert p.apply(add, (5, 6)) == 11
        res = p.apply_async(add, (7, 8))
        assert res.get(timeout=10) == 15
        assert res.ready() and res.successful()
    finally:
        p.terminate()


def test_mp_pool_imap(ray_start_shared):
    def square(x):
        return x * x

    p = Pool(processes=2)
    try:
        assert list(p.imap(square, range(6), chunksize=2)) == \
            [x * x for x in range(6)]
        assert sorted(p.imap_unordered(square, range(6), chunksize=2)) \
            == sorted(x * x for x in range(6))
    finally:
        p.terminate()


def test_mp_pool_initializer_runs_per_worker(ray_start_shared):
    import os

    def initializer(tag):
        os.environ["MP_POOL_TAG"] = tag

    def read_tag(_):
        import os as _os
        return _os.environ.get("MP_POOL_TAG")

    p = Pool(processes=2, initializer=initializer, initargs=("t",))
    try:
        # the initializer ran in the WORKER processes, so tasks see its
        # effect while the driver environment is untouched
        assert p.map(read_tag, range(2), chunksize=1) == ["t", "t"]
        assert os.environ.get("MP_POOL_TAG") is None
    finally:
        p.terminate()


def test_mp_pool_error_propagates(ray_start_shared):
    def boom(x):
        raise RuntimeError("boom")

    p = Pool(processes=1)
    try:
        with pytest.raises(Exception, match="boom"):
            p.map(boom, [1])
        res = p.apply_async(boom, (1,))
        res.wait(timeout=10)
        assert not res.successful()
    finally:
        p.terminate()


def test_mp_pool_join_waits_for_inflight(ray_start_shared, tmp_path):
    marker = str(tmp_path / "done.txt")

    def slow_write(path):
        import time as _t
        _t.sleep(0.5)
        with open(path, "w") as f:
            f.write("done")
        return path

    p = Pool(processes=1)
    p.map_async(slow_write, [marker])
    p.close()
    p.join()  # must block until the worker finished writing
    import os
    assert os.path.exists(marker)
    p.terminate()


def test_mp_pool_lifecycle(ray_start_shared):
    p = Pool(processes=1)
    with pytest.raises(ValueError):
        p.join()  # still running
    p.close()
    p.join()
    with pytest.raises(ValueError):
        p.map(_square, [1])
    p.terminate()  # release the worker actor back to the shared fixture


# ------------------------------------------------- scheduling strategies

def test_node_affinity_strategy(ray_start_shared):
    rt = ray_start_shared
    node_hex = ray_tpu.get_runtime_context().get_node_id()

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_hex, soft=False))
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    assert ray_tpu.get(where.remote(), timeout=30) == node_hex


def test_node_label_strategy_validation():
    with pytest.raises(ValueError):
        NodeLabelSchedulingStrategy({})
    s = NodeLabelSchedulingStrategy({"zone": "us-central2-b"})
    assert s.kind == "NODE_LABEL"
    assert s.labels == {"zone": "us-central2-b"}


# ------------------------------------------------------- autoscaler SDK

def test_request_resources_scales_to_fit(ray_start_shared):
    from ray_tpu.autoscaler import (
        AutoscalerConfig, FakeMultiNodeProvider, NodeTypeConfig,
        StandardAutoscaler)
    from ray_tpu.autoscaler.sdk import request_resources

    rt = ray_start_shared  # head node: 4 CPUs
    autoscaler = StandardAutoscaler(
        AutoscalerConfig(node_types=[
            NodeTypeConfig("cpu4", {"CPU": 4.0}, max_workers=10)],
            idle_timeout_s=3600.0),
        FakeMultiNodeProvider(rt), rt)
    provider = autoscaler.provider
    try:
        # no request -> no launches (no load demand here either)
        autoscaler.update()
        assert len(provider.non_terminated_nodes()) == 0

        # ask for 16 CPUs total; head has 4, so ceil(12/4)=3 nodes
        request_resources(num_cpus=16)
        autoscaler.update()
        assert len(provider.non_terminated_nodes()) == 3

        # idempotent: the request is target-size, not additive
        autoscaler.update()
        assert len(provider.non_terminated_nodes()) == 3

        # bundle form: one 4-CPU shape already fits the new capacity
        request_resources(bundles=[{"CPU": 4.0}])
        autoscaler.update()
        assert len(provider.non_terminated_nodes()) == 3

        # clearing the request stops influencing reconciliation
        request_resources()
        autoscaler.update()
        assert len(provider.non_terminated_nodes()) == 3
    finally:
        request_resources()  # don't leak the KV request to later tests


# ------------------------------------------------- check_serialize

def test_inspect_serializability():
    import threading
    from ray_tpu.util.check_serialize import inspect_serializability

    ok, failures = inspect_serializability(lambda x: x + 1,
                                           print_info=False)
    assert ok and not failures

    lock = threading.Lock()

    def captures_lock():
        return lock

    ok, failures = inspect_serializability(captures_lock,
                                           print_info=False)
    assert not ok
    assert any(f.name == "lock" for f in failures)

    class Holder:
        def __init__(self):
            self.fine = 42
            self.bad = threading.Lock()

    ok, failures = inspect_serializability(Holder(), print_info=False)
    assert not ok
    assert any(f.name == "bad" for f in failures)
