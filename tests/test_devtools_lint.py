"""graftlint rule engine: one firing + one non-firing fixture per rule,
suppression comments, and baseline round-trip."""

import json
import textwrap

import pytest

from ray_tpu.devtools import lint


def run(source, select=None):
    """Lint an in-memory fixture; returns the list of Findings."""
    return lint.lint_file("fixture.py", source=textwrap.dedent(source),
                          select=select)


def rules_hit(source, select=None):
    return {f.rule for f in run(source, select=select)}


# -- GL001 unguarded shared state -------------------------------------

GL001_POS = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0

        def bump(self):
            self._count += 1
"""

GL001_NEG = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0

        def bump(self):
            with self._lock:
                self._count += 1
"""


def test_gl001_fires_on_unlocked_mutation():
    findings = run(GL001_POS, select=["GL001"])
    assert [f.rule for f in findings] == ["GL001"]
    assert "_count" in findings[0].message


def test_gl001_quiet_when_locked_or_lockless():
    assert rules_hit(GL001_NEG, select=["GL001"]) == set()
    # no lock on the class -> no shared-state contract to enforce
    assert rules_hit("""
        class Plain:
            def bump(self):
                self._count = 1
    """, select=["GL001"]) == set()


def test_gl001_exempts_init():
    assert rules_hit("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = {}
    """, select=["GL001"]) == set()


# -- GL002 lock held across blocking call -----------------------------

def test_gl002_fires_on_sleep_under_lock():
    hit = rules_hit("""
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1.0)
    """, select=["GL002"])
    assert hit == {"GL002"}


def test_gl002_quiet_outside_lock():
    assert rules_hit("""
        import time

        def slow():
            time.sleep(1.0)
    """, select=["GL002"]) == set()


# -- GL003 busy-wait polling loop -------------------------------------

def test_gl003_fires_on_sleep_poll_with_event_available():
    hit = rules_hit("""
        import threading
        import time

        class C:
            def __init__(self):
                self._done = threading.Event()

            def wait(self):
                while not self.finished:
                    time.sleep(0.01)
    """, select=["GL003"])
    assert hit == {"GL003"}


def test_gl003_quiet_without_condition_or_event():
    assert rules_hit("""
        import time

        class C:
            def wait(self):
                while not self.finished:
                    time.sleep(0.01)
    """, select=["GL003"]) == set()


# -- GL004 swallowed exception ----------------------------------------

def test_gl004_fires_on_silent_pass():
    hit = rules_hit("""
        def f():
            try:
                g()
            except Exception:
                pass
    """, select=["GL004"])
    assert hit == {"GL004"}
    # bare except too
    hit = rules_hit("""
        def f():
            try:
                g()
            except:
                pass
    """, select=["GL004"])
    assert hit == {"GL004"}


def test_gl004_quiet_when_logged_or_raised():
    assert rules_hit("""
        import logging
        logger = logging.getLogger(__name__)

        def f():
            try:
                g()
            except Exception:
                logger.exception("g failed")
    """, select=["GL004"]) == set()
    assert rules_hit("""
        def f():
            try:
                g()
            except:
                raise
    """, select=["GL004"]) == set()


# -- GL005 forbidden backend import -----------------------------------

def test_gl005_fires_on_cuda_backend_import():
    assert rules_hit("import torch.cuda\n", select=["GL005"]) == {"GL005"}
    assert rules_hit("from cupy import array\n",
                     select=["GL005"]) == {"GL005"}


def test_gl005_quiet_on_allowed_imports():
    assert rules_hit("import jax\nimport numpy\n",
                     select=["GL005"]) == set()


# -- GL006 metric naming convention -----------------------------------

def test_gl006_fires_on_bad_prefix_and_missing_suffix():
    findings = run("""
        from ray_tpu.util.metrics import Counter
        BAD_PREFIX = Counter("serve_requests_total")
        BAD_SUFFIX = Counter("ray_tpu_serve_requests")
    """, select=["GL006"])
    assert [f.rule for f in findings] == ["GL006", "GL006"]


def test_gl006_quiet_on_conforming_names():
    assert rules_hit("""
        from ray_tpu.util.metrics import Counter, Gauge, Histogram
        C = Counter("ray_tpu_serve_requests_total")
        G = Gauge("ray_tpu_engine_batch_occupancy")
        H = Histogram("ray_tpu_request_latency_seconds")
    """, select=["GL006"]) == set()


# -- GL007 trace-context drop -----------------------------------------

def test_gl007_fires_on_tracelss_taskspec():
    hit = rules_hit("""
        from ray_tpu.core.task_spec import TaskSpec

        def submit():
            return TaskSpec(task_id=1, function_id="f", args=[])
    """, select=["GL007"])
    assert hit == {"GL007"}


def test_gl007_quiet_with_trace_id():
    assert rules_hit("""
        from ray_tpu.core.task_spec import TaskSpec

        def submit(tid):
            return TaskSpec(task_id=1, function_id="f", args=[],
                            trace_id=tid)
    """, select=["GL007"]) == set()


# -- GL008 non-daemon background thread -------------------------------

def test_gl008_fires_on_non_daemon_thread():
    hit = rules_hit("""
        import threading

        def start():
            t = threading.Thread(target=loop)
            t.start()
    """, select=["GL008"])
    assert hit == {"GL008"}


def test_gl008_quiet_on_daemon_thread():
    assert rules_hit("""
        import threading

        def start():
            t = threading.Thread(target=loop, daemon=True)
            t.start()
    """, select=["GL008"]) == set()
    # daemon set via attribute before start()
    assert rules_hit("""
        import threading

        def start():
            t = threading.Thread(target=loop)
            t.daemon = True
            t.start()
    """, select=["GL008"]) == set()


# -- suppression comments ---------------------------------------------

def test_per_line_suppression():
    src = """
        def f():
            try:
                g()
            except Exception:  # graftlint: disable=GL004
                pass  # justified: best-effort fixture
    """
    assert rules_hit(src, select=["GL004"]) == set()


def test_suppression_is_rule_specific():
    src = """
        def f():
            try:
                g()
            except Exception:  # graftlint: disable=GL001
                pass
    """
    # wrong rule id on the comment -> GL004 still fires
    assert rules_hit(src, select=["GL004"]) == {"GL004"}


def test_disable_all_suppresses_everything():
    src = """
        def f():
            try:
                g()
            except Exception:  # graftlint: disable=all
                pass
    """
    assert rules_hit(src, select=["GL004"]) == set()


# -- baseline round-trip ----------------------------------------------

def test_baseline_round_trip(tmp_path):
    findings = run(GL001_POS, select=["GL001"])
    assert findings
    path = tmp_path / "baseline.json"
    lint.write_baseline(findings, str(path))

    loaded = lint.load_baseline(str(path))
    assert loaded  # non-empty mapping of fingerprint -> count
    payload = json.loads(path.read_text())
    assert payload["version"] == 1

    # grandfathered findings are filtered out...
    assert lint.apply_baseline(findings, loaded) == []
    # ...but a NEW finding beyond the baselined count still surfaces
    doubled = findings + findings
    fresh = lint.apply_baseline(doubled, loaded)
    assert len(fresh) == len(findings)


def test_baseline_key_is_line_drift_stable():
    shifted = "\n\n\n" + textwrap.dedent(GL001_POS)
    original = run(GL001_POS, select=["GL001"])
    moved = lint.lint_file("fixture.py", source=shifted, select=["GL001"])
    assert original[0].line != moved[0].line
    assert original[0].key == moved[0].key


# -- CLI surface -------------------------------------------------------

def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass
    """))
    assert lint.main([str(bad), "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "GL004" in out

    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert lint.main([str(good), "--no-baseline"]) == 0


def test_cli_write_then_check_baseline(tmp_path, monkeypatch, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass
    """))
    monkeypatch.chdir(tmp_path)
    assert lint.main([str(bad), "--write-baseline"]) == 0
    assert (tmp_path / lint.BASELINE_DEFAULT).is_file()
    capsys.readouterr()
    # same findings now grandfathered -> clean
    assert lint.main([str(bad)]) == 0
    assert "clean" in capsys.readouterr().out


def test_syntax_error_reported_not_raised(tmp_path):
    findings = lint.lint_file("broken.py", source="def f(:\n")
    assert [f.rule for f in findings] == ["GL000"]


# -- GL009 loop-thread blocking call (interprocedural) -----------------

GL009_POS = """
    import time

    def _helper():
        time.sleep(0.5)

    class Proto:
        def __init__(self, io, sock):
            self.conn = io.register_message_conn(
                sock, self._on_msg, self._on_close)

        def _on_msg(self, conn, msg):
            _helper()

        def _on_close(self, conn):
            pass
"""

GL009_NEG = """
    import time

    def background_poll():
        time.sleep(1.0)   # never reachable from a loop callback

    class Proto:
        def __init__(self, io, sock):
            self.conn = io.register_message_conn(
                sock, self._on_msg, self._on_close)

        def _on_msg(self, conn, msg):
            self.last = msg

        def _on_close(self, conn):
            pass
"""


def test_gl009_fires_two_hops_from_registration():
    """loop callback -> module helper -> time.sleep: the finding lands
    on the sleep with the full seed-to-sink chain in the message."""
    findings = run(GL009_POS, select=["GL009"])
    assert [f.rule for f in findings] == ["GL009"]
    msg = findings[0].message
    assert "time.sleep" in msg
    assert "register_message_conn" in msg
    assert "_on_msg" in msg and "_helper" in msg


def test_gl009_quiet_off_loop_and_for_nonblocking_callbacks():
    assert rules_hit(GL009_NEG, select=["GL009"]) == set()


def test_gl009_fires_via_call_soon_and_loop_only():
    assert rules_hit("""
        import time

        class Pump:
            def kick(self, io):
                io.call_soon(self._work)

            def _work(self):
                time.sleep(0.1)
    """, select=["GL009"]) == {"GL009"}
    assert rules_hit("""
        import time
        from ray_tpu.devtools.threadguard import loop_only

        class Pump:
            @loop_only
            def _work(self):
                self._lock.acquire()
    """, select=["GL009"]) == {"GL009"}


def test_gl009_nonblocking_acquire_and_path_join_exempt():
    assert rules_hit("""
        import os

        class Pump:
            def kick(self, io):
                io.call_soon(self._work)

            def _work(self):
                if self._lock.acquire(blocking=False):
                    self._p = os.path.join("a", "b")
    """, select=["GL009"]) == set()


# -- GL010 metric RPC from the loop thread -----------------------------

GL010_POS = """
    from ray_tpu.util.metrics import Counter

    REQS = Counter("rtpu_proto_requests_total", "requests")

    class Proto:
        def __init__(self, io):
            io.call_soon(self._tick)

        def _tick(self):
            REQS.inc()
"""

GL010_NEG = """
    from ray_tpu.util.metrics import Counter

    REQS = Counter("rtpu_proto_requests_total", "requests")

    class Proto:
        def __init__(self, io):
            io.call_soon(self._tick)

        def _tick(self):
            REQS.inc_local()

        def off_loop(self):
            REQS.inc()   # fine: not a loop-thread path
"""


def test_gl010_fires_on_loop_path_metric_write():
    findings = run(GL010_POS, select=["GL010"])
    assert [f.rule for f in findings] == ["GL010"]
    assert "inc_local()" in findings[0].message


def test_gl010_quiet_for_record_local_and_off_loop():
    assert rules_hit(GL010_NEG, select=["GL010"]) == set()


# -- GL011 off-loop mutation of loop-owned state -----------------------

GL011_POS = """
    from ray_tpu.devtools.threadguard import loop_owned

    @loop_owned("pending")
    class Proto:
        def __init__(self, io):
            self._io = io
            self.pending = []
            io.call_soon(self._drain)

        def _drain(self):
            self.pending.clear()

        def cancel(self):
            self.pending.clear()
"""

GL011_NEG = """
    from ray_tpu.devtools.threadguard import loop_owned

    @loop_owned("pending")
    class Proto:
        def __init__(self, io):
            self._io = io
            self.pending = []
            io.call_soon(self._drain)

        def _drain(self):
            self.pending.clear()

        def cancel(self):
            self._io.call_soon(self._do_cancel)

        def _do_cancel(self):
            self.pending.clear()
"""


def test_gl011_fires_on_off_loop_mutation():
    findings = run(GL011_POS, select=["GL011"])
    assert [f.rule for f in findings] == ["GL011"]
    assert "pending" in findings[0].message
    assert "cancel" in findings[0].message


def test_gl011_quiet_when_routed_through_call_soon():
    assert rules_hit(GL011_NEG, select=["GL011"]) == set()


def test_gl011_loop_prefix_convention_on_registered_class():
    assert rules_hit("""
        class Proto:
            def __init__(self, io, sock):
                self._loop_queue = []
                io.register_message_conn(sock, self._on_msg, None)

            def _on_msg(self, conn, msg):
                self._loop_queue.append(msg)

            def drop(self):
                self._loop_queue.clear()
    """, select=["GL011"]) == {"GL011"}


# -- GL012 async callback registered on the loop -----------------------

GL012_POS = """
    class Proto:
        def __init__(self, io, sock):
            self.conn = io.register_message_conn(
                sock, self._on_msg, self._on_close)

        async def _on_msg(self, conn, msg):
            pass

        def _on_close(self, conn):
            pass
"""


def test_gl012_fires_on_async_callback():
    findings = run(GL012_POS, select=["GL012"])
    assert [f.rule for f in findings] == ["GL012"]
    assert "async def" in findings[0].message
    assert "_on_msg" in findings[0].message


def test_gl012_fires_on_awaitable_returning_callback():
    assert rules_hit("""
        async def _pump():
            pass

        def on_msg(conn, msg):
            return _pump()

        def wire(io, sock):
            io.register_message_conn(sock, on_msg, None)
    """, select=["GL012"]) == {"GL012"}


def test_gl012_quiet_on_sync_callbacks():
    assert rules_hit(GL009_NEG, select=["GL012"]) == set()


# -- GL013 tracing-span RPC from the loop thread -----------------------

GL013_POS = """
    from ray_tpu.util import tracing

    class Proto:
        def __init__(self, io):
            io.call_soon(self._tick)

        def _tick(self):
            with tracing.span("dispatch", component="io"):
                pass
"""

GL013_NEG = """
    from ray_tpu.util import tracing
    from ray_tpu.util import flight_recorder as _flight

    class Proto:
        def __init__(self, io):
            io.call_soon(self._tick)

        def _tick(self):
            rec = _flight.RECORDER
            if rec is not None:
                rec.record("io", "tick", rec.clock(), 0, None)

        def off_loop(self):
            with tracing.span("ok"):   # fine: not a loop-thread path
                pass
"""


def test_gl013_fires_on_loop_path_span_emission():
    findings = run(GL013_POS, select=["GL013"])
    assert [f.rule for f in findings] == ["GL013"]
    assert "flight_recorder" in findings[0].message


def test_gl013_fires_on_direct_record_span():
    assert rules_hit("""
        from ray_tpu.util.tracing import record_span

        def on_msg(conn, msg):
            record_span("dispatch", "io", 0.0, 0.0, None)

        def wire(io, sock):
            io.register_message_conn(sock, on_msg, None)
    """, select=["GL013"]) == {"GL013"}


def test_gl013_quiet_for_flight_recorder_and_off_loop():
    assert rules_hit(GL013_NEG, select=["GL013"]) == set()
    # an unrelated local span() helper is not the tracing emitter
    assert rules_hit("""
        def span(name):
            return name

        def on_msg(conn, msg):
            span("dispatch")

        def wire(io, sock):
            io.register_message_conn(sock, on_msg, None)
    """, select=["GL013"]) == set()


# -- project rules respect suppression & selection ---------------------

def test_project_rule_respects_per_line_disable():
    src = GL009_POS.replace(
        "time.sleep(0.5)",
        "time.sleep(0.5)  # graftlint: disable=GL009")
    assert rules_hit(src, select=["GL009"]) == set()


# -- output formats ----------------------------------------------------

def test_cli_json_format(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        def f():
            try:
                g()
            except Exception:
                pass
    """))
    assert lint.main([str(bad), "--no-baseline",
                      "--format=json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert isinstance(payload, list) and payload
    rec = payload[0]
    assert rec["rule"] == "GL004"
    assert rec["path"] == str(bad)
    assert {"line", "col", "message", "scope"} <= set(rec)

    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert lint.main([str(good), "--no-baseline",
                      "--format=json"]) == 0
    assert json.loads(capsys.readouterr().out) == []


def test_cli_github_format(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\n"
                   "def f(io):\n"
                   "    io.call_soon(g)\n"
                   "def g():\n"
                   "    time.sleep(1)\n")
    assert lint.main([str(bad), "--no-baseline",
                      "--format=github"]) == 1
    out = capsys.readouterr().out
    assert "::error file=" in out
    assert "title=graftlint GL009" in out
    # newlines in messages must be %0A-escaped per workflow-command rules
    assert "\n::error" in out or out.startswith("::error")

    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert lint.main([str(good), "--no-baseline",
                      "--format=github"]) == 0
    assert "::notice" in capsys.readouterr().out


# -- GL014 ObjectRef from raw binary() ---------------------------------

def test_gl014_fires_on_raw_binary_roundtrip():
    findings = run("""
        from ray_tpu.core.object_ref import ObjectRef

        def rehydrate(ref):
            return ObjectRef(ObjectID(ref.binary()))
    """, select=["GL014"])
    assert [f.rule for f in findings] == ["GL014"]
    assert "binary()" in findings[0].message


def test_gl014_tracks_tainted_name():
    # the bytes flow through a local name before the re-wrap
    assert rules_hit("""
        from ray_tpu.core.object_ref import ObjectRef

        def rehydrate(ref):
            raw = ref.binary()
            return ObjectRef(raw)
    """, select=["GL014"]) == {"GL014"}


def test_gl014_quiet_on_legit_construction():
    # constructing from an ObjectID (the serialization path) is fine,
    # as is calling .binary() for logging without re-wrapping it
    assert rules_hit("""
        from ray_tpu.core.object_ref import ObjectRef

        def make(oid):
            return ObjectRef(oid)

        def describe(ref):
            return ref.binary().hex()
    """, select=["GL014"]) == set()


def test_gl014_per_line_disable():
    src = ("from ray_tpu.core.object_ref import ObjectRef\n"
           "def f(ref):\n"
           "    return ObjectRef(ref.binary())"
           "  # graftlint: disable=GL014\n")
    assert rules_hit(src, select=["GL014"]) == set()


# -- GL015 put()/submit result dropped in a loop -----------------------

GL015_POS_DIRECT = """
    def broadcast(workers, blob):
        for w in workers:
            w.ping.remote(blob)
"""

GL015_POS_TWO_HOP = """
    def push(w):
        w.ping.remote(1)

    def run(workers):
        for w in workers:
            push(w)
"""


def test_gl015_fires_on_direct_loop_drop():
    findings = run(GL015_POS_DIRECT, select=["GL015"])
    assert [f.rule for f in findings] == ["GL015"]
    assert "inside a loop in broadcast()" in findings[0].message


def test_gl015_fires_on_subscripted_receiver():
    # pool[i].f.remote() defeats plain dotted-name resolution; the
    # .remote leaf must still fire
    assert rules_hit("""
        def repush(self, idxs):
            while idxs:
                idx = idxs.pop()
                if idx >= 0:
                    self.runners[idx].set_weights.remote(1)
    """, select=["GL015"]) == {"GL015"}


def test_gl015_fires_on_ray_tpu_put_in_loop():
    assert rules_hit("""
        import ray_tpu

        def fill(items):
            for it in items:
                ray_tpu.put(it)
    """, select=["GL015"]) == {"GL015"}


def test_gl015_two_hop_names_the_chain():
    findings = run(GL015_POS_TWO_HOP, select=["GL015"])
    assert [f.rule for f in findings] == ["GL015"]
    assert "run -> push" in findings[0].message


def test_gl015_quiet_when_ref_is_kept_or_not_a_pin():
    # refs kept: the holder can release them
    assert rules_hit("""
        def broadcast(workers, blob):
            refs = []
            for w in workers:
                refs.append(w.ping.remote(blob))
            return refs
    """, select=["GL015"]) == set()
    # a bare q.put() is a queue, not ray_tpu.put: no pin is created
    assert rules_hit("""
        def drain(q, items):
            for it in items:
                q.put(it)
    """, select=["GL015"]) == set()
    # a drop outside any loop, never called from one: bounded, quiet
    assert rules_hit("""
        def nudge(w):
            w.stop.remote()
    """, select=["GL015"]) == set()


def test_gl015_per_line_disable():
    src = GL015_POS_DIRECT.replace(
        "w.ping.remote(blob)",
        "w.ping.remote(blob)  # graftlint: disable=GL015")
    assert rules_hit(src, select=["GL015"]) == set()


# -- GL016 untied pinned view ------------------------------------------

GL016_POS = """
    import pickle

    def unpack(payload, buffers, on_release):
        value = pickle.loads(payload, buffers=buffers)
        on_release()
        return value
"""

GL016_NEG_FINALIZE = """
    import pickle
    import weakref

    def unpack(payload, buffers, on_release):
        value = pickle.loads(payload, buffers=buffers)
        holder = buffers[0]
        weakref.finalize(holder, on_release)
        return value
"""


def test_gl016_fires_on_inline_release():
    findings = run(GL016_POS, select=["GL016"])
    assert [f.rule for f in findings] == ["GL016"]
    assert "on_release" in findings[0].message


def test_gl016_quiet_when_release_tied_to_value():
    assert rules_hit(GL016_NEG_FINALIZE, select=["GL016"]) == set()
    # a holder class carrying the release in __del__ also counts
    assert rules_hit("""
        import pickle

        def unpack(payload, buffers, on_release):
            class _Holder:
                def __del__(self):
                    on_release()
            value = pickle.loads(payload, buffers=buffers)
            return value, _Holder()
    """, select=["GL016"]) == set()


def test_gl016_sees_tie_two_hops_away():
    # the finalize lives in a helper the unpacker calls via a wrapper
    assert rules_hit("""
        import pickle
        import weakref

        def _tie(holder, on_release):
            weakref.finalize(holder, on_release)

        def _wire(buffers, on_release):
            _tie(buffers[0], on_release)

        def unpack(payload, buffers, on_release):
            value = pickle.loads(payload, buffers=buffers)
            _wire(buffers, on_release)
            on_release()
            return value
    """, select=["GL016"]) == set()


def test_gl016_quiet_without_oob_buffers():
    # in-band loads with an unrelated on_release call: not a view
    assert rules_hit("""
        import pickle

        def unpack(payload, on_release):
            value = pickle.loads(payload)
            on_release()
            return value
    """, select=["GL016"]) == set()


# -- GL017 count-state mutation outside the lock -----------------------

GL017_POS_UNLOCKED = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._counts = {}

        def add(self, oid):
            self._counts[oid] = self._counts.get(oid, 0) + 1
"""

GL017_NEG_LOCKED = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._counts = {}

        def add(self, oid):
            with self._lock:
                self._counts[oid] = self._counts.get(oid, 0) + 1
"""


def test_gl017_fires_on_unlocked_self_mutation():
    findings = run(GL017_POS_UNLOCKED, select=["GL017"])
    assert [f.rule for f in findings] == ["GL017"]
    assert "_counts" in findings[0].message


def test_gl017_fires_on_foreign_mutation_even_under_lock():
    # reaching into another object's count state is never OK
    assert rules_hit("""
        def poke(counter, oid):
            with counter._lock:
                counter._pins[oid] = 0
    """, select=["GL017"]) == {"GL017"}
    assert rules_hit("""
        def wipe(counter):
            counter._counts.clear()
    """, select=["GL017"]) == {"GL017"}


def test_gl017_quiet_when_locked_or_initializing():
    assert rules_hit(GL017_NEG_LOCKED, select=["GL017"]) == set()
    # __init__ container creation is the allowed rebind
    assert rules_hit("""
        class Counter:
            def __init__(self):
                self._pins = {}
    """, select=["GL017"]) == set()
    # reads are free
    assert rules_hit("""
        class Counter:
            def peek(self, oid):
                return self._counts.get(oid, 0)
    """, select=["GL017"]) == set()


def test_gl017_per_line_disable():
    src = GL017_POS_UNLOCKED.replace(
        "self._counts[oid] = self._counts.get(oid, 0) + 1",
        "self._counts[oid] = 1  # graftlint: disable=GL017")
    assert rules_hit(src, select=["GL017"]) == set()


# -- GL018 silent lifecycle mutation ----------------------------------

GL018_POS_SUBSCRIPT = """
    class Gcs:
        def kill(self, actor_id):
            rec = self.actors[actor_id]
            rec.state = "DEAD"
"""

GL018_NEG_EMITS = """
    class Gcs:
        def kill(self, actor_id):
            rec = self.actors[actor_id]
            rec.state = "DEAD"
            self.add_cluster_event("ACTOR_DEAD", "ERROR",
                                   actor_id=actor_id)
"""


def test_gl018_fires_on_silent_state_flip():
    findings = run(GL018_POS_SUBSCRIPT, select=["GL018"])
    assert [f.rule for f in findings] == ["GL018"]
    assert "state" in findings[0].message


def test_gl018_fires_on_table_loops_and_direct_subscript():
    assert rules_hit("""
        class Gcs:
            def sweep(self):
                for rec in self.nodes.values():
                    rec.state = "DEAD"
    """, select=["GL018"]) == {"GL018"}
    assert rules_hit("""
        class Gcs:
            def flip(self, aid):
                self.actors[aid].state = "DEAD"
    """, select=["GL018"]) == {"GL018"}
    # .get() is record-sourced too
    assert rules_hit("""
        class Gcs:
            def flip(self, aid):
                rec = self.actors.get(aid)
                if rec is not None:
                    rec.state = "DEAD"
    """, select=["GL018"]) == {"GL018"}


def test_gl018_quiet_when_emitting_or_off_table():
    assert rules_hit(GL018_NEG_EMITS, select=["GL018"]) == set()
    # update_actor_state / mark_node_dead emit internally
    assert rules_hit("""
        class Gcs:
            def kill(self, actor_id):
                rec = self.actors[actor_id]
                rec.state = "DEAD"
                self.update_actor_state(actor_id, "DEAD")
    """, select=["GL018"]) == set()
    # non-table records carry no event contract
    assert rules_hit("""
        class App:
            def flip(self, name):
                rec = self.deployments[name]
                rec.state = "STOPPED"
    """, select=["GL018"]) == set()


def test_gl018_per_line_disable():
    src = GL018_POS_SUBSCRIPT.replace(
        'rec.state = "DEAD"',
        'rec.state = "DEAD"  # graftlint: disable=GL018')
    assert rules_hit(src, select=["GL018"]) == set()


# -- GL019 unbounded retry ---------------------------------------------

GL019_POS_HOT_SPIN = """
    def redial(self):
        while not self._stopped:
            try:
                return self._connect()
            except OSError:
                continue
"""

GL019_NEG_BACKOFF = """
    def redial(self):
        from ray_tpu.util.backoff import Backoff
        backoff = Backoff(initial_s=0.1, max_s=2.0, deadline_s=30.0)
        while not self._stopped:
            try:
                return self._connect()
            except OSError:
                if not backoff.wait():
                    return None
                continue
"""


def test_gl019_fires_on_hot_retry_loop():
    findings = run(GL019_POS_HOT_SPIN, select=["GL019"])
    assert [f.rule for f in findings] == ["GL019"]
    assert "backoff" in findings[0].message


def test_gl019_quiet_with_pacing():
    assert rules_hit(GL019_NEG_BACKOFF, select=["GL019"]) == set()
    # a plain sleep also paces the loop
    assert rules_hit("""
        import time
        def poll(self):
            while True:
                try:
                    return self._fetch()
                except OSError:
                    time.sleep(0.5)
                    continue
    """, select=["GL019"]) == set()
    # an explicit timeout kwarg on a blocking call paces the loop
    assert rules_hit("""
        def drain(self):
            while not self._stop.is_set():
                try:
                    self._queue.put(1, timeout=0.1)
                    return True
                except Full:
                    continue
    """, select=["GL019"]) == set()


def test_gl019_nested_scopes_do_not_leak():
    # continue inside a NESTED loop does not re-enter the outer one
    assert rules_hit("""
        def pump(self):
            while self._streams:
                for item in self._batch():
                    try:
                        self._emit(item)
                    except ValueError:
                        continue
                self._streams.pop()
    """, select=["GL019"]) == set()
    # a wait inside a nested function does not pace the outer loop
    assert rules_hit("""
        def redial(self):
            while True:
                def pause():
                    time.sleep(1)
                try:
                    return self._connect()
                except OSError:
                    continue
    """, select=["GL019"]) == {"GL019"}


def test_gl019_per_line_disable():
    src = GL019_POS_HOT_SPIN.replace(
        "while not self._stopped:",
        "while not self._stopped:  # graftlint: disable=GL019")
    assert rules_hit(src, select=["GL019"]) == set()


# -- GL020 unclosed phase bracket -------------------------------------

GL020_POS_EARLY_RETURN = """
    from ray_tpu.util import flight_recorder as fr

    def send(self, spec):
        t0 = fr.phase_begin("net", "wire-write")
        if self._closed:
            return None
        self._sock.send(spec)
        fr.phase_end("net", "wire-write", t0)
"""

GL020_POS_RAISE = """
    from ray_tpu.util import flight_recorder as fr

    def encode(self, spec):
        t0 = fr.phase_begin("ser", "frame-encode")
        if spec is None:
            raise ValueError("no spec")
        out = dumps(spec)
        fr.phase_end("ser", "frame-encode", t0)
        return out
"""

GL020_POS_NO_END = """
    from ray_tpu.util import flight_recorder as fr

    def leak(self):
        t0 = fr.phase_begin("net", "never-closed")
        self._work()
"""

GL020_NEG_FINALLY = """
    from ray_tpu.util import flight_recorder as fr

    def send(self, spec):
        t0 = fr.phase_begin("net", "wire-write")
        try:
            if self._closed:
                return None
            self._sock.send(spec)
        finally:
            fr.phase_end("net", "wire-write", t0)
"""

GL020_NEG_STRAIGHT_LINE = """
    from ray_tpu.util import flight_recorder as fr

    def send(self, spec):
        t0 = fr.phase_begin("net", "wire-write")
        self._sock.send(spec)
        fr.phase_end("net", "wire-write", t0)
        return True
"""


def test_gl020_fires_on_early_return_and_raise():
    findings = run(GL020_POS_EARLY_RETURN, select=["GL020"])
    assert [f.rule for f in findings] == ["GL020"]
    assert "finally" in findings[0].message
    assert rules_hit(GL020_POS_RAISE, select=["GL020"]) == {"GL020"}


def test_gl020_fires_when_end_missing_entirely():
    findings = run(GL020_POS_NO_END, select=["GL020"])
    assert [f.rule for f in findings] == ["GL020"]
    assert "no phase_end" in findings[0].message


def test_gl020_quiet_on_finally_and_straight_line():
    assert rules_hit(GL020_NEG_FINALLY, select=["GL020"]) == set()
    assert rules_hit(GL020_NEG_STRAIGHT_LINE, select=["GL020"]) == set()


def test_gl020_per_line_disable():
    src = GL020_POS_EARLY_RETURN.replace(
        "return None",
        "return None  # graftlint: disable=GL020")
    assert rules_hit(src, select=["GL020"]) == set()


# -- GL021 rank-dependent collective ----------------------------------

GL021_POS_DIRECT = """
    from ray_tpu.parallel import collective

    def sync(arr, rank):
        if rank == 0:
            collective.allreduce(arr)
"""

GL021_POS_TWO_HOP = """
    from ray_tpu.parallel import collective

    def _sync(arr):
        collective.allreduce(arr)

    def step(arr, rank):
        if rank != 0:
            _sync(arr)
"""

GL021_NEG_BROADCAST_ROOT = """
    from ray_tpu.parallel import collective
    import numpy as np

    def share(arr, rank):
        if rank == 0:
            payload = arr
        else:
            payload = np.zeros_like(arr)
        return collective.broadcast(payload, src_rank=0)

    def share_guarded(arr, rank):
        if rank == 0:
            collective.broadcast(arr, src_rank=0)
"""

GL021_NEG_UNGUARDED = """
    from ray_tpu.parallel import collective

    def sync(arr, rank):
        out = collective.allreduce(arr)
        if rank == 0:
            print(out[:4])
        return out
"""


def test_gl021_fires_on_rank_guarded_collective():
    findings = run(GL021_POS_DIRECT, select=["GL021"])
    assert [f.rule for f in findings] == ["GL021"]
    assert "allreduce" in findings[0].message
    assert "rank" in findings[0].message


def test_gl021_fires_through_a_call_hop():
    findings = run(GL021_POS_TWO_HOP, select=["GL021"])
    assert [f.rule for f in findings] == ["GL021"]
    assert "step -> _sync" in findings[0].message


def test_gl021_quiet_on_broadcast_root_and_unguarded():
    assert rules_hit(GL021_NEG_BROADCAST_ROOT, select=["GL021"]) == set()
    assert rules_hit(GL021_NEG_UNGUARDED, select=["GL021"]) == set()
    # a barrier() on some unrelated object is not a collective
    assert rules_hit("""
        def flush(q, rank):
            if rank == 0:
                q.barrier()
    """, select=["GL021"]) == set()


def test_gl021_per_line_disable():
    src = GL021_POS_DIRECT.replace(
        "collective.allreduce(arr)",
        "collective.allreduce(arr)  # graftlint: disable=GL021")
    assert rules_hit(src, select=["GL021"]) == set()


# -- GL022 ef_key collision -------------------------------------------

GL022_POS = """
    from ray_tpu.parallel import collective

    def sync(g1, g2):
        collective.allreduce(g1, compression="int8", ef_key="grad")
        collective.allreduce(g2, compression="int8", ef_key="grad")
"""

GL022_NEG_DISTINCT_KEYS = """
    from ray_tpu.parallel import collective

    def sync(g1, g2):
        collective.allreduce(g1, compression="int8", ef_key="grad/1")
        collective.allreduce(g2, compression="int8", ef_key="grad/2")
"""

GL022_NEG_SAME_TENSOR = """
    from ray_tpu.parallel import collective

    def sync(g1):
        collective.allreduce(g1, compression="int8", ef_key="grad")
        collective.allreduce(g1, compression="int8", ef_key="grad")
"""

GL022_NEG_DIFFERENT_GROUPS = """
    from ray_tpu.parallel import collective

    def sync(g1, g2):
        collective.allreduce(g1, group_name="a", compression="int8",
                             ef_key="grad")
        collective.allreduce(g2, group_name="b", compression="int8",
                             ef_key="grad")
"""


def test_gl022_fires_on_shared_key_different_tensors():
    findings = run(GL022_POS, select=["GL022"])
    assert [f.rule for f in findings] == ["GL022"]
    assert "'grad'" in findings[0].message
    assert "different tensor" in findings[0].message


def test_gl022_quiet_on_distinct_keys_tensor_or_group():
    assert rules_hit(GL022_NEG_DISTINCT_KEYS, select=["GL022"]) == set()
    assert rules_hit(GL022_NEG_SAME_TENSOR, select=["GL022"]) == set()
    assert rules_hit(GL022_NEG_DIFFERENT_GROUPS,
                     select=["GL022"]) == set()


def test_gl022_per_line_disable():
    src = GL022_POS.replace(
        'collective.allreduce(g2, compression="int8", ef_key="grad")',
        'collective.allreduce(g2, compression="int8", ef_key="grad")'
        '  # graftlint: disable=GL022')
    assert rules_hit(src, select=["GL022"]) == set()


# -- GL023 unpaired reduce-scatter ------------------------------------

GL023_POS = """
    from ray_tpu.parallel import collective

    def step(vec):
        shard, off = collective.reduce_scatter_flat(vec)
        return shard
"""

GL023_NEG_SAME_FN = """
    from ray_tpu.parallel import collective

    def step(vec):
        shard, off = collective.reduce_scatter_flat(vec)
        return collective.allgather_flat(shard)
"""

GL023_NEG_SIBLING = """
    from ray_tpu.parallel import collective

    def _scatter(vec):
        return collective.reduce_scatter_flat(vec)

    def _gather(shard):
        return collective.allgather_flat(shard)

    def step(vec):
        shard, off = _scatter(vec)
        return _gather(shard)
"""


def test_gl023_fires_on_unpaired_reduce_scatter():
    findings = run(GL023_POS, select=["GL023"])
    assert [f.rule for f in findings] == ["GL023"]
    assert "allgather" in findings[0].message


def test_gl023_quiet_when_paired_directly_or_via_family():
    assert rules_hit(GL023_NEG_SAME_FN, select=["GL023"]) == set()
    assert rules_hit(GL023_NEG_SIBLING, select=["GL023"]) == set()


def test_gl023_per_line_disable():
    src = GL023_POS.replace(
        "collective.reduce_scatter_flat(vec)",
        "collective.reduce_scatter_flat(vec)"
        "  # graftlint: disable=GL023")
    assert rules_hit(src, select=["GL023"]) == set()
