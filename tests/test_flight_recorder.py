"""Flight recorder: ring semantics, clock alignment across processes,
post-mortem journal tails, Perfetto export schema, and the overhead
ratio guard (PR 12)."""

import json
import time

import numpy as np
import pytest

from ray_tpu.util import flight_recorder as fr


@pytest.fixture
def fresh_recorder():
    """Isolate the module-level recorder/store state per test."""
    saved = (fr.RECORDER, fr._STORE, fr._anchor)
    fr._STORE = fr.FlightStore()
    yield
    fr.RECORDER, fr._STORE, fr._anchor = saved


# --- ring semantics ---------------------------------------------------

def test_ring_wraparound_keeps_newest(fresh_recorder):
    rec = fr.enable("test:ring", capacity=16)
    for i in range(40):
        rec.record("io", "ev", 1000 + i, 10, {"i": i})
    events = rec.snapshot()
    # only the newest `capacity` events survive, oldest first
    assert [ev[0] for ev in events] == list(range(24, 40))
    assert events[0][5] == {"i": 24} and events[-1][5] == {"i": 39}
    # incremental snapshot picks up exactly the new suffix
    assert [ev[0] for ev in rec.snapshot(since_seq=37)] == [38, 39]


def test_disabled_recorder_is_inert(fresh_recorder):
    fr.disable()
    assert fr.RECORDER is None and not fr.enabled()
    fr.record("io", "ev", 0, 0)          # cold-path helpers no-op
    fr.instant("io", "mark")
    assert fr.local_tail() is None


def test_store_push_dedups_on_seq(fresh_recorder):
    fr.store_push("worker:aa", [(0, 100, 1, "io", "a", None),
                               (1, 200, 1, "io", "b", None)], 5)
    # a re-push of an overlapping increment must not duplicate
    fr.store_push("worker:aa", [(1, 200, 1, "io", "b", None),
                               (2, 300, 1, "io", "c", None)], 5)
    [(label, offset, events)] = fr.get_store().journals()
    assert label == "worker:aa" and offset == 5
    assert [ev[0] for ev in events] == [0, 1, 2]


# --- export schema ----------------------------------------------------

def test_chrome_events_schema(fresh_recorder):
    rec = fr.enable("test:export", capacity=64)
    t0 = fr.clock_ns()
    rec.record("pipeline", "FWD", t0, 2_000_000,
               {"stage": 0, "mb": 1, "phase": "steady"})
    rec.instant("object", "serve_out", {"bytes": 64})
    fr.store_push("worker:bb", [(0, t0, 1_000, "shuffle", "map_wave",
                                 {"order": 0})], 0)
    all_events = json.loads(json.dumps(fr.chrome_events()))
    meta = [ev for ev in all_events if ev["ph"] == "M"]
    events = [ev for ev in all_events if ev["ph"] != "M"]
    assert len(events) == 3
    pids = {ev["pid"] for ev in events}
    assert pids == {"flight:test:export", "flight:worker:bb"}
    for ev in events:
        assert {"name", "cat", "ph", "ts", "pid", "tid"} <= set(ev)
        assert isinstance(ev["ts"], (int, float))
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        else:
            assert ev["ph"] == "i" and ev.get("s") == "t"
    # Perfetto polish: every track leads with process_name/thread_name
    # metadata naming the role instead of the bare journal label.
    proc_names = {ev["pid"]: ev["args"]["name"] for ev in meta
                  if ev["name"] == "process_name"}
    assert set(proc_names) == pids
    assert proc_names["flight:worker:bb"] == "worker-bb"
    thread_rows = {(ev["pid"], ev["tid"]) for ev in meta
                   if ev["name"] == "thread_name"}
    assert ("flight:test:export", "pipeline") in thread_rows
    assert ("flight:worker:bb", "shuffle") in thread_rows


def test_whereis_attribution_from_synthetic_journal(fresh_recorder,
                                                    tmp_path):
    # one stage, two steps: 60% compute → bubble 0.4; S=2, m=8 → 1/9
    journal = {"worker:stage0": [
        (0, 1_000, 4_000_000, "pipeline", "SEND",
         {"stage": 0, "step": 0, "mb": 0, "kind": "act",
          "phase": "steady"}),
        (1, 0, 10_000_000, "pipeline", "stage_step",
         {"stage": 0, "step": 0, "schedule": "1f1b", "S": 2, "m": 8,
          "wall_s": 0.01, "compute_s": 0.006}),
        (2, 12_000_000, 10_000_000, "pipeline", "stage_step",
         {"stage": 0, "step": 1, "schedule": "1f1b", "S": 2, "m": 8,
          "wall_s": 0.01, "compute_s": 0.006}),
        (3, 5_000, 3_000_000, "prefetch", "consumer_wait", None),
        (4, 9_000, 1_000_000, "collective", "allreduce",
         {"dtype": "float32", "wire": 1024, "ratio": 3.9}),
    ]}
    from ray_tpu.devtools import whereis
    report = whereis.attribution(journal)
    assert report["steps"] == 2 and report["stages"] == 1
    assert report["measured_bubble"] == pytest.approx(0.4)
    assert report["theoretical_bubble"] == pytest.approx(1 / 9, abs=1e-3)
    assert report["fractions"]["compute"] == pytest.approx(0.6)
    assert report["fractions"]["comms"] == pytest.approx(0.2)
    assert report["collectives"]["count"] == 1
    assert report["collectives"]["mean_compression_ratio"] == 3.9
    text = whereis.render(report)
    assert "measured bubble: 0.400" in text
    # CLI round-trip through the dump-file format
    dump = tmp_path / "journal.json"
    dump.write_text(json.dumps(
        {"journals": {k: [list(ev) for ev in v]
                      for k, v in journal.items()}}))
    report2 = whereis.attribution(whereis._load_journals(str(dump)))
    assert report2["measured_bubble"] == report["measured_bubble"]


# --- clock alignment across processes ---------------------------------

@pytest.mark.watchdog(180)
def test_clock_alignment_two_workers(monkeypatch):
    """Workers run with a +1.5s injected clock skew; the ping-pong sync
    must fold their journals back into the driver's time domain: every
    aligned worker event lands inside the driver-observed run window
    (tolerance ≪ the injected skew)."""
    import ray_tpu

    monkeypatch.setenv("RTPU_FLIGHT_TEST_SKEW_NS", "1500000000")
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, system_config={
        "flight_recorder_enabled": True,
        "flight_flush_interval_s": 0.05,
    })
    try:
        @ray_tpu.remote(num_cpus=0)
        def stamp(tag):
            from ray_tpu.util import flight_recorder
            flight_recorder.instant("test", "stamp", {"tag": tag})
            return tag

        t0 = fr.clock_ns()
        assert sorted(ray_tpu.get([stamp.remote(i)
                                   for i in range(8)])) == list(range(8))
        deadline = time.time() + 10
        while time.time() < deadline:
            merged = fr.merged_journals()
            stamps = [ev for label, events in merged.items()
                      if label.startswith("worker:")
                      for ev in events if ev[4] == "stamp"]
            if len(stamps) >= 8:
                break
            time.sleep(0.1)     # flusher interval is 50ms
        t1 = fr.clock_ns()
        assert len(stamps) >= 8, f"journals never flushed: {merged.keys()}"
        tol_ns = 500_000_000    # 0.5s ≪ the 1.5s injected skew
        for ev in stamps:
            assert t0 - tol_ns <= ev[1] <= t1 + tol_ns, (
                f"unaligned event {ev}: outside [{t0}, {t1}] by "
                f"{max(t0 - ev[1], ev[1] - t1) / 1e6:.1f}ms")
    finally:
        ray_tpu.shutdown()


# --- post-mortem ------------------------------------------------------

def _model_fns():
    import jax.numpy as jnp

    def apply_layer(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def loss_fn(out, tgt):
        return jnp.mean((out - tgt) ** 2)

    return apply_layer, loss_fn


@pytest.mark.watchdog(300)
def test_postmortem_tail_rides_dag_error(monkeypatch):
    """An injected stage failure (PR-10 ("fail", sid, ·) hook) surfaces
    a DAGExecutionError whose message embeds the dead stage's last-N
    journal events."""
    import ray_tpu
    from ray_tpu.dag import DAGExecutionError
    from ray_tpu.train.pipeline import LayeredModel, PipelineRunner

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, system_config={
        "flight_recorder_enabled": True,
        "flight_flush_interval_s": 0.05,
        "task_max_retries": 0,
    })
    try:
        rng = np.random.RandomState(0)
        d = 8
        layers = [{"w": rng.randn(d, d).astype(np.float32) * 0.1,
                   "b": np.zeros(d, dtype=np.float32)}
                  for _ in range(2)]
        x = rng.randn(8, d).astype(np.float32)
        y = rng.randn(8, d).astype(np.float32)
        runner = PipelineRunner(
            LayeredModel(layers, *_model_fns()),
            num_stages=2, num_microbatches=4, schedule="1f1b",
            recv_timeout_s=3.0)
        try:
            assert runner.step(x, y)["loss"] is not None
            runner.inject_failure(1)
            with pytest.raises(DAGExecutionError) as err:
                runner.execute_async(x, y).get(60.0)
            msg = str(err.value)
            assert "flight recorder (last" in msg
            # the tail shows what the stage was doing when it died
            assert "pipeline:" in msg
        finally:
            runner.shutdown()
    finally:
        ray_tpu.shutdown()


@pytest.mark.watchdog(180)
def test_postmortem_tail_on_worker_crash():
    """A worker dying mid-task (os._exit) surfaces the collector's copy
    of its journal in the WorkerCrashedError/ActorUnavailableError."""
    import ray_tpu

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, system_config={
        "flight_recorder_enabled": True,
        "flight_flush_interval_s": 0.05,
        "task_max_retries": 0,
    })
    try:
        @ray_tpu.remote(max_restarts=0)
        class A:
            def work(self, i):
                fr.instant("test", "work", {"i": i})
                return i

            def crash(self):
                import os
                os._exit(1)

        a = A.remote()
        assert ray_tpu.get([a.work.remote(i) for i in range(4)]) == \
            list(range(4))
        time.sleep(0.3)              # let the flusher push the journal
        a.crash.remote()
        with pytest.raises(Exception) as err:
            ray_tpu.get(a.work.remote(99), timeout=30)
        msg = str(err.value)
        assert "flight recorder (last" in msg and "test:work" in msg
    finally:
        ray_tpu.shutdown()


# --- overhead guard (satellite: ratio-based per PERF.md) --------------

@pytest.mark.watchdog(300)
def test_recorder_overhead_ratio_guard(ray_start_regular):
    """Recorder-enabled vs disabled wall time on a tight task loop must
    stay under a generous ratio bound: the record path is two loads +
    a compare when off, and one tuple store when on."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(500)])   # warmup

    def run_loop(n=1500):
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        return time.perf_counter() - t0

    saved = fr.RECORDER
    try:
        timings = {}
        for mode in ("off", "on", "off", "on"):    # interleave: best-of
            if mode == "on":
                fr.enable("driver:overhead")
            else:
                fr.disable()
            timings.setdefault(mode, []).append(run_loop())
        ratio = min(timings["on"]) / min(timings["off"])
    finally:
        fr.RECORDER = saved
    # generous: shared-CI noise dominates; the real cost is ~ns/event
    assert ratio < 2.0, f"recorder overhead ratio {ratio:.2f} >= 2.0"
