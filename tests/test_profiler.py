"""Perf observatory (PR 18): sampling profiler attribution, folded /
speedscope export, submit-path phase chains, profdiff round-trip,
percentile None-contract, and the overhead ratio guards."""

import json
import threading
import time

import pytest

from ray_tpu.devtools import profdiff, profiler
from ray_tpu.util import flight_recorder as fr
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import timeline


@pytest.fixture
def fresh_profiler():
    """Isolate module-level sampler/store state per test."""
    saved = (profiler.PROFILER, profiler._STORE)
    profiler.PROFILER = None
    profiler._STORE = profiler.ProfileStore()
    yield
    sampler = profiler.PROFILER
    if sampler is not None:
        sampler.stop()
    profiler.PROFILER, profiler._STORE = saved


# --- sampler ----------------------------------------------------------

def _busy_spin(deadline: float) -> int:
    acc = 0
    while time.perf_counter() < deadline:
        acc += 1
    return acc


@pytest.mark.skipif(not hasattr(__import__("sys"), "_current_frames"),
                    reason="no sys._current_frames on this interpreter")
def test_sampler_attributes_busy_function(fresh_profiler):
    """>= 50% of main-thread samples must land in the seeded busy
    function — the whole point of the profiler is attribution."""
    sampler = profiler.enable("driver:test", hz=250)
    try:
        _busy_spin(time.perf_counter() + 0.4)
    finally:
        profiler.disable()
    snap = sampler.snapshot()
    assert snap["samples"] > 0 and snap["hz"] == 250
    main = {s: n for s, n in snap["counts"].items()
            if s.startswith("main;")}
    assert main, f"no main-thread samples in {list(snap['counts'])[:5]}"
    mine = sum(n for s, n in main.items() if "_busy_spin" in s)
    frac = mine / sum(main.values())
    assert frac >= 0.5, f"only {frac:.0%} attributed to _busy_spin"
    # folded convention: root first, role prefix, file:func frames
    stack = next(s for s in main if "_busy_spin" in s)
    assert stack.split(";")[-1] == "test_profiler.py:_busy_spin"


def test_sampler_never_samples_itself(fresh_profiler):
    sampler = profiler.Sampler("t", hz=50)
    # not started: drive one sample from this thread and check the
    # sampler's own thread id is excluded by construction
    sampler.sample_once()
    assert all("rtpu-profiler" not in s for s in sampler.counts)


def test_role_folding():
    assert profiler._role("rtpu-io-loop-0") == "io-loop"
    assert profiler._role("task-runner-3") == "executor"
    assert profiler._role("actor-loop-1") == "executor"
    assert profiler._role("ThreadPoolExecutor-0_1") == "executor"
    assert profiler._role("MainThread") == "main"
    assert profiler._role("flight-flush") == "flight-flush"
    assert profiler._role("") == "other"
    assert profiler._role("my-thread") == "my-thread"


def test_enable_disable_gate(fresh_profiler):
    assert not profiler.enabled()
    sampler = profiler.enable("driver:gate", hz=97)
    assert profiler.enabled() and profiler.PROFILER is sampler
    back = profiler.disable()
    assert back is sampler and not profiler.enabled()
    assert profiler.disable() is None          # idempotent


def test_env_gate_off_means_no_thread(fresh_profiler, monkeypatch):
    monkeypatch.delenv(profiler._ENV_FLAG, raising=False)
    profiler.init_driver()
    assert not profiler.enabled()
    monkeypatch.setenv(profiler._ENV_FLAG, "1")
    try:
        profiler.init_driver()
        assert profiler.enabled()
    finally:
        profiler.disable()


# --- store + export ---------------------------------------------------

def test_store_replace_on_push(fresh_profiler):
    profiler.store_push("worker:aa", {"main;f": 3}, 3, 101)
    profiler.store_push("worker:aa", {"main;f": 9, "main;g": 1}, 10, 101)
    procs = profiler.get_store().profiles()
    assert procs["worker:aa"]["samples"] == 10
    assert procs["worker:aa"]["counts"] == {"main;f": 9, "main;g": 1}


def test_folded_dump_and_speedscope(fresh_profiler, tmp_path):
    profiler.store_push("worker:aa", {"main;a.py:f;a.py:g": 4}, 4, 101)
    profiler.store_push("worker:bb", {"executor;b.py:h": 2}, 2, 101)

    folded = profiler.folded()
    assert folded == {"worker:aa;main;a.py:f;a.py:g": 4,
                      "worker:bb;executor;b.py:h": 2}
    assert profiler.folded(proc="worker:bb") == {
        "worker:bb;executor;b.py:h": 2}

    out = tmp_path / "prof.folded"
    text = profiler.dump(str(out))
    assert out.read_text() == text
    assert "worker:aa;main;a.py:f;a.py:g 4" in text.splitlines()

    scope = timeline.speedscope_profile(
        profiles=profiler.merged_profiles())
    assert scope["$schema"].startswith("https://www.speedscope.app")
    by_name = {p["name"]: p for p in scope["profiles"]}
    assert set(by_name) == {"worker:aa", "worker:bb"}
    frames = [f["name"] for f in scope["shared"]["frames"]]
    prof = by_name["worker:aa"]
    assert prof["endValue"] == sum(prof["weights"]) == 4
    # frame indices resolve through the shared table, root first
    (stack,) = prof["samples"]
    assert [frames[i] for i in stack] == ["main", "a.py:f", "a.py:g"]


def test_profile_dump_api(fresh_profiler):
    import ray_tpu
    profiler.store_push("worker:aa", {"main;f": 1}, 1, 101)
    assert "worker:aa;main;f 1" in ray_tpu.profile_dump()


# --- profdiff ---------------------------------------------------------

def _phase_table(us):
    return {"phases": {name: {"count": 100, "mean_us": v}
                       for name, v in us.items()}}


def test_profdiff_roundtrip_and_exit_codes(tmp_path, capsys):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_phase_table(
        {"frame-encode": 40.0, "wire-write": 25.0})))
    b.write_text(json.dumps(_phase_table(
        {"frame-encode": 9.0, "wire-write": 60.0})))

    assert profdiff.main([str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "frame-encode" in out and "wire-write" in out

    # wire-write regressed 2.4x: --fail-ratio 1.3 must exit 1
    assert profdiff.main([str(a), str(b), "--fail-ratio", "1.3"]) == 1
    captured = capsys.readouterr()
    assert "REGRESSION" in captured.out
    assert "wire-write" in captured.err

    # identical captures pass any ratio
    assert profdiff.main([str(a), str(a), "--fail-ratio", "1.01"]) == 0
    capsys.readouterr()

    assert profdiff.main([str(a)]) == 2      # usage
    capsys.readouterr()


def test_profdiff_normalizes_bench_rows_and_profiles(tmp_path):
    bench = [{"bench": "trivial_tasks", "per_second": 6500.0},
             {"bench": "task_phases",
              "phases": {"spec-build": {"count": 300, "mean_us": 12.5}}}]
    norm = profdiff.normalize(bench)
    assert norm["phases"] == {"spec-build": 12.5}
    assert norm["counts"] == {"spec-build": 300}

    cap = {"kind": "rtpu-profile",
           "procs": {"driver:1": {"counts": {"main;a;f": 8, "main;b;f": 4},
                                  "samples": 12, "hz": 101}}}
    norm = profdiff.normalize(cap)
    assert norm["frames"] == {"f": 12} and norm["samples"] == 12

    report = profdiff.diff(profdiff.normalize(cap),
                           profdiff.normalize(cap))
    assert report["frames"][0]["delta_pct"] == 0.0


def test_profdiff_min_count_ignores_noise_phases():
    a = {"phases": {"x": 10.0}, "counts": {"x": 3}, "frames": {},
         "samples": 0}
    b = {"phases": {"x": 100.0}, "counts": {"x": 3}, "frames": {},
         "samples": 0}
    report = profdiff.diff(a, b, min_count=5)
    assert report["worst"] is None           # 3 samples: noise, not fail


# --- percentile None-contract (satellite b) ---------------------------

def test_percentile_from_counts_never_raises_on_empty():
    assert metrics_mod.percentile_from_counts([], [], 0.99) is None
    assert metrics_mod.percentile_from_counts([], [0], 0.99) is None
    assert metrics_mod.percentile_from_counts([], [5], 0.99) is None
    assert metrics_mod.percentile_from_counts([1.0, 2.0],
                                              [0, 0, 0], 0.5) is None


def test_histogram_percentile_none_when_unobserved():
    h = metrics_mod.Histogram("test_prof_unobserved_hist",
                              boundaries=[0.1, 1.0])
    assert h.percentile(0.5) is None
    assert h.snapshot() is None


# --- e2e: phase chain over a live runtime -----------------------------

@pytest.mark.watchdog(120)
def test_phase_chain_records_all_phases(ray_start_regular):
    import ray_tpu
    from ray_tpu.core import task_phase
    from ray_tpu.core.config import get_config
    from ray_tpu.devtools import whereis

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    cfg = get_config()
    saved = (fr.RECORDER, cfg.task_phase_sample_n)
    task_phase.reset()
    try:
        cfg.task_phase_sample_n = 1          # sample every task
        fr.enable("driver:phase-test", capacity=4096)
        lo = fr.clock_ns()
        ray_tpu.get([nop.remote() for _ in range(50)])
        hi = fr.clock_ns()
        report = whereis.task_path_attribution(
            fr.merged_journals(), window_ns=(lo, hi))
    finally:
        fr.RECORDER, cfg.task_phase_sample_n = saved
        task_phase.reset()

    assert set(report["phases"]) == set(task_phase.PHASES)
    assert report["tasks_sampled"] >= 40     # ring may shed the oldest
    for name, row in report["phases"].items():
        assert row["count"] > 0 and row["mean_us"] >= 0.0, name
    assert report["mean_chain_us"] > 0
    # sample-every-task chains tile nearly the whole window
    assert report["coverage"] is not None and report["coverage"] > 0.5
    # rendering must not raise and must carry the table
    text = whereis.render_task_path(report)
    assert "wire-write" in text and "coverage" in text


@pytest.mark.watchdog(120)
def test_phase_sampling_gate_is_cheap_when_untracked(ray_start_regular):
    """With the recorder off, sample_begin returns 0 and _TRACKED stays
    empty — the unsampled hot path must leave no chains behind."""
    import ray_tpu
    from ray_tpu.core import task_phase

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    saved = fr.RECORDER
    try:
        fr.disable()
        task_phase.reset()
        ray_tpu.get([nop.remote() for _ in range(200)])
        assert task_phase._TRACKED == {}
        assert task_phase.sample_begin() == 0
    finally:
        fr.RECORDER = saved


# --- overhead guards (satellite e) ------------------------------------

@pytest.mark.watchdog(300)
def test_profiler_overhead_disabled_ratio(ray_start_regular):
    """With every observatory gate off, interleaved runs of the same
    loop must agree within 5% — the disabled path is two loads and a
    compare, so any drift here is a gate that grew a body."""
    import ray_tpu
    from ray_tpu.core import task_phase

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(500)])   # warmup

    def run_loop(n=1500):
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        return time.perf_counter() - t0

    saved = (fr.RECORDER, profiler.PROFILER)
    try:
        fr.disable()
        profiler.disable()
        task_phase.reset()
        timings = {"a": [], "b": []}
        for arm in ("a", "b", "a", "b", "a", "b"):
            timings[arm].append(run_loop())
        ratio = min(timings["b"]) / min(timings["a"])
    finally:
        fr.RECORDER, profiler.PROFILER = saved
    assert ratio < 1.05, f"disabled-path drift ratio {ratio:.3f} >= 1.05"


@pytest.mark.watchdog(300)
def test_profiler_overhead_enabled_ratio(ray_start_regular):
    """Full observatory on — sampler at 101 Hz + recorder + 1-in-64
    phase sampling — vs everything off, interleaved best-of: the
    enabled loop must stay under 1.5x."""
    import ray_tpu
    from ray_tpu.core import task_phase
    from ray_tpu.core.config import get_config

    @ray_tpu.remote(num_cpus=0)
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(500)])   # warmup

    def run_loop(n=1500):
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        return time.perf_counter() - t0

    cfg = get_config()
    saved = (fr.RECORDER, profiler.PROFILER, cfg.task_phase_sample_n)
    try:
        timings = {}
        for mode in ("off", "on", "off", "on"):    # interleave: best-of
            if mode == "on":
                cfg.task_phase_sample_n = 64
                fr.enable("driver:overhead")
                profiler.enable("driver:overhead", hz=101)
            else:
                cfg.task_phase_sample_n = saved[2]
                fr.disable()
                profiler.disable()
            task_phase.reset()
            timings.setdefault(mode, []).append(run_loop())
        ratio = min(timings["on"]) / min(timings["off"])
    finally:
        profiler.disable()
        fr.RECORDER, profiler.PROFILER, cfg.task_phase_sample_n = saved
        task_phase.reset()
    assert ratio < 1.5, f"observatory overhead ratio {ratio:.2f} >= 1.5"
