"""JaxTrainer end-to-end tests: SPMD single-worker, multi-worker DDP via
host collectives, checkpoint/resume, failure policy.

reference models: train/v2/tests (controller state machine, JAX backend),
air_benchmark_torch_mnist (release_tests.yaml:197) as the DDP recipe.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.parallel import _compat
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    Result,
    RunConfig,
    ScalingConfig,
)


def test_single_worker_spmd(ray_start_regular, tmp_path):
    """One worker, 8-device CPU mesh inside the worker: DDP via GSPMD."""

    def train_loop(config):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        import ray_tpu.train as train
        from ray_tpu.models.mlp import MLPConfig, mlp_init, mlp_loss
        from ray_tpu.parallel.mesh import MeshSpec, make_mesh
        from ray_tpu.parallel.sharding import shard_pytree, ShardingConfig

        mesh = make_mesh(MeshSpec.for_devices(len(jax.devices())))
        cfg = MLPConfig(in_dim=16, hidden=(32,), out_dim=4)
        params = mlp_init(jax.random.PRNGKey(0), cfg)
        params = shard_pytree(params, mesh,
                              ShardingConfig(mode="ddp").rules())
        x = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(1), (64, 16)),
            NamedSharding(mesh, P(("data",))))
        y = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(2), (64,), 0, 4),
            NamedSharding(mesh, P(("data",))))

        @jax.jit
        def step(p):
            loss, grads = jax.value_and_grad(mlp_loss)(p, x, y)
            return jax.tree.map(lambda a, g: a - 0.1 * g, p, grads), loss

        for epoch in range(3):
            params, loss = step(params)
            train.report({"loss": float(loss), "epoch": epoch})

    trainer = JaxTrainer(
        train_loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="spmd_test", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == 3
    assert result.metrics_history[-1]["loss"] < result.metrics_history[0]["loss"]


def test_multi_worker_ddp_host_allreduce(ray_start_regular, tmp_path):
    """2 workers, per-worker local compute + host-collective gradient
    allreduce (the X2 DDP path without a shared mesh)."""

    def train_loop(config):
        import jax
        import jax.numpy as jnp
        import ray_tpu.train as train
        from ray_tpu.train.collective import allreduce_gradients
        from ray_tpu.models.mlp import MLPConfig, mlp_init, mlp_loss

        ctx = train.get_context()
        cfg = MLPConfig(in_dim=8, hidden=(16,), out_dim=2)
        params = mlp_init(jax.random.PRNGKey(0), cfg)  # same init everywhere
        # Different data shard per rank.
        x = jax.random.normal(jax.random.PRNGKey(10 + ctx.world_rank), (32, 8))
        y = jax.random.randint(jax.random.PRNGKey(20 + ctx.world_rank),
                               (32,), 0, 2)
        for epoch in range(2):
            loss, grads = jax.value_and_grad(mlp_loss)(params, x, y)
            grads = allreduce_gradients(grads, op="mean")
            params = jax.tree.map(lambda a, g: a - 0.1 * g, params, grads)
            train.report({"loss": float(loss), "rank": ctx.world_rank,
                          "epoch": epoch})
        # Params must be identical across ranks after synced updates.
        flat = jax.tree_util.tree_leaves(params)
        checksum = float(sum(jnp.sum(p) for p in flat))
        train.report({"checksum": checksum})

    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ddp_test", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert "checksum" in result.metrics


def test_checkpoint_report_and_resume(ray_start_regular, tmp_path):
    def train_loop(config):
        import os
        import tempfile
        import ray_tpu.train as train
        from ray_tpu.train import Checkpoint

        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with ckpt.as_directory() as d:
                with open(os.path.join(d, "step.txt")) as f:
                    start = int(f.read())
        for step in range(start, start + 2):
            tmp = tempfile.mkdtemp()
            with open(os.path.join(tmp, "step.txt"), "w") as f:
                f.write(str(step + 1))
            train.report({"step": step + 1},
                         checkpoint=Checkpoint(tmp)
                         if ctx.world_rank == 0 else None)

    run_cfg = RunConfig(name="resume_test", storage_path=str(tmp_path))
    r1 = JaxTrainer(train_loop,
                    scaling_config=ScalingConfig(num_workers=1),
                    run_config=run_cfg).fit()
    assert r1.error is None
    assert r1.metrics["step"] == 2

    # Second run resumes from the persisted checkpoint.
    r2 = JaxTrainer(train_loop,
                    scaling_config=ScalingConfig(num_workers=1),
                    run_config=run_cfg).fit()
    assert r2.error is None
    assert r2.metrics["step"] == 4


def test_failure_policy_retries(ray_start_regular, tmp_path):
    marker = str(tmp_path / "died_once")

    def train_loop(config):
        import os
        import ray_tpu.train as train
        if not os.path.exists(config["marker"]):
            open(config["marker"], "w").close()
            os._exit(1)  # hard crash on first attempt
        train.report({"recovered": 1})

    trainer = JaxTrainer(
        train_loop, train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="failure_test", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["recovered"] == 1
    assert "RESTARTING" in trainer.state_history


def test_failure_policy_exhausted(ray_start_regular, tmp_path):
    def train_loop(config):
        import os
        os._exit(1)

    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="fatal_test", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is not None
    assert "ERRORED" in trainer.state_history


@pytest.mark.skipif(
    "cpu" in os.environ.get("JAX_PLATFORMS", "").lower()
    and not _compat.CPU_COLLECTIVES_AVAILABLE,
    reason="CPU gang needs gloo collectives in jaxlib: "
           + _compat.CPU_COLLECTIVES_UNAVAILABLE_REASON)
def test_gang_multiprocess_spmd_global_mesh(ray_start_cluster, tmp_path):
    """VERDICT round-1 item 6: gang-launch N real worker processes,
    jax.distributed.initialize over loopback, and prove the gang shares
    ONE global device view (device_count = sum of local devices) with a
    working cross-process collective. No hardware: each process has 8
    virtual CPU devices (conftest XLA_FLAGS, inherited by workers)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    cluster = ray_start_cluster
    cluster.add_node(resources={"CPU": 8, "TPU": 8})

    def train_loop(config):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.experimental import multihost_utils
        import ray_tpu.train as train

        ctx = train.get_context()
        n_local = jax.local_device_count()
        n_global = jax.device_count()
        # cross-process collective through the global runtime
        ranks = multihost_utils.process_allgather(
            jnp.array([ctx.world_rank]))
        train.report({
            "rank": ctx.world_rank,
            "process_index": jax.process_index(),
            "n_local": n_local,
            "n_global": n_global,
            "ranks_seen": sorted(int(r) for r in np.asarray(ranks).ravel()),
        })

    trainer = JaxTrainer(
        train_loop,
        scaling_config=ScalingConfig(num_workers=2, use_tpu=True,
                                     tpu_chips_per_worker=4),
        run_config=RunConfig(name="gang_spmd", storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    finals = [reports[-1][0] for reports in result.all_reports]
    assert {m["process_index"] for m in finals} == {0, 1}
    for m in finals:
        assert m["n_global"] == 2 * m["n_local"]
        assert m["ranks_seen"] == [0, 1]
