"""Compiled-graph tests (reference: python/ray/dag/tests/experimental/
test_accelerated_dag.py — execute/get roundtrips, chains, fan-out,
app-error propagation, teardown)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import DAGExecutionError, InputNode, MultiOutputNode


@ray_tpu.remote
class Adder:
    def __init__(self, inc=1):
        self.inc = inc
        self.calls = 0

    def add(self, x):
        self.calls += 1
        return x + self.inc

    def add2(self, x, y):
        return x + y

    def boom(self, x):
        raise ValueError(f"bad input {x}")

    def num_calls(self):
        return self.calls


def test_uncompiled_dag_execute(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    ref = dag.execute(5)
    assert ray_tpu.get(ref) == 16


def test_uncompiled_function_node(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return x * 2

    with InputNode() as inp:
        dag = double.bind(double.bind(inp))
    assert ray_tpu.get(dag.execute(3)) == 12


def test_compiled_chain(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        for i in range(10):
            assert compiled.execute(i).get() == i + 11
    finally:
        compiled.teardown()


def test_compiled_same_actor_local_passthrough(ray_start_regular):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(0).get() == 2
        # both nodes ran on the one actor
        assert compiled.execute(0).get() == 2
    finally:
        compiled.teardown()
    assert ray_tpu.get(a.num_calls.remote()) == 4


def test_compiled_fanout_multi_output(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(100)
    with InputNode() as inp:
        dag = MultiOutputNode([a.add.bind(inp), b.add.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(7).get() == [8, 107]
    finally:
        compiled.teardown()


def test_compiled_join_two_inputs(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(2)
    c = Adder.remote(0)
    with InputNode() as inp:
        dag = c.add2.bind(a.add.bind(inp[0]), b.add.bind(inp[1]))
    compiled = dag.experimental_compile()
    try:
        # (3+1) + (4+2)
        assert compiled.execute(3, 4).get() == 10
    finally:
        compiled.teardown()


def test_compiled_numpy_payload(ray_start_regular):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        x = np.arange(131072, dtype=np.float32)
        out = compiled.execute(x).get()
        np.testing.assert_allclose(np.asarray(out), x + 1)
    finally:
        compiled.teardown()


def test_compiled_app_error_keeps_dag_alive(ray_start_regular):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.boom.bind(inp)
    compiled = dag.experimental_compile()
    try:
        with pytest.raises(DAGExecutionError, match="bad input 1"):
            compiled.execute(1).get()
        # DAG still works after an application error
        with pytest.raises(DAGExecutionError, match="bad input 2"):
            compiled.execute(2).get()
    finally:
        compiled.teardown()


def test_compiled_error_propagates_downstream(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.boom.bind(inp))
    compiled = dag.experimental_compile()
    try:
        with pytest.raises(DAGExecutionError, match="boom"):
            compiled.execute(1).get()
    finally:
        compiled.teardown()


def test_teardown_frees_actor(ray_start_regular):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled.execute(1).get() == 2
    compiled.teardown()
    # actor serves normal calls again after teardown
    assert ray_tpu.get(a.add.remote(5)) == 6
    with pytest.raises(RuntimeError):
        compiled.execute(1)


def test_compiled_pipelined_executes(ray_start_regular):
    """Several in-flight executions within the channel window."""
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        refs = [compiled.execute(i) for i in range(3)]
        assert [r.get() for r in refs] == [1, 2, 3]
    finally:
        compiled.teardown()
