"""Compiled-graph tests (reference: python/ray/dag/tests/experimental/
test_accelerated_dag.py — execute/get roundtrips, chains, fan-out,
app-error propagation, teardown)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import DAGExecutionError, InputNode, MultiOutputNode


@ray_tpu.remote
class Adder:
    def __init__(self, inc=1):
        self.inc = inc
        self.calls = 0

    def add(self, x):
        self.calls += 1
        return x + self.inc

    def add2(self, x, y):
        return x + y

    def boom(self, x):
        raise ValueError(f"bad input {x}")

    def num_calls(self):
        return self.calls


def test_uncompiled_dag_execute(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    ref = dag.execute(5)
    assert ray_tpu.get(ref) == 16


def test_uncompiled_function_node(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return x * 2

    with InputNode() as inp:
        dag = double.bind(double.bind(inp))
    assert ray_tpu.get(dag.execute(3)) == 12


def test_compiled_chain(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        for i in range(10):
            assert compiled.execute(i).get() == i + 11
    finally:
        compiled.teardown()


def test_compiled_same_actor_local_passthrough(ray_start_regular):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(0).get() == 2
        # both nodes ran on the one actor
        assert compiled.execute(0).get() == 2
    finally:
        compiled.teardown()
    assert ray_tpu.get(a.num_calls.remote()) == 4


def test_compiled_fanout_multi_output(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(100)
    with InputNode() as inp:
        dag = MultiOutputNode([a.add.bind(inp), b.add.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(7).get() == [8, 107]
    finally:
        compiled.teardown()


def test_compiled_join_two_inputs(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(2)
    c = Adder.remote(0)
    with InputNode() as inp:
        dag = c.add2.bind(a.add.bind(inp[0]), b.add.bind(inp[1]))
    compiled = dag.experimental_compile()
    try:
        # (3+1) + (4+2)
        assert compiled.execute(3, 4).get() == 10
    finally:
        compiled.teardown()


def test_compiled_numpy_payload(ray_start_regular):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        x = np.arange(131072, dtype=np.float32)
        out = compiled.execute(x).get()
        np.testing.assert_allclose(np.asarray(out), x + 1)
    finally:
        compiled.teardown()


def test_compiled_app_error_keeps_dag_alive(ray_start_regular):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.boom.bind(inp)
    compiled = dag.experimental_compile()
    try:
        with pytest.raises(DAGExecutionError, match="bad input 1"):
            compiled.execute(1).get()
        # DAG still works after an application error
        with pytest.raises(DAGExecutionError, match="bad input 2"):
            compiled.execute(2).get()
    finally:
        compiled.teardown()


def test_compiled_error_propagates_downstream(ray_start_regular):
    a = Adder.remote(1)
    b = Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.boom.bind(inp))
    compiled = dag.experimental_compile()
    try:
        with pytest.raises(DAGExecutionError, match="boom"):
            compiled.execute(1).get()
    finally:
        compiled.teardown()


def test_teardown_frees_actor(ray_start_regular):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled.execute(1).get() == 2
    compiled.teardown()
    # actor serves normal calls again after teardown
    assert ray_tpu.get(a.add.remote(5)) == 6
    with pytest.raises(RuntimeError):
        compiled.execute(1)


def test_compiled_pipelined_executes(ray_start_regular):
    """Several in-flight executions within the channel window."""
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        refs = [compiled.execute(i) for i in range(3)]
        assert [r.get() for r in refs] == [1, 2, 3]
    finally:
        compiled.teardown()


def test_compiled_dag_cross_node():
    """Actors on DIFFERENT nodes: edges move over pre-established
    worker-to-worker TCP channels (reference analog: NCCL channels for
    cross-GPU compiled-graph edges) while co-located edges stay shm."""
    from ray_tpu.core.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"resources": {"CPU": 2}})
    try:
        cluster.add_node(resources={"CPU": 2, "island": 1.0})

        @ray_tpu.remote
        class Stage:
            def __init__(self, add):
                self.add = add
            def apply(self, x):
                return x + self.add

        # a: head node; b: pinned to the second node
        a = Stage.remote(1)
        b = Stage.options(resources={"island": 0.1},
                          num_cpus=1).remote(10)
        with InputNode() as inp:
            dag = b.apply.bind(a.apply.bind(inp))
        compiled = dag.experimental_compile()
        try:
            # pipelined executions through the cross-node hop
            refs = [compiled.execute(i) for i in range(6)]
            assert [r.get(timeout=60) for r in refs] == [
                i + 11 for i in range(6)]
        finally:
            compiled.teardown()

        # errors still propagate across the TCP hop
        @ray_tpu.remote
        class Boom:
            def go(self, x):
                raise ValueError("cross-node kaboom")

        c = Boom.options(resources={"island": 0.1},
                         num_cpus=1).remote()
        with InputNode() as inp:
            dag2 = c.go.bind(a.apply.bind(inp))
        compiled2 = dag2.experimental_compile()
        try:
            with pytest.raises(Exception, match="kaboom"):
                compiled2.execute(1).get(timeout=60)
        finally:
            compiled2.teardown()
    finally:
        cluster.shutdown()


def test_compiled_dag_cross_host_daemon():
    """The second actor lives on a REAL node-daemon process (separate
    OS process joined over TCP): channel frames flow worker-to-worker
    across process/arena boundaries."""
    from ray_tpu.core.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"resources": {"CPU": 2}},
                      system_config={"head_port": 0})
    proc = None
    try:
        _node_id, proc = cluster.add_remote_node(
            resources={"CPU": 2, "remote_island": 1.0})

        @ray_tpu.remote
        class Stage:
            def __init__(self, mul):
                self.mul = mul
            def apply(self, x):
                return x * self.mul

        a = Stage.remote(3)  # head
        b = Stage.options(resources={"remote_island": 0.1},
                          num_cpus=1).remote(7)  # daemon host
        with InputNode() as inp:
            dag = b.apply.bind(a.apply.bind(inp))
        compiled = dag.experimental_compile()
        try:
            refs = [compiled.execute(i) for i in range(5)]
            assert [r.get(timeout=90) for r in refs] == [
                i * 21 for i in range(5)]
        finally:
            compiled.teardown()
    finally:
        if proc is not None:
            proc.kill()
        cluster.shutdown()
